"""Caffe → native Keras-graph importer.

Reference: `zoo/.../models/caffe/CaffeLoader.scala:718` +
`LayerConverter.scala:792` (prototxt/caffemodel → BigDL graph). Here:
- the deploy prototxt (protobuf TEXT format) provides the architecture and
  input shapes, parsed by a ~60-line recursive text-format reader;
- the .caffemodel (binary NetParameter) provides the weights, decoded with
  the same wire decoder the ONNX importer uses (`onnx/wire.py`) against the
  caffe.proto field numbers;
- layers map onto the jax layer library in NCHW (`dim_ordering="th"`), so
  caffe's OIHW kernels and flatten order carry over bit-compatibly.

Supported layers (the classic classification-net set the reference's
converter suite covers): Input, Convolution, InnerProduct, Pooling
(MAX/AVE, caffe ceil-mode output sizes emulated with asymmetric padding),
ReLU, Sigmoid, TanH, Softmax, Dropout (inference no-op), LRN
(across-channels), BatchNorm (+ scale-factor blob), Scale, Concat, Eltwise
(SUM/PROD/MAX), Flatten.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional

import numpy as np

from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.learn.torch_bridge import _with_weights
from analytics_zoo_tpu.onnx import wire
from analytics_zoo_tpu.ops.autograd import LambdaLayer

# ---------------------------------------------------------------------------
# caffe.proto schemas (field numbers frozen by the BVLC proto)
# ---------------------------------------------------------------------------
BLOB_SHAPE = {1: ("dim", "varint")}

BLOB = {
    1: ("num", "varint"), 2: ("channels", "varint"),
    3: ("height", "varint"), 4: ("width", "varint"),
    5: ("data", "float"), 7: ("shape", ("msg", BLOB_SHAPE)),
}

LAYER = {
    1: ("name", "string"),
    2: ("type", "string"),
    3: ("bottom", "string"),
    4: ("top", "string"),
    7: ("blobs", ("msg", BLOB)),
}

NET = {
    1: ("name", "string"),
    100: ("layer", ("msg", LAYER)),
}


# ---------------------------------------------------------------------------
# prototxt text-format parser
# ---------------------------------------------------------------------------
_TOKEN = re.compile(r'"(?:[^"\\]|\\.)*"|[{}:]|[^\s{}:]+')


def parse_prototxt(text: str) -> Dict[str, List]:
    """Protobuf text format → {field: [values...]} tree (every field
    repeated, mirroring the wire decoder's shape)."""
    # strip comments — but not '#' inside quoted strings
    text = re.sub(r'("(?:[^"\\]|\\.)*")|#.*',
                  lambda m: m.group(1) or "", text)
    tokens = _TOKEN.findall(text)
    pos = 0

    def parse_block():
        nonlocal pos
        out: Dict[str, List] = {}
        while pos < len(tokens):
            tok = tokens[pos]
            if tok == "}":
                pos += 1
                return out
            name = tok
            pos += 1
            if pos < len(tokens) and tokens[pos] == ":":
                pos += 1
                val = tokens[pos]
                pos += 1
                if val.startswith('"'):
                    val = val[1:-1]
                else:
                    try:
                        val = int(val)
                    except ValueError:
                        try:
                            val = float(val)
                        except ValueError:
                            pass  # enum name / bool keyword stays str
                out.setdefault(name, []).append(val)
            elif pos < len(tokens) and tokens[pos] == "{":
                pos += 1
                out.setdefault(name, []).append(parse_block())
            else:
                raise ValueError(f"Malformed prototxt near {name!r}")
        return out

    return parse_block()


def _blob_array(blob: Dict) -> np.ndarray:
    data = np.asarray(blob.get("data", []), np.float32)
    if blob.get("shape"):
        dims = blob["shape"][0].get("dim", [])
    else:  # legacy num/channels/height/width
        dims = [blob.get(k, [1])[0]
                for k in ("num", "channels", "height", "width")]
        while len(dims) > 1 and dims[0] == 1:
            dims = dims[1:]
    return data.reshape([int(d) for d in dims]) if dims else data


def _first(d: Dict, key: str, default=None):
    v = d.get(key)
    return v[0] if v else default


def _pool_pad_for_ceil(size: int, k: int, s: int, p: int):
    """Caffe pooling uses CEIL output sizing; emulate with extra right/
    bottom padding so a floor-mode valid pool matches."""
    out = int(math.ceil((size + 2 * p - k) / s)) + 1
    # caffe clips windows that start beyond the padded input
    if p > 0 and (out - 1) * s >= size + p:
        out -= 1
    extra = (out - 1) * s + k - (size + 2 * p)
    return out, max(extra, 0)


class _CaffeGraphBuilder:
    def __init__(self, arch: Dict, weights: Dict[str, List[np.ndarray]]):
        self.arch = arch
        self.weights = weights
        self.nodes: Dict[str, Any] = {}
        self.inputs: List = []
        self.shapes: Dict[str, tuple] = {}   # tensor name → (C, H, W)

    def _in(self, layer: Dict):
        return self.nodes[layer["bottom"][0]]

    # -- layer handlers ----------------------------------------------------
    def _input(self, layer: Dict):
        ip = (layer.get("input_param") or [{}])[0]
        shape_blk = (ip.get("shape") or [{}])[0]
        dims = [int(d) for d in shape_blk.get("dim", [])]
        if not dims:
            raise ValueError(
                f"Input layer {_first(layer, 'name')!r} needs input_param "
                "{ shape { dim ... } }")
        inp = Input(shape=tuple(dims[1:]))
        self.inputs.append(inp)
        top = layer["top"][0]
        self.nodes[top] = inp
        self.shapes[top] = tuple(dims[1:])

    @staticmethod
    def _conv_params(p: Dict):
        """Shared convolution_param extraction for Convolution and
        Deconvolution (kernel/stride/pad h-w, group, dilation, bias)."""
        return dict(
            num_out=int(_first(p, "num_output")),
            kh=int(_first(p, "kernel_h", _first(p, "kernel_size", 1))),
            kw=int(_first(p, "kernel_w", _first(p, "kernel_size", 1))),
            sh=int(_first(p, "stride_h", _first(p, "stride", 1))),
            sw=int(_first(p, "stride_w", _first(p, "stride", 1))),
            ph=int(_first(p, "pad_h", _first(p, "pad", 0))),
            pw=int(_first(p, "pad_w", _first(p, "pad", 0))),
            group=int(_first(p, "group", 1)),
            dilation=int(_first(p, "dilation", 1)),
            bias_term=str(_first(p, "bias_term",
                                 "true")).lower() != "false")

    def _conv(self, layer: Dict, name: str):
        p = (layer.get("convolution_param") or [{}])[0]
        cp = self._conv_params(p)
        num_out, kh, kw = cp["num_out"], cp["kh"], cp["kw"]
        sh, sw, ph, pw = cp["sh"], cp["sw"], cp["ph"], cp["pw"]
        group, dilation = cp["group"], cp["dilation"]
        bias_term = cp["bias_term"]
        x = self._in(layer)
        if ph or pw:
            x = L.ZeroPadding2D((ph, pw), dim_ordering="th")(x)
        blobs = self.weights.get(name, [])
        if not blobs:
            raise ValueError(f"No weights for Convolution {name!r}")
        w = blobs[0]                                  # [O, I/group, kh, kw]
        params = {"kernel": np.transpose(w, (2, 3, 1, 0)).copy()}
        if bias_term and len(blobs) > 1:
            params["bias"] = blobs[1]
        use_bias = bias_term and len(blobs) > 1
        if dilation != 1:
            conv = L.AtrousConvolution2D(
                num_out, kh, kw, atrous_rate=(dilation, dilation),
                subsample=(sh, sw), border_mode="valid",
                dim_ordering="th", use_bias=use_bias, groups=group)
        else:
            conv = L.Convolution2D(num_out, kh, kw, subsample=(sh, sw),
                                   border_mode="valid", dim_ordering="th",
                                   use_bias=use_bias, groups=group)
        return _with_weights(conv, params)(x)

    def _inner_product(self, layer: Dict, name: str, in_rank: int):
        p = (layer.get("inner_product_param", [{}]) or [{}])[0]
        num_out = int(_first(p, "num_output"))
        bias_term = str(_first(p, "bias_term", "true")).lower() != "false"
        x = self._in(layer)
        if in_rank > 2:
            x = L.Flatten()(x)        # caffe IP flattens implicitly
        blobs = self.weights.get(name, [])
        if not blobs:
            raise ValueError(f"No weights for InnerProduct {name!r}")
        w = blobs[0]                                  # [out, in]
        params = {"kernel": w.reshape(num_out, -1).T.copy()}
        if bias_term and len(blobs) > 1:
            params["bias"] = blobs[1]
        dense = L.Dense(num_out,
                        use_bias=bias_term and len(blobs) > 1)
        return _with_weights(dense, params)(x)

    def _slice(self, layer: Dict):
        """caffe Slice: cut `bottom` along slice_param.axis at slice_point
        boundaries (or evenly among tops when absent); one top per part."""
        p = (layer.get("slice_param", [{}]) or [{}])[0]
        axis = int(_first(p, "axis", 1))
        tops = layer.get("top", [])
        in_shape = self.shapes.get(layer["bottom"][0]) or ()
        if axis < 0:
            axis += len(in_shape) + 1     # shapes exclude batch
        if axis < 1:
            raise NotImplementedError(
                "Slice along the batch dimension")
        size = in_shape[axis - 1]
        points = [int(v) for v in p.get("slice_point", [])]
        if not points:
            if size is None or size % len(tops):
                raise NotImplementedError(
                    "Slice without slice_point needs an evenly divisible "
                    "axis")
            step = size // len(tops)
            points = [step * i for i in range(1, len(tops))]
        bounds = [0] + points + [size]
        src = self._in(layer)
        for i, t in enumerate(tops):
            lo, hi = bounds[i], bounds[i + 1]

            def cut(x, lo=lo, hi=hi, ax=axis):
                sl = [slice(None)] * x.ndim
                sl[ax] = slice(lo, hi)
                return x[tuple(sl)]
            node = LambdaLayer(cut)(src)
            self.nodes[t] = node
            shp = list(in_shape)
            shp[axis - 1] = hi - lo
            self.shapes[t] = tuple(shp)

    def _deconv(self, layer: Dict, name: str):
        p = (layer.get("convolution_param") or [{}])[0]
        cp = self._conv_params(p)
        num_out, kh, kw = cp["num_out"], cp["kh"], cp["kw"]
        sh, sw, ph, pw = cp["sh"], cp["sw"], cp["ph"], cp["pw"]
        if cp["group"] != 1:
            raise NotImplementedError("Grouped Deconvolution")
        if cp["dilation"] != 1:
            raise NotImplementedError("Dilated Deconvolution")
        bias_term = cp["bias_term"]
        blobs = self.weights.get(name, [])
        if not blobs:
            raise ValueError(f"No weights for Deconvolution {name!r}")
        w = blobs[0]                                   # [I, O, kh, kw]
        use_bias = bias_term and len(blobs) > 1
        deconv = L.Deconvolution2D(num_out, kh, kw, subsample=(sh, sw),
                                   border_mode="valid", dim_ordering="th",
                                   use_bias=use_bias)
        params = {"kernel": np.transpose(w, (2, 3, 0, 1)).copy()}  # HWIO
        if use_bias:
            params["bias"] = blobs[1]
        node = _with_weights(deconv, params)(self._in(layer))
        if ph or pw:
            # caffe crops `pad` from each side of the full deconv output
            node = L.Cropping2D(((ph, ph), (pw, pw)),
                                dim_ordering="th")(node)
        return node

    def _pool(self, layer: Dict, shape):
        p = (layer.get("pooling_param", [{}]) or [{}])[0]
        mode = str(_first(p, "pool", "MAX")).upper()
        if str(_first(p, "global_pooling", "false")).lower() == "true":
            cls = L.GlobalMaxPooling2D if mode in ("MAX", "0") \
                else L.GlobalAveragePooling2D
            # caffe global pooling keeps [N, C, 1, 1]
            pooled = cls(dim_ordering="th")(self._in(layer))
            return L.Reshape((shape[0], 1, 1))(pooled)
        kh = int(_first(p, "kernel_h", _first(p, "kernel_size", 2)))
        kw = int(_first(p, "kernel_w", _first(p, "kernel_size", 2)))
        sh = int(_first(p, "stride_h", _first(p, "stride", 1)))
        sw = int(_first(p, "stride_w", _first(p, "stride", 1)))
        ph = int(_first(p, "pad_h", _first(p, "pad", 0)))
        pw = int(_first(p, "pad_w", _first(p, "pad", 0)))
        _, extra_h = _pool_pad_for_ceil(shape[1], kh, sh, ph)
        _, extra_w = _pool_pad_for_ceil(shape[2], kw, sw, pw)
        x = self._in(layer)
        is_ave = "AVE" in mode or mode == "1"
        if is_ave and (ph or pw or extra_h or extra_w):
            # caffe AVE divides by the window area clipped to the PADDED
            # input (pad zeros count; the ceil-extra region does not)
            def ave_fn(t, ph=ph, pw=pw, eh=extra_h, ew=extra_w,
                       kh=kh, kw=kw, sh=sh, sw=sw):
                import jax
                import jax.numpy as jnp
                tp = jnp.pad(t, ((0, 0), (0, 0), (ph, ph + eh),
                                 (pw, pw + ew)))
                cnt = jnp.pad(jnp.ones_like(t),
                              ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                              constant_values=1.0)
                cnt = jnp.pad(cnt, ((0, 0), (0, 0), (0, eh), (0, ew)))
                win = (1, 1, kh, kw)
                st = (1, 1, sh, sw)
                ssum = jax.lax.reduce_window(tp, 0.0, jax.lax.add, win,
                                             st, "VALID")
                area = jax.lax.reduce_window(cnt, 0.0, jax.lax.add, win,
                                             st, "VALID")
                return ssum / jnp.maximum(area, 1.0)
            return LambdaLayer(ave_fn)(x)
        if ph or pw or extra_h or extra_w:
            from analytics_zoo_tpu.ops.autograd import pad_lambda
            x = pad_lambda(((0, 0), (0, 0), (ph, ph + extra_h),
                            (pw, pw + extra_w)), value=-np.inf)(x)
        cls = L.MaxPooling2D if mode in ("MAX", "0") else L.AveragePooling2D
        return cls(pool_size=(kh, kw), strides=(sh, sw),
                   border_mode="valid", dim_ordering="th")(x)

    def _batchnorm(self, layer: Dict, name: str):
        p = (layer.get("batch_norm_param", [{}]) or [{}])[0]
        eps = float(_first(p, "eps", 1e-5))
        blobs = self.weights.get(name, [])
        if len(blobs) < 3:
            raise ValueError(f"BatchNorm {name!r} needs 3 blobs")
        factor = float(blobs[2].reshape(-1)[0]) or 1.0
        mean = blobs[0] / factor
        var = blobs[1] / factor
        C = mean.shape[0]
        bn = L.BatchNormalization(epsilon=eps, axis=1)
        return _with_weights(bn, {
            "gamma": np.ones(C, np.float32),
            "beta": np.zeros(C, np.float32),
            "moving_mean": mean, "moving_var": var})(self._in(layer))

    def _scale(self, layer: Dict, name: str):
        p = (layer.get("scale_param", [{}]) or [{}])[0]
        bias_term = str(_first(p, "bias_term", "false")).lower() == "true"
        blobs = self.weights.get(name, [])
        gamma = blobs[0].reshape(-1)
        beta = blobs[1].reshape(-1) if bias_term and len(blobs) > 1 \
            else np.zeros_like(gamma)

        def scale_fn(t, g=gamma, b=beta):
            return t * g[None, :, None, None] + b[None, :, None, None]
        return LambdaLayer(scale_fn)(self._in(layer))

    def _lrn(self, layer: Dict):
        p = (layer.get("lrn_param", [{}]) or [{}])[0]
        size = int(_first(p, "local_size", 5))
        alpha = float(_first(p, "alpha", 1.0))
        beta = float(_first(p, "beta", 0.75))
        kk = float(_first(p, "k", 1.0))
        region = str(_first(p, "norm_region", "ACROSS_CHANNELS"))
        if "WITHIN" in region.upper():
            raise NotImplementedError("WITHIN_CHANNEL LRN")
        # caffe divides alpha by local_size already in its formula — our
        # LRN2D does the same (alpha/n), so pass through
        return L.LRN2D(alpha=alpha, k=kk, beta=beta, n=size,
                       dim_ordering="th")(self._in(layer))

    def _eltwise(self, layer: Dict):
        p = (layer.get("eltwise_param", [{}]) or [{}])[0]
        op = str(_first(p, "operation", "SUM")).upper()
        mode = {"SUM": "sum", "1": "sum", "PROD": "mul", "0": "mul",
                "MAX": "max", "2": "max"}.get(op)
        if mode is None:
            raise NotImplementedError(f"Eltwise {op}")
        return L.Merge(mode=mode)([self.nodes[b] for b in layer["bottom"]])

    # -- assembly ----------------------------------------------------------
    def handle(self, layer: Dict):
        ltype = _first(layer, "type")
        name = _first(layer, "name")
        tops = layer.get("top", [])
        top = tops[0] if tops else name
        bottom = layer.get("bottom", [None])[0]
        in_shape = self.shapes.get(bottom)

        if ltype == "Input":
            self._input(layer)
            return
        if ltype in ("Data", "ImageData", "Accuracy", "SoftmaxWithLoss",
                     "Silence"):
            return                        # train-only layers skipped
        if ltype == "Convolution":
            node = self._conv(layer, name)
        elif ltype == "InnerProduct":
            node = self._inner_product(layer, name,
                                       len(in_shape) + 1 if in_shape
                                       else 2)
        elif ltype == "Pooling":
            node = self._pool(layer, in_shape)
        elif ltype == "ReLU":
            node = L.Activation("relu")(self._in(layer))
        elif ltype == "Sigmoid":
            node = L.Activation("sigmoid")(self._in(layer))
        elif ltype == "TanH":
            node = L.Activation("tanh")(self._in(layer))
        elif ltype == "Softmax":
            node = LambdaLayer(
                lambda t: __import__("jax").nn.softmax(t, axis=1))(
                    self._in(layer))
        elif ltype == "Dropout":
            node = self._in(layer)        # inference no-op (in-place)
        elif ltype == "BatchNorm":
            node = self._batchnorm(layer, name)
        elif ltype == "Scale":
            node = self._scale(layer, name)
        elif ltype == "LRN":
            node = self._lrn(layer)
        elif ltype == "Concat":
            p = (layer.get("concat_param", [{}]) or [{}])[0]
            axis = int(_first(p, "axis", 1))
            node = L.Merge(mode="concat", concat_axis=axis)(
                [self.nodes[b] for b in layer["bottom"]])
        elif ltype == "Eltwise":
            node = self._eltwise(layer)
        elif ltype == "Flatten":
            node = L.Flatten()(self._in(layer))
        elif ltype == "PReLU":
            blobs = self.weights.get(str(_first(layer, "name")), [])
            prelu = L.PReLU()
            if blobs:
                # caffe blob is per-channel (C,); the layer's alphas carry
                # the full non-batch shape (C,H,W) — broadcast up
                in_shape = self.shapes.get(layer["bottom"][0])
                alpha = blobs[0].reshape(-1)
                full = np.broadcast_to(
                    alpha.reshape((-1,) + (1,) * (len(in_shape) - 1)),
                    in_shape).copy()
                prelu = _with_weights(prelu, {"alpha": full})
            node = prelu(self._in(layer))
        elif ltype == "ELU":
            p = (layer.get("elu_param", [{}]) or [{}])[0]
            node = L.ELU(float(_first(p, "alpha", 1.0)))(self._in(layer))
        elif ltype == "AbsVal":
            node = L.Abs()(self._in(layer))
        elif ltype == "Power":
            # caffe: y = (shift + scale * x) ^ power
            p = (layer.get("power_param", [{}]) or [{}])[0]
            power = float(_first(p, "power", 1.0))
            scale = float(_first(p, "scale", 1.0))
            shift = float(_first(p, "shift", 0.0))
            node = LambdaLayer(
                lambda x, pw=power, sc=scale, sh=shift:
                (sh + sc * x) ** pw)(self._in(layer))
        elif ltype == "Exp":
            # y = base ^ (shift + scale * x); base -1 means e
            p = (layer.get("exp_param", [{}]) or [{}])[0]
            base = float(_first(p, "base", -1.0))
            scale = float(_first(p, "scale", 1.0))
            shift = float(_first(p, "shift", 0.0))
            import jax.numpy as jnp
            node = LambdaLayer(
                lambda x, b=base, sc=scale, sh=shift:
                jnp.exp(sh + sc * x) if b == -1.0
                else b ** (sh + sc * x))(self._in(layer))
        elif ltype == "Log":
            # y = log_base(shift + scale * x)
            p = (layer.get("log_param", [{}]) or [{}])[0]
            base = float(_first(p, "base", -1.0))
            scale = float(_first(p, "scale", 1.0))
            shift = float(_first(p, "shift", 0.0))
            import jax.numpy as jnp
            denom = 1.0 if base == -1.0 else float(np.log(base))
            node = LambdaLayer(
                lambda x, d=denom, sc=scale, sh=shift:
                jnp.log(sh + sc * x) / d)(self._in(layer))
        elif ltype == "Reshape":
            p = (layer.get("reshape_param", [{}]) or [{}])[0]
            if int(_first(p, "axis", 0)) != 0 \
                    or int(_first(p, "num_axes", -1)) != -1:
                raise NotImplementedError(
                    "Reshape with axis/num_axes is not supported")
            shape_blk = (p.get("shape") or [{}])[0]
            dims = [int(d) for d in shape_blk.get("dim", [])]
            # caffe: 0 copies the input dim, -1 infers; dim[0] is batch
            in_shape = self.shapes.get(layer["bottom"][0]) or ()
            target = []
            for i, d in enumerate(dims[1:]):
                if d == 0:
                    if i >= len(in_shape):
                        raise NotImplementedError(
                            "Reshape 0-dim beyond input rank")
                    target.append(int(in_shape[i]))
                else:
                    target.append(d)
            node = L.Reshape(tuple(target))(self._in(layer))
        elif ltype == "Permute":
            p = (layer.get("permute_param", [{}]) or [{}])[0]
            order = [int(d) for d in p.get("order", [])]
            if order and order[0] != 0:
                raise NotImplementedError(
                    "Permute moving the batch dimension")
            # caffe fills unspecified axes in natural order
            rank = len(self.shapes.get(layer["bottom"][0]) or ()) + 1
            full = order + [a for a in range(rank) if a not in order]
            node = L.Permute(tuple(full[1:]))(self._in(layer))
        elif ltype == "Split":
            # identity fan-out: every top aliases the bottom
            src = self._in(layer)
            for t in layer.get("top", []):
                self.nodes[t] = src
                self.shapes[t] = self.shapes.get(layer["bottom"][0])
            return
        elif ltype == "Slice":
            self._slice(layer)
            return
        elif ltype == "Deconvolution":
            node = self._deconv(layer, str(_first(layer, "name")))
        else:
            raise NotImplementedError(
                f"Caffe layer type {ltype!r} is not supported")
        self.nodes[top] = node
        self.shapes[top] = tuple(node.shape[1:]) \
            if hasattr(node, "shape") else None

    def build(self) -> Model:
        # legacy top-level input declaration
        if self.arch.get("input"):
            dims = [int(d) for d in self.arch.get("input_dim", [])]
            if self.arch.get("input_shape"):
                dims = [int(d)
                        for d in self.arch["input_shape"][0].get("dim", [])]
            name = self.arch["input"][0]
            inp = Input(shape=tuple(dims[1:]))
            self.inputs.append(inp)
            self.nodes[name] = inp
            self.shapes[name] = tuple(dims[1:])
        for layer in self.arch.get("layer", []):
            self.handle(layer)
        # network output: the top that is never consumed as a bottom;
        # a tensor re-produced in place (top == bottom, the caffe ReLU/BN
        # idiom) does not count as consumed by its own producer
        consumed = set()
        for lay in self.arch.get("layer", []):
            tops = set(lay.get("top", []))
            for b in lay.get("bottom", []):
                if b not in tops:
                    consumed.add(b)
        outs = [n for t, n in self.nodes.items()
                if t not in consumed and not any(n is i
                                                 for i in self.inputs)]
        if not outs and self.nodes:
            outs = [list(self.nodes.values())[-1]]
        return Model(self.inputs if len(self.inputs) > 1
                     else self.inputs[0],
                     outs if len(outs) > 1 else outs[-1])


def load_caffe(def_path: str, model_path: str) -> Model:
    """`Net.loadCaffe(defPath, modelPath)` (`Net.scala:103`): deploy
    prototxt + binary caffemodel → native Model with pinned weights."""
    with open(def_path) as fh:
        arch = parse_prototxt(fh.read())
    with open(model_path, "rb") as fh:
        net = wire.decode(fh.read(), NET)
    weights = {}
    for layer in net.get("layer", []):
        blobs = [_blob_array(b) for b in layer.get("blobs", [])]
        if blobs:
            weights[layer["name"][0]] = blobs
    model = _CaffeGraphBuilder(arch, weights).build()
    sample = []
    for inp in (model.inputs if isinstance(model.inputs, list)
                else [model.inputs]):
        shape = tuple(1 if d is None else d for d in inp.shape)
        sample.append(np.zeros(shape, np.float32))
    model.ensure_built(sample if len(sample) > 1 else sample[0])
    return model
