from analytics_zoo_tpu.caffe.caffe_loader import load_caffe  # noqa: F401
