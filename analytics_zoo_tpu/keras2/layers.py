"""Keras2-flavoured layer API (`zoo/.../pipeline/api/keras2/layers/`).

The reference carries a second, keras-2.x-style parameter surface for a
subset of layers (Dense/Conv/pooling/merge) alongside the Keras1 set. Here
they are thin adapters over the same jax implementations in
`analytics_zoo_tpu.keras.layers` — argument names translated
(units/filters/kernel_size/strides/padding/kernel_initializer/data_format),
merge modes exposed as classes (Add/Multiply/.../Concatenate/Dot).
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

from analytics_zoo_tpu.keras import layers as k1
from analytics_zoo_tpu.keras.engine import Layer


def _pair(v) -> tuple:
    return (v, v) if isinstance(v, int) else tuple(v)


def _data_format_to_ordering(data_format: Optional[str]) -> str:
    if data_format in (None, "channels_last"):
        return "tf"
    if data_format == "channels_first":
        return "th"
    raise ValueError(f"Unsupported data_format: {data_format}")


class Dense(k1.Dense):
    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 kernel_initializer="glorot_uniform", **kw):
        super().__init__(units, activation=activation, use_bias=use_bias,
                         init=kernel_initializer, **kw)


class Conv1D(k1.Convolution1D):
    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform", **kw):
        super().__init__(filters, kernel_size, subsample=(strides,),
                         border_mode=padding, activation=activation,
                         use_bias=use_bias, init=kernel_initializer, **kw)


class Conv2D(k1.Convolution2D):
    def __init__(self, filters: int, kernel_size, strides=(1, 1),
                 padding: str = "valid", data_format: Optional[str] = None,
                 activation=None, use_bias: bool = True,
                 kernel_initializer="glorot_uniform", **kw):
        kh, kw_ = _pair(kernel_size)
        super().__init__(filters, kh, kw_, subsample=_pair(strides),
                         border_mode=padding,
                         dim_ordering=_data_format_to_ordering(data_format),
                         activation=activation, use_bias=use_bias,
                         init=kernel_initializer, **kw)


class MaxPooling1D(k1.MaxPooling1D):
    def __init__(self, pool_size: int = 2, strides: Optional[int] = None,
                 padding: str = "valid", **kw):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, **kw)


class AveragePooling1D(k1.AveragePooling1D):
    def __init__(self, pool_size: int = 2, strides: Optional[int] = None,
                 padding: str = "valid", **kw):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, **kw)


class MaxPooling2D(k1.MaxPooling2D):
    def __init__(self, pool_size=(2, 2), strides=None,
                 padding: str = "valid", data_format: Optional[str] = None,
                 **kw):
        super().__init__(pool_size=_pair(pool_size),
                         strides=_pair(strides) if strides else None,
                         border_mode=padding,
                         dim_ordering=_data_format_to_ordering(data_format),
                         **kw)


class AveragePooling2D(k1.AveragePooling2D):
    def __init__(self, pool_size=(2, 2), strides=None,
                 padding: str = "valid", data_format: Optional[str] = None,
                 **kw):
        super().__init__(pool_size=_pair(pool_size),
                         strides=_pair(strides) if strides else None,
                         border_mode=padding,
                         dim_ordering=_data_format_to_ordering(data_format),
                         **kw)


class GlobalMaxPooling2D(k1.GlobalMaxPooling2D):
    def __init__(self, data_format: Optional[str] = None, **kw):
        super().__init__(dim_ordering=_data_format_to_ordering(data_format),
                         **kw)


class GlobalAveragePooling2D(k1.GlobalAveragePooling2D):
    def __init__(self, data_format: Optional[str] = None, **kw):
        super().__init__(dim_ordering=_data_format_to_ordering(data_format),
                         **kw)


# -- merge classes (`keras2/layers/merge.py` flavour) -----------------------
class _MergeBase(k1.Merge):
    mode = "sum"

    def __init__(self, **kw):
        super().__init__(mode=type(self).mode, **kw)


class Add(_MergeBase):
    mode = "sum"


class Multiply(_MergeBase):
    mode = "mul"


class Average(_MergeBase):
    mode = "ave"


class Maximum(_MergeBase):
    mode = "max"


class Subtract(Layer):
    def call(self, params, xs, *, training=False, rng=None):
        a, b = xs
        return a - b

    def compute_output_shape(self, input_shapes):
        return input_shapes[0]


class Minimum(Layer):
    def call(self, params, xs, *, training=False, rng=None):
        out = xs[0]
        for x in xs[1:]:
            import jax.numpy as jnp
            out = jnp.minimum(out, x)
        return out

    def compute_output_shape(self, input_shapes):
        return input_shapes[0]


class Concatenate(k1.Merge):
    def __init__(self, axis: int = -1, **kw):
        super().__init__(mode="concat", concat_axis=axis, **kw)


class Dot(Layer):
    """keras2 Dot: per-sample tensordot over the given axes (batch dim
    excluded); `normalize=True` L2-normalizes along the contraction axis
    first (cosine proximity)."""

    def __init__(self, axes=-1, normalize: bool = False, **kw):
        super().__init__(**kw)
        self.axes = tuple(axes) if isinstance(axes, (list, tuple)) \
            else (axes, axes)
        self.normalize = normalize

    def _sample_axes(self, shapes):
        # translate full-tensor axes to per-sample (batch-stripped) axes
        out = []
        for ax, shape in zip(self.axes, shapes):
            nd = len(shape)
            a = ax if ax >= 0 else nd + ax
            if a == 0:
                raise ValueError("Dot axes cannot include the batch dim")
            out.append(a - 1)
        return tuple(out)

    def call(self, params, xs, *, training=False, rng=None):
        import jax
        import jax.numpy as jnp
        a, b = xs
        ax_a, ax_b = self._sample_axes([a.shape, b.shape])
        if self.normalize:
            a = a / jnp.clip(jnp.linalg.norm(a, axis=ax_a + 1, keepdims=True),
                             1e-7, None)
            b = b / jnp.clip(jnp.linalg.norm(b, axis=ax_b + 1, keepdims=True),
                             1e-7, None)
        y = jax.vmap(
            lambda u, v: jnp.tensordot(u, v, axes=((ax_a,), (ax_b,))))(a, b)
        if y.ndim == 1:
            y = y[:, None]
        return y

    def compute_output_shape(self, input_shapes):
        sa, sb = input_shapes
        ax_a, ax_b = self._sample_axes([sa, sb])
        rest_a = [d for i, d in enumerate(sa[1:]) if i != ax_a]
        rest_b = [d for i, d in enumerate(sb[1:]) if i != ax_b]
        out = tuple([sa[0]] + rest_a + rest_b)
        return out if len(out) > 1 else (sa[0], 1)


def add(inputs, name=None):
    return Add(name=name)(inputs)


def multiply(inputs, name=None):
    return Multiply(name=name)(inputs)


def average(inputs, name=None):
    return Average(name=name)(inputs)


def maximum(inputs, name=None):
    return Maximum(name=name)(inputs)


def concatenate(inputs, axis=-1, name=None):
    return Concatenate(axis=axis, name=name)(inputs)


# ---------------------------------------------------------------------------
# Remaining keras2 inventory (the reference's full keras2 layer set is now
# covered: Activation/Dropout/Flatten/Softmax, Cropping1D,
# LocallyConnected1D, and the 1D/3D global pools)
# ---------------------------------------------------------------------------
class Activation(k1.Activation):
    pass


class Dropout(k1.Dropout):
    def __init__(self, rate: float, **kw):
        super().__init__(rate, **kw)


class Flatten(k1.Flatten):
    def __init__(self, data_format: Optional[str] = None, **kw):
        if data_format == "channels_first":
            # tf.keras transposes channels_first input to channels_last
            # ordering before flattening; silently accepting the flag would
            # permute the feature order fed to downstream Dense weights
            raise NotImplementedError(
                "Flatten(data_format='channels_first') is not supported")
        if data_format not in (None, "channels_last"):
            raise ValueError(f"Unsupported data_format: {data_format}")
        super().__init__(**kw)


class Softmax(k1.Softmax):
    pass


class Cropping1D(k1.Cropping1D):
    pass


class LocallyConnected1D(k1.LocallyConnected1D):
    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True,
                 kernel_initializer="glorot_uniform", **kw):
        if padding != "valid":
            raise ValueError(
                "LocallyConnected1D only supports padding='valid'")
        super().__init__(filters, kernel_size, activation=activation,
                         subsample_length=strides, use_bias=use_bias,
                         init=kernel_initializer, **kw)


def _check_1d_format(data_format: Optional[str]) -> None:
    if data_format not in (None, "channels_last"):
        raise ValueError(
            "1D global pools are channels_last only "
            f"(got data_format={data_format!r})")


class GlobalMaxPooling1D(k1.GlobalMaxPooling1D):
    def __init__(self, data_format: Optional[str] = None, **kw):
        _check_1d_format(data_format)
        super().__init__(**kw)


class GlobalAveragePooling1D(k1.GlobalAveragePooling1D):
    def __init__(self, data_format: Optional[str] = None, **kw):
        _check_1d_format(data_format)
        super().__init__(**kw)


class GlobalMaxPooling3D(k1.GlobalMaxPooling3D):
    def __init__(self, data_format: Optional[str] = None, **kw):
        super().__init__(
            dim_ordering=_data_format_to_ordering(data_format), **kw)


class GlobalAveragePooling3D(k1.GlobalAveragePooling3D):
    def __init__(self, data_format: Optional[str] = None, **kw):
        super().__init__(
            dim_ordering=_data_format_to_ordering(data_format), **kw)
