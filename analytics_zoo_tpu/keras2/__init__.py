from analytics_zoo_tpu.keras2 import layers  # noqa: F401
