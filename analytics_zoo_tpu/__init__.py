"""analytics_zoo_tpu — a TPU-native analytics + AI platform.

A brand-new JAX/XLA/Pallas framework with the capabilities of Analytics Zoo
(reference: yang-gis/analytics-zoo): sharded data pipelines, a Keras-style model
API with an autograd DSL, a unified Estimator for distributed training, a
built-in model zoo, AutoML time-series forecasting, and low-latency serving.

Where the reference federates four execution engines (BigDL-JVM, TF-JNI, JEP
PyTorch, OpenVINO) over Spark/Flink/Ray (reference `README.md:6`), this stack is
one engine: jit/pjit-compiled XLA programs over a `jax.sharding.Mesh`, with
GSPMD collectives replacing all five of the reference's gradient transports
(reference survey §2.5).
"""

__version__ = "0.1.0"

from analytics_zoo_tpu.common.context import (  # noqa: F401
    init_zoo_context,
    init_orca_context,
    stop_orca_context,
    ZooContext,
    OrcaContext,
)
from analytics_zoo_tpu.common.mesh import DeviceMesh  # noqa: F401
from analytics_zoo_tpu.common.config import ZooConfig  # noqa: F401
