"""NNEstimator/NNModel/NNClassifier over pandas DataFrames and XShards.

Behavioral contract from `nnframes/NNEstimator.scala:197` + python mirror
(`nn_classifier.py`): builder-style setters (setBatchSize/setMaxEpoch/
setLearningRate/setFeaturesCol/setLabelCol/setCachingSample →
snake_case), `fit(df) -> NNModel`, `NNModel.transform(df)` appends a
`prediction` column, `NNClassifier` trains on integer labels with
(sparse) cross-entropy and its model predicts the argmax class
(1-based by default, like BigDL's ClassNLL convention).

Scale path (the reference trains over a cluster-wide Spark DataFrame):
`fit`/`transform` also accept an `XShards` of pandas DataFrames — each
shard assembles independently (no single concatenated frame), training
delegates to the sharded `learn.Estimator` machinery, and `transform`
maps per shard like the reference's `mapPartitions`
(`NNEstimator.scala:641`). `set_sample_preprocessing` mirrors
`setSamplePreprocessing`: a per-row callable (e.g. a chained
ImageProcessing) applied at assembly time."""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from analytics_zoo_tpu.data.shards import XShards
from analytics_zoo_tpu.keras.engine import KerasNet


def _assemble(df: pd.DataFrame, cols: Sequence[str],
              preprocess: Optional[Callable] = None) -> np.ndarray:
    """Feature assembly: one array-valued column passes through (stacked);
    several scalar columns concatenate — the NNEstimator featureSize
    flattening (`NNEstimator.scala` supports both)."""
    if len(cols) == 1 and len(df) and \
            isinstance(df[cols[0]].iloc[0], (list, tuple, np.ndarray)):
        rows = (np.asarray(v, np.float32) for v in df[cols[0]])
        if preprocess is not None:
            rows = (np.asarray(preprocess(r), np.float32) for r in rows)
        return np.stack(list(rows))
    if preprocess is not None:
        # per-row transforms are defined on array-valued features only —
        # silently skipping them would train on untransformed data
        raise ValueError("sample_preprocessing needs a single array-valued "
                         f"feature column; got scalar columns {list(cols)}")
    if len(cols) == 1:
        return df[cols[0]].to_numpy(np.float32)[:, None]
    return np.stack([df[c].to_numpy(np.float32) for c in cols], axis=1)


class NNEstimator:
    def __init__(self, model: KerasNet, criterion: Union[str, Any] = "mse",
                 optimizer: Union[str, Any] = "adam"):
        self.model = model
        self.criterion = criterion
        self.optimizer = optimizer
        self.batch_size = 32
        self.max_epoch = 1
        self.features_col: List[str] = ["features"]
        self.label_col = "label"
        self.caching_sample = True
        self._lr: Optional[float] = None
        self._validation = None
        self._preprocessing: Optional[Callable] = None

    # -- builder setters (`NNEstimator.scala` setters) ---------------------
    def set_batch_size(self, v: int) -> "NNEstimator":
        self.batch_size = v
        return self

    def set_max_epoch(self, v: int) -> "NNEstimator":
        self.max_epoch = v
        return self

    def set_learning_rate(self, v: float) -> "NNEstimator":
        self._lr = v
        return self

    def set_features_col(self, v: Union[str, Sequence[str]]) -> "NNEstimator":
        self.features_col = [v] if isinstance(v, str) else list(v)
        return self

    def set_label_col(self, v: str) -> "NNEstimator":
        self.label_col = v
        return self

    def set_caching_sample(self, v: bool) -> "NNEstimator":
        self.caching_sample = v
        return self

    def set_validation(self, df: pd.DataFrame,
                       trigger=None) -> "NNEstimator":
        self._validation = df
        return self

    def set_sample_preprocessing(self, fn: Callable) -> "NNEstimator":
        """Per-row feature transform applied at assembly time — the
        `setSamplePreprocessing` role (chained ImageProcessing etc.)."""
        self._preprocessing = fn
        return self

    # -- fit ---------------------------------------------------------------
    def _label_array(self, df: pd.DataFrame) -> np.ndarray:
        y = np.asarray(list(df[self.label_col]), np.float32)
        # regression targets get a trailing feature dim so elementwise
        # losses align with [B, 1] model outputs (no silent broadcast)
        return y[:, None] if y.ndim == 1 else y

    def _compile(self):
        if self._lr is not None:
            import optax
            opt = optax.adam(self._lr) if isinstance(self.optimizer, str) \
                else self.optimizer
        else:
            opt = self.optimizer
        self.model.compile(opt, self.criterion)

    def fit(self, df: Union[pd.DataFrame, XShards]) -> "NNModel":
        if isinstance(df, XShards):
            return self._fit_shards(df)
        x = _assemble(df, self.features_col, self._preprocessing)
        y = self._label_array(df)
        self._compile()
        val = None
        if self._validation is not None:
            val = (_assemble(self._validation, self.features_col,
                             self._preprocessing),
                   self._label_array(self._validation))
        self.model.fit(x, y, batch_size=min(self.batch_size, len(x)),
                       nb_epoch=self.max_epoch, validation_data=val)
        return self._make_model()

    def _fit_shards(self, shards: XShards) -> "NNModel":
        """XShards of DataFrames: assemble per shard (no concatenated
        frame) and train through the sharded Estimator path — the
        `NNEstimator.scala:197` cluster-wide fit.

        With a sample preprocessing the assembly re-runs EVERY epoch
        (stochastic augmentations draw fresh each pass, matching the
        reference's per-pass Spark preprocessing) and runs serially —
        ImageProcessing chains carry a non-thread-safe RandomState."""
        from analytics_zoo_tpu.learn.estimator import Estimator

        live = [s for s in shards.collect() if len(s)]
        if not live:
            raise ValueError("NNEstimator.fit: all shards are empty")
        shards = XShards(live)
        # whole-batch-only training: clamp like the pandas path does
        batch = min(self.batch_size, sum(len(s) for s in live))
        self._compile()
        val = None
        if self._validation is not None:
            val = (_assemble(self._validation, self.features_col,
                             self._preprocessing),
                   self._label_array(self._validation))
        est = Estimator(self.model)

        def assemble():
            return shards.transform_shard(
                lambda d: {"x": _assemble(d, self.features_col,
                                          self._preprocessing),
                           "y": self._label_array(d)},
                parallel=self._preprocessing is None)

        if self._preprocessing is None:
            est.fit(assemble(), epochs=self.max_epoch,
                    batch_size=batch, validation_data=val)
        else:
            # ONE fit over all epochs (optimizer moments/step count must
            # survive epoch boundaries); fresh augmentation draw + fresh
            # shuffle order per epoch via the trainer's per-epoch batch
            # source hook.
            from analytics_zoo_tpu.data.dataset import TPUDataset
            from analytics_zoo_tpu.learn.trainer import iter_batches

            first = TPUDataset.from_xshards(assemble(), batch_size=batch)

            def epoch_batches(epoch):
                ds = first if epoch == 0 else TPUDataset.from_xshards(
                    assemble(), batch_size=batch)
                return iter_batches(ds.x, ds.y, batch, shuffle=True,
                                    seed=epoch)

            est.fit(first, epochs=self.max_epoch, batch_size=batch,
                    validation_data=val, batch_iter_factory=epoch_batches)
        return self._make_model()

    def _make_model(self) -> "NNModel":
        model = NNModel(self.model, self.features_col)
        model._preprocessing = self._preprocessing
        return model


class NNModel:
    """Transformer: adds a `prediction` column (`NNEstimator.scala:641`)."""

    def __init__(self, model: KerasNet,
                 features_col: Union[str, Sequence[str]] = "features"):
        self.model = model
        self.features_col = [features_col] if isinstance(features_col, str) \
            else list(features_col)
        self.batch_size = 32
        self._preprocessing: Optional[Callable] = None

    def set_batch_size(self, v: int) -> "NNModel":
        self.batch_size = v
        return self

    def set_features_col(self, v: Union[str, Sequence[str]]) -> "NNModel":
        self.features_col = [v] if isinstance(v, str) else list(v)
        return self

    def set_sample_preprocessing(self, fn: Callable) -> "NNModel":
        self._preprocessing = fn
        return self

    def _predict(self, df: pd.DataFrame) -> np.ndarray:
        x = _assemble(df, self.features_col, self._preprocessing)
        return np.asarray(self.model.predict(
            x, batch_per_thread=self.batch_size))

    def transform(self, df: Union[pd.DataFrame, XShards]
                  ) -> Union[pd.DataFrame, XShards]:
        """Appends `prediction`. XShards map per shard — the
        `mapPartitions` shape of `NNEstimator.scala:641`. Serial when a
        preprocessing is set (RandomState is not thread-safe)."""
        if isinstance(df, XShards):
            return df.transform_shard(
                self.transform, parallel=self._preprocessing is None)
        out = df.copy()
        if not len(df):
            out["prediction"] = []
            return out
        preds = self._predict(df)
        out["prediction"] = [p if np.ndim(p) else float(p) for p in preds]
        return out


class NNClassifier(NNEstimator):
    """Integer-label classification (`nn_classifier.py:140`). Labels are
    1-based by default (the BigDL ClassNLL convention the reference keeps);
    pass `zero_based_label=True` for 0-based data. No silent inference —
    a 0-based dataset that happens to lack class 0 would otherwise be
    shifted wrongly without any error."""

    def __init__(self, model: KerasNet, criterion: Union[str, Any] =
                 "sparse_categorical_crossentropy",
                 optimizer: Union[str, Any] = "adam",
                 zero_based_label: bool = False):
        super().__init__(model, criterion, optimizer)
        self.zero_based_label = zero_based_label

    def _label_array(self, df: pd.DataFrame) -> np.ndarray:
        y = df[self.label_col].to_numpy().astype(np.int32)
        if not self.zero_based_label:
            y = y - 1
        if y.min() < 0:
            raise ValueError(
                "Negative class index after label-base shift; pass "
                "zero_based_label=True for 0-based labels")
        return y

    def _make_model(self) -> "NNClassifierModel":
        model = NNClassifierModel(self.model, self.features_col,
                                  zero_based_label=self.zero_based_label)
        model._preprocessing = self._preprocessing
        return model


class NNClassifierModel(NNModel):
    """Argmax prediction column (`nn_classifier.py:573`)."""

    def __init__(self, model: KerasNet,
                 features_col: Union[str, Sequence[str]] = "features",
                 zero_based_label: bool = True):
        super().__init__(model, features_col)
        self.zero_based_label = zero_based_label

    def transform(self, df: Union[pd.DataFrame, XShards]
                  ) -> Union[pd.DataFrame, XShards]:
        if isinstance(df, XShards):
            return df.transform_shard(
                self.transform, parallel=self._preprocessing is None)
        out = df.copy()
        if not len(df):
            out["prediction"] = np.zeros((0,), np.int64)
            return out
        probs = self._predict(df)
        cls = np.argmax(probs, axis=-1)
        if not self.zero_based_label:
            cls = cls + 1
        out["prediction"] = cls.astype(np.int64)
        return out


class NNImageReader:
    """`NNImageReader.readImages`: directory -> DataFrame with image arrays
    ('image' column) + 'path' (+ 'label' when the dir layout has classes)."""

    @staticmethod
    def read_images(path: str, with_label: bool = False,
                    resize: Optional[int] = None,
                    one_based_label: bool = True,
                    num_shards: Optional[int] = None
                    ) -> Union[pd.DataFrame, XShards]:
        """Directory → DataFrame, or (num_shards given) an XShards of
        row-range DataFrame shards for the distributed NNFrames path."""
        from analytics_zoo_tpu.data.image import ImageResize, ImageSet
        iset = ImageSet.read(path, with_label=with_label,
                             one_based_label=one_based_label)
        if resize:
            iset = iset.transform(ImageResize(resize, resize))
        data = {"image": [im.astype(np.float32) for im in iset.images],
                "path": iset.paths}
        if iset.labels is not None:
            data["label"] = iset.labels
        df = pd.DataFrame(data)
        if num_shards is None:
            return df
        parts = np.array_split(np.arange(len(df)), num_shards)
        return XShards([df.iloc[idx].reset_index(drop=True)
                        for idx in parts if len(idx)])
