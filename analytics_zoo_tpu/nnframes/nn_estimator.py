"""NNEstimator/NNModel/NNClassifier over pandas DataFrames.

Behavioral contract from `nnframes/NNEstimator.scala:197` + python mirror
(`nn_classifier.py`): builder-style setters (setBatchSize/setMaxEpoch/
setLearningRate/setFeaturesCol/setLabelCol/setCachingSample →
snake_case), `fit(df) -> NNModel`, `NNModel.transform(df)` appends a
`prediction` column, `NNClassifier` trains on integer labels with
(sparse) cross-entropy and its model predicts the argmax class
(1-based by default, like BigDL's ClassNLL convention)."""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from analytics_zoo_tpu.keras.engine import KerasNet


def _assemble(df: pd.DataFrame, cols: Sequence[str]) -> np.ndarray:
    """Feature assembly: one array-valued column passes through (stacked);
    several scalar columns concatenate — the NNEstimator featureSize
    flattening (`NNEstimator.scala` supports both)."""
    if len(cols) == 1:
        first = df[cols[0]].iloc[0]
        if isinstance(first, (list, tuple, np.ndarray)):
            return np.stack([np.asarray(v, np.float32)
                             for v in df[cols[0]]])
        return df[cols[0]].to_numpy(np.float32)[:, None]
    return np.stack([df[c].to_numpy(np.float32) for c in cols], axis=1)


class NNEstimator:
    def __init__(self, model: KerasNet, criterion: Union[str, Any] = "mse",
                 optimizer: Union[str, Any] = "adam"):
        self.model = model
        self.criterion = criterion
        self.optimizer = optimizer
        self.batch_size = 32
        self.max_epoch = 1
        self.features_col: List[str] = ["features"]
        self.label_col = "label"
        self.caching_sample = True
        self._lr: Optional[float] = None
        self._validation = None

    # -- builder setters (`NNEstimator.scala` setters) ---------------------
    def set_batch_size(self, v: int) -> "NNEstimator":
        self.batch_size = v
        return self

    def set_max_epoch(self, v: int) -> "NNEstimator":
        self.max_epoch = v
        return self

    def set_learning_rate(self, v: float) -> "NNEstimator":
        self._lr = v
        return self

    def set_features_col(self, v: Union[str, Sequence[str]]) -> "NNEstimator":
        self.features_col = [v] if isinstance(v, str) else list(v)
        return self

    def set_label_col(self, v: str) -> "NNEstimator":
        self.label_col = v
        return self

    def set_caching_sample(self, v: bool) -> "NNEstimator":
        self.caching_sample = v
        return self

    def set_validation(self, df: pd.DataFrame,
                       trigger=None) -> "NNEstimator":
        self._validation = df
        return self

    # -- fit ---------------------------------------------------------------
    def _label_array(self, df: pd.DataFrame) -> np.ndarray:
        y = np.asarray(list(df[self.label_col]), np.float32)
        # regression targets get a trailing feature dim so elementwise
        # losses align with [B, 1] model outputs (no silent broadcast)
        return y[:, None] if y.ndim == 1 else y

    def _compile(self):
        if self._lr is not None:
            import optax
            opt = optax.adam(self._lr) if isinstance(self.optimizer, str) \
                else self.optimizer
        else:
            opt = self.optimizer
        self.model.compile(opt, self.criterion)

    def fit(self, df: pd.DataFrame) -> "NNModel":
        x = _assemble(df, self.features_col)
        y = self._label_array(df)
        self._compile()
        val = None
        if self._validation is not None:
            val = (_assemble(self._validation, self.features_col),
                   self._label_array(self._validation))
        self.model.fit(x, y, batch_size=min(self.batch_size, len(x)),
                       nb_epoch=self.max_epoch, validation_data=val)
        return self._make_model()

    def _make_model(self) -> "NNModel":
        return NNModel(self.model, self.features_col)


class NNModel:
    """Transformer: adds a `prediction` column (`NNEstimator.scala:641`)."""

    def __init__(self, model: KerasNet,
                 features_col: Union[str, Sequence[str]] = "features"):
        self.model = model
        self.features_col = [features_col] if isinstance(features_col, str) \
            else list(features_col)
        self.batch_size = 32

    def set_batch_size(self, v: int) -> "NNModel":
        self.batch_size = v
        return self

    def set_features_col(self, v: Union[str, Sequence[str]]) -> "NNModel":
        self.features_col = [v] if isinstance(v, str) else list(v)
        return self

    def _predict(self, df: pd.DataFrame) -> np.ndarray:
        x = _assemble(df, self.features_col)
        return np.asarray(self.model.predict(
            x, batch_per_thread=self.batch_size))

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        preds = self._predict(df)
        out = df.copy()
        out["prediction"] = [p if np.ndim(p) else float(p) for p in preds]
        return out


class NNClassifier(NNEstimator):
    """Integer-label classification (`nn_classifier.py:140`). Labels are
    1-based by default (the BigDL ClassNLL convention the reference keeps);
    pass `zero_based_label=True` for 0-based data. No silent inference —
    a 0-based dataset that happens to lack class 0 would otherwise be
    shifted wrongly without any error."""

    def __init__(self, model: KerasNet, criterion: Union[str, Any] =
                 "sparse_categorical_crossentropy",
                 optimizer: Union[str, Any] = "adam",
                 zero_based_label: bool = False):
        super().__init__(model, criterion, optimizer)
        self.zero_based_label = zero_based_label

    def _label_array(self, df: pd.DataFrame) -> np.ndarray:
        y = df[self.label_col].to_numpy().astype(np.int32)
        if not self.zero_based_label:
            y = y - 1
        if y.min() < 0:
            raise ValueError(
                "Negative class index after label-base shift; pass "
                "zero_based_label=True for 0-based labels")
        return y

    def _make_model(self) -> "NNClassifierModel":
        return NNClassifierModel(self.model, self.features_col,
                                 zero_based_label=self.zero_based_label)


class NNClassifierModel(NNModel):
    """Argmax prediction column (`nn_classifier.py:573`)."""

    def __init__(self, model: KerasNet,
                 features_col: Union[str, Sequence[str]] = "features",
                 zero_based_label: bool = True):
        super().__init__(model, features_col)
        self.zero_based_label = zero_based_label

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        probs = self._predict(df)
        cls = np.argmax(probs, axis=-1)
        if not self.zero_based_label:
            cls = cls + 1
        out = df.copy()
        out["prediction"] = cls.astype(np.int64)
        return out


class NNImageReader:
    """`NNImageReader.readImages`: directory -> DataFrame with image arrays
    ('image' column) + 'path' (+ 'label' when the dir layout has classes)."""

    @staticmethod
    def read_images(path: str, with_label: bool = False,
                    resize: Optional[int] = None,
                    one_based_label: bool = True) -> pd.DataFrame:
        from analytics_zoo_tpu.data.image import ImageResize, ImageSet
        iset = ImageSet.read(path, with_label=with_label,
                             one_based_label=one_based_label)
        if resize:
            iset = iset.transform(ImageResize(resize, resize))
        data = {"image": [im.astype(np.float32) for im in iset.images],
                "path": iset.paths}
        if iset.labels is not None:
            data["label"] = iset.labels
        return pd.DataFrame(data)
