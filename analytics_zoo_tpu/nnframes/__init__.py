"""NNFrames — DataFrame ML pipeline integration (SURVEY §2.8).

Reference: `NNEstimator`/`NNModel`/`NNClassifier(Model)`
(`nnframes/NNEstimator.scala:197,641`, py `nn_classifier.py:140,573`): Spark
ML Estimator/Transformer pairs that train a model on a DataFrame and add a
`prediction` column. Spark DataFrames don't exist here; the same pipeline
surface runs on pandas DataFrames (the repo's tabular interchange format,
like orca's `to_dataset` path), with feature assembly from scalar columns or
array-valued columns.
"""

from analytics_zoo_tpu.nnframes.nn_estimator import (  # noqa: F401
    NNClassifier, NNClassifierModel, NNEstimator, NNImageReader, NNModel)
