"""Sparse embedding-gradient path: segment-sum + fused row-wise Adam
(ISSUE 9 tentpole, part 2).

`learn/lazy_embedding.py` already updates only the touched rows, but it
measured SLOWER than the dense sweep at MovieLens density because (a)
the gradient w.r.t. a [vocab, dim] table still MATERIALIZES densely
(the gather's VJP is zeros + scatter-add: two full-table passes) and
(b) XLA's large-table `.at[].set` scatter is not in-place (full-table
copies per update). This module removes both:

- **No dense gradients.** The fused one-step gathers each table's
  batch rows OUTSIDE the differentiated function, rewrites the batch's
  id column to `arange(B)` (`LazyEmbeddingSpec.set_ids_fn`), and places
  the [B, dim] rows array at the table's leaf. The model's own gather
  then reads `rows[0..B)` — identical forward values — and the
  backward produces a [B, dim] per-example row-gradient. A
  vocab-sized cotangent never exists.
- **Segment-sum.** Duplicate ids inside the batch are merged by
  sort + neighbor-compare (static shapes): slot j of the compacted
  output holds the j-th unique id and the SUM of its entries' row
  grads — exactly the scatter-add the dense VJP would have done,
  over B rows instead of the vocabulary.
- **Fused gather→Adam→scatter kernel.** One Pallas kernel walks the
  B slots; a scalar-prefetch index map DMAs exactly the touched
  (param, m, v) rows in and the updated rows out, in place via
  `input_output_aliases`. Untouched rows are untouched BYTES — they
  are never read, let alone written. Row-Adam semantics are torch
  SparseAdam, matching `lazy_embedding.row_adam_update`: moments decay
  only for touched rows, bias correction by the global step count.

Duplicate/empty slots: the compaction puts valid slots first; every
invalid slot redirects its index map to the LAST valid slot's row and
skips its writes (`pl.when`). Consecutive same-index blocks stay
resident in VMEM and flush once, so the skipped writes cannot clobber
the valid update and no slot ever maps to an unwritten block.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pallas.fused_adam import (_adam_math, _fold_scalars,
                                                 _resolve_interpret)


def segment_compact(ids, d_rows):
    """Sort-dedup-sum the batch's per-example row grads into compacted
    slots. Returns (uids, valid, g_slots):

    - uids[j]  — the j-th unique id for j < n_valid; every later slot
      redirects to the last valid slot's id (the kernel's safe target);
    - valid[j] — 1 for the unique slots, 0 for the redirected tail;
    - g_slots[j] — the segment-summed gradient of uids[j] (0 on the
      tail).

    All static shapes (B slots for a B-row batch), jit/scan friendly.
    """
    B = ids.shape[0]
    ids = ids.astype(jnp.int32)
    order = jnp.argsort(ids)
    sids = ids[order]
    first = jnp.concatenate([jnp.ones((1,), bool), sids[1:] != sids[:-1]])
    seg = jnp.cumsum(first) - 1                    # slot per sorted entry
    n_valid = first.sum()
    g_slots = jnp.zeros_like(d_rows).at[seg].add(d_rows[order])
    uids = jnp.zeros((B,), jnp.int32).at[seg].set(sids)
    slot = jnp.arange(B)
    valid = slot < n_valid
    uids = jnp.where(valid, uids, uids[n_valid - 1])
    return uids, valid.astype(jnp.int32), g_slots


def _row_kernel(b1, b2, uids_ref, valid_ref, s_ref, p_ref, m_ref, v_ref,
                g_ref, p_out, m_out, v_out):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(valid_ref[i] > 0)
    def _():
        g = g_ref[...].astype(jnp.float32)
        p = p_ref[...].astype(jnp.float32)
        p_new, m_new, v_new = _adam_math(p, m_ref[...], v_ref[...], g,
                                         s_ref[0], s_ref[1], s_ref[2],
                                         b1, b2)
        p_out[...] = p_new.astype(p_out.dtype)
        m_out[...] = m_new
        v_out[...] = v_new


def segment_adam_cost(n_slots: int, dim: int,
                      p_dtype=jnp.float32) -> Tuple[float, float]:
    """(flops, bytes): 7 row-passes over the TOUCHED rows only — the
    whole point of the sparse path, and what the cost_estimate tells
    the roofline layer instead of a dense-table sweep."""
    n = n_slots * dim
    pbytes = jnp.dtype(p_dtype).itemsize
    return 12.0 * n, float(n * (4 + 2 * pbytes + 4 * 4))


def segment_adam_update(table, mu, nu, ids, d_rows, count, *, lr,
                        b1: float = 0.9, b2: float = 0.999,
                        eps: float = 1e-8,
                        interpret: Optional[bool] = None):
    """Row-sparse Adam over the rows `ids` touches, grads given as
    per-example [B, dim] rows (duplicates summed here). Returns
    (table, mu, nu) with ONLY touched rows rewritten; every other row
    is bitwise the input. `count` is the global step (SparseAdam bias
    correction)."""
    uids, valid, g_slots = segment_compact(ids, d_rows)
    scal = _fold_scalars(count, lr, b1, b2, eps, 0.0)
    return kernel_apply(table, mu, nu, uids, valid, g_slots, scal,
                        b1=b1, b2=b2, interpret=interpret)


def kernel_apply(table, mu, nu, uids, valid, g_slots, scal, *,
                 b1: float = 0.9, b2: float = 0.999,
                 interpret: Optional[bool] = None):
    """The bare fused gather→Adam→scatter kernel over pre-compacted
    slots — split from `segment_adam_update` so the roofline layer can
    lower and cost EXACTLY the pallas region (the compaction's
    sort/scatter upstream is ordinary XLA work that cost analysis
    already counts right)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    interpret = _resolve_interpret(interpret)
    B = uids.shape[0]
    dim = table.shape[1]
    flops, bytes_ = segment_adam_cost(B, dim, table.dtype)
    tab_spec = pl.BlockSpec((1, dim), lambda i, uids, valid: (uids[i], 0))
    slot_spec = pl.BlockSpec((1, dim), lambda i, uids, valid: (i, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  tab_spec, tab_spec, tab_spec, slot_spec],
        out_specs=[tab_spec, tab_spec, tab_spec],
    )
    return pl.pallas_call(
        functools.partial(_row_kernel, b1, b2),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(table.shape, table.dtype),
                   jax.ShapeDtypeStruct(mu.shape, jnp.float32),
                   jax.ShapeDtypeStruct(nu.shape, jnp.float32)],
        # operands: (uids, valid, scal, table, mu, nu, g_slots) — the
        # big tables alias their outputs: in-place row scatter, no
        # full-table copy (the failure mode of the XLA `.at[].set`
        # path bench_ncf measured)
        input_output_aliases={3: 0, 4: 1, 5: 2},
        cost_estimate=pl.CostEstimate(flops=flops, bytes_accessed=bytes_,
                                      transcendentals=B * dim),
        interpret=interpret,
    )(uids, valid, scal, table, mu, nu, g_slots)


# ---------------------------------------------------------------------------
# fused one-step: rows-reindexed backward + fused dense rest
# ---------------------------------------------------------------------------
def make_fused_one_step(apply_fn, loss_fn, optimizer, specs,
                        apply_and_state_fn=None,
                        mixed_precision: bool = False,
                        interpret: Optional[bool] = None):
    """The fused twin of `lazy_embedding.make_lazy_one_step`: same
    (params, opt_state, xb, yb, rng) signature and the same opt_state
    layout (`lazy_embedding.init_state`), with the declared tables on
    the sparse fused path and every other parameter on `optimizer`
    (the fused dense kernel when the trainer engaged it, plain optax
    otherwise — `fused_apply` duck-typing as in `trainer._make_one_step`).

    Tables whose spec carries `set_ids_fn` take the rows-reindexed
    backward (no dense cotangent); a spec without it falls back to the
    dense gradient with the touched rows gathered after the fact —
    still the fused in-place row update, just not the grad saving."""
    from analytics_zoo_tpu.learn.lazy_embedding import (_get, _key, _set,
                                                        split_rest)
    from analytics_zoo_tpu.learn.trainer import _cast_tree, _merge_state

    reindexed = [s for s in specs if getattr(s, "set_ids_fn", None)]
    dense = [s for s in specs if not getattr(s, "set_ids_fn", None)]
    fused_rest = getattr(optimizer, "fused_apply", None)

    def one_step(params, opt_state, xb, yb, rng):
        ids_by_key = {_key(s): s.ids_fn(xb).astype(jnp.int32)
                      for s in specs}
        # gather the touched rows OUTSIDE the differentiated function
        # and point the model at them through rewritten position ids
        rows_in = {_key(s): _get(params, s.path)[ids_by_key[_key(s)]]
                   for s in reindexed}
        xb_sub = xb
        for s in reindexed:
            pos = jnp.arange(ids_by_key[_key(s)].shape[0], dtype=jnp.int32)
            xb_sub = s.set_ids_fn(xb_sub, pos)
        # differentiate w.r.t. a tree WITHOUT the reindexed table
        # leaves: leaving them in (unused) would make jax materialize a
        # vocab-sized zero cotangent per table — the very pass this
        # path deletes
        params_head = split_rest(params, reindexed)

        def compute_loss(p, rows):
            for s in reindexed:
                p = _set(p, s.path, rows[_key(s)])
            if mixed_precision:
                p = _cast_tree(p, jnp.bfloat16)
                # inputs stay uncast: ids above 256 are not exactly
                # representable in bf16 (see trainer.one_step)
            if apply_and_state_fn is not None:
                pred, state_upd = apply_and_state_fn(p, xb_sub,
                                                     training=True, rng=rng)
            else:
                pred, state_upd = apply_fn(p, xb_sub, training=True,
                                           rng=rng), {}
            if mixed_precision:
                pred = jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.float32), pred)
            return loss_fn(yb, pred), state_upd

        (loss, state_upd), (grads, row_grads) = jax.value_and_grad(
            compute_loss, argnums=(0, 1), has_aux=True)(params_head,
                                                        rows_in)
        if mixed_precision:
            grads = _cast_tree(grads, jnp.float32, only=jnp.bfloat16)
            row_grads = _cast_tree(row_grads, jnp.float32,
                                   only=jnp.bfloat16)
            state_upd = _cast_tree(state_upd, jnp.float32,
                                   only=jnp.bfloat16)

        t = opt_state["t"] + 1
        tables = dict(opt_state["tables"])
        for s in reindexed:
            k = _key(s)
            table, mu, nu = segment_adam_update(
                _get(params, s.path), *tables[k], ids_by_key[k],
                row_grads[k], t, lr=s.lr, b1=s.b1, b2=s.b2, eps=s.eps,
                interpret=interpret)
            params = _set(params, s.path, table)
            tables[k] = (mu, nu)
        for s in dense:
            # dense-cotangent fallback: gather the touched rows of the
            # materialized table grad (duplicates are NOT re-summed —
            # the dense VJP already accumulated them, so feed each
            # unique id its dense-grad row exactly once)
            k = _key(s)
            ids = ids_by_key[k]
            g_table = _get(grads, s.path)
            table, mu, nu = segment_adam_update(
                _get(params, s.path), *tables[k], ids,
                _dedup_rows(g_table, ids), t, lr=s.lr, b1=s.b1, b2=s.b2,
                eps=s.eps, interpret=interpret)
            params = _set(params, s.path, table)
            tables[k] = (mu, nu)

        rest_grads = split_rest(grads, specs)
        rest_params = split_rest(params, specs)
        if fused_rest is not None:
            new_rest, rest_state = fused_rest(rest_grads,
                                              opt_state["rest"],
                                              rest_params)
        else:
            import optax
            updates, rest_state = optimizer.update(
                rest_grads, opt_state["rest"], rest_params)
            new_rest = optax.apply_updates(rest_params, updates)
        params = jax.tree_util.tree_map(
            lambda new, old: old if new is None else new,
            new_rest, params, is_leaf=lambda x: x is None)
        params = _merge_state(params, state_upd)
        return params, {"rest": rest_state, "tables": tables, "t": t}, loss

    return one_step


def _dedup_rows(g_table, ids):
    """Per-example rows of an ALREADY-accumulated dense table grad,
    aligned with the ORIGINAL `ids` order: one entry per unique id
    carries its dense-grad row, every other duplicate carries zeros —
    so `segment_compact`'s re-sum reproduces the dense accumulation
    exactly once per row."""
    ids = ids.astype(jnp.int32)
    order = jnp.argsort(ids)
    sids = ids[order]
    dup_sorted = jnp.concatenate([jnp.zeros((1,), bool),
                                  sids[1:] == sids[:-1]])
    # scatter the sorted-order dup flags back to original positions
    dup = jnp.zeros_like(dup_sorted).at[order].set(dup_sorted)
    return jnp.where(dup[:, None], 0.0, g_table[ids])
