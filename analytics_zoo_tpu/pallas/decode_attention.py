"""Decode attention — single-token queries against the pooled KV cache.

The generative decode step (serving/decode.py) asks one question per
leased slot: "given this slot's ONE new query vector, attend over the
first `lengths[s]` cached positions of that slot's KV rows". Unlike
flash attention (O(T²) work per call) decode attention is memory-bound:
the arithmetic is two [1,D]×[D,L] products per head, but every byte of
the live KV prefix streams from HBM each step. The kernel therefore
reads the KV pool IN PLACE — `pallas_call` takes the full
`[slots, H, max_kv_len, D]` pool buffers and the grid only visits the
first `kv_bucket // block_k` key blocks, so no slice copy of the pool
is ever materialized and the bytes actually moved scale with the
serving bucket, not the pool capacity.

Grid: (slots, heads, k-blocks) with the k axis innermost and
"arbitrary", online-softmax state (acc, m, l) in VMEM scratch across k
steps — the same canonical shape as `flash_attention`, degenerated to a
1-row query block. Positions at or past `lengths[s]` are masked with a
large negative additive constant (not -inf: a fully-masked first block
would turn the running max into -inf and poison the rescale with
inf-inf). `lengths` must be >= 1 per slot — the engine guarantees it
(prefill writes at least one position before any step; dead slots are
passed length 1 and their output rows are discarded host-side).

Off-TPU the exact jnp gather path (`_reference_decode_attention`) runs
instead — same math, no tiling — decided statically from the backend
like `flash_attention._flash_supported`.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pallas.dropout import _tpu_params


def _attend_window(q, k, v, lengths, kv_bucket):
    """The shared exact-attention core: q [S, H, D] against a
    MATERIALIZED window k/v [S, H, kv_bucket, D], masked past
    `lengths`. Both the contiguous and the paged reference paths call
    this with identical shapes, so a paged window gathered from blocks
    produces bitwise-identical outputs to the contiguous slice it
    mirrors — the property the paged-parity tests pin."""
    D = q.shape[-1]
    scores = jnp.einsum("shd,shld->shl", q, k) / math.sqrt(D)
    scores = scores.astype(jnp.float32)
    pos = jnp.arange(kv_bucket, dtype=jnp.int32)
    mask = pos[None, None, :] < lengths[:, None, None]
    scores = jnp.where(mask, scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("shl,shld->shd", weights, v)


def _reference_decode_attention(q, k_pool, v_pool, lengths, kv_bucket):
    """Exact decode attention over the first `kv_bucket` pool positions.
    q: [S, H, D]; k_pool/v_pool: [S, H, L, D]; lengths: int32 [S]."""
    k = jax.lax.slice_in_dim(k_pool, 0, kv_bucket, axis=2)
    v = jax.lax.slice_in_dim(v_pool, 0, kv_bucket, axis=2)
    return _attend_window(q, k, v, lengths, kv_bucket)


def gather_kv_window(pool, tables, kv_bucket: int):
    """Materialize the logical [S, H, kv_bucket, D] window of a BLOCK
    pool [num_blocks, H, block_len, D] through per-sequence block
    tables [S, >= kv_bucket // block_len]. Pure gather — the values are
    exactly the bytes the blocks hold, in logical position order."""
    num_blocks, H, block_len, D = pool.shape
    n_kb = kv_bucket // block_len
    tb = tables[:, :n_kb]                       # [S, n_kb]
    g = pool[tb]                                # [S, n_kb, H, bl, D]
    g = jnp.moveaxis(g, 2, 1)                   # [S, H, n_kb, bl, D]
    return g.reshape(g.shape[0], H, kv_bucket, D)


def _reference_paged_decode_attention(q, k_pool, v_pool, tables, lengths,
                                      kv_bucket):
    """Exact paged decode attention: gather the block window, then the
    SAME math as the contiguous reference."""
    k = gather_kv_window(k_pool, tables, kv_bucket)
    v = gather_kv_window(v_pool, tables, kv_bucket)
    return _attend_window(q, k, v, lengths, kv_bucket)


def _decode_supported() -> bool:
    """Static backend gate (no exception-driven fallback): the Mosaic
    kernel runs on TPU; CPU tests take the exact reference path."""
    return jax.default_backend() == "tpu"


def _decode_cost(q, kv_bucket, n_heads, itemsize):
    """Analytic roofline model (check_pallas_cost lint: HLO cost
    analysis sees ~0 inside a Mosaic call). Decode is MEMORY-bound:
    bytes are dominated by streaming the live K and V prefixes —
    2 · S·H·kv_bucket·D — while flops are just the two bucket×D
    products per (slot, head); the roofline accountant must see that
    ratio or it would misread decode steps as idle compute."""
    from jax.experimental import pallas as pl

    S, H, D = q.shape[0], n_heads, q.shape[-1]
    kv_bytes = 2.0 * S * H * kv_bucket * D * itemsize
    qo_bytes = 2.0 * S * H * D * itemsize + 4.0 * S
    return pl.CostEstimate(
        flops=4.0 * S * H * kv_bucket * D,          # QKᵀ + PV
        bytes_accessed=float(kv_bytes + qo_bytes),
        transcendentals=float(S * H * kv_bucket))


def _decode_kernel(scale, n_kb, q_ref, k_ref, v_ref, len_ref, o_ref,
                   acc_sc, m_sc, l_sc):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    ki = pl.program_id(2)
    block_k = k_ref.shape[2]

    @pl.when(ki == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, -1e30)
        l_sc[...] = jnp.zeros_like(l_sc)

    qb = q_ref[0]                                          # [1, D]
    kb = k_ref[0, 0]                                       # [bk, D]
    vb = v_ref[0, 0]
    scores = jnp.dot(qb, kb.T,
                     preferred_element_type=jnp.float32) * scale  # [1, bk]
    pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)
    scores = jnp.where(pos < len_ref[s, 0], scores, -1e30)
    m_prev, l_prev = m_sc[...], l_sc[...]
    m_new = jnp.maximum(m_prev, scores.max(axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)
    acc_sc[...] = acc_sc[...] * alpha + jnp.dot(
        p.astype(v_ref.dtype), vb, preferred_element_type=jnp.float32)
    m_sc[...] = m_new
    l_sc[...] = l_prev * alpha + p.sum(axis=1, keepdims=True)

    @pl.when(ki == n_kb - 1)
    def _flush():
        o_ref[0] = (acc_sc[...] / l_sc[...]).astype(o_ref.dtype)


def decode_attention(q, k_pool, v_pool, lengths, kv_bucket: int,
                     block_k: int = 128,
                     interpret: Optional[bool] = None):
    """One decode step of attention for every slot.

    q: [S, H, D] — the current token's query per slot.
    k_pool/v_pool: [S, H, L, D] — the FULL KV pool; only positions
    [0, kv_bucket) are read (kv_bucket is the static serving bucket,
    `<= L`, chosen per step by the DecodeScheduler).
    lengths: int32 [S] — live KV length per slot, all >= 1; positions
    >= lengths[s] are masked. Returns [S, H, D].
    """
    S, H, D = q.shape
    L = k_pool.shape[2]
    if not 1 <= kv_bucket <= L:
        raise ValueError(f"kv_bucket {kv_bucket} outside [1, {L}]")
    lengths = lengths.astype(jnp.int32)
    if not (_decode_supported() or interpret):
        return _reference_decode_attention(q, k_pool, v_pool, lengths,
                                           kv_bucket)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_k = min(block_k, kv_bucket)
    if kv_bucket % block_k:
        # bucket ladders are powers of two >= 1; a non-dividing block
        # falls back to the exact path rather than padding the pool
        return _reference_decode_attention(q, k_pool, v_pool, lengths,
                                           kv_bucket)
    n_kb = kv_bucket // block_k
    scale = 1.0 / math.sqrt(D)
    item = jnp.dtype(q.dtype).itemsize
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale, n_kb),
        grid=(S, H, n_kb),
        in_specs=[
            pl.BlockSpec((1, 1, D), lambda s, h, j: (s, h, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda s, h, j: (s, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda s, h, j: (s, h, j, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, D), lambda s, h, j: (s, h, 0)),
        out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, D), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
        compiler_params=_tpu_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=_decode_cost(q, kv_bucket, H, item),
        interpret=bool(interpret) if interpret is not None else False,
    )(q, k_pool, v_pool, lengths.reshape(S, 1))
    return out


# ---------------------------------------------------------------------------
# paged variant (ISSUE 19): block-table indirection into a block pool
# ---------------------------------------------------------------------------
def _paged_cost(q, kv_bucket, n_heads, block_len, itemsize):
    """Same memory-bound roofline as `_decode_cost` plus the table
    stream: the kernel still moves 2 · S·H·kv_bucket·D KV bytes per
    step — block indirection changes WHICH bytes, not how many — and
    reads S · kv_bucket/block_len int32 table entries from SMEM."""
    from jax.experimental import pallas as pl

    S, H, D = q.shape[0], n_heads, q.shape[-1]
    kv_bytes = 2.0 * S * H * kv_bucket * D * itemsize
    qo_bytes = 2.0 * S * H * D * itemsize + 4.0 * S
    table_bytes = 4.0 * S * (kv_bucket // block_len)
    return pl.CostEstimate(
        flops=4.0 * S * H * kv_bucket * D,          # QKᵀ + PV
        bytes_accessed=float(kv_bytes + qo_bytes + table_bytes),
        transcendentals=float(S * H * kv_bucket))


def _paged_kernel(scale, n_kb, block_len, tbl_ref, q_ref, k_ref, v_ref,
                  len_ref, o_ref, acc_sc, m_sc, l_sc):
    """Identical online-softmax walk to `_decode_kernel`; the ONLY
    difference is upstream — the BlockSpec index map routed k/v block
    `j` through the prefetched table, so `k_ref`/`v_ref` here hold the
    slot's j-th LOGICAL block wherever it physically lives. Masking is
    by logical position, exactly as before."""
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, -1e30)
        l_sc[...] = jnp.zeros_like(l_sc)

    qb = q_ref[0]                                          # [1, D]
    kb = k_ref[0, 0]                                       # [bl, D]
    vb = v_ref[0, 0]
    scores = jnp.dot(qb, kb.T,
                     preferred_element_type=jnp.float32) * scale  # [1, bl]
    pos = ki * block_len + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_len), 1)
    scores = jnp.where(pos < len_ref[s, 0], scores, -1e30)
    m_prev, l_prev = m_sc[...], l_sc[...]
    m_new = jnp.maximum(m_prev, scores.max(axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)
    acc_sc[...] = acc_sc[...] * alpha + jnp.dot(
        p.astype(v_ref.dtype), vb, preferred_element_type=jnp.float32)
    m_sc[...] = m_new
    l_sc[...] = l_prev * alpha + p.sum(axis=1, keepdims=True)

    @pl.when(ki == n_kb - 1)
    def _flush():
        o_ref[0] = (acc_sc[...] / l_sc[...]).astype(o_ref.dtype)


def paged_decode_attention(q, k_pool, v_pool, tables, lengths,
                           kv_bucket: int,
                           interpret: Optional[bool] = None):
    """One decode step of attention for every slot, KV read through
    per-sequence block tables.

    q: [S, H, D] — the current token's query per slot.
    k_pool/v_pool: [num_blocks, H, block_len, D] — the FULL block
    pool; slot ``s``'s logical positions ``[j*block_len, (j+1)*
    block_len)`` live in physical block ``tables[s, j]``.
    tables: int32 [S, T] with ``T >= kv_bucket // block_len``; only the
    first ``kv_bucket // block_len`` entries are read (entries past a
    slot's live length may point anywhere valid — the scratch block by
    convention — because masking is by `lengths`).
    lengths: int32 [S] — live KV length per slot, all >= 1.
    Returns [S, H, D].

    The grid is (slots, heads, k-blocks) exactly like the contiguous
    kernel; the table rides in as a scalar-prefetch operand
    (`PrefetchScalarGridSpec`) so the k/v BlockSpec index maps can
    dereference it — the indirection costs an SMEM read per grid step,
    not a gather copy of the pool.
    """
    S, H, D = q.shape
    num_blocks, _, block_len, _ = k_pool.shape
    if kv_bucket < 1 or kv_bucket % block_len:
        raise ValueError(
            f"kv_bucket {kv_bucket} must be a positive multiple of "
            f"block_len {block_len}")
    n_kb = kv_bucket // block_len
    if tables.shape[-1] < n_kb:
        raise ValueError(
            f"block table has {tables.shape[-1]} entries, kv_bucket "
            f"{kv_bucket} needs {n_kb}")
    lengths = lengths.astype(jnp.int32)
    tables = tables.astype(jnp.int32)
    if not (_decode_supported() or interpret):
        return _reference_paged_decode_attention(
            q, k_pool, v_pool, tables, lengths, kv_bucket)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    scale = 1.0 / math.sqrt(D)
    item = jnp.dtype(q.dtype).itemsize
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,            # tables[:, :n_kb]
        grid=(S, H, n_kb),
        in_specs=[
            pl.BlockSpec((1, 1, D), lambda s, h, j, tbl: (s, h, 0)),
            pl.BlockSpec((1, 1, block_len, D),
                         lambda s, h, j, tbl: (tbl[s, j], h, 0, 0)),
            pl.BlockSpec((1, 1, block_len, D),
                         lambda s, h, j, tbl: (tbl[s, j], h, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, D), lambda s, h, j, tbl: (s, h, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, D), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, scale, n_kb, block_len),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
        compiler_params=_tpu_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=_paged_cost(q, kv_bucket, H, block_len, item),
        interpret=bool(interpret) if interpret is not None else False,
    )(tables[:, :n_kb], q, k_pool, v_pool, lengths.reshape(S, 1))
    return out
