"""Decode attention — single-token queries against the pooled KV cache.

The generative decode step (serving/decode.py) asks one question per
leased slot: "given this slot's ONE new query vector, attend over the
first `lengths[s]` cached positions of that slot's KV rows". Unlike
flash attention (O(T²) work per call) decode attention is memory-bound:
the arithmetic is two [1,D]×[D,L] products per head, but every byte of
the live KV prefix streams from HBM each step. The kernel therefore
reads the KV pool IN PLACE — `pallas_call` takes the full
`[slots, H, max_kv_len, D]` pool buffers and the grid only visits the
first `kv_bucket // block_k` key blocks, so no slice copy of the pool
is ever materialized and the bytes actually moved scale with the
serving bucket, not the pool capacity.

Grid: (slots, heads, k-blocks) with the k axis innermost and
"arbitrary", online-softmax state (acc, m, l) in VMEM scratch across k
steps — the same canonical shape as `flash_attention`, degenerated to a
1-row query block. Positions at or past `lengths[s]` are masked with a
large negative additive constant (not -inf: a fully-masked first block
would turn the running max into -inf and poison the rescale with
inf-inf). `lengths` must be >= 1 per slot — the engine guarantees it
(prefill writes at least one position before any step; dead slots are
passed length 1 and their output rows are discarded host-side).

Off-TPU the exact jnp gather path (`_reference_decode_attention`) runs
instead — same math, no tiling — decided statically from the backend
like `flash_attention._flash_supported`.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pallas.dropout import _tpu_params


def _reference_decode_attention(q, k_pool, v_pool, lengths, kv_bucket):
    """Exact decode attention over the first `kv_bucket` pool positions.
    q: [S, H, D]; k_pool/v_pool: [S, H, L, D]; lengths: int32 [S]."""
    D = q.shape[-1]
    k = jax.lax.slice_in_dim(k_pool, 0, kv_bucket, axis=2)
    v = jax.lax.slice_in_dim(v_pool, 0, kv_bucket, axis=2)
    scores = jnp.einsum("shd,shld->shl", q, k) / math.sqrt(D)
    scores = scores.astype(jnp.float32)
    pos = jnp.arange(kv_bucket, dtype=jnp.int32)
    mask = pos[None, None, :] < lengths[:, None, None]
    scores = jnp.where(mask, scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("shl,shld->shd", weights, v)


def _decode_supported() -> bool:
    """Static backend gate (no exception-driven fallback): the Mosaic
    kernel runs on TPU; CPU tests take the exact reference path."""
    return jax.default_backend() == "tpu"


def _decode_cost(q, kv_bucket, n_heads, itemsize):
    """Analytic roofline model (check_pallas_cost lint: HLO cost
    analysis sees ~0 inside a Mosaic call). Decode is MEMORY-bound:
    bytes are dominated by streaming the live K and V prefixes —
    2 · S·H·kv_bucket·D — while flops are just the two bucket×D
    products per (slot, head); the roofline accountant must see that
    ratio or it would misread decode steps as idle compute."""
    from jax.experimental import pallas as pl

    S, H, D = q.shape[0], n_heads, q.shape[-1]
    kv_bytes = 2.0 * S * H * kv_bucket * D * itemsize
    qo_bytes = 2.0 * S * H * D * itemsize + 4.0 * S
    return pl.CostEstimate(
        flops=4.0 * S * H * kv_bucket * D,          # QKᵀ + PV
        bytes_accessed=float(kv_bytes + qo_bytes),
        transcendentals=float(S * H * kv_bucket))


def _decode_kernel(scale, n_kb, q_ref, k_ref, v_ref, len_ref, o_ref,
                   acc_sc, m_sc, l_sc):
    from jax.experimental import pallas as pl

    s = pl.program_id(0)
    ki = pl.program_id(2)
    block_k = k_ref.shape[2]

    @pl.when(ki == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, -1e30)
        l_sc[...] = jnp.zeros_like(l_sc)

    qb = q_ref[0]                                          # [1, D]
    kb = k_ref[0, 0]                                       # [bk, D]
    vb = v_ref[0, 0]
    scores = jnp.dot(qb, kb.T,
                     preferred_element_type=jnp.float32) * scale  # [1, bk]
    pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)
    scores = jnp.where(pos < len_ref[s, 0], scores, -1e30)
    m_prev, l_prev = m_sc[...], l_sc[...]
    m_new = jnp.maximum(m_prev, scores.max(axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)
    acc_sc[...] = acc_sc[...] * alpha + jnp.dot(
        p.astype(v_ref.dtype), vb, preferred_element_type=jnp.float32)
    m_sc[...] = m_new
    l_sc[...] = l_prev * alpha + p.sum(axis=1, keepdims=True)

    @pl.when(ki == n_kb - 1)
    def _flush():
        o_ref[0] = (acc_sc[...] / l_sc[...]).astype(o_ref.dtype)


def decode_attention(q, k_pool, v_pool, lengths, kv_bucket: int,
                     block_k: int = 128,
                     interpret: Optional[bool] = None):
    """One decode step of attention for every slot.

    q: [S, H, D] — the current token's query per slot.
    k_pool/v_pool: [S, H, L, D] — the FULL KV pool; only positions
    [0, kv_bucket) are read (kv_bucket is the static serving bucket,
    `<= L`, chosen per step by the DecodeScheduler).
    lengths: int32 [S] — live KV length per slot, all >= 1; positions
    >= lengths[s] are masked. Returns [S, H, D].
    """
    S, H, D = q.shape
    L = k_pool.shape[2]
    if not 1 <= kv_bucket <= L:
        raise ValueError(f"kv_bucket {kv_bucket} outside [1, {L}]")
    lengths = lengths.astype(jnp.int32)
    if not (_decode_supported() or interpret):
        return _reference_decode_attention(q, k_pool, v_pool, lengths,
                                           kv_bucket)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    block_k = min(block_k, kv_bucket)
    if kv_bucket % block_k:
        # bucket ladders are powers of two >= 1; a non-dividing block
        # falls back to the exact path rather than padding the pool
        return _reference_decode_attention(q, k_pool, v_pool, lengths,
                                           kv_bucket)
    n_kb = kv_bucket // block_k
    scale = 1.0 / math.sqrt(D)
    item = jnp.dtype(q.dtype).itemsize
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale, n_kb),
        grid=(S, H, n_kb),
        in_specs=[
            pl.BlockSpec((1, 1, D), lambda s, h, j: (s, h, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda s, h, j: (s, h, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda s, h, j: (s, h, j, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, D), lambda s, h, j: (s, h, 0)),
        out_shape=jax.ShapeDtypeStruct((S, H, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, D), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, 1), jnp.float32),
        ],
        compiler_params=_tpu_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=_decode_cost(q, kv_bucket, H, item),
        interpret=bool(interpret) if interpret is not None else False,
    )(q, k_pool, v_pool, lengths.reshape(S, 1))
    return out
