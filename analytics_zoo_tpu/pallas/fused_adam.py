"""Fused Adam — the optimizer sweep as ONE blocked Pallas pass (ISSUE 9).

BENCH r05 pinned NCF at 33% of its achievable memory bound and the
roofline per-op breakdown (docs/ROOFLINE.md) blamed the dense-Adam
sweep: optax builds the update as a chain of materialized trees (new
mu, new nu, the updates tree, then `apply_updates`), and XLA's fusion
does not collapse the chain back to the information-theoretic floor —
the sweep reads/writes the parameter set 10-12× per step where 7
element-passes suffice (read g; read+write p, m, v). Structural
repacking (flat/stacked buffers) could not fix this because the extra
passes are *between* ops, not between tensors. This module goes below
XLA: one kernel reads a (grad, m, v, param) tile from HBM, applies the
whole Adam update in VMEM, and writes (m, v, param) back — 7 passes
total, in-place via `input_output_aliases`, the FlashAttention
IO-aware-kernel argument applied to the optimizer.

Numerics: bias correction is folded into two scalars computed OUTSIDE
the kernel (`a = lr·√c2/c1`, `b = eps·√c2` with `c_i = 1 - βᵢᵗ`), so
the in-kernel math is `p ← p − a·m̂/(√v̂ + b) − lr·wd·p` with
`m̂, v̂` the *uncorrected* new moments — algebraically identical to
`optax.adam`/`adamw` (decoupled weight decay), moments always f32,
params f32 or bf16 (cast at the write). Schedules stay host-side: the
caller passes the resolved per-step `lr`.

Every `pallas_call` carries an analytic `cost_estimate` (XLA's HLO
cost analysis cannot see inside a custom call), so the roofline layer
(`observability/roofline.py`) keeps counting the fused step's true HBM
bytes — `update_cost()` is that model, exported for tests and benches.

`interpret=None` auto-selects interpreter mode off-TPU so tier-1
exercises the exact kernel code path on the CPU rig; `fused_available`
probes one tiny compile so any Pallas lowering failure degrades to
plain optax with a single WARNING instead of a mid-fit crash.
"""

from __future__ import annotations

import functools
import logging
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

log = logging.getLogger("analytics_zoo_tpu.pallas")

# Per-operand VMEM budget for a block: 7 live buffers (4 in + 3 out)
# double-buffered must fit comfortably under ~16 MB/core; 512 KB/block
# → ≤ 7 MB resident, big enough to amortize DMA issue overhead.
_BLOCK_BYTES = 512 * 1024


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    """Off-TPU backends run the kernel through the Pallas interpreter —
    same code path, same block walk — so CPU tests test the kernel."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def _block_rows(rows: int, cols: int) -> int:
    """Largest multiple-of-8 row count whose f32 block stays under the
    VMEM budget (min 8 — smaller blocks pad to the (8, 128) f32 tile
    anyway)."""
    bm = max(8, _BLOCK_BYTES // (4 * max(cols, 1)))
    bm -= bm % 8
    return min(max(bm, 8), max(rows, 1))


def _fold_scalars(count, lr, b1: float, b2: float, eps: float,
                  weight_decay: float):
    """(a, b, lr·wd) f32 vector: the whole bias-correction folded into
    scalars so the kernel is pure elementwise math. `count` is the NEW
    step number t (post-increment), `lr` may be traced (schedules)."""
    t = jnp.asarray(count, jnp.float32)
    c1 = 1.0 - jnp.asarray(b1, jnp.float32) ** t
    c2 = 1.0 - jnp.asarray(b2, jnp.float32) ** t
    sq2 = jnp.sqrt(c2)
    lr = jnp.asarray(lr, jnp.float32)
    return jnp.stack([lr * sq2 / c1, eps * sq2, lr * weight_decay])


def _adam_math(p, m, v, g, a, b, lrwd, b1: float, b2: float):
    """The shared update — used verbatim by the kernel body, the scalar
    (ndim-0) jnp path, and the segment kernel, so every path is the
    same math by construction."""
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * (g * g)
    p_new = p - a * m_new / (jnp.sqrt(v_new) + b) - lrwd * p
    return p_new, m_new, v_new


def _fused_kernel(b1, b2, s_ref, p_ref, m_ref, v_ref, g_ref,
                  p_out, m_out, v_out):
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    p_new, m_new, v_new = _adam_math(p, m_ref[...], v_ref[...], g,
                                     s_ref[0], s_ref[1], s_ref[2], b1, b2)
    p_out[...] = p_new.astype(p_out.dtype)
    m_out[...] = m_new
    v_out[...] = v_new


def leaf_cost(shape, dtype) -> Tuple[float, float]:
    """(flops, HBM bytes) of one fused update of one leaf: read g +
    read/write each of p (param dtype), m, v (f32) — the 7-pass floor
    the kernel achieves. ~12 elementwise flops + one sqrt per element."""
    import numpy as np
    n = int(np.prod(shape)) if shape else 1
    pbytes = jnp.dtype(dtype).itemsize
    return 12.0 * n, float(n * (4 + 2 * pbytes + 4 * 4))


def update_cost(params) -> Tuple[float, float]:
    """Analytic (flops, bytes) of one fused sweep over a whole tree —
    the roofline model benches and tests compare gauges against."""
    flops = bytes_ = 0.0
    for leaf in jax.tree_util.tree_leaves(params):
        f, b = leaf_cost(jnp.shape(leaf), leaf.dtype)
        flops += f
        bytes_ += b
    return flops, bytes_


def _leaf_update(p, m, v, g, scal, b1: float, b2: float, interpret: bool):
    """One leaf through the kernel: viewed as (rows, last-dim), blocked
    over rows. Leading-dim collapse keeps the minor dim — a free
    relayout on TPU — unlike the flat 1-D repacking designs
    `ops/flat_optimizer.py` measured and rejected."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if p.ndim == 0:
        # scalars are un-tileable; same math, jnp (bias scales etc.)
        g32 = g.astype(jnp.float32)
        p_new, m_new, v_new = _adam_math(p.astype(jnp.float32), m, v, g32,
                                         scal[0], scal[1], scal[2], b1, b2)
        return p_new.astype(p.dtype), m_new, v_new

    shape = p.shape
    cols = shape[-1]
    rows = p.size // cols
    p2, m2, v2, g2 = (x.reshape(rows, cols) for x in (p, m, v, g))
    bm = _block_rows(rows, cols)
    flops, bytes_ = leaf_cost(shape, p.dtype)

    def bs():
        return pl.BlockSpec((bm, cols), lambda i: (i, 0))

    p_new, m_new, v_new = pl.pallas_call(
        functools.partial(_fused_kernel, b1, b2),
        grid=(pl.cdiv(rows, bm),),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  bs(), bs(), bs(), bs()],
        out_specs=[bs(), bs(), bs()],
        out_shape=[jax.ShapeDtypeStruct((rows, cols), p.dtype),
                   jax.ShapeDtypeStruct((rows, cols), jnp.float32),
                   jax.ShapeDtypeStruct((rows, cols), jnp.float32)],
        # in-place: the params/moments buffers ARE the outputs — the
        # donation contract of the trainer step stays buffer reuse
        input_output_aliases={1: 0, 2: 1, 3: 2},
        cost_estimate=pl.CostEstimate(flops=flops, bytes_accessed=bytes_,
                                      transcendentals=p.size),
        interpret=interpret,
    )(scal, p2, m2, v2, g2)
    return (p_new.reshape(shape), m_new.reshape(shape),
            v_new.reshape(shape))


def fused_adam_step(params, mu, nu, grads, count, *, lr,
                    b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8,
                    weight_decay: float = 0.0,
                    interpret: Optional[bool] = None):
    """One fused Adam step over a pytree: returns (params, mu, nu) with
    every leaf updated by one kernel pass. `count` is the new step
    number (1 on the first call); `lr` may be a traced scalar."""
    interpret = _resolve_interpret(interpret)
    scal = _fold_scalars(count, lr, b1, b2, eps, weight_decay)
    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_m = treedef.flatten_up_to(mu)
    flat_v = treedef.flatten_up_to(nu)
    flat_g = treedef.flatten_up_to(grads)
    out = [_leaf_update(p, m, v, g, scal, b1, b2, interpret)
           for p, m, v, g in zip(flat_p, flat_m, flat_v, flat_g)]
    return tuple(jax.tree_util.tree_unflatten(treedef, [o[i] for o in out])
                 for i in range(3))


# ---------------------------------------------------------------------------
# availability probe: lowering failure → plain optax, one WARNING
# ---------------------------------------------------------------------------
_probe_cache = {}


def fused_available(interpret: Optional[bool] = None) -> bool:
    """One tiny end-to-end kernel compile+run per (backend, interpret)
    mode. Any Pallas/Mosaic failure is caught HERE — once, with one
    WARNING — so the trainer degrades to plain optax instead of dying
    mid-fit on the first real step."""
    interpret = _resolve_interpret(interpret)
    key = (jax.default_backend(), interpret)
    if key in _probe_cache:
        return _probe_cache[key]
    try:
        p = jnp.ones((8, 128), jnp.float32)
        z = jnp.zeros((8, 128), jnp.float32)
        out = jax.jit(lambda p, z: fused_adam_step(
            {"w": p}, {"w": z}, {"w": z}, {"w": z + 0.5}, 1, lr=1e-3,
            interpret=interpret))(p, z)
        jax.block_until_ready(out)
        ok = True
    except Exception as e:  # noqa: BLE001 — degrade, never crash the fit
        log.warning(
            "fused optimizer kernels unavailable on this backend "
            "(%s: %s); falling back to plain optax", type(e).__name__, e)
        ok = False
    _probe_cache[key] = ok
    return ok
