"""Dropout tuned for TPU: uint8 random bytes by default, with a Pallas
in-kernel-RNG alternative and a jax.random fallback.

Motivation (docs/ROOFLINE.md): XLA's `RngBitGenerator` is not fusible —
every `jax.random.bernoulli` materializes a full uint32 bit tensor to HBM
(4 bytes per masked element, written by the RNG op and read back by the
select). Profiled on v5e (BERT-base, batch 256, seq 128, dropout on all
sites): 16.4 ms/step of rng-bit-generator time plus ~15 ms/step of u32
copies/slices — the whole measured dropout tax.

Three implementations, selected by `ZOO_DROPOUT_IMPL`:

- `u8` (default on TPU) — draw ONE random byte per element
  (`jax.random.bits(..., uint8)`) and keep iff byte < t where
  t = round(keep*256). Scaling uses the exact keep probability t/256, so
  the estimator stays unbiased; the rate is quantized to 1/256 (0.1 →
  0.1016). Bits traffic drops 4x and the compare+select still fuses into
  the surrounding XLA chain. Measured: dropout-on step time equals
  dropout-off within noise (interleaved min-of-5: 191.4 vs 190.1 ms vs
  225.9 ms for u32 bernoulli).
- `pallas` — bits generated INSIDE a Pallas kernel (`pltpu.prng_seed` +
  `prng_random_bits`) per tile; the custom VJP reseeds the identical
  per-tile PRNG in the backward pass (no residual stored; same
  deterministic keep-rule as the in-kernel flash-attention dropout).
  Zero RNG HBM traffic, but the kernel boundary breaks XLA fusions —
  profiled NET SLOWER than u8 in BERT context (+10.3 ms/step kernels,
  +5.7 ms/step lost fusion vs −16.4 rng). Kept for composition in
  hand-written kernels and as the regeneration pattern's reference.
- `u32` — plain `jax.random.bernoulli` (default off-TPU; exact rate).

The reference has per-layer JVM dropout (`keras/layers/Dropout.scala`);
choosing the mask representation for HBM-bandwidth and XLA-fusion
behavior is the TPU-native redesign of that layer's hot path.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp


def _tpu_params(**kwargs):
    """`pltpu.CompilerParams(...)` across the jax rename: jax ≤0.4.x
    spells it `TPUCompilerParams`, newer trees `CompilerParams` — the
    pre-rename spelling raised AttributeError on this jaxlib and took
    every Pallas kernel (and its tier-1 tests) down with it."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kwargs)


def _dropout_threshold(rate: float) -> int:
    """keep iff bits >= threshold (uint32 compare) — the keep-rule of the
    full-width Pallas kernel below (`impl=pallas`)."""
    return min(int(rate * 2 ** 32), 2 ** 32 - 1)


def _byte_threshold(rate: float) -> int:
    """keep iff byte < t — the shared uint8 keep-rule: t = round(keep*256),
    clamped to [1, 255]. Scale by the EXACT keep probability t/256 for an
    unbiased estimator (the rate is quantized to 1/256). Used by
    `_u8_dropout` here and `flash_attention._keep_scale` (imported) so the
    byte rule never diverges between the two modules."""
    return max(1, min(255, int(round((1.0 - rate) * 256))))


def _plain_dropout(rng, rate: float, x):
    """jax.random fallback — inverted dropout, same semantics."""
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, jnp.shape(x))
    return jnp.where(mask, x / keep, 0.0)


def _u8_dropout(rng, rate: float, x):
    """Inverted dropout from uint8 random bytes: keep iff byte < t where
    t = round(keep*256), scaled by the EXACT keep probability t/256 (so
    the estimator stays unbiased; the rate is quantized to 1/256 — 0.1
    becomes 0.1016). Bernoulli via uint32 bits materializes 4 bytes of
    RNG output per element to HBM (XLA cannot fuse RngBitGenerator into
    consumers); bytes cut that traffic 4x and the compare+select still
    fuses into the surrounding chain."""
    t = _byte_threshold(rate)
    bits = jax.random.bits(rng, jnp.shape(x), jnp.uint8)
    keep_eff = t / 256.0
    return jnp.where(bits < t, x / jnp.asarray(keep_eff, x.dtype),
                     jnp.zeros((), x.dtype))


def _tile_rows(m: int, c: int) -> int:
    """Largest divisor of m (power-of-two preferred) keeping a tile at or
    under ~256K elements — block + bits + out in VMEM stay ~3 MB f32."""
    cap = max(1, (256 * 1024) // c)
    best = 1
    for bm in (1024, 512, 256, 128, 64, 32, 16, 8, 4, 2):
        if bm <= cap and m % bm == 0:
            return bm
    for bm in range(min(cap, m), 0, -1):
        if m % bm == 0:
            best = bm
            break
    return best


def _kernel(rate, x_ref, s_ref, o_ref):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    i = pl.program_id(0)
    pltpu.prng_seed(s_ref[0, 0], i)
    bits = pltpu.prng_random_bits(x_ref.shape)
    keep = bits.astype(jnp.uint32) >= jnp.uint32(_dropout_threshold(rate))
    xb = x_ref[...]
    scale = jnp.asarray(1.0 / (1.0 - rate), xb.dtype)
    o_ref[...] = jnp.where(keep, xb * scale, 0).astype(o_ref.dtype)


def _apply(x2d, seed, rate, interpret):
    """Run the kernel over a [M, C] view (C a multiple of 128)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, C = x2d.shape
    bm = _tile_rows(M, C)
    item = jnp.dtype(x2d.dtype).itemsize
    return pl.pallas_call(
        functools.partial(_kernel, rate),
        grid=(M // bm,),
        in_specs=[
            pl.BlockSpec((bm, C), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((bm, C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((M, C), x2d.dtype),
        # analytic roofline model (check_pallas_cost lint): one read +
        # one write of x, ~3 elementwise ops (threshold/scale/select) —
        # the PRNG bits never touch HBM
        cost_estimate=pl.CostEstimate(flops=3.0 * M * C,
                                      bytes_accessed=float(2 * M * C * item),
                                      transcendentals=0),
        compiler_params=_tpu_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(x2d, seed)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _fused(x2d, seed, rate, interpret):
    return _apply(x2d, seed, rate, interpret)


def _fused_fwd(x2d, seed, rate, interpret):
    # no residual tensors: the backward regenerates the mask from the seed
    return _apply(x2d, seed, rate, interpret), seed


def _fused_bwd(rate, interpret, seed, dout):
    # d/dx [mask*scale*x] = mask*scale — the same kernel applied to dout
    return _apply(dout, seed, rate, interpret), jnp.zeros_like(seed)


_fused.defvjp(_fused_fwd, _fused_bwd)


def _view_2d(x):
    """Reshape-only [M, C] view with C a lane-aligned multiple of 128, or
    None when no such view exists without padding."""
    n = math.prod(x.shape)
    if x.ndim >= 2 and x.shape[-1] % 128 == 0:
        return (n // x.shape[-1], x.shape[-1])
    if n % 128 == 0:
        for c in (1024, 512, 256, 128):
            if n % c == 0:
                return (n // c, c)
    return None


def fused_dropout(x, rate: float, *, rng=None,
                  seed: Optional[jax.Array] = None):
    """Inverted dropout over `x` at `rate`. Pass a PRNG key via `rng` (a
    scalar int32 seed is derived) or a scalar int32 `seed` directly.
    Differentiable. rate >= 1 zeroes the tensor (the bernoulli keep=0
    degenerate case, matching `keras/layers/Dropout.scala` semantics)."""
    if rate <= 0.0:
        return x
    if rate >= 1.0:
        return jnp.zeros_like(x)
    if rng is None and seed is None:
        raise ValueError("fused_dropout needs `rng` or `seed`")
    impl = os.environ.get("ZOO_DROPOUT_IMPL")
    if impl is None:
        impl = "u8" if jax.default_backend() == "tpu" else "u32"
    if impl not in ("u8", "u32", "pallas"):
        raise ValueError(f"ZOO_DROPOUT_IMPL={impl!r} (want u8|u32|pallas)")
    if rng is None:
        rng = jax.random.PRNGKey(jnp.asarray(seed, jnp.int32))
    if impl == "u32":
        return _plain_dropout(rng, rate, x)
    shape2d = (_view_2d(x)
               if impl == "pallas" and jax.default_backend() == "tpu"
               else None)
    if shape2d is None:
        # pallas needs a TPU and a lane-aligned view; next-best is u8
        return _u8_dropout(rng, rate, x)
    if seed is None:
        seed = jax.random.randint(rng, (), 0, 2 ** 31 - 1, jnp.int32)
    seed = jnp.asarray(seed, jnp.int32).reshape(1, 1)
    out = _fused(x.reshape(shape2d), seed, float(rate), False)
    return out.reshape(x.shape)
