"""Flash attention — Pallas TPU kernels with reference fallback.

The reference has no fused attention at all (its longest-sequence support is
full O(L²) attention on one device, survey §5 long-context note); this module
is part of the beyond-reference long-context capability.

Kernel structure (the canonical TPU flash shape, pallas_guide.md): the grid
is (batch·heads, q-blocks, k-blocks) with the k axis innermost and marked
"arbitrary", so Pallas pipelines K/V block DMAs while online-softmax state
(acc, m, l) lives in VMEM scratch across k steps — VMEM stays O(block²)
at any sequence length. Matmuls run in the input dtype (bf16 on the MXU)
with f32 accumulation; softmax statistics stay f32. The backward pass is a
custom VJP with two more kernels (dQ over q-blocks, dK/dV over k-blocks)
recomputing weights from the saved logsumexp instead of materializing [T,T]
— so training (BERT, ring attention shards) runs flash end-to-end.

Attention dropout runs INSIDE the kernels: `pltpu.prng_seed(seed, tile)`
reseeds per (batch·head, q-block, k-block) tile, so the backward kernels
regenerate bit-identical masks without storing them. The softmax
denominator uses undropped weights (dropout applies to the normalized
weights — `drop(p)/l == drop(p/l)`), matching the semantics of dropping
softmax output.

`flash_attention` falls back to a jnp implementation when Pallas is
unavailable for the current backend (e.g. CPU tests) — same math, no
tiling; dropout there uses jax.random (different bits, same distribution).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pallas.dropout import _byte_threshold, _tpu_params


def _reference_attention(q, k, v, mask=None, dropout_rate: float = 0.0,
                         dropout_key=None):
    """Exact O(L²) attention — the shared non-flash numerics (also what
    `keras.transformer.dot_product_attention` delegates to)."""
    depth = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(depth)
    scores = scores.astype(jnp.float32)
    if mask is not None:
        scores = scores + mask
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_rate > 0.0 and dropout_key is not None:
        keep = 1.0 - dropout_rate
        m = jax.random.bernoulli(dropout_key, keep, weights.shape)
        weights = jnp.where(m, weights / keep, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


def _flash_supported(mask) -> bool:
    """The Pallas kernel runs on TPU and supports padding masks
    ([B,1,1,T]); full [B,1,T,T] masks or other backends use the exact
    reference path (decided statically — no exception-driven fallback)."""
    if jax.default_backend() != "tpu":
        return False
    if mask is not None and mask.ndim == 4 and mask.shape[2] != 1:
        return False
    return True


def _auto_block(T: int) -> int:
    """Largest multiple of 128 that divides T, capped at 1024 — big tiles
    amortize DMA/softmax-state overhead (see the v5e table in
    docs/ROOFLINE.md) without padding sequence lengths like 1152 that a
    1024 block would round up to 2048 (~3× wasted attention work).
    Lengths with no 128-multiple divisor fall back to 128 + the pad
    path."""
    for b in (1024, 512, 256, 128):
        if T % b == 0:
            return b
    return 128


def flash_attention(q, k, v, mask: Optional[jax.Array] = None,
                    dropout_rate: float = 0.0,
                    dropout_seed: Optional[jax.Array] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    bwd_block_q: Optional[int] = None,
                    bwd_block_k: Optional[int] = None,
                    interpret: Optional[bool] = None):
    """q,k,v: [B, H, T, Dh]. mask: additive [B,1,1,T] (padding) or
    [B,1,T,T] (full; reference path only). `dropout_rate` > 0 needs
    `dropout_seed` (scalar int32). Differentiable (custom VJP); the mask
    receives a zero cotangent (padding masks are data, not parameters).
    Returns [B, H, T, Dh].

    Block sizes default to the largest 128-multiple divisor of T up to
    1024: per-tile work must amortize the DMA + softmax-state overhead —
    measured on v5e at T=2048, 1024×1024 blocks run the fwd+bwd 4.4×
    faster than 128×128 and beat the XLA reference attention (~12 vs
    ~19 ms fwd). VMEM stays O(block_q·block_k) f32 (~4 MB at 1024²) plus
    the K/V double buffers."""
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("flash_attention: dropout_rate > 0 needs a "
                         "dropout_seed (deterministic in-kernel masks)")
    use_dropout = dropout_rate > 0.0
    if mask is not None and mask.ndim == 4 and mask.shape[2] != 1:
        # full [B,1,T,T] masks always take the exact reference path — the
        # kernels assume a broadcastable padding mask
        key = jax.random.PRNGKey(dropout_seed) if use_dropout else None
        return _reference_attention(q, k, v, mask,
                                    dropout_rate if use_dropout else 0.0,
                                    key)
    if not (_flash_supported(mask) or interpret):
        key = None
        if use_dropout:
            key = jax.random.PRNGKey(jnp.asarray(dropout_seed, jnp.int32)
                                     if not hasattr(dropout_seed, "dtype")
                                     else dropout_seed)
        return _reference_attention(q, k, v, mask,
                                    dropout_rate if use_dropout else 0.0,
                                    key)
    B, H, T, D = q.shape
    if block_q is None:
        block_q = _auto_block(T)
    if block_k is None:
        block_k = _auto_block(T)
    # Backward kernels hold more VMEM live per tile (pnorm, dw, plus the
    # dq/dk/dv accumulators) than the forward, so their sweet spot can be
    # smaller; default to the forward blocks.
    env_bwd = os.environ.get("ZOO_FLASH_BWD_BLOCK")
    if env_bwd and bwd_block_q is None and bwd_block_k is None:
        # tuning HINT, not a contract: applied only where it is legal for
        # THIS call — a process can hold models with several seq lengths
        try:
            env_val = int(env_bwd)
        except ValueError:
            raise ValueError(f"ZOO_FLASH_BWD_BLOCK={env_bwd!r}: not an int")
        applicable = (env_val > 0 and env_val % 128 == 0
                      and T % env_val == 0
                      # dropout masks regenerate per (qi, ki) tile — the
                      # backward must match the forward tiling exactly
                      and (not use_dropout
                           or (env_val == block_q and env_val == block_k)))
        if applicable:
            bwd_block_q = bwd_block_k = env_val
    if bwd_block_q is None:
        bwd_block_q = block_q
    if bwd_block_k is None:
        bwd_block_k = block_k
    if use_dropout and (bwd_block_q != block_q or bwd_block_k != block_k):
        # explicit caller-passed mismatch is a programming error
        raise ValueError("flash_attention: in-kernel dropout requires "
                         "bwd blocks == fwd blocks (mask regeneration is "
                         "tile-indexed)")
    if mask is None:
        mask = jnp.zeros((B, 1, 1, T), jnp.float32)
    block = math.lcm(block_q, block_k, bwd_block_q, bwd_block_k)
    if T % block:
        pad = (-T) % block
        qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        maskp = jnp.pad(mask, ((0, 0), (0, 0), (0, 0), (0, pad)),
                        constant_values=-1e9)
        out = flash_attention(qp, kp, vp, maskp, dropout_rate, dropout_seed,
                              block_q, block_k, bwd_block_q, bwd_block_k,
                              interpret)
        return out[:, :, :T]
    seed = jnp.asarray(dropout_seed if use_dropout else 0,
                       jnp.int32).reshape(1, 1)
    rate = float(dropout_rate) if use_dropout else 0.0
    return _flash(q, k, v, mask, seed, rate, block_q, block_k,
                  bwd_block_q, bwd_block_k,
                  bool(interpret) if interpret is not None else False)


# ---------------------------------------------------------------------------
# custom-VJP core (assumes T % lcm(block_q, block_k) == 0, mask [B,1,1,T])
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, mask, seed, rate, block_q, block_k, bwd_block_q,
           bwd_block_k, interpret):
    out, _ = _flash_fwd(q, k, v, mask, seed, rate, block_q, block_k,
                        interpret)
    return out


def _keep_scale(s_ref, rate, n_qb, n_kb, qi, ki, shape):
    """Deterministic per-tile dropout scale: 1/keep where kept, 0 where
    dropped. Identical bits in forward and both backward kernels (the tile
    index folds (bh, qi, ki); prng_seed on this mosaic takes 2 scalars).

    The PRNG is the expensive part (~20 cycles/word on v5e — measured
    45 ms/step across the three kernels at seq 2048 when drawing one
    uint32 per element), so draw one word per FOUR elements and use each
    byte as an independent keep-draw: keep iff byte < t, t =
    round(keep*256), scaled by the exact keep probability t/256 (unbiased;
    rate quantized to 1/256 like `pallas/dropout._u8_dropout`). Which
    byte lands on which column is an arbitrary fixed bijection — the mask
    stays iid Bernoulli and regenerates bit-identically in the backward
    kernels."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh = pl.program_id(0)
    tile = (bh * n_qb + qi) * n_kb + ki
    pltpu.prng_seed(s_ref[0, 0], tile)
    words = pltpu.prng_random_bits((shape[0], shape[1] // 4))
    words = words.astype(jnp.uint32)
    t = _byte_threshold(rate)
    bytes_ = jnp.concatenate(
        [(words >> (8 * j)) & jnp.uint32(0xFF) for j in range(4)], axis=1)
    return jnp.where(bytes_ < jnp.uint32(t), 256.0 / t, 0.0)


def _fwd_kernel(rate, scale, n_qb, n_kb, q_ref, k_ref, v_ref, m_ref, s_ref,
                o_ref, lse_ref, acc_sc, m_sc, l_sc):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        acc_sc[...] = jnp.zeros_like(acc_sc)
        m_sc[...] = jnp.full_like(m_sc, -jnp.inf)
        l_sc[...] = jnp.zeros_like(l_sc)

    qb = q_ref[0]                                          # [bq, D]
    kb = k_ref[0]
    vb = v_ref[0]
    mb = m_ref[0]                                          # [1, bk]
    scores = jnp.dot(qb, kb.T,
                     preferred_element_type=jnp.float32) * scale + mb
    m_prev, l_prev = m_sc[...], l_sc[...]
    m_new = jnp.maximum(m_prev, scores.max(axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(scores - m_new)
    if rate > 0.0:
        p_drop = p * _keep_scale(s_ref, rate, n_qb, n_kb, qi, ki,
                                 (block_q, block_k))
    else:
        p_drop = p
    acc_sc[...] = acc_sc[...] * alpha + jnp.dot(
        p_drop.astype(v_ref.dtype), vb, preferred_element_type=jnp.float32)
    m_sc[...] = m_new
    l_sc[...] = l_prev * alpha + p.sum(axis=1, keepdims=True)

    @pl.when(ki == n_kb - 1)
    def _flush():
        o_ref[0] = (acc_sc[...] / l_sc[...]).astype(o_ref.dtype)
        lse_ref[0] = m_sc[...] + jnp.log(l_sc[...])        # [bq, 1]


def _attn_cost(n_matmuls, q, extra_f32_out_elems=0):
    """Analytic roofline model for one attention kernel over [B,H,T,D]
    (check_pallas_cost lint: HLO cost analysis sees ~0 inside a Mosaic
    call). `n_matmuls` counts the T×T×D matmul-shaped products the
    kernel runs per head (2 flops each); bytes are the O(T·D) streams —
    q/k/v-sized reads and writes — NOT the O(T²) scores, which is the
    IO-aware point of flash attention; exp() is one per score."""
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    bh = B * H
    item = jnp.dtype(q.dtype).itemsize
    streams = 4 + n_matmuls  # rough: q,k,v(+dout...) in, grads/out out
    return pl.CostEstimate(
        flops=2.0 * n_matmuls * bh * T * T * D,
        bytes_accessed=float(bh * T * D * item * streams
                             + extra_f32_out_elems * 4),
        transcendentals=float(bh * T * T))


def _flash_fwd(q, k, v, mask, seed, rate, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = q.shape
    scale = 1.0 / math.sqrt(D)
    n_qb, n_kb = T // block_q, T // block_k
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    mf = jnp.repeat(mask[:, 0, :, :], H, axis=0)           # [B*H, 1, T]

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, rate, scale, n_qb, n_kb),
        grid=(B * H, n_qb, n_kb),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_tpu_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=_attn_cost(2, q,                    # QKᵀ + PV
                                 extra_f32_out_elems=B * H * T),
        interpret=interpret,
    )(qf, kf, vf, mf, seed)
    out = out.reshape(B, H, T, D)
    return out, (q, k, v, mask, seed, out, lse)


def _dq_kernel(rate, scale, n_qb, n_kb, q_ref, k_ref, v_ref, m_ref, s_ref,
               do_ref, lse_ref, delta_ref, dq_ref, dq_sc):
    """Standalone dq (accumulate over ki in scratch): the fallback when
    n_kb is large enough that the fused kernel's per-ki dq partials
    (n_kb × T × D f32 in HBM) would cost real memory — see _flash_bwd."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    qb = q_ref[0]
    kb = k_ref[0]
    vb = v_ref[0]
    mb = m_ref[0]
    dob = do_ref[0]
    lse = lse_ref[0]                                       # [bq, 1]
    delta = delta_ref[0]                                   # [bq, 1]
    pnorm = jnp.exp(jnp.dot(qb, kb.T,
                            preferred_element_type=jnp.float32)
                    * scale + mb - lse)                    # softmax weights
    dw = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
    if rate > 0.0:
        dw = dw * _keep_scale(s_ref, rate, n_qb, n_kb, qi, ki,
                              (block_q, block_k))
    ds = pnorm * (dw - delta)                              # [bq, bk]
    dq_sc[...] += jnp.dot(ds.astype(k_ref.dtype), kb,
                          preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _flush():
        dq_ref[0] = (dq_sc[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(rate, scale, n_qb, n_kb, q_ref, k_ref, v_ref, m_ref, s_ref,
                do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_sc, dv_sc):
    """dk/dv-only companion of _dq_kernel for the large-n_kb fallback."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    qb = q_ref[0]
    kb = k_ref[0]
    vb = v_ref[0]
    mb = m_ref[0]                                          # [1, bk]
    dob = do_ref[0]
    lse = lse_ref[0]                                       # [bq, 1]
    delta = delta_ref[0]
    pnorm = jnp.exp(jnp.dot(qb, kb.T,
                            preferred_element_type=jnp.float32)
                    * scale + mb - lse)                    # [bq, bk]
    dw = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
    if rate > 0.0:
        keep_scale = _keep_scale(s_ref, rate, n_qb, n_kb, qi, ki,
                                 (block_q, block_k))
        dw = dw * keep_scale
        dv_p = pnorm * keep_scale
    else:
        dv_p = pnorm
    ds = pnorm * (dw - delta)
    dk_sc[...] += jnp.dot(ds.T.astype(q_ref.dtype), qb,
                          preferred_element_type=jnp.float32)
    dv_sc[...] += jnp.dot(dv_p.T.astype(do_ref.dtype), dob,
                          preferred_element_type=jnp.float32)

    @pl.when(qi == n_qb - 1)
    def _flush():
        dk_ref[0] = (dk_sc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(rate, scale, n_qb, n_kb, q_ref, k_ref, v_ref, m_ref,
                      s_ref, do_ref, lse_ref, delta_ref, dqp_ref, dk_ref,
                      dv_ref, dk_sc, dv_sc):
    """ONE backward kernel (round-5 fusion): the previous dq/dkv pair each
    recomputed `pnorm` and `dw` — 7 matmuls per tile where 5 suffice (and
    two dropout-mask regenerations where one does). dk/dv accumulate over
    qi exactly as before; dq has the transposed accumulation order, so
    each grid step writes its PARTIAL contribution ds·K to its own
    [ki]-indexed output block (no revisited-output accumulation) and the
    caller reduces the n_kb partials — at 1024-blocks that is a 2-term
    sum, trivially XLA-fused against the matmul that consumes dq."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    block_k = k_ref.shape[1]
    block_q = q_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    qb = q_ref[0]
    kb = k_ref[0]
    vb = v_ref[0]
    mb = m_ref[0]                                          # [1, bk]
    dob = do_ref[0]
    lse = lse_ref[0]                                       # [bq, 1]
    delta = delta_ref[0]
    pnorm = jnp.exp(jnp.dot(qb, kb.T,
                            preferred_element_type=jnp.float32)
                    * scale + mb - lse)                    # [bq, bk]
    dw = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
    if rate > 0.0:
        keep_scale = _keep_scale(s_ref, rate, n_qb, n_kb, qi, ki,
                                 (block_q, block_k))
        dw = dw * keep_scale
        dv_p = pnorm * keep_scale
    else:
        dv_p = pnorm
    ds = pnorm * (dw - delta)
    dqp_ref[0, 0] = jnp.dot(ds.astype(k_ref.dtype), kb,
                            preferred_element_type=jnp.float32)
    dk_sc[...] += jnp.dot(ds.T.astype(q_ref.dtype), qb,
                          preferred_element_type=jnp.float32)
    dv_sc[...] += jnp.dot(dv_p.T.astype(do_ref.dtype), dob,
                          preferred_element_type=jnp.float32)

    @pl.when(qi == n_qb - 1)
    def _flush():
        dk_ref[0] = (dk_sc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _flash_bwd(rate, _fwd_block_q, _fwd_block_k, block_q, block_k, interpret,
               res, dout):
    # _fwd_block_* are unused: mask regeneration derives its tile indices
    # from the bwd blocks, which flash_attention() forces equal to the fwd
    # blocks whenever dropout is active.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q, k, v, mask, seed, out, lse = res
    B, H, T, D = q.shape
    scale = 1.0 / math.sqrt(D)
    n_qb, n_kb = T // block_q, T // block_k
    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    dof = dout.reshape(B * H, T, D)
    mf = jnp.repeat(mask[:, 0, :, :], H, axis=0)
    # delta[i] = rowsum(dO * O) — the softmax-jacobian diagonal term
    delta = jnp.sum(dof.astype(jnp.float32)
                    * out.reshape(B * H, T, D).astype(jnp.float32),
                    axis=-1, keepdims=True)                # [BH, T, 1]

    # Fused single-kernel backward when (a) the dq-partials buffer is
    # cheap (n_kb × T × D f32 per head-batch; ≤4 partials ≈ ≤2 dq-sized
    # f32 buffers) and (b) the tile fits scoped VMEM — the fused kernel
    # holds pnorm/dw/ds (+ the dropout mask) live together, ~19.7 MB of
    # f32 tiles at 1024². Round-5 measured the alternative of raising
    # `vmem_limit_bytes` to 48 MB so 1024² compiles: 12.2 ms bwd vs the
    # two-kernel pair's 9.6 ms at the same tiling (B=16,H=12,T=2048,
    # D=64, all three grads consumed) — that much live VMEM destroys
    # Mosaic's DMA/compute overlap, so the fused form only pays at
    # tiles ≤512k where it measured ~9.0 ms (1024×512). Otherwise fall
    # back to the two-kernel form — its dq accumulates in VMEM scratch
    # with O(T·D) HBM, paying the duplicated pnorm/dw matmuls instead.
    if n_kb <= 4 and block_q * block_k <= 512 * 1024:
        dqp, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, rate, scale, n_qb, n_kb),
            grid=(B * H, n_kb, n_qb),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b, 0, j)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_q, D),
                             lambda b, j, i: (b, j, i, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B * H, n_kb, T, D), jnp.float32),
                jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
                jax.ShapeDtypeStruct((B * H, T, D), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
            compiler_params=_tpu_params(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            # scores, dv, dw, dq-partial, dk matmuls; the dqp partials
            # buffer is an extra n_kb×T×D f32 write stream
            cost_estimate=_attn_cost(5, q,
                                     extra_f32_out_elems=B * H * n_kb
                                     * T * D),
            interpret=interpret,
        )(qf, kf, vf, mf, seed, dof, lse, delta)
        # the transposed-order accumulation, done where it is cheap: n_kb
        # partials summed by XLA (f32), then scaled — bytes ≈ one
        # dq-sized read per partial, noise next to the matmuls it
        # replaced
        dq = (dqp.sum(axis=1) * scale).astype(q.dtype)
    else:
        dq = pl.pallas_call(
            functools.partial(_dq_kernel, rate, scale, n_qb, n_kb),
            grid=(B * H, n_qb, n_kb),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, 1, block_k), lambda b, i, j: (b, 0, j)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, D),
                                   lambda b, i, j: (b, i, 0)),
            out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
            compiler_params=_tpu_params(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            cost_estimate=_attn_cost(3, q),   # scores, dw/ds, dq
            interpret=interpret,
        )(qf, kf, vf, mf, seed, dof, lse, delta)
        dk, dv = pl.pallas_call(
            functools.partial(_dkv_kernel, rate, scale, n_qb, n_kb),
            grid=(B * H, n_kb, n_qb),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, 1, block_k), lambda b, j, i: (b, 0, j)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
                jax.ShapeDtypeStruct((B * H, T, D), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
            ],
            compiler_params=_tpu_params(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            cost_estimate=_attn_cost(4, q),   # scores, dv, ds, dk
            interpret=interpret,
        )(qf, kf, vf, mf, seed, dof, lse, delta)

    shape = (B, H, T, D)
    # padding masks are data, not parameters — zero cotangent
    return (dq.reshape(shape), dk.reshape(shape), dv.reshape(shape),
            jnp.zeros_like(mask), jnp.zeros_like(seed))


def _flash_fwd_rule(q, k, v, mask, seed, rate, block_q, block_k,
                    bwd_block_q, bwd_block_k, interpret):
    return _flash_fwd(q, k, v, mask, seed, rate, block_q, block_k,
                      interpret)


_flash.defvjp(_flash_fwd_rule, _flash_bwd)
