"""Flash attention — Pallas TPU kernel with reference fallback.

The reference has no fused attention at all (its longest-sequence support is
full O(L²) attention on one device, survey §5 long-context note); this module
is part of the beyond-reference long-context capability. The Pallas kernel
tiles Q over the grid and streams K/V blocks through VMEM with online softmax
(the standard flash algorithm, see `/opt/skills/guides/pallas_guide.md`), so
memory is O(block² · heads) instead of O(L²).

`flash_attention` falls back to a jnp implementation when Pallas is
unavailable for the current backend (e.g. CPU tests) — same numerics, no
tiling.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


def _reference_attention(q, k, v, mask=None):
    """Exact O(L²) attention — the shared non-flash numerics (also what
    `keras.transformer.dot_product_attention` delegates to)."""
    depth = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(depth)
    scores = scores.astype(jnp.float32)
    if mask is not None:
        scores = scores + mask
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


def _flash_supported(mask) -> bool:
    """The Pallas kernel runs on TPU and supports padding masks
    ([B,1,1,T]); full [B,1,T,T] masks or other backends use the exact
    reference path (decided statically — no exception-driven fallback)."""
    if jax.default_backend() != "tpu":
        return False
    if mask is not None and mask.ndim == 4 and mask.shape[2] != 1:
        return False
    return True


def flash_attention(q, k, v, mask: Optional[jax.Array] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """q,k,v: [B, H, T, Dh]. mask: additive [B,1,1,T] (padding) or
    [B,1,T,T] (full; reference path only). Returns [B, H, T, Dh]."""
    if not (_flash_supported(mask) or interpret):
        return _reference_attention(q, k, v, mask)
    return _flash_pallas(q, k, v, mask, block_q, block_k, interpret)


def _flash_pallas(q, k, v, mask, block_q, block_k, interpret):
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    block = math.lcm(block_q, block_k)
    if T % block:
        # pad sequence to the lcm of both block sizes with masked-out keys
        pad = (-T) % block
        qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        kp = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        if mask is None:
            mask = jnp.zeros((B, 1, 1, T), jnp.float32)
        maskp = jnp.pad(mask, ((0, 0), (0, 0), (0, 0), (0, pad)),
                        constant_values=-1e9)
        out = _flash_pallas(qp, kp, vp, maskp, block_q, block_k, interpret)
        return out[:, :, :T]

    if mask is None:
        mask = jnp.zeros((B, 1, 1, T), jnp.float32)
    scale = 1.0 / math.sqrt(D)
    n_kb = T // block_k

    def kernel(q_ref, k_ref, v_ref, m_ref, o_ref):
        # One Q block vs all K/V blocks with online softmax; 2D-shaped
        # carries because TPU vector ops want >=2D (pallas_guide.md).
        qb = q_ref[0].astype(jnp.float32) * scale          # [bq, D]
        acc = jnp.zeros((block_q, D), jnp.float32)
        m_i = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
        l_i = jnp.zeros((block_q, 1), jnp.float32)

        def body(s, carry):
            acc, m_i, l_i = carry
            kb = k_ref[0, pl.ds(s * block_k, block_k), :].astype(jnp.float32)
            vb = v_ref[0, pl.ds(s * block_k, block_k), :].astype(jnp.float32)
            mb = m_ref[0, :, pl.ds(s * block_k, block_k)]   # [1, bk]
            scores = qb @ kb.T + mb                         # [bq, bk]
            m_new = jnp.maximum(m_i, scores.max(axis=1, keepdims=True))
            alpha = jnp.exp(m_i - m_new)
            p = jnp.exp(scores - m_new)
            acc = acc * alpha + p @ vb
            l_i = l_i * alpha + p.sum(axis=1, keepdims=True)
            return acc, m_new, l_i

        acc, m_i, l_i = jax.lax.fori_loop(0, n_kb, body, (acc, m_i, l_i))
        o_ref[0] = (acc / l_i).astype(o_ref.dtype)

    qf = q.reshape(B * H, T, D)
    kf = k.reshape(B * H, T, D)
    vf = v.reshape(B * H, T, D)
    mf = jnp.repeat(mask[:, 0, :, :], H, axis=0)            # [B*H, 1, T]

    out = pl.pallas_call(
        kernel,
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, T), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=bool(interpret) if interpret is not None else False,
    )(qf, kf, vf, mf)
    return out.reshape(B, H, T, D)
