"""Autograd DSL: symbolic `Variable` math, `Lambda` layers, `CustomLoss`.

The reference builds a symbolic math DSL over its graph nodes
(`zoo/.../pipeline/api/autograd/math.scala:378` `Variable`,
`autograd/Lambda.scala:49`, `autograd/CustomLoss.scala:66`; python mirror
`pyzoo/zoo/pipeline/api/autograd.py`) so users can write custom ops/losses
without writing a layer. Here every `Variable` op is thin sugar over jax: an
op records a pure jnp function into the same `Node` graph the functional
`Model` API uses; shape inference is `jax.eval_shape` (no hand-written shape
rules to drift). Under jit the whole expression fuses — a Variable DSL loss
costs nothing over hand-written jax.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.keras.engine import Input, Layer, Model, Node


def _infer_shape(fn: Callable, in_shapes: Sequence) -> tuple:
    """Shape inference by abstract evaluation; None batch dims become 1."""
    def dummy(s):
        return jax.ShapeDtypeStruct(
            tuple(1 if d is None else d for d in s), jnp.float32)

    outs = jax.eval_shape(fn, *[dummy(s) for s in in_shapes])
    shape = outs.shape
    # restore the None batch dim if inputs had one
    if in_shapes and in_shapes[0] and in_shapes[0][0] is None and shape:
        shape = (None,) + tuple(shape[1:])
    return tuple(shape)


class LambdaLayer(Layer):
    """A parameterless layer from a pure function (`Lambda.scala:49`)."""

    def __init__(self, function: Callable, **kw):
        super().__init__(**kw)
        self.function = function

    def call(self, params, x, *, training=False, rng=None):
        if isinstance(x, (list, tuple)):
            return self.function(*x)
        return self.function(x)

    def compute_output_shape(self, input_shape):
        shapes = input_shape if isinstance(input_shape, list) else [input_shape]
        return _infer_shape(self.function, shapes)


# keep the pyzoo name
Lambda = LambdaLayer


def pad_lambda(pad_cfg, value: float = 0.0) -> LambdaLayer:
    """A LambdaLayer that jnp.pads with `value` — the one shared padding
    path for the ONNX/Caffe importers' conv and pool mappings."""
    def fn(t, pc=tuple(pad_cfg), v=value):
        import jax.numpy as jnp
        return jnp.pad(t, pc, constant_values=v)
    return LambdaLayer(fn)


class Variable:
    """Symbolic tensor with math operators (`math.scala:378`). Wraps a graph
    Node; interchangeable with Keras functional-API nodes."""

    def __init__(self, input_shape=None, node: Optional[Node] = None,
                 name: Optional[str] = None):
        if node is not None:
            self.node = node
        elif input_shape is not None:
            self.node = Input(shape=tuple(input_shape), name=name)
        else:
            raise ValueError("Variable needs input_shape or node")

    @property
    def shape(self):
        return self.node.shape

    # -- op plumbing -------------------------------------------------------
    @staticmethod
    def _lift(fn: Callable, *vs: "Variable", name: str = "op") -> "Variable":
        layer = LambdaLayer(fn, name=None)
        layer.name = layer.name.replace("lambdalayer", name)
        nodes = [v.node for v in vs]
        out = layer(nodes if len(nodes) > 1 else nodes[0])
        return Variable(node=out)

    def _binop(self, other, fn, name):
        if isinstance(other, Variable):
            return Variable._lift(fn, self, other, name=name)
        const = other
        return Variable._lift(lambda a: fn(a, const), self, name=name)

    def _rbinop(self, other, fn, name):
        const = other
        return Variable._lift(lambda a: fn(const, a), self, name=name)

    # -- operators ---------------------------------------------------------
    def __add__(self, other):
        return self._binop(other, lambda a, b: a + b, "add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, lambda a, b: a - b, "sub")

    def __rsub__(self, other):
        return self._rbinop(other, lambda a, b: a - b, "rsub")

    def __mul__(self, other):
        return self._binop(other, lambda a, b: a * b, "mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, lambda a, b: a / b, "div")

    def __rtruediv__(self, other):
        return self._rbinop(other, lambda a, b: a / b, "rdiv")

    def __pow__(self, p):
        return self._binop(p, lambda a, b: a ** b, "pow")

    def __neg__(self):
        return Variable._lift(lambda a: -a, self, name="neg")

    def __getitem__(self, idx):
        return Variable._lift(lambda a: a[idx], self, name="slice")


# ---------------------------------------------------------------------------
# Module-level math functions (`pyzoo/zoo/pipeline/api/autograd.py` surface)
# ---------------------------------------------------------------------------
def _unary(fn, name):
    def op(v: Variable) -> Variable:
        return Variable._lift(fn, v, name=name)
    op.__name__ = name
    return op


abs = _unary(jnp.abs, "abs")          # noqa: A001
square = _unary(jnp.square, "square")
sqrt = _unary(jnp.sqrt, "sqrt")
exp = _unary(jnp.exp, "exp")
log = _unary(jnp.log, "log")
neg = _unary(lambda a: -a, "neg")
erf = _unary(jax.lax.erf, "erf")
softsign = _unary(jax.nn.soft_sign, "softsign")
softplus = _unary(jax.nn.softplus, "softplus")


def sum(v: Variable, axis: int = 0, keepdims: bool = False) -> Variable:  # noqa: A001
    """Reference semantics (`autograd.py` sum): axis counts non-batch dims?
    The pyzoo surface passes the raw axis; we keep jnp semantics."""
    return Variable._lift(
        lambda a: jnp.sum(a, axis=axis, keepdims=keepdims), v, name="sum")


def mean(v: Variable, axis: int = 0, keepdims: bool = False) -> Variable:
    return Variable._lift(
        lambda a: jnp.mean(a, axis=axis, keepdims=keepdims), v, name="mean")


def clip(v: Variable, min: float, max: float) -> Variable:  # noqa: A002
    return Variable._lift(lambda a: jnp.clip(a, min, max), v, name="clip")


def pow(v: Variable, a: float) -> Variable:  # noqa: A001
    return v ** a


def maximum(a: Variable, b) -> Variable:
    if isinstance(b, Variable):
        return Variable._lift(jnp.maximum, a, b, name="maximum")
    return Variable._lift(lambda x: jnp.maximum(x, b), a, name="maximum")


def mm(x: Variable, y: Variable, axes: Optional[Sequence[int]] = None
       ) -> Variable:
    """Batched matmul contracting the given axes (`autograd.py mm`)."""
    if axes is None:
        return Variable._lift(jnp.matmul, x, y, name="mm")
    ax, ay = axes

    def fn(a, b):
        return jax.lax.dot_general(
            a, b, dimension_numbers=(((ax,), (ay,)), ((0,), (0,))))
    return Variable._lift(fn, x, y, name="mm")


def dot(x: Variable, y: Variable, axes=None, normalize: bool = False
        ) -> Variable:
    def fn(a, b):
        if normalize:
            a = a / jnp.clip(jnp.linalg.norm(a, axis=-1, keepdims=True),
                             1e-7, None)
            b = b / jnp.clip(jnp.linalg.norm(b, axis=-1, keepdims=True),
                             1e-7, None)
        return jnp.sum(a * b, axis=-1, keepdims=True)
    return Variable._lift(fn, x, y, name="dot")


def softmax(v: Variable, axis: int = -1) -> Variable:
    return Variable._lift(lambda a: jax.nn.softmax(a, axis=axis), v,
                          name="softmax")


def expand_dims(v: Variable, axis: int) -> Variable:
    return Variable._lift(lambda a: jnp.expand_dims(a, axis), v,
                          name="expand_dims")


def squeeze(v: Variable, axis: int) -> Variable:
    return Variable._lift(lambda a: jnp.squeeze(a, axis), v, name="squeeze")


def stack(vs: Sequence[Variable], axis: int = 1) -> Variable:
    return Variable._lift(lambda *xs: jnp.stack(xs, axis=axis), *vs,
                          name="stack")


def concatenate(vs: Sequence[Variable], axis: int = -1) -> Variable:
    return Variable._lift(lambda *xs: jnp.concatenate(xs, axis=axis), *vs,
                          name="concat")


# ---------------------------------------------------------------------------
# CustomLoss (`CustomLoss.scala:66`, pyzoo CustomLoss)
# ---------------------------------------------------------------------------
class CustomLoss:
    """Build a loss objective from a Variable expression over
    (y_true, y_pred) placeholders:

    >>> y_true = Variable(input_shape=(3,))
    >>> y_pred = Variable(input_shape=(3,))
    >>> loss = CustomLoss(mean(square(y_true - y_pred), axis=1), y_true, y_pred)
    >>> model.compile("adam", loss)
    """

    def __init__(self, loss_var: Variable, y_true: Variable,
                 y_pred: Variable):
        self._model = Model([y_true.node, y_pred.node], loss_var.node)
        self._params = self._model.build(jax.random.PRNGKey(0))

    def __call__(self, y_true, y_pred):
        out = self._model.apply(self._params, [y_true, y_pred])
        return jnp.mean(out)


def custom_loss_from_fn(fn: Callable) -> Callable:
    """Wrap a plain jax fn(y_true, y_pred)->scalar as a loss (the TPU-native
    shortcut the DSL compiles down to anyway)."""
    return fn

