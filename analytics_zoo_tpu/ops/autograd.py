"""Autograd DSL: symbolic `Variable` math, `Lambda` layers, `CustomLoss`.

The reference builds a symbolic math DSL over its graph nodes
(`zoo/.../pipeline/api/autograd/math.scala:378` `Variable`,
`autograd/Lambda.scala:49`, `autograd/CustomLoss.scala:66`; python mirror
`pyzoo/zoo/pipeline/api/autograd.py`) so users can write custom ops/losses
without writing a layer. Here every `Variable` op is thin sugar over jax: an
op records a pure jnp function into the same `Node` graph the functional
`Model` API uses; shape inference is `jax.eval_shape` (no hand-written shape
rules to drift). Under jit the whole expression fuses — a Variable DSL loss
costs nothing over hand-written jax.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.keras.engine import Input, Layer, Model, Node


def _infer_shape(fn: Callable, in_shapes: Sequence) -> tuple:
    """Shape inference by abstract evaluation; None batch dims become 1."""
    def dummy(s):
        return jax.ShapeDtypeStruct(
            tuple(1 if d is None else d for d in s), jnp.float32)

    outs = jax.eval_shape(fn, *[dummy(s) for s in in_shapes])
    shape = outs.shape
    # restore the None batch dim if any input had one (Parameter/Constant
    # sources have fully-concrete shapes and broadcast against the batch)
    if shape and any(s and s[0] is None for s in in_shapes):
        shape = (None,) + tuple(shape[1:])
    return tuple(shape)


class LambdaLayer(Layer):
    """A parameterless layer from a pure function (`Lambda.scala:49`)."""

    def __init__(self, function: Callable, **kw):
        super().__init__(**kw)
        self.function = function

    def call(self, params, x, *, training=False, rng=None):
        if isinstance(x, (list, tuple)):
            return self.function(*x)
        return self.function(x)

    def compute_output_shape(self, input_shape):
        shapes = input_shape if isinstance(input_shape, list) else [input_shape]
        return _infer_shape(self.function, shapes)


# keep the pyzoo name
Lambda = LambdaLayer


def pad_lambda(pad_cfg, value: float = 0.0) -> LambdaLayer:
    """A LambdaLayer that jnp.pads with `value` — the one shared padding
    path for the ONNX/Caffe importers' conv and pool mappings."""
    def fn(t, pc=tuple(pad_cfg), v=value):
        import jax.numpy as jnp
        return jnp.pad(t, pc, constant_values=v)
    return LambdaLayer(fn)


class Variable:
    """Symbolic tensor with math operators (`math.scala:378`). Wraps a graph
    Node; interchangeable with Keras functional-API nodes."""

    def __init__(self, input_shape=None, node: Optional[Node] = None,
                 name: Optional[str] = None):
        if node is not None:
            self.node = node
        elif input_shape is not None:
            self.node = Input(shape=tuple(input_shape), name=name)
        else:
            raise ValueError("Variable needs input_shape or node")

    @property
    def shape(self):
        return self.node.shape

    # -- op plumbing -------------------------------------------------------
    @staticmethod
    def _lift(fn: Callable, *vs: "Variable", name: str = "op") -> "Variable":
        layer = LambdaLayer(fn, name=None)
        layer.name = layer.name.replace("lambdalayer", name)
        nodes = [v.node for v in vs]
        out = layer(nodes if len(nodes) > 1 else nodes[0])
        return Variable(node=out)

    def _binop(self, other, fn, name):
        if isinstance(other, Variable):
            return Variable._lift(fn, self, other, name=name)
        const = other
        return Variable._lift(lambda a: fn(a, const), self, name=name)

    def _rbinop(self, other, fn, name):
        const = other
        return Variable._lift(lambda a: fn(const, a), self, name=name)

    # -- operators ---------------------------------------------------------
    def __add__(self, other):
        return self._binop(other, lambda a, b: a + b, "add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binop(other, lambda a, b: a - b, "sub")

    def __rsub__(self, other):
        return self._rbinop(other, lambda a, b: a - b, "rsub")

    def __mul__(self, other):
        return self._binop(other, lambda a, b: a * b, "mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binop(other, lambda a, b: a / b, "div")

    def __rtruediv__(self, other):
        return self._rbinop(other, lambda a, b: a / b, "rdiv")

    def __pow__(self, p):
        return self._binop(p, lambda a, b: a ** b, "pow")

    def __neg__(self):
        return Variable._lift(lambda a: -a, self, name="neg")

    def __getitem__(self, idx):
        return Variable._lift(lambda a: a[idx], self, name="slice")

    def _resolve_nonbatch_dim(self, dim: int, op: str) -> int:
        """Normalize `dim` against this variable's rank and reject the batch
        dimension (the reference contract for slice/index_select)."""
        rank = len(self.shape)
        if not -rank <= dim < rank:
            raise ValueError(f"{op}: dim {dim} out of range for rank {rank}")
        d = dim % rank
        if d == 0 and self.shape[0] is None:
            raise ValueError(f"Cannot {op} the batch dimension")
        return d

    # -- torch-style narrowing (`autograd.py:317,340`) ---------------------
    def slice(self, dim: int, start_index: int, length: int = 1) -> "Variable":
        """Narrow `dim` to [start_index, start_index+length) without reducing
        rank; length=-1 runs to the end. dim counts the batch dim (0), which
        cannot be narrowed — matching the reference contract."""
        d = self._resolve_nonbatch_dim(dim, "slice")

        def fn(a, d=d, s=start_index, l=length):
            ln = a.shape[d] - s if l == -1 else l
            return jax.lax.slice_in_dim(a, s, s + ln, axis=d)
        return Variable._lift(fn, self, name="slice")

    def index_select(self, dim: int, index: int) -> "Variable":
        """Select one index along `dim`, removing that dim (-1 selects the
        last position). The batch dim cannot be selected."""
        d = self._resolve_nonbatch_dim(dim, "index_select")
        size = self.shape[d]
        if size is not None and not -size <= index < size:
            raise IndexError(
                f"index_select: index {index} out of range for dim {dim} "
                f"of size {size}")

        def fn(a, d=d, i=index):
            return jnp.take(a, i % a.shape[d], axis=d)
        return Variable._lift(fn, self, name="index_select")

    def squeeze(self, dim: Optional[int] = None) -> "Variable":
        """Delete singleton dim(s). With dim=None all non-batch singleton
        dims are removed (the dynamic batch dim is never squeezed — a dummy
        batch of 1 must not change the graph's rank)."""
        if dim is not None:
            d = self._resolve_nonbatch_dim(dim, "squeeze")
            return Variable._lift(lambda a: jnp.squeeze(a, d), self,
                                  name="squeeze")

        def fn(a):
            axes = tuple(i for i in range(1, a.ndim) if a.shape[i] == 1)
            return jnp.squeeze(a, axes) if axes else a
        return Variable._lift(fn, self, name="squeeze")


# ---------------------------------------------------------------------------
# Module-level math functions (`pyzoo/zoo/pipeline/api/autograd.py` surface)
# ---------------------------------------------------------------------------
def _unary(fn, name):
    def op(v: Variable) -> Variable:
        return Variable._lift(fn, v, name=name)
    op.__name__ = name
    return op


abs = _unary(jnp.abs, "abs")          # noqa: A001
square = _unary(jnp.square, "square")
sqrt = _unary(jnp.sqrt, "sqrt")
exp = _unary(jnp.exp, "exp")
log = _unary(jnp.log, "log")
neg = _unary(lambda a: -a, "neg")
erf = _unary(jax.lax.erf, "erf")
softsign = _unary(jax.nn.soft_sign, "softsign")
softplus = _unary(jax.nn.softplus, "softplus")


def sum(v: Variable, axis: int = 0, keepdims: bool = False) -> Variable:  # noqa: A001
    """Reference semantics (`autograd.py` sum): axis counts non-batch dims?
    The pyzoo surface passes the raw axis; we keep jnp semantics."""
    return Variable._lift(
        lambda a: jnp.sum(a, axis=axis, keepdims=keepdims), v, name="sum")


def mean(v: Variable, axis: int = 0, keepdims: bool = False) -> Variable:
    return Variable._lift(
        lambda a: jnp.mean(a, axis=axis, keepdims=keepdims), v, name="mean")


def clip(v: Variable, min: float, max: float) -> Variable:  # noqa: A002
    return Variable._lift(lambda a: jnp.clip(a, min, max), v, name="clip")


def pow(v: Variable, a: float) -> Variable:  # noqa: A001
    return v ** a


def maximum(a: Variable, b) -> Variable:
    if isinstance(b, Variable):
        return Variable._lift(jnp.maximum, a, b, name="maximum")
    return Variable._lift(lambda x: jnp.maximum(x, b), a, name="maximum")


def mm(x: Variable, y: Variable, axes: Optional[Sequence[int]] = None
       ) -> Variable:
    """Batched matmul contracting the given axes (`autograd.py mm`)."""
    if axes is None:
        return Variable._lift(jnp.matmul, x, y, name="mm")
    ax, ay = axes

    def fn(a, b):
        return jax.lax.dot_general(
            a, b, dimension_numbers=(((ax,), (ay,)), ((0,), (0,))))
    return Variable._lift(fn, x, y, name="mm")


def dot(x: Variable, y: Variable, axes=None, normalize: bool = False
        ) -> Variable:
    def fn(a, b):
        if normalize:
            a = a / jnp.clip(jnp.linalg.norm(a, axis=-1, keepdims=True),
                             1e-7, None)
            b = b / jnp.clip(jnp.linalg.norm(b, axis=-1, keepdims=True),
                             1e-7, None)
        return jnp.sum(a * b, axis=-1, keepdims=True)
    return Variable._lift(fn, x, y, name="dot")


def l2_normalize(v: Variable, axis: int) -> Variable:
    """Normalize wrt the L2 norm along `axis` (`autograd.py:80`
    l2_normalize). Uses the TF epsilon (1e-12) under the root."""
    def fn(a):
        sq = jnp.sum(jnp.square(a), axis=axis, keepdims=True)
        return a * jax.lax.rsqrt(jnp.maximum(sq, 1e-12))
    return Variable._lift(fn, v, name="l2_normalize")


def slice(v: Variable, dim: int, start_index: int, length: int = 1  # noqa: A001
          ) -> Variable:
    return v.slice(dim, start_index, length)


def index_select(v: Variable, dim: int, index: int) -> Variable:
    return v.index_select(dim, index)


def softmax(v: Variable, axis: int = -1) -> Variable:
    return Variable._lift(lambda a: jax.nn.softmax(a, axis=axis), v,
                          name="softmax")


def expand_dims(v: Variable, axis: int) -> Variable:
    return Variable._lift(lambda a: jnp.expand_dims(a, axis), v,
                          name="expand_dims")


def squeeze(v: Variable, axis: Optional[int] = None) -> Variable:
    return v.squeeze(axis)  # batch-dim-safe method semantics


def stack(vs: Sequence[Variable], axis: int = 1) -> Variable:
    return Variable._lift(lambda *xs: jnp.stack(xs, axis=axis), *vs,
                          name="stack")


def concatenate(vs: Sequence[Variable], axis: int = -1) -> Variable:
    return Variable._lift(lambda *xs: jnp.concatenate(xs, axis=axis), *vs,
                          name="concat")


# ---------------------------------------------------------------------------
# Parameter / Constant (`pyzoo/zoo/pipeline/api/autograd.py:462,524`)
# ---------------------------------------------------------------------------
class ParameterLayer(Layer):
    """Zero-input source layer holding one trainable tensor. Default init is
    RandomUniform(-0.05, 0.05), matching the reference's default
    (`autograd.py:462` Parameter docstring)."""

    def __init__(self, shape: Sequence[int], init_weight=None,
                 trainable: bool = True, init_range: float = 0.05, **kw):
        super().__init__(**kw)
        self.pshape = tuple(int(d) for d in shape)
        self.init_weight = init_weight
        self.trainable = trainable
        self.init_range = init_range

    def build(self, rng, input_shape):
        if self.init_weight is not None:
            val = jnp.asarray(self.init_weight, jnp.float32)
            if val.shape != self.pshape:
                raise ValueError(
                    f"init_weight shape {val.shape} != Parameter shape "
                    f"{self.pshape}")
        else:
            val = jax.random.uniform(
                rng, self.pshape, jnp.float32,
                -self.init_range, self.init_range)
        return {"value": val}

    def call(self, params, x, *, training=False, rng=None):
        v = params["value"]
        return v if self.trainable else jax.lax.stop_gradient(v)

    def compute_output_shape(self, input_shape):
        return self.pshape


class Parameter(Variable):
    """A trainable standalone Variable (`autograd.py:462`). Usable anywhere
    in a functional graph / Variable expression; its value lives in the
    enclosing model's param tree under this Parameter's name, so it is
    updated by the optimizer like any layer weight.

    Functional-core deviation from the reference: `get_weight`/`set_weight`
    operate on an explicit params tree (the reference mutates JVM state).
    Before build, `set_weight` replaces the init value.
    """

    def __init__(self, shape: Sequence[int], init_weight=None,
                 trainable: bool = True, name: Optional[str] = None):
        layer = ParameterLayer(shape, init_weight=init_weight,
                               trainable=trainable, name=name)
        # zero-input source node (Layer.__call__ requires inputs)
        super().__init__(node=Node(layer=layer, inputs=[],
                                   shape=layer.pshape))
        self._layer = layer

    @property
    def name(self) -> str:
        return self._layer.name

    def get_weight(self, params=None):
        """Current value: from `params` (a built model's tree) if given,
        else the init value."""
        if params is not None:
            return params[self.name]["value"]
        return self._layer.init_weight

    def set_weight(self, value, params=None):
        """With `params`, return a new tree with this Parameter replaced;
        without, set the init value used at the next build."""
        value = jnp.asarray(value, jnp.float32)
        if value.shape != self._layer.pshape:
            raise ValueError(
                f"set_weight shape {value.shape} != Parameter shape "
                f"{self._layer.pshape}")
        if params is not None:
            new = dict(params)
            new[self.name] = {"value": value}
            return new
        self._layer.init_weight = value
        return None


class ConstantLayer(Layer):
    """Zero-input source layer emitting a captured constant (folded by
    jit)."""

    def __init__(self, data, **kw):
        super().__init__(**kw)
        self.data = jnp.asarray(data, jnp.float32)

    def call(self, params, x, *, training=False, rng=None):
        return self.data

    def compute_output_shape(self, input_shape):
        return tuple(self.data.shape)


class Constant(Variable):
    """A constant Variable without weights (`autograd.py:524`)."""

    def __init__(self, data, name: Optional[str] = None):
        layer = ConstantLayer(data, name=name)
        super().__init__(node=Node(layer=layer, inputs=[],
                                   shape=tuple(layer.data.shape)))


# ---------------------------------------------------------------------------
# CustomLoss (`CustomLoss.scala:66`, pyzoo CustomLoss)
# ---------------------------------------------------------------------------
class CustomLoss:
    """Build a loss objective from a Variable expression over
    (y_true, y_pred) placeholders:

    >>> y_true = Variable(input_shape=(3,))
    >>> y_pred = Variable(input_shape=(3,))
    >>> loss = CustomLoss(mean(square(y_true - y_pred), axis=1), y_true, y_pred)
    >>> model.compile("adam", loss)
    """

    def __init__(self, loss_var: Variable, y_true: Variable,
                 y_pred: Variable):
        self._model = Model([y_true.node, y_pred.node], loss_var.node)
        self._params = self._model.build(jax.random.PRNGKey(0))

    def __call__(self, y_true, y_pred):
        out = self._model.apply(self._params, [y_true, y_pred])
        return jnp.mean(out)


def custom_loss_from_fn(fn: Callable) -> Callable:
    """Wrap a plain jax fn(y_true, y_pred)->scalar as a loss (the TPU-native
    shortcut the DSL compiles down to anyway)."""
    return fn

