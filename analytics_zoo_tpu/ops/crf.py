"""Linear-chain CRF ops (sequence tagging).

The reference's NER model tags through nlp-architect's CRF layer
(`pyzoo/zoo/tfpark/text/keras/ner.py:21`, crf_mode 'reg'/'pad'). Here the
CRF is two pure functions over emission scores — both `lax.scan`s, so they
jit and batch on TPU:

- `crf_log_likelihood`: forward-algorithm partition function → exact
  sequence log-likelihood (training loss = its negation).
- `viterbi_decode`: max-product dynamic program → best tag path.

Shapes: emissions [B, T, K], tags [B, T] int, transitions [K, K]
(transitions[i, j] = score of moving from tag i to tag j), optional mask
[B, T] (1 = real step) for 'pad' mode.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _score_sequence(emissions, tags, transitions, mask):
    """Unnormalized score of the given tag path."""
    B, T, K = emissions.shape
    emit = jnp.take_along_axis(emissions, tags[..., None],
                               axis=2)[..., 0]          # [B, T]
    trans = transitions[tags[:, :-1], tags[:, 1:]]      # [B, T-1]
    emit_score = jnp.sum(emit * mask, axis=1)
    trans_score = jnp.sum(trans * mask[:, 1:], axis=1)
    return emit_score + trans_score


def _log_partition(emissions, transitions, mask):
    """Forward algorithm over time (scan), masked steps pass through."""
    B, T, K = emissions.shape

    def step(alpha, inputs):
        emit_t, mask_t = inputs                          # [B, K], [B]
        # alpha[b, i] + transitions[i, j] + emit[b, j] → logsumexp over i
        scores = alpha[:, :, None] + transitions[None] + emit_t[:, None, :]
        new_alpha = jax.scipy.special.logsumexp(scores, axis=1)
        alpha = jnp.where(mask_t[:, None] > 0, new_alpha, alpha)
        return alpha, None

    alpha0 = emissions[:, 0]
    xs = (jnp.swapaxes(emissions[:, 1:], 0, 1),
          jnp.swapaxes(mask[:, 1:], 0, 1))
    alpha, _ = jax.lax.scan(step, alpha0, xs)
    return jax.scipy.special.logsumexp(alpha, axis=1)    # [B]


def crf_log_likelihood(emissions, tags, transitions,
                       mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Per-sequence log p(tags | emissions); negate for the loss."""
    emissions = jnp.asarray(emissions)
    tags = jnp.asarray(tags, jnp.int32)
    if mask is None:
        mask = jnp.ones(tags.shape, emissions.dtype)
    else:
        mask = jnp.asarray(mask, emissions.dtype)
    score = _score_sequence(emissions, tags, transitions, mask)
    log_z = _log_partition(emissions, transitions, mask)
    return score - log_z


def crf_loss(emissions, tags, transitions,
             mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mean negative log-likelihood (training objective)."""
    return -jnp.mean(crf_log_likelihood(emissions, tags, transitions, mask))


def viterbi_decode(emissions, transitions,
                   mask: Optional[jnp.ndarray] = None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Best path per sequence → (tags [B, T], score [B]). Masked (padded)
    steps repeat the last real tag."""
    emissions = jnp.asarray(emissions)
    B, T, K = emissions.shape
    if mask is None:
        mask = jnp.ones((B, T), emissions.dtype)
    else:
        mask = jnp.asarray(mask, emissions.dtype)

    def fwd(carry, inputs):
        delta = carry                                     # [B, K]
        emit_t, mask_t = inputs
        scores = delta[:, :, None] + transitions[None]    # [B, K, K]
        best_prev = jnp.argmax(scores, axis=1)            # [B, K]
        new_delta = jnp.max(scores, axis=1) + emit_t
        delta = jnp.where(mask_t[:, None] > 0, new_delta, delta)
        # for masked steps the backpointer is the identity
        best_prev = jnp.where(mask_t[:, None] > 0, best_prev,
                              jnp.arange(K)[None, :])
        return delta, best_prev

    delta0 = emissions[:, 0]
    xs = (jnp.swapaxes(emissions[:, 1:], 0, 1),
          jnp.swapaxes(mask[:, 1:], 0, 1))
    delta, backptrs = jax.lax.scan(fwd, delta0, xs)       # [T-1, B, K]

    last = jnp.argmax(delta, axis=1)                      # [B]
    score = jnp.max(delta, axis=1)

    def back(carry, bp_t):
        tag = carry                                       # [B]
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first, rev_tags = jax.lax.scan(back, last, backptrs, reverse=True)
    tags = jnp.concatenate([first[None], rev_tags], axis=0)   # [T, B]
    return jnp.swapaxes(tags, 0, 1), score
