from analytics_zoo_tpu.ops import objectives  # noqa: F401
from analytics_zoo_tpu.ops import metrics  # noqa: F401
from analytics_zoo_tpu.ops import optimizers  # noqa: F401
