"""Optimizers and LR schedules as optax transforms, with the reference's
compile-string registry.

Mirrors `KerasUtils.toBigDLOptimMethod` (`KerasUtils.scala:207-216`) — same
strings, same default hyperparameters — plus the Zoo-specific methods:
`AdamWeightDecay` (BERT-style decoupled weight decay with linear warmup then
linear decay, `keras/optimizers/AdamWeightDecay.scala:30-133`), `PolyEpochDecay`
(`keras/optimizers/Adam.scala:141`), and the `Fixed` schedule
(`common/Optim.scala:29`). On TPU an optimizer is a pure
`optax.GradientTransformation`; its state lives sharded alongside the
parameters under pjit, which subsumes the reference's slice-local optimizer
state (`docs/docs/wp-bigdl.md:150-166`).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import optax


# ---------------------------------------------------------------------------
# Schedules
# ---------------------------------------------------------------------------
def warmup_linear_decay(lr: float, total_steps: int,
                        warmup_portion: float = -1.0) -> optax.Schedule:
    """The reference's `warmupMethod` (`AdamWeightDecay.scala:54-58,117`):
    with x = step/total, lr_factor = x/warmup while x < warmup, else 1 - x
    (linear decay to zero at `total`). warmup_portion=-1 → no warmup, constant.
    """
    if warmup_portion is None or warmup_portion < 0:
        return optax.constant_schedule(lr)
    warmup_steps = max(int(total_steps * warmup_portion), 1)

    def schedule(step):
        x = step / total_steps
        import jax.numpy as jnp
        return lr * jnp.where(x < warmup_portion,
                              x / warmup_portion,
                              1.0 - x)
    return schedule


def poly_epoch_decay(lr: float, power: float, max_epochs: int,
                     steps_per_epoch: int) -> optax.Schedule:
    """`PolyEpochDecay` (`Adam.scala:141-151`): lr * (1 - epoch/maxEpochs)^power,
    epoch-granular."""
    def schedule(step):
        import jax.numpy as jnp
        epoch = jnp.minimum(step // steps_per_epoch, max_epochs)
        return lr * (1.0 - epoch / max_epochs) ** power
    return schedule


def fixed(lr: float) -> optax.Schedule:
    """`Fixed` schedule (`common/Optim.scala:29`)."""
    return optax.constant_schedule(lr)


# ---------------------------------------------------------------------------
# Optimizers
# ---------------------------------------------------------------------------
def adam_weight_decay(lr: float = 1e-3,
                      warmup_portion: float = -1.0,
                      total_steps: int = -1,
                      schedule: str = "linear",
                      beta1: float = 0.9,
                      beta2: float = 0.999,
                      epsilon: float = 1e-6,
                      weight_decay: float = 0.01,
                      mask: Optional[Any] = None) -> optax.GradientTransformation:
    """BERT AdamWeightDecay (`AdamWeightDecay.scala:40-52` defaults): decoupled
    weight decay 0.01, eps 1e-6, linear warmup over `warmup_portion` of
    `total_steps` then linear decay to zero."""
    if schedule != "linear":
        raise ValueError(f"Unsupported warmup schedule: {schedule}")
    if total_steps > 0:
        sched = warmup_linear_decay(lr, total_steps, warmup_portion)
    else:
        sched = optax.constant_schedule(lr)
    return optax.adamw(sched, b1=beta1, b2=beta2, eps=epsilon,
                       weight_decay=weight_decay, mask=mask)


# Registry — exact strings + defaults of `KerasUtils.toBigDLOptimMethod`
# (`KerasUtils.scala:207-216`).
_REGISTRY: Dict[str, Callable[[], optax.GradientTransformation]] = {
    "sgd": lambda: optax.sgd(learning_rate=0.01),
    "rmsprop": lambda: optax.rmsprop(learning_rate=0.001, decay=0.9),
    "adamax": lambda: optax.adamax(learning_rate=0.002, eps=1e-8),
    "adagrad": lambda: optax.adagrad(learning_rate=0.01),
    "adadelta": lambda: optax.adadelta(learning_rate=1.0, rho=0.95, eps=1e-8),
    "adam": lambda: optax.adam(learning_rate=0.001),
    "adamw": lambda: adam_weight_decay(),
    "adam_weight_decay": lambda: adam_weight_decay(),
}


def get(optimizer: Any) -> optax.GradientTransformation:
    """Resolve an optimizer compile string (or pass a GradientTransformation
    through). Unknown strings raise, matching the reference."""
    if isinstance(optimizer, optax.GradientTransformation):
        return optimizer
    key = str(optimizer).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unsupported optimizer: {optimizer}")
    return _REGISTRY[key]()
