"""Optimizers and LR schedules as optax transforms, with the reference's
compile-string registry.

Mirrors `KerasUtils.toBigDLOptimMethod` (`KerasUtils.scala:207-216`) — same
strings, same default hyperparameters — plus the Zoo-specific methods:
`AdamWeightDecay` (BERT-style decoupled weight decay with linear warmup then
linear decay, `keras/optimizers/AdamWeightDecay.scala:30-133`), `PolyEpochDecay`
(`keras/optimizers/Adam.scala:141`), and the `Fixed` schedule
(`common/Optim.scala:29`). On TPU an optimizer is a pure
`optax.GradientTransformation`; its state lives sharded alongside the
parameters under pjit, which subsumes the reference's slice-local optimizer
state (`docs/docs/wp-bigdl.md:150-166`).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, NamedTuple, Optional

import optax

log = logging.getLogger("analytics_zoo_tpu.ops")


# ---------------------------------------------------------------------------
# Schedules
# ---------------------------------------------------------------------------
def warmup_linear_decay(lr: float, total_steps: int,
                        warmup_portion: float = -1.0) -> optax.Schedule:
    """The reference's `warmupMethod` (`AdamWeightDecay.scala:54-58,117`):
    with x = step/total, lr_factor = x/warmup while x < warmup, else 1 - x
    (linear decay to zero at `total`). warmup_portion=-1 → no warmup, constant.
    """
    if warmup_portion is None or warmup_portion < 0:
        return optax.constant_schedule(lr)
    warmup_steps = max(int(total_steps * warmup_portion), 1)

    def schedule(step):
        x = step / total_steps
        import jax.numpy as jnp
        return lr * jnp.where(x < warmup_portion,
                              x / warmup_portion,
                              1.0 - x)
    return schedule


def poly_epoch_decay(lr: float, power: float, max_epochs: int,
                     steps_per_epoch: int) -> optax.Schedule:
    """`PolyEpochDecay` (`Adam.scala:141-151`): lr * (1 - epoch/maxEpochs)^power,
    epoch-granular."""
    def schedule(step):
        import jax.numpy as jnp
        epoch = jnp.minimum(step // steps_per_epoch, max_epochs)
        return lr * (1.0 - epoch / max_epochs) ** power
    return schedule


def fixed(lr: float) -> optax.Schedule:
    """`Fixed` schedule (`common/Optim.scala:29`)."""
    return optax.constant_schedule(lr)


# ---------------------------------------------------------------------------
# Optimizers
# ---------------------------------------------------------------------------
def adam_weight_decay(lr: float = 1e-3,
                      warmup_portion: float = -1.0,
                      total_steps: int = -1,
                      schedule: str = "linear",
                      beta1: float = 0.9,
                      beta2: float = 0.999,
                      epsilon: float = 1e-6,
                      weight_decay: float = 0.01,
                      mask: Optional[Any] = None) -> optax.GradientTransformation:
    """BERT AdamWeightDecay (`AdamWeightDecay.scala:40-52` defaults): decoupled
    weight decay 0.01, eps 1e-6, linear warmup over `warmup_portion` of
    `total_steps` then linear decay to zero."""
    if schedule != "linear":
        raise ValueError(f"Unsupported warmup schedule: {schedule}")
    if total_steps > 0:
        sched = warmup_linear_decay(lr, total_steps, warmup_portion)
    else:
        sched = optax.constant_schedule(lr)
    return optax.adamw(sched, b1=beta1, b2=beta2, eps=epsilon,
                       weight_decay=weight_decay, mask=mask)


# ---------------------------------------------------------------------------
# Fused-kernel optimizer (ISSUE 9): the one-HBM-pass Adam sweep
# ---------------------------------------------------------------------------
class FusedAdamState(NamedTuple):
    """Mirrors `optax.ScaleByAdamState` field-for-field (count, mu, nu)
    so sharding rule tables and checkpoint layouts treat the fused
    state exactly like the stock Adam state: the mu/nu trees flatten
    with paths ending in each parameter's path, so
    `parallel.sharding.tree_shardings` mirrors the param specs onto
    the moments and replicates the scalar count."""

    count: Any
    mu: Any
    nu: Any


class FusedGradientTransformation(NamedTuple):
    """An optax-shaped (init, update) pair plus the fused fast path.

    `update` keeps the standard optax contract — it returns an updates
    tree for `optax.apply_updates` — so any generic consumer works,
    at the cost of one extra subtract/add pass. Hot paths (the
    trainer's one-step) call `fused_apply(grads, state, params) ->
    (new_params, new_state)` instead: the Pallas kernel writes the new
    parameters in place and no updates tree ever exists."""

    init: Callable
    update: Callable
    fused_apply: Callable


def fused_adam(learning_rate: Any = 1e-3, b1: float = 0.9,
               b2: float = 0.999, eps: float = 1e-8,
               weight_decay: float = 0.0,
               interpret: Optional[bool] = None
               ) -> FusedGradientTransformation:
    """Adam/AdamW as ONE blocked Pallas kernel pass over each leaf
    (`pallas/fused_adam.py`): read (grad, m, v, param), write
    (m, v, param), bias correction folded, decoupled weight decay,
    fp32 moments with f32/bf16 params. `learning_rate` may be a float
    or an optax schedule (called with the pre-increment step count,
    matching `optax.scale_by_learning_rate`).

    jax imports stay INSIDE each nested function (module globals, not
    closure cells): `compile_cache.key.fingerprint` walks closure cells
    for the persistent step key, and a captured module would drag the
    whole package namespace into the walk."""

    def init_fn(params):
        import jax
        import jax.numpy as jnp
        zeros = lambda p: jnp.zeros(jnp.shape(p), jnp.float32)  # noqa: E731
        return FusedAdamState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree_util.tree_map(zeros, params),
            nu=jax.tree_util.tree_map(zeros, params))

    def _step(grads, state, params):
        from analytics_zoo_tpu.pallas.fused_adam import fused_adam_step
        if params is None:
            raise ValueError(
                "fused_adam is a params-aware transformation; call "
                "update(grads, state, params) with the parameter tree")
        lr = learning_rate(state.count) if callable(learning_rate) \
            else learning_rate
        count = state.count + 1
        new_p, new_mu, new_nu = fused_adam_step(
            params, state.mu, state.nu, grads, count, lr=lr, b1=b1, b2=b2,
            eps=eps, weight_decay=weight_decay, interpret=interpret)
        return new_p, FusedAdamState(count, new_mu, new_nu)

    def update_fn(grads, state, params=None):
        import jax
        new_p, new_state = _step(grads, state, params)
        updates = jax.tree_util.tree_map(lambda n, p: n - p, new_p, params)
        return updates, new_state

    return FusedGradientTransformation(init_fn, update_fn, _step)


# String-spec → fused equivalent: EXACTLY the hyperparameters the
# registry entry would have compiled, so toggling the config flag
# changes the kernels, never the math. Only default-hyperparameter
# specs map — a warmup/decay `adam_weight_decay(...)` instance carries
# its schedule in closures we cannot (and must not guess to) replicate.
_FUSED_EQUIV: Dict[str, Callable[[], FusedGradientTransformation]] = {
    "adam": lambda: fused_adam(learning_rate=0.001),
    "adamw": lambda: fused_adam(learning_rate=0.001, eps=1e-6,
                                weight_decay=0.01),
    "adam_weight_decay": lambda: fused_adam(learning_rate=0.001, eps=1e-6,
                                            weight_decay=0.01),
}


def as_fused(optimizer: Any, spec: Any) -> Optional[Any]:
    """The fused twin of a compiled optimizer, or None when no exact
    twin exists (the caller then logs ONE warning and keeps the plain
    path). `spec` is the model's compile string (`_optimizer_spec`);
    an already-fused transformation passes through."""
    if getattr(optimizer, "fused_apply", None) is not None:
        return optimizer
    key = str(spec).lower() if spec is not None else None
    maker = _FUSED_EQUIV.get(key)
    return maker() if maker is not None else None


# Registry — exact strings + defaults of `KerasUtils.toBigDLOptimMethod`
# (`KerasUtils.scala:207-216`).
_REGISTRY: Dict[str, Callable[[], optax.GradientTransformation]] = {
    "sgd": lambda: optax.sgd(learning_rate=0.01),
    "rmsprop": lambda: optax.rmsprop(learning_rate=0.001, decay=0.9),
    "adamax": lambda: optax.adamax(learning_rate=0.002, eps=1e-8),
    "adagrad": lambda: optax.adagrad(learning_rate=0.01),
    "adadelta": lambda: optax.adadelta(learning_rate=1.0, rho=0.95, eps=1e-8),
    "adam": lambda: optax.adam(learning_rate=0.001),
    "adamw": lambda: adam_weight_decay(),
    "adam_weight_decay": lambda: adam_weight_decay(),
}


def get(optimizer: Any) -> optax.GradientTransformation:
    """Resolve an optimizer compile string (or pass a GradientTransformation
    through — duck-typed on (init, update) so the fused transformations
    qualify). Unknown strings raise, matching the reference."""
    if isinstance(optimizer, optax.GradientTransformation) or (
            callable(getattr(optimizer, "init", None))
            and callable(getattr(optimizer, "update", None))):
        return optimizer
    key = str(optimizer).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unsupported optimizer: {optimizer}")
    return _REGISTRY[key]()
