"""Validation metrics with the reference's compile-string registry.

Mirrors `keras/metrics/*.scala` and the dispatch of `KerasUtils.toBigDLMetrics`
(`KerasUtils.scala:218-248`): `"accuracy"`/`"acc"` resolve *by loss string* to
Sparse/Categorical/Binary accuracy, plus top5/mae/auc/loss; orca's python names
(`orca/learn/metrics.py:26-156`) map onto the same classes.

Design: metrics are functional accumulators safe inside jit —
`init() -> state`, `update(state, y_true, y_pred) -> state` (pure, jittable),
`compute(state) -> float`. States are pytrees of arrays so they cross the
host/device boundary and `jax.lax.scan` cleanly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

Array = jax.Array
State = Dict[str, Array]


def _f32(x):
    return jnp.asarray(x, jnp.float32)


class Metric:
    name = "metric"

    def init(self) -> State:
        return {"total": jnp.zeros((), jnp.float32),
                "count": jnp.zeros((), jnp.float32)}

    def update(self, state: State, y_true: Array, y_pred: Array) -> State:
        value, weight = self._batch(y_true, y_pred)
        return {"total": state["total"] + value,
                "count": state["count"] + weight}

    def compute(self, state: State) -> Array:
        return state["total"] / jnp.maximum(state["count"], 1.0)

    def _batch(self, y_true, y_pred) -> Tuple[Array, Array]:
        """Return (sum-of-metric, weight) for one batch."""
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class SparseCategoricalAccuracy(Metric):
    """0-based integer labels vs argmax over last axis."""
    name = "sparse_categorical_accuracy"

    def _batch(self, y_true, y_pred):
        labels = jnp.asarray(y_true, jnp.int32)
        if labels.ndim == jnp.ndim(y_pred):
            labels = jnp.squeeze(labels, -1)
        hits = (jnp.argmax(y_pred, -1).astype(jnp.int32) == labels)
        return _f32(hits).sum(), _f32(jnp.size(hits))


class CategoricalAccuracy(Metric):
    """One-hot labels (`metrics/Accuracy.scala` CategoricalAccuracy)."""
    name = "categorical_accuracy"

    def _batch(self, y_true, y_pred):
        hits = (jnp.argmax(y_pred, -1) == jnp.argmax(y_true, -1))
        return _f32(hits).sum(), _f32(jnp.size(hits))


class BinaryAccuracy(Metric):
    name = "binary_accuracy"

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold

    def _batch(self, y_true, y_pred):
        pred = (_f32(y_pred) > self.threshold)
        hits = (pred == (_f32(y_true) > self.threshold))
        return _f32(hits).sum(), _f32(jnp.size(hits))


class Top5Accuracy(Metric):
    """`ZooTop5Accuracy` (`keras/metrics`)."""
    name = "top5_accuracy"

    def __init__(self, k: int = 5):
        self.k = k

    def _batch(self, y_true, y_pred):
        labels = jnp.asarray(y_true, jnp.int32)
        if labels.ndim == jnp.ndim(y_pred):
            labels = jnp.squeeze(labels, -1)
        _, topk = jax.lax.top_k(_f32(y_pred), self.k)
        hits = jnp.any(topk == labels[..., None], axis=-1)
        return _f32(hits).sum(), _f32(jnp.size(hits))


class MAE(Metric):
    name = "mae"

    def _batch(self, y_true, y_pred):
        err = jnp.abs(_f32(y_pred) - _f32(y_true))
        return err.sum(), _f32(jnp.size(err))


class MSE(Metric):
    name = "mse"

    def _batch(self, y_true, y_pred):
        err = jnp.square(_f32(y_pred) - _f32(y_true))
        return err.sum(), _f32(jnp.size(err))


class Loss(Metric):
    """Averages a loss objective as a validation metric
    (`toBigDLMetrics` "loss")."""
    name = "loss"

    def __init__(self, objective=None):
        from analytics_zoo_tpu.ops import objectives
        self.objective = (objectives.get(objective)
                          if objective is not None
                          else objectives.MeanSquaredError())

    def _batch(self, y_true, y_pred):
        n = _f32(jnp.shape(y_pred)[0] if jnp.ndim(y_pred) else 1)
        return self.objective(y_true, y_pred) * n, n


class AUC(Metric):
    """Area under ROC via fixed-threshold binning (jit-friendly, matches
    BigDL's thresholded AUC semantics; `orca/learn/metrics.py` AUC).

    Accumulates TP/FP counts at `num_thresholds` evenly spaced thresholds and
    trapezoid-integrates at compute()."""
    name = "auc"

    def __init__(self, num_thresholds: int = 200):
        self.num_thresholds = num_thresholds

    def init(self) -> State:
        z = jnp.zeros((self.num_thresholds,), jnp.float32)
        return {"tp": z, "fp": z, "pos": jnp.zeros((), jnp.float32),
                "neg": jnp.zeros((), jnp.float32)}

    def update(self, state, y_true, y_pred):
        score = _f32(y_pred).reshape(-1)
        label = (_f32(y_true).reshape(-1) > 0.5)
        # thresholds in (0,1); epsilon margins like tf.keras AUC
        thr = jnp.linspace(0.0, 1.0, self.num_thresholds)
        pred_pos = score[None, :] >= thr[:, None]          # [T, N]
        tp = jnp.sum(pred_pos & label[None, :], axis=1)
        fp = jnp.sum(pred_pos & ~label[None, :], axis=1)
        return {"tp": state["tp"] + _f32(tp),
                "fp": state["fp"] + _f32(fp),
                "pos": state["pos"] + _f32(label).sum(),
                "neg": state["neg"] + _f32(~label).sum()}

    def compute(self, state):
        tpr = state["tp"] / jnp.maximum(state["pos"], 1.0)
        fpr = state["fp"] / jnp.maximum(state["neg"], 1.0)
        # thresholds descend fpr; integrate |∫ tpr d(fpr)|
        return jnp.abs(jnp.trapezoid(tpr, fpr))


class Accuracy(Metric):
    """Orca's loss-agnostic Accuracy (`orca/learn/metrics.py:26`): picks
    sparse vs categorical by label rank at update time is not jit-friendly, so
    we resolve on first update by shape."""
    name = "accuracy"

    def _batch(self, y_true, y_pred):
        if jnp.ndim(y_true) == jnp.ndim(y_pred) and \
                jnp.shape(y_true)[-1] == jnp.shape(y_pred)[-1] and \
                jnp.shape(y_pred)[-1] > 1:
            return CategoricalAccuracy()._batch(y_true, y_pred)
        if jnp.ndim(y_pred) >= 2 and jnp.shape(y_pred)[-1] > 1:
            return SparseCategoricalAccuracy()._batch(y_true, y_pred)
        return BinaryAccuracy()._batch(y_true, y_pred)


# ---------------------------------------------------------------------------
# Registry + loss-aware dispatch (`KerasUtils.scala:218-248`)
# ---------------------------------------------------------------------------
_ACC_BY_LOSS = {
    "sparse_categorical_crossentropy": SparseCategoricalAccuracy,
    "categorical_crossentropy": CategoricalAccuracy,
    "binary_crossentropy": BinaryAccuracy,
}


def get(metric: Any, loss: Optional[str] = None) -> Metric:
    """Resolve one metric string; `"accuracy"`/`"acc"` need the loss string for
    the reference's loss-aware dispatch."""
    if isinstance(metric, Metric):
        return metric
    key = str(metric).lower()
    if key in ("accuracy", "acc"):
        if loss is None:
            return Accuracy()
        loss_key = str(loss).lower()
        if loss_key not in _ACC_BY_LOSS:
            raise ValueError(
                f"Unsupported metric: accuracy and loss: {loss} combination")
        return _ACC_BY_LOSS[loss_key]()
    table = {
        "top5accuracy": Top5Accuracy,
        "top5acc": Top5Accuracy,
        "mae": MAE,
        "mse": MSE,
        "auc": AUC,
        "loss": Loss,
        "sparse_categorical_accuracy": SparseCategoricalAccuracy,
        "categorical_accuracy": CategoricalAccuracy,
        "binary_accuracy": BinaryAccuracy,
    }
    if key not in table:
        raise ValueError(f"Unsupported metric: {metric}")
    return table[key]()


def resolve(metrics: Optional[Sequence[Any]], loss: Optional[str] = None
            ) -> List[Metric]:
    """Resolve a metrics list against a loss, like `toBigDLMetrics`."""
    if metrics is None:
        return []
    return [get(m, loss) for m in metrics]
