"""RETIRED (ISSUE 9): bucket-packed optimizer sweep, superseded by the
fused Pallas kernels in `analytics_zoo_tpu/pallas/fused_adam.py`.

This module was the TPU analogue of the reference's flat
``AllReduceParameter`` storage (`Topology.scala:1204`): master params
carried as one stacked ``[count, *shape]`` f32 buffer per distinct leaf
shape, so the Adam phase became a few big streaming fusions instead of
one small program per tensor (BERT-base: 153 leaves → 9 buffers,
sweep 37.4 → 4.6 ms/step).

Measured design history, kept for the record (docs/ROOFLINE.md round 5):

- a 1-D concat ravel (``optax.flatten`` shape) compiles on TPU to a
  ``reshape`` of the vector into ``f32[N/2,2]`` whose (8,128)-tiled
  layout pads the minor dim 2→128 — a 64×, 28 GB allocation and a
  compile-time OOM;
- a tile-exact ``[rows,128]`` packing collapses the sweep but restoring
  weight-shaped views is a physical tile shuffle (+32 ms/step of
  bitcast_convert fusions) — net zero;
- shape-bucketed stacking (the shipped design) kept the sweep collapse
  and the zero-cost views — but the per-step total did not move: the
  extra HBM passes are BETWEEN optax's materialized trees (new mu, new
  nu, the updates tree, apply_updates), not between tensors, so no
  structural repacking can remove them.

The fused kernels remove the passes themselves — one blocked
read-(g,m,v,p)/write-(m,v,p) HBM pass per leaf, in place — which is why
``fit(..., flat_optimizer=True)`` now raises in the trainer and this
module is a shim. Use ``fit(..., fused_optimizer=True)`` (config
`ZooConfig.fused_optimizer` / env ``ZOO_FUSED_OPT=1``) instead.
"""

from __future__ import annotations


class ParamSpec:
    """Retired. The bucket-packed parameter carrier for the former
    ``flat_optimizer=True`` fit mode; see the module docstring for the
    design history and `pallas/fused_adam.py` for the replacement."""

    _RETIRED = ("ops.flat_optimizer.ParamSpec was retired by ISSUE 9: "
                "the bucket-packed sweep is superseded by the fused "
                "Pallas optimizer kernels — use "
                "fit(..., fused_optimizer=True) "
                "(ZooConfig.fused_optimizer / ZOO_FUSED_OPT=1) instead")

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(self._RETIRED)

    @classmethod
    def from_tree(cls, tree):
        raise NotImplementedError(cls._RETIRED)
