"""Bucket-packed optimizer sweep (the TPU analogue of the reference's
flat ``AllReduceParameter`` gradient/weight storage, `Topology.scala:1204`
— few big contiguous buffers swept by the optimizer instead of one small
update program per tensor).

``ParamSpec`` is the shipped mechanism: `learn/trainer.py` uses it when
``fit(..., flat_optimizer=True)`` to carry the master parameters as one
stacked ``[count, *shape]`` f32 buffer per distinct leaf shape and to
differentiate with respect to those buckets. See the class docstring for
the measured design history (including the two rejected flat-vector
layouts and why ``optax.flatten`` compile-OOMs on TPU at BERT scale).
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import optax

_spec_uids = itertools.count()


class ParamSpec:
    """Static description of a parameter pytree for bucket-packed training.

    The trainer's flat mode carries parameters as ONE stacked
    ``[count, *shape]`` f32 buffer per DISTINCT leaf shape (BERT-base:
    153 leaves -> 9 buffers), so the optimizer phase is a handful of big
    streaming fusions instead of one small program per tensor.
    ``unravel`` hands each consumer a dim-0 slice of its bucket — a pure
    view with the leaf's exact layout, so the bf16 operand casts keep
    fusing into the forward pass.

    Two rejected designs, both measured on BERT-base (110.7 M params):
    a 1-D concat ravel (``optax.flatten`` shape) compiles on TPU to a
    ``reshape`` of the vector into ``f32[N/2,2]`` whose (8,128)-tiled
    layout pads the minor dim 2->128 — a 64x, 28 GB allocation,
    compile-time OOM; a tile-exact ``[rows,128]`` packing compiles and
    collapses the Adam sweep 37.4 -> 4.6 ms/step, but reshaping row
    blocks back to ``[768,3072]``-style weight shapes is a physical
    tile shuffle (+32 ms/step of bitcast_convert fusions) — net zero.
    Shape-bucketed stacking keeps the sweep collapse AND the zero-cost
    views. All leaves must be float32 (mixed precision keeps f32
    masters, so this is the trainer's steady state)."""

    def __init__(self, treedef, shapes):
        self.treedef = treedef
        self.shapes = shapes
        # bucket leaves by exact shape; order within a bucket = leaf
        # order. One pass with a per-group running counter: each leaf's
        # position IS the group's current count (BERT-scale trees have
        # hundreds of leaves — the old rescan-per-leaf was O(n²))
        by_shape: dict = {}
        self.slots = []                      # per leaf: (group, pos)
        counts: list = []                    # running per-group counters
        for s in shapes:
            g = by_shape.setdefault(s, len(by_shape))
            if g == len(counts):
                counts.append(0)
            self.slots.append((g, counts[g]))
            counts[g] += 1
        self.group_shapes = list(by_shape)   # insertion-ordered
        self.group_counts = counts
        self.n = sum(int(np.prod(s)) if s else 1 for s in shapes)
        self._unravel_jit = None
        self._ravel_jit = None
        # monotonic identity for compile-cache keys: id() of a replaced
        # spec can be recycled by the allocator after GC
        self.uid = next(_spec_uids)

    @classmethod
    def from_tree(cls, tree) -> "ParamSpec":
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        bad = [tuple(l.shape) for l in leaves if l.dtype != jnp.float32]
        if bad:
            raise ValueError(
                f"flat-parameter training needs all-f32 leaves; got "
                f"non-f32 shapes {bad[:3]}")
        return cls(treedef, [tuple(l.shape) for l in leaves])

    def ravel(self, tree):
        """Pack the tree into one stacked [count, *shape] buffer per
        distinct shape (singleton buckets stay unstacked: zero-copy)."""
        leaves = jax.tree_util.tree_leaves(tree)
        groups: list = [[] for _ in self.group_shapes]
        for leaf, (g, _pos) in zip(leaves, self.slots):
            groups[g].append(leaf)
        return tuple(ls[0] if len(ls) == 1 else jnp.stack(ls)
                     for ls in groups)

    def unravel(self, buffers):
        leaves = []
        for (g, pos), shape in zip(self.slots, self.shapes):
            buf = buffers[g]
            if self.group_counts[g] == 1:
                leaves.append(buf)
            else:
                leaves.append(jax.lax.index_in_dim(buf, pos, axis=0,
                                                   keepdims=False))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def unravel_device(self, flat2d):
        """jit'd unravel for host-side touch points (checkpoint save,
        validation hand-off) — compiled once per spec."""
        if self._unravel_jit is None:
            self._unravel_jit = jax.jit(self.unravel)
        return self._unravel_jit(flat2d)

    def ravel_device(self, tree):
        """jit'd ravel, compiled once per spec: warm-restart fit calls
        must hit the compile cache, not re-trace the packing program
        (a fresh jax.jit wrapper per call would be keyed on itself)."""
        if self._ravel_jit is None:
            self._ravel_jit = jax.jit(self.ravel)
        return self._ravel_jit(tree)
