"""Loss objectives with the reference's compile-string registry.

Mirrors the 15-objective library of `zoo/.../pipeline/api/keras/objectives/`
and the exact string registry of `KerasUtils.toBigDLCriterion`
(`keras/layers/utils/KerasUtils.scala:180-203`) — same strings, same aliases,
same error on unknown names. Implemented as pure jax functions (class instances
are stateless callables), reduction = mean over the batch, computed in float32
regardless of input dtype so bf16 activations don't destabilize training.

Conventions (Keras semantics, as the reference follows Keras):
- probability-space crossentropies by default; `from_logits=True` fuses the
  softmax/sigmoid for numerical stability (preferred on TPU).
- `sparse_categorical_crossentropy` takes 0-based integer labels
  (`SparseCategoricalCrossEntropy.scala` zeroBasedLabel=true default).
- hinge losses expect targets in {-1, 1}.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

Array = jax.Array
EPS = 1e-7


def _f32(x) -> Array:
    return jnp.asarray(x, jnp.float32)


def _align(y_true, y_pred):
    """Align a rank-off-by-one target with a trailing size-1 prediction dim
    (or vice versa). Without this, `[B] - [B, 1]` silently broadcasts to
    `[B, B]` and the loss optimizes toward the global mean."""
    y_true, y_pred = _f32(y_true), _f32(y_pred)
    if y_true.ndim == y_pred.ndim - 1 and y_pred.shape[-1] == 1:
        y_true = y_true[..., None]
    elif y_pred.ndim == y_true.ndim - 1 and y_true.shape[-1] == 1:
        y_pred = y_pred[..., None]
    return y_true, y_pred


class Objective:
    """Base class: a callable loss(y_true, y_pred) -> scalar."""

    def __call__(self, y_true: Array, y_pred: Array) -> Array:
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class MeanSquaredError(Objective):
    def __call__(self, y_true, y_pred):
        y_true, y_pred = _align(y_true, y_pred)
        return jnp.mean(jnp.square(y_pred - y_true))


class MeanAbsoluteError(Objective):
    def __call__(self, y_true, y_pred):
        y_true, y_pred = _align(y_true, y_pred)
        return jnp.mean(jnp.abs(y_pred - y_true))


class MeanAbsolutePercentageError(Objective):
    def __call__(self, y_true, y_pred):
        y_true, y_pred = _align(y_true, y_pred)
        diff = jnp.abs(y_pred - y_true) / jnp.clip(jnp.abs(y_true), EPS, None)
        return 100.0 * jnp.mean(diff)


class MeanSquaredLogarithmicError(Objective):
    def __call__(self, y_true, y_pred):
        y_true, y_pred = _align(y_true, y_pred)
        a = jnp.log1p(jnp.clip(y_pred, EPS, None))
        b = jnp.log1p(jnp.clip(y_true, EPS, None))
        return jnp.mean(jnp.square(a - b))


class BinaryCrossEntropy(Objective):
    def __init__(self, from_logits: bool = False):
        self.from_logits = from_logits

    def __call__(self, y_true, y_pred):
        y_true, y_pred = _align(y_true, y_pred)
        if self.from_logits:
            # stable: max(x,0) - x*y + log1p(exp(-|x|))
            x = y_pred
            per = jnp.maximum(x, 0) - x * y_true + jnp.log1p(jnp.exp(-jnp.abs(x)))
        else:
            p = jnp.clip(y_pred, EPS, 1.0 - EPS)
            per = -(y_true * jnp.log(p) + (1.0 - y_true) * jnp.log1p(-p))
        return jnp.mean(per)


class CategoricalCrossEntropy(Objective):
    """One-hot targets over the last axis."""

    def __init__(self, from_logits: bool = False):
        self.from_logits = from_logits

    def __call__(self, y_true, y_pred):
        y_true, y_pred = _align(y_true, y_pred)
        if self.from_logits:
            logp = jax.nn.log_softmax(y_pred, axis=-1)
        else:
            p = y_pred / jnp.clip(jnp.sum(y_pred, -1, keepdims=True), EPS, None)
            logp = jnp.log(jnp.clip(p, EPS, 1.0))
        return jnp.mean(-jnp.sum(y_true * logp, axis=-1))


class SparseCategoricalCrossEntropy(Objective):
    """Integer (0-based) class labels (`SparseCategoricalCrossEntropy.scala`)."""

    def __init__(self, from_logits: bool = False):
        self.from_logits = from_logits

    def __call__(self, y_true, y_pred):
        y_pred = _f32(y_pred)
        labels = jnp.asarray(y_true, jnp.int32)
        if labels.ndim == y_pred.ndim:  # squeeze trailing [*, 1] label dim
            labels = jnp.squeeze(labels, -1)
        if self.from_logits:
            logp = jax.nn.log_softmax(y_pred, axis=-1)
        else:
            logp = jnp.log(jnp.clip(y_pred, EPS, 1.0))
        picked = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return jnp.mean(-picked)


class Hinge(Objective):
    def __call__(self, y_true, y_pred):
        y_true, y_pred = _align(y_true, y_pred)
        return jnp.mean(jnp.maximum(1.0 - y_true * y_pred, 0.0))


class SquaredHinge(Objective):
    def __call__(self, y_true, y_pred):
        y_true, y_pred = _align(y_true, y_pred)
        return jnp.mean(jnp.square(jnp.maximum(1.0 - y_true * y_pred, 0.0)))


class RankHinge(Objective):
    """Pairwise ranking hinge for text matching (`objectives/RankHinge.scala`):
    batch rows alternate positive/negative samples; loss =
    max(0, margin - (score_pos - score_neg)) per pair."""

    def __init__(self, margin: float = 1.0):
        self.margin = margin

    def __call__(self, y_true, y_pred):
        del y_true  # ordering carries the supervision
        s = _f32(y_pred).reshape(-1)
        pos, neg = s[0::2], s[1::2]
        return jnp.mean(jnp.maximum(self.margin - pos + neg, 0.0))


class KullbackLeiblerDivergence(Objective):
    def __call__(self, y_true, y_pred):
        y_true = jnp.clip(_f32(y_true), EPS, 1.0)
        y_pred = jnp.clip(_f32(y_pred), EPS, 1.0)
        return jnp.mean(jnp.sum(y_true * jnp.log(y_true / y_pred), axis=-1))


class Poisson(Objective):
    def __call__(self, y_true, y_pred):
        y_true, y_pred = _align(y_true, y_pred)
        return jnp.mean(y_pred - y_true * jnp.log(y_pred + EPS))


class CosineProximity(Objective):
    def __call__(self, y_true, y_pred):
        y_true = _f32(y_true)
        y_pred = _f32(y_pred)
        t = y_true / jnp.clip(jnp.linalg.norm(y_true, axis=-1, keepdims=True), EPS, None)
        p = y_pred / jnp.clip(jnp.linalg.norm(y_pred, axis=-1, keepdims=True), EPS, None)
        return -jnp.mean(jnp.sum(t * p, axis=-1))


# ---------------------------------------------------------------------------
# Registry — exact strings of `KerasUtils.toBigDLCriterion`
# (`KerasUtils.scala:180-203`).
# ---------------------------------------------------------------------------
_REGISTRY: Dict[str, Callable[[], Objective]] = {
    "binary_crossentropy": BinaryCrossEntropy,
    "categorical_crossentropy": CategoricalCrossEntropy,
    "mse": MeanSquaredError,
    "mean_squared_error": MeanSquaredError,
    "mae": MeanAbsoluteError,
    "mean_absolute_error": MeanAbsoluteError,
    "hinge": Hinge,
    "mape": MeanAbsolutePercentageError,
    "mean_absolute_percentage_error": MeanAbsolutePercentageError,
    "msle": MeanSquaredLogarithmicError,
    "mean_squared_logarithmic_error": MeanSquaredLogarithmicError,
    "squared_hinge": SquaredHinge,
    "sparse_categorical_crossentropy": SparseCategoricalCrossEntropy,
    "kld": KullbackLeiblerDivergence,
    "kullback_leibler_divergence": KullbackLeiblerDivergence,
    "cosine_proximity": CosineProximity,
    "poisson": Poisson,
    "rank_hinge": RankHinge,
}


def get(loss: Any, **kwargs) -> Objective:
    """Resolve a loss from its compile string (or pass through an Objective /
    plain callable). Raises on unknown strings, matching the reference's
    IllegalArgumentException."""
    if isinstance(loss, Objective):
        return loss
    if callable(loss):
        wrapped = loss

        class _Fn(Objective):
            def __call__(self, y_true, y_pred):
                return wrapped(y_true, y_pred)
        return _Fn()
    key = str(loss).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unsupported loss: {loss}")
    return _REGISTRY[key](**kwargs)
