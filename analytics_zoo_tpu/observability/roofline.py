"""Roofline accounting from XLA cost analysis (ISSUE 6 tentpole, part 1).

PR 2 gave latencies and counts; this module answers *hardware
utilization*: how many FLOPs and HBM bytes did each executable actually
move per second, against what the chip can do. The FLOP/byte counts come
from XLA itself — `compiled.cost_analysis()` on the executables the
serving warmup, the trainer step, and the AOT compile cache already hold
— so no hand-supplied `flops_per_step` is needed and the numbers track
the REAL program (fusion included), not an analytic model.

Two layers:

- `cost_of(stages_obj)` — harvest `{flops, bytes}` from a
  `jax.stages.Compiled` or `Lowered` (the two agree on this backend; a
  deserialized AOT executable works too). Returns None when the backend
  exposes no cost model — every caller degrades to "no roofline gauges",
  never an error.
- `RooflineAccountant` — per-`kind` ("serving", "train") accumulation of
  (flops, bytes, busy-seconds) publishing both cumulative counters and
  live derived gauges: achieved TFLOP/s, achieved HBM GB/s, MFU, and HBM
  utilization as a fraction of the **session roofline**.

The session roofline is the *measured* achievable bound
(`bench.py session_hbm_gbps` / `session_mxu_tflops`, the Adam-shaped
sweep + chained-matmul calibration in `bench_ncf.py`), installed via
`set_session_roofline(...)` or the `ZOO_SESSION_HBM_GBPS` /
`ZOO_SESSION_TFLOPS` env vars; absent those it falls back to the
nameplate peaks in `utils/roofline.py`. That makes the BENCH r05
"NCF at 33% of achievable bound" number a live gauge
(`roofline_hbm_utilization{kind="train"}`) instead of one-off analysis,
and — per the ROADMAP NCF item — measured against the session yardstick
so tunnel noise can't fake progress.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Optional, Tuple

log = logging.getLogger("analytics_zoo_tpu.observability")


class ExecCost:
    """FLOPs and HBM bytes one call of an executable performs, per XLA's
    own cost analysis.

    Basis contract: an ExecCost is the LOGICAL GLOBAL cost of one call
    — the model's work counted once, however many devices execute it.
    XLA reports two different bases depending on what you ask:
    `Lowered.cost_analysis()` runs on the UNPARTITIONED module (the
    logical basis), while `Compiled.cost_analysis()` on a
    GSPMD-partitioned executable runs on the per-device module — and
    per-device × span is NOT the logical cost, because work that
    replicates across a mesh axis (e.g. the optimizer update across
    the data axis of a data×fsdp mesh) is counted once per device
    (measured factors 2–8× on an 8-device mesh depending on the
    program). Classic MFU divides MODEL flops by peak, so harvesters
    use the lowered module for any multi-device program (one trace per
    signature, no compile) and executables only where the two agree
    (single-device), then pass `account(..., n_devices=span)` so the
    denominator covers the devices that did the work."""

    __slots__ = ("flops", "bytes")

    def __init__(self, flops: float, bytes_: float):
        self.flops = float(flops)
        self.bytes = float(bytes_)

    def __repr__(self):
        return f"ExecCost(flops={self.flops:g}, bytes={self.bytes:g})"


def cost_of(stages_obj) -> Optional[ExecCost]:
    """Harvest per-call FLOPs / bytes-accessed from a `jax.stages`
    Compiled or Lowered object (cost_analysis returns a list of one dict
    on this jax, a plain dict on newer ones). None — never a raise —
    when the backend has no cost model or the numbers are empty: the
    roofline layer is telemetry, and telemetry must not take down the
    path it measures.

    Caveat: XLA's HLO cost analysis counts a While-loop body ONCE, not
    times its trip count — a `lax.scan`/`fori_loop` program reports one
    iteration's cost. The trainer exploits this (the per-step cost is
    exactly what it scales by the iteration count); a model whose
    FORWARD hides work inside a loop will have its serving cost
    understated by the trip count."""
    if stages_obj is None:
        return None
    try:
        ca = stages_obj.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if not isinstance(ca, dict):
            return None
        flops = float(ca.get("flops") or 0.0)
        bytes_ = float(ca.get("bytes accessed") or 0.0)
    except Exception as e:  # noqa: BLE001 — experimental backends throw
        log.debug("cost_analysis unavailable: %s: %s", type(e).__name__, e)
        return None
    if flops <= 0.0 and bytes_ <= 0.0:
        return None
    return ExecCost(flops, bytes_)


def device_span(tree) -> int:
    """The SPMD partition count of a program called with `tree` as (part
    of) its arguments: the largest device set any leaf is committed to.
    1 for single-device programs; the mesh size for GSPMD programs whose
    params/batch are NamedSharding'd over a mesh. Used to convert XLA's
    per-device executable cost to the global basis (see ExecCost)."""
    span = 1
    try:
        import jax
        for leaf in jax.tree_util.tree_leaves(tree):
            sharding = getattr(leaf, "sharding", None)
            if sharding is None:
                continue
            try:
                span = max(span, len(sharding.device_set))
            except Exception:  # noqa: BLE001 — exotic sharding object
                continue
    except Exception:  # noqa: BLE001 — telemetry only
        return span
    return span


# ---------------------------------------------------------------------------
# Session roofline: the measured achievable bound (falls back to nameplate)
# ---------------------------------------------------------------------------
_session_lock = threading.Lock()
_session: Dict[str, Optional[float]] = {"hbm_gbps": None, "tflops": None}


def set_session_roofline(hbm_gbps: Optional[float] = None,
                         tflops: Optional[float] = None,
                         registry=None) -> None:
    """Install the session's MEASURED achievable bounds (the bench
    calibration sweeps) as the roofline denominator, and publish them as
    gauges so every scrape shows what "100%" meant."""
    from analytics_zoo_tpu.observability.registry import get_registry
    reg = registry if registry is not None else get_registry()
    with _session_lock:
        if hbm_gbps is not None:
            _session["hbm_gbps"] = float(hbm_gbps)
        if tflops is not None:
            _session["tflops"] = float(tflops)
    if hbm_gbps is not None:
        reg.gauge("roofline_session_hbm_gbps",
                  "measured achievable HBM GB/s this session (the "
                  "utilization denominator; nameplate when unset)"
                  ).set(float(hbm_gbps))
    if tflops is not None:
        reg.gauge("roofline_session_tflops",
                  "measured achievable bf16 TFLOP/s this session (the "
                  "MFU denominator; nameplate when unset)"
                  ).set(float(tflops))


def session_roofline(device=None) -> Tuple[float, float]:
    """(HBM bytes/s, FLOP/s) roofline denominators: the measured session
    bound when installed (`set_session_roofline` / env
    ZOO_SESSION_HBM_GBPS / ZOO_SESSION_TFLOPS), else the nameplate peak
    of `device` (default: device 0)."""
    with _session_lock:
        hbm_gbps = _session["hbm_gbps"]
        tflops = _session["tflops"]
    if hbm_gbps is None:
        env = os.environ.get("ZOO_SESSION_HBM_GBPS")
        hbm_gbps = float(env) if env else None
    if tflops is None:
        env = os.environ.get("ZOO_SESSION_TFLOPS")
        tflops = float(env) if env else None
    if hbm_gbps is not None and tflops is not None:
        return hbm_gbps * 1e9, tflops * 1e12
    from analytics_zoo_tpu.utils.roofline import peak_flops, peak_hbm
    if device is None:
        import jax
        device = jax.devices()[0]
    return (hbm_gbps * 1e9 if hbm_gbps is not None else peak_hbm(device),
            tflops * 1e12 if tflops is not None else peak_flops(device))


# ---------------------------------------------------------------------------
# The accountant
# ---------------------------------------------------------------------------
class RooflineAccountant:
    """Per-kind (flops, bytes, busy-seconds) accumulation → registry.

    `account(kind, flops, bytes, seconds)` is the single entry point:
    the serving predict path calls it per materialized batch (with the
    batch's measured dispatch+materialize seconds), the trainer once per
    epoch (with the epoch's device wall time). Counters accumulate
    forever (the Prometheus model); the derived gauges are computed from
    THIS call's window — the latest batch / latest epoch — so a cold
    fit's compile-laden first epoch depresses only its own reading and
    the gauges recover to the true steady-state rate from the next
    window on (cumulative-since-reset rates would stay diluted for the
    whole run). `snapshot(kind)` still reports the accumulation since
    the last `reset(kind)` — a model reload or a fresh fit resets its
    kind so the bench-facing averages describe the CURRENT program.

    Never raises out of `account` — one bad division must not take down
    a dispatch path."""

    def __init__(self, registry=None):
        from analytics_zoo_tpu.observability.registry import get_registry
        self._registry = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        # kind -> [flops, bytes, seconds] since last reset(kind)
        self._acc: Dict[str, list] = {}

    # registration is get-or-create and therefore safe to repeat per
    # call: it also heals after a test's registry.clear()
    def _reg(self):
        reg = self._registry
        return (
            reg.counter("roofline_flops_total",
                        "FLOPs executed, per XLA cost analysis, by kind"),
            reg.counter("roofline_hbm_bytes_total",
                        "HBM bytes accessed, per XLA cost analysis, by "
                        "kind"),
            reg.counter("roofline_busy_seconds_total",
                        "measured busy wall seconds the flops/bytes "
                        "counters were accumulated over, by kind"),
            reg.gauge("roofline_achieved_tflops",
                      "achieved TFLOP/s since the kind's last reset "
                      "(cost-analysis FLOPs / measured seconds)"),
            reg.gauge("roofline_achieved_hbm_gbps",
                      "achieved HBM GB/s since the kind's last reset"),
            reg.gauge("roofline_mfu",
                      "achieved FLOP/s over the session FLOP roofline "
                      "(cost-analysis MFU; no flops_per_step needed)"),
            reg.gauge("roofline_hbm_utilization",
                      "achieved HBM bytes/s over the session HBM "
                      "roofline (the %-of-achievable-bound gauge)"),
        )

    def account(self, kind: str, flops: float, bytes_: float,
                seconds: float, device=None, n_devices: int = 1) -> None:
        """`flops`/`bytes_` are GLOBAL (see ExecCost); `n_devices` is
        how many devices the program spanned, scaling the MFU/HBM
        denominators to the roofline of the participating slice —
        per-chip session bounds × n. The achieved_* gauges stay global
        (what the whole mesh delivered)."""
        try:
            if seconds <= 0.0 or (flops <= 0.0 and bytes_ <= 0.0):
                return
            with self._lock:
                acc = self._acc.setdefault(kind,
                                           [0.0, 0.0, 0.0, 1, 0.0])
                acc[0] += flops
                acc[1] += bytes_
                acc[2] += seconds
                acc[3] = max(acc[3], max(1, int(n_devices)))
            (c_flops, c_bytes, c_secs, g_tflops, g_gbps, g_mfu,
             g_hbm) = self._reg()
            c_flops.inc(flops, kind=kind)
            c_bytes.inc(bytes_, kind=kind)
            c_secs.inc(seconds, kind=kind)
            # gauges from THIS window: the latest epoch/batch rate
            g_tflops.set(flops / seconds / 1e12, kind=kind)
            g_gbps.set(bytes_ / seconds / 1e9, kind=kind)
            hbm_roof, flops_roof = session_roofline(device)
            n = max(1, int(n_devices))
            if flops_roof > 0:
                g_mfu.set(flops / seconds / (flops_roof * n), kind=kind)
            if hbm_roof > 0:
                g_hbm.set(bytes_ / seconds / (hbm_roof * n), kind=kind)
        except Exception as e:  # noqa: BLE001 — telemetry must not raise
            log.debug("roofline accounting failed: %s: %s",
                      type(e).__name__, e)

    def account_stall(self, kind: str, stall_seconds: float) -> None:
        """Input-stall accumulation (ISSUE 15): wall seconds the kind's
        hot loop spent BLOCKED on its input pipeline (the trainer's
        prefetch-queue wait) inside the busy window `account` measures.
        Surfaces in `snapshot(kind)` as `input_stall_seconds` and
        `input_stall_fraction` — the roofline's answer to "is this fit
        compute-bound or input-bound": an epoch at 40% MFU with a 0.5
        stall fraction is a HOST problem, not a kernel problem. Never
        raises."""
        try:
            if stall_seconds <= 0.0:
                return
            with self._lock:
                acc = self._acc.setdefault(kind,
                                           [0.0, 0.0, 0.0, 1, 0.0])
                acc[4] += stall_seconds
        except Exception as e:  # noqa: BLE001 — telemetry must not raise
            log.debug("roofline stall accounting failed: %s: %s",
                      type(e).__name__, e)

    def reset(self, kind: Optional[str] = None) -> None:
        """Zero the rate accumulators (counters keep accumulating): a
        reloaded serving model / a fresh fit starts its gauges clean."""
        with self._lock:
            if kind is None:
                self._acc.clear()
            else:
                self._acc.pop(kind, None)

    def snapshot(self, kind: str) -> Dict[str, float]:
        """The kind's accumulators since its last reset (bench JSON).
        `devices` is the largest program span accounted in the window;
        mfu/hbm_utilization divide by that many chips' roofline, like
        the live gauges."""
        with self._lock:
            f, b, s, n, stall = self._acc.get(
                kind, (0.0, 0.0, 0.0, 1, 0.0))
        out: Dict[str, Any] = {"flops": f, "bytes": b, "seconds": s,
                               "devices": n,
                               "input_stall_seconds": stall}
        if s > 0:
            out["achieved_tflops"] = f / s / 1e12
            out["achieved_hbm_gbps"] = b / s / 1e9
            # the input-stall column (ISSUE 15): what share of the busy
            # window the loop sat blocked on host input
            out["input_stall_fraction"] = min(1.0, stall / s)
            try:
                hbm_roof, flops_roof = session_roofline()
                out["mfu"] = f / s / (flops_roof * n)
                out["hbm_utilization"] = b / s / (hbm_roof * n)
            except Exception:  # noqa: BLE001 — no device, no roofline
                pass
        return out


_default_accountant: Optional[RooflineAccountant] = None
_default_lock = threading.Lock()


def get_accountant() -> RooflineAccountant:
    """The process-wide accountant on the default registry — serving and
    training both publish here, like `get_registry()`."""
    global _default_accountant
    with _default_lock:
        if _default_accountant is None:
            _default_accountant = RooflineAccountant()
        return _default_accountant
