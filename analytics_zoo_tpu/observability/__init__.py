"""Unified observability layer (ISSUE 2 + ISSUE 6): one metrics
registry, one tracer, one exposition path for serving AND training —
plus the deep-profiling layer that makes the stack self-measuring.

- `MetricsRegistry` / `get_registry()` — labeled Counter/Gauge/Histogram
  families; the Histogram is the log-bucketed streaming histogram from
  `serving/timer.py`, generalized.
- `render_prometheus(registry)` — Prometheus 0.0.4 text, served by the
  HTTP frontend's `GET /metrics` under `Accept: text/plain`.
- `Tracer` — request-scoped spans with Chrome trace-event JSON export
  (Perfetto-viewable), threaded through the serving pipeline.
- `MetricsReporter` — periodic one-line digest thread (optionally
  evaluating an `SLOTracker` each report).
- `RooflineAccountant` / `cost_of` / `set_session_roofline` — hardware
  utilization (achieved TFLOP/s, MFU, HBM GB/s vs the measured session
  roofline) derived from XLA cost analysis, no hand-supplied FLOPs.
- `ProfileCapture` / `StackSampler` — bounded on-demand `jax.profiler`
  captures (`POST /profile`, `fit_keras(profile_steps=...)`) and a
  host-side stack-sampling profiler for the pipeline threads.
- `DeviceMemoryWatcher` / `leak_check` — per-device live/peak HBM
  gauges and a leak assertion for tests.
- `SLOObjectives` / `SLOTracker` — declarative latency/availability
  objectives with burn-rate gauges and the `/healthz` readiness input.
"""

from analytics_zoo_tpu.observability.capture import (CaptureActiveError,
                                                     ProfileCapture,
                                                     StackSampler,
                                                     load_trace_events)
from analytics_zoo_tpu.observability.memwatch import (DeviceMemoryLeak,
                                                      DeviceMemoryWatcher,
                                                      device_memory_snapshot,
                                                      leak_check)
from analytics_zoo_tpu.observability.prometheus import (CONTENT_TYPE,
                                                        render_prometheus)
from analytics_zoo_tpu.observability.registry import (Counter, Gauge,
                                                      Histogram,
                                                      LogHistogram,
                                                      MetricsRegistry,
                                                      get_registry)
from analytics_zoo_tpu.observability.reporter import MetricsReporter, digest
from analytics_zoo_tpu.observability.roofline import (ExecCost,
                                                      RooflineAccountant,
                                                      cost_of,
                                                      get_accountant,
                                                      session_roofline,
                                                      set_session_roofline)
from analytics_zoo_tpu.observability.slo import SLOObjectives, SLOTracker
from analytics_zoo_tpu.observability.tracing import (Span, Tracer,
                                                     span_coverage,
                                                     span_from_dict,
                                                     span_to_dict)

__all__ = [
    "CONTENT_TYPE", "CaptureActiveError", "Counter", "DeviceMemoryLeak",
    "DeviceMemoryWatcher", "ExecCost", "Gauge", "Histogram",
    "LogHistogram", "MetricsRegistry", "MetricsReporter",
    "ProfileCapture", "RooflineAccountant", "SLOObjectives", "SLOTracker",
    "Span", "StackSampler", "Tracer", "cost_of", "device_memory_snapshot",
    "digest", "get_accountant", "get_registry", "leak_check",
    "load_trace_events", "render_prometheus", "session_roofline",
    "set_session_roofline", "span_coverage", "span_from_dict",
    "span_to_dict",
]
