"""Unified observability layer (ISSUE 2): one metrics registry, one
tracer, one exposition path for serving AND training.

- `MetricsRegistry` / `get_registry()` — labeled Counter/Gauge/Histogram
  families; the Histogram is the log-bucketed streaming histogram from
  `serving/timer.py`, generalized.
- `render_prometheus(registry)` — Prometheus 0.0.4 text, served by the
  HTTP frontend's `GET /metrics` under `Accept: text/plain`.
- `Tracer` — request-scoped spans with Chrome trace-event JSON export
  (Perfetto-viewable), threaded through the serving pipeline.
- `MetricsReporter` — periodic one-line digest thread.
"""

from analytics_zoo_tpu.observability.prometheus import (CONTENT_TYPE,
                                                        render_prometheus)
from analytics_zoo_tpu.observability.registry import (Counter, Gauge,
                                                      Histogram,
                                                      LogHistogram,
                                                      MetricsRegistry,
                                                      get_registry)
from analytics_zoo_tpu.observability.reporter import MetricsReporter, digest
from analytics_zoo_tpu.observability.tracing import (Span, Tracer,
                                                     span_coverage)

__all__ = [
    "CONTENT_TYPE", "Counter", "Gauge", "Histogram", "LogHistogram",
    "MetricsRegistry", "MetricsReporter", "Span", "Tracer", "digest",
    "get_registry", "render_prometheus", "span_coverage",
]
