"""On-demand profiler capture (ISSUE 6 tentpole, part 2).

Two instruments, both strictly zero-cost while idle:

- `ProfileCapture` — bounded, rotated `jax.profiler` trace captures.
  One capture at a time (an overlapping request raises
  `CaptureActiveError`, which the HTTP frontend maps to 409); artifact
  directories rotate under a root so an operator who forgets a cron'd
  capture can't fill the disk. Drives `POST /profile?seconds=N` on the
  frontend and `fit_keras(profile_steps=(start, stop))`.
- `StackSampler` — a host-side stack-sampling profiler for named
  threads (the serving pipeline's reader/decode/dispatch/sink). The
  existing spans say WHICH stage holds the host-side gap;
  the sampler says WHERE INSIDE it — `sys._current_frames()` sampled at
  `interval_s`, aggregated per (thread, innermost-frame), well below
  span granularity and cheap enough to run alongside a trace capture
  (one dict walk per sample, no tracing hooks installed — threads not
  being sampled pay nothing).

Neither touches the request path when inactive: no hooks, no wrappers —
the steady-state overhead of an attached-but-idle ProfileCapture is
zero by construction (test-asserted in tests/test_profiling_slo.py).
"""

from __future__ import annotations

import collections
import gzip
import json
import logging
import os
import shutil
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("analytics_zoo_tpu.observability")

# serving pipeline thread-name prefixes (server.py start() specs)
SERVING_THREAD_PREFIXES = ("serving-", "infer-replica-")

MAX_CAPTURE_SECONDS = 120.0

# jax.profiler's trace session is PROCESS-global, so the single-flight
# guard must be too: the frontend's capture and a concurrent
# fit_keras(profile_steps=...) window are separate ProfileCapture
# instances, and both must see one lock or the loser gets an opaque
# profiler error instead of the documented CaptureActiveError/409
_capture_lock = threading.Lock()


class CaptureActiveError(RuntimeError):
    """A capture is already running; the profiler is single-flight (two
    concurrent jax.profiler traces would corrupt each other's session)."""


class ProfileCapture:
    """Bounded, rotated `jax.profiler.trace` captures under one root.

    `start(tag)` begins a capture into a fresh artifact dir and returns
    its path; `stop()` ends it and returns a manifest (dir, files,
    seconds). `capture(seconds)` is the blocking convenience the HTTP
    endpoint uses. At most `max_artifacts` capture dirs are kept —
    oldest deleted first."""

    def __init__(self, root: str, max_artifacts: int = 8,
                 registry=None):
        if max_artifacts < 1:
            raise ValueError(
                f"max_artifacts must be >= 1, got {max_artifacts}")
        self.root = os.path.abspath(os.path.expanduser(root))
        self.max_artifacts = int(max_artifacts)
        self._lock = _capture_lock           # process-wide single-flight
        self._active_dir: Optional[str] = None
        self._t0 = 0.0
        self._seq = 0
        from analytics_zoo_tpu.observability.registry import get_registry
        reg = registry if registry is not None else get_registry()
        self._captures = reg.counter(
            "profile_captures_total",
            "profiler captures taken, by how they ended (ok, error)")
        self._active_gauge = reg.gauge(
            "profile_capture_active",
            "1 while a profiler capture is running")
        # seed the series only while no capture runs anywhere: the gauge
        # (like the lock and the jax profiler session) is process-global,
        # and constructing a second instance mid-capture (a fit's
        # profile_steps window while the frontend traces) must not
        # report the live capture as finished
        if not _capture_lock.locked():
            self._active_gauge.set(0)

    @property
    def active(self) -> bool:
        return self._active_dir is not None

    def start(self, tag: str = "capture") -> str:
        """Begin a capture; returns the artifact dir. Raises
        `CaptureActiveError` when one is already running."""
        if not self._lock.acquire(blocking=False):
            raise CaptureActiveError(
                "a profiler capture is already running")
        try:
            os.makedirs(self.root, exist_ok=True)
            self._seq += 1
            safe_tag = "".join(c if c.isalnum() or c in "-_" else "-"
                               for c in tag)[:48] or "capture"
            art = os.path.join(
                self.root,
                time.strftime("%Y%m%d-%H%M%S") + f"-{self._seq:03d}-"
                + safe_tag)
            os.makedirs(art, exist_ok=True)
            import jax
            jax.profiler.start_trace(art)
        except Exception:
            self._lock.release()
            self._captures.inc(outcome="error")
            raise
        self._active_dir = art
        self._t0 = time.perf_counter()
        self._active_gauge.set(1)
        return art

    def stop(self) -> Dict[str, object]:
        """End the running capture; returns {dir, files, seconds}. The
        rotation pass runs here, so the bound holds without a janitor."""
        if self._active_dir is None:
            raise RuntimeError("no capture is running")
        art, self._active_dir = self._active_dir, None
        seconds = time.perf_counter() - self._t0
        try:
            import jax
            jax.profiler.stop_trace()
            self._captures.inc(outcome="ok")
        except Exception as e:  # noqa: BLE001 — a dead profiler session
            # must still release the single-flight lock
            self._captures.inc(outcome="error")
            log.warning("stop_trace failed: %s: %s", type(e).__name__, e)
        finally:
            self._active_gauge.set(0)
            self._lock.release()
        files = sorted(
            os.path.relpath(os.path.join(dp, f), art)
            for dp, _dirs, fs in os.walk(art) for f in fs)
        self._rotate()
        return {"dir": art, "files": files,
                "seconds": round(seconds, 4)}

    def capture(self, seconds: float, tag: str = "capture",
                sample_threads: Optional[Sequence[str]] =
                SERVING_THREAD_PREFIXES,
                sample_interval_s: float = 0.005) -> Dict[str, object]:
        """Blocking bounded capture: start, sleep, stop. When
        `sample_threads` is given, a `StackSampler` runs alongside and
        its report lands in the manifest under "host_stacks" — one
        request answers both "what did the device do" (the trace
        artifact) and "where did the host threads spin" (the stacks)."""
        seconds = min(float(seconds), MAX_CAPTURE_SECONDS)
        if seconds <= 0:
            raise ValueError(f"seconds must be > 0, got {seconds}")
        sampler = None
        self.start(tag)
        try:
            if sample_threads:
                sampler = StackSampler(interval_s=sample_interval_s,
                                       thread_prefixes=sample_threads)
                sampler.start()
            time.sleep(seconds)
        finally:
            if sampler is not None:
                stacks = sampler.stop()
            manifest = self.stop()
        if sampler is not None:
            manifest["host_stacks"] = stacks
        return manifest

    def artifacts(self) -> List[str]:
        """Capture dirs under the root, oldest first."""
        if not os.path.isdir(self.root):
            return []
        return sorted(
            os.path.join(self.root, d) for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d)))

    def _rotate(self):
        arts = self.artifacts()
        for stale in arts[:max(0, len(arts) - self.max_artifacts)]:
            shutil.rmtree(stale, ignore_errors=True)


def load_trace_events(artifact_dir: str) -> List[dict]:
    """Parse the trace-event JSON out of a capture artifact (the
    `*.trace.json.gz` the jax profiler writes) — the "loadable" check
    tests and tools use without standing up Perfetto."""
    for dp, _dirs, files in os.walk(artifact_dir):
        for f in files:
            if f.endswith(".trace.json.gz"):
                with gzip.open(os.path.join(dp, f), "rt") as fh:
                    blob = json.load(fh)
                return blob.get("traceEvents", [])
    raise FileNotFoundError(
        f"no *.trace.json.gz under {artifact_dir}")


class StackSampler:
    """Low-overhead host-side stack sampling for named threads.

    A daemon thread snapshots `sys._current_frames()` every
    `interval_s` and, for each live thread whose name starts with one of
    `thread_prefixes`, counts the innermost application frame (and the
    full collapsed stack for flame-style aggregation). Threads outside
    the prefix set cost nothing; sampled threads cost one frame walk per
    tick — there are NO tracing hooks, so the sampled code runs at full
    speed between ticks.

    `stop()` (or `report()`) returns, per thread name, the top frames
    with sample counts and percentages — the attribution below the
    serving spans' granularity the ROADMAP's 0.24 ms host-gap item
    needs."""

    def __init__(self, interval_s: float = 0.005,
                 thread_prefixes: Sequence[str] = SERVING_THREAD_PREFIXES,
                 max_seconds: float = MAX_CAPTURE_SECONDS,
                 top: int = 10):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = float(interval_s)
        self.thread_prefixes = tuple(thread_prefixes)
        self.max_seconds = float(max_seconds)
        self.top = int(top)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # thread name -> Counter of "fn (file:line)" innermost frames
        self._frames: Dict[str, collections.Counter] = {}
        # thread name -> Counter of collapsed "a;b;c" stacks
        self._stacks: Dict[str, collections.Counter] = {}
        self._samples = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "StackSampler":
        if self._thread is not None:
            raise RuntimeError("sampler already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="stack-sampler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> Dict[str, object]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        return self.report()

    def __enter__(self) -> "StackSampler":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- sampling ----------------------------------------------------------
    def _loop(self):
        deadline = time.monotonic() + self.max_seconds
        while not self._stop.wait(self.interval_s):
            if time.monotonic() > deadline:
                return                     # bounded: never sample forever
            try:
                self._sample_once()
            except Exception:  # noqa: BLE001 — a torn frame snapshot
                continue       # (threads die mid-walk) is expected

    def _sample_once(self):
        names = {t.ident: t.name for t in threading.enumerate()
                 if t.name.startswith(self.thread_prefixes)}
        if not names:
            return
        frames = sys._current_frames()
        with self._lock:
            self._samples += 1
            for ident, name in names.items():
                frame = frames.get(ident)
                if frame is None:
                    continue
                stack = []
                f = frame
                while f is not None and len(stack) < 24:
                    code = f.f_code
                    stack.append(f"{code.co_name} "
                                 f"({os.path.basename(code.co_filename)}"
                                 f":{f.f_lineno})")
                    f = f.f_back
                self._frames.setdefault(
                    name, collections.Counter())[stack[0]] += 1
                self._stacks.setdefault(
                    name, collections.Counter())[";".join(
                        reversed(stack))] += 1

    # -- views -------------------------------------------------------------
    def report(self) -> Dict[str, object]:
        """{thread: {samples, top: [{frame, count, pct}]}} plus the
        total tick count — percentages are of that thread's samples."""
        with self._lock:
            out: Dict[str, object] = {"samples": self._samples,
                                      "interval_s": self.interval_s,
                                      "threads": {}}
            for name, ctr in sorted(self._frames.items()):
                n = sum(ctr.values())
                out["threads"][name] = {
                    "samples": n,
                    "top": [{"frame": fr, "count": c,
                             "pct": round(100.0 * c / n, 1)}
                            for fr, c in ctr.most_common(self.top)],
                }
            return out

    def top_stacks(self, thread: str, n: int = 5) -> List[Tuple[str, int]]:
        with self._lock:
            ctr = self._stacks.get(thread)
            return list(ctr.most_common(n)) if ctr else []
