"""Prometheus text-format (0.0.4) exposition for a `MetricsRegistry`.

The frontend's `GET /metrics` serves this when the client sends
`Accept: text/plain` (content negotiation in `serving/http_frontend.py`;
the JSON snapshot remains the default). Rendering rules:

- `# HELP` / `# TYPE` per family, series lines `name{label="v"} value`.
- Counters/gauges render their value directly.
- Histograms render the Prometheus cumulative-bucket triplet:
  `name_bucket{le="<upper>"}` for every NON-EMPTY log bucket (the
  geometry has 107 buckets; emitting only occupied ones keeps scrape
  payloads proportional to observed spread, and cumulative counts stay
  valid on any bucket subset as long as `+Inf` closes the series),
  plus `name_sum` and `name_count`.

Label values escape `\\`, `"` and newlines per the exposition spec.
"""

from __future__ import annotations

from typing import Dict, List

from analytics_zoo_tpu.observability.registry import (Counter, Gauge,
                                                      Histogram,
                                                      MetricsRegistry)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def render_prometheus(registry: MetricsRegistry) -> str:
    """The full registry as Prometheus 0.0.4 text. Ends with the
    spec-required trailing newline."""
    lines: List[str] = []
    for fam in registry.families():
        help_text = _escape(fam.description) if fam.description else fam.name
        lines.append(f"# HELP {fam.name} {help_text}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        if isinstance(fam, (Counter, Gauge)):
            for s in fam._series_snapshot():
                lines.append(f"{fam.name}{_fmt_labels(s['labels'])} "
                             f"{_fmt_value(s['value'])}")
        elif isinstance(fam, Histogram):
            for key in fam.label_keys():
                labels = dict(key)
                # freeze bucket counts under the family lock so the
                # cumulative series can't go non-monotonic mid-render
                with fam._lock:
                    h = fam._series[key]
                    counts = list(h.counts)
                    total, count = h.total, h.count
                    uppers = [h.bucket_upper(i) for i in range(len(counts))]
                cum = 0
                for i, c in enumerate(counts):
                    if not c:
                        continue
                    cum += c
                    le = 'le="%s"' % _fmt_value(uppers[i])
                    lines.append(f"{fam.name}_bucket"
                                 f"{_fmt_labels(labels, le)} {cum}")
                inf = 'le="+Inf"'
                lines.append(f"{fam.name}_bucket"
                             f"{_fmt_labels(labels, inf)} {count}")
                lines.append(f"{fam.name}_sum{_fmt_labels(labels)} "
                             f"{_fmt_value(total)}")
                lines.append(f"{fam.name}_count{_fmt_labels(labels)} "
                             f"{count}")
    return "\n".join(lines) + "\n"
