"""Declarative SLOs with burn-rate evaluation (ISSUE 6 tentpole, part 4).

The serving config declares objectives —

    params:
      slo:
        latency_ms: 50          # p-quantile latency target
        latency_quantile: 0.95
        availability: 0.999     # non-degraded fraction of results
        window_s: 300

— and `SLOTracker` evaluates them against the metrics the pipeline
already publishes: windowed latency quantiles from the
`serving_batch_ms` log-histogram's bucket counts (delta between ring
samples, so the window really is a window, not process-lifetime), and
availability from `serving_records_total{outcome=served|failed}` (the
sink counts NaN-degraded records as `failed`).

Burn rate is the standard SRE ratio — how fast the error budget is
being spent relative to its sustainable rate:

- availability: (1 - observed) / (1 - target); 1.0 = spending exactly
  the budget, >1 = burning it down.
- latency: fraction of window observations over the target, over the
  allowed fraction (1 - quantile).

`MetricsReporter(slo=tracker)` evaluates on its digest cadence (so the
burn gauges stay fresh for scrapes), and `ClusterServing.health()` /
the frontend's `/healthz` evaluate on demand (internally rate-limited).
Evaluation publishes `slo_latency_ms`, `slo_availability`,
`slo_burn_rate{objective}`, and `slo_met{objective}` gauges.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger("analytics_zoo_tpu.observability")


@dataclass
class SLOObjectives:
    """The declarative objective set (all optional — an SLO block with
    only latency, or only availability, is legal)."""

    latency_ms: Optional[float] = None
    latency_quantile: float = 0.95
    availability: Optional[float] = None
    window_s: float = 300.0
    latency_family: str = "serving_batch_ms"

    def validate(self) -> "SLOObjectives":
        if self.latency_ms is not None and self.latency_ms <= 0:
            raise ValueError(
                f"slo.latency_ms={self.latency_ms} must be > 0")
        if not (0.0 < self.latency_quantile < 1.0):
            raise ValueError(
                f"slo.latency_quantile={self.latency_quantile} must be "
                "in (0, 1)")
        if self.availability is not None and not (
                0.0 < self.availability <= 1.0):
            raise ValueError(
                f"slo.availability={self.availability} must be in (0, 1]")
        if self.window_s <= 0:
            raise ValueError(
                f"slo.window_s={self.window_s} must be > 0")
        return self

    @property
    def empty(self) -> bool:
        return self.latency_ms is None and self.availability is None


class _Sample:
    """One ring entry: cumulative state at time t, so (cur - old) is the
    window accumulation."""

    __slots__ = ("t", "counts", "count", "served", "failed", "base",
                 "growth")

    def __init__(self, t, counts, count, served, failed,
                 base=1e-3, growth=1.2):
        self.t = t
        self.counts = counts       # summed histogram bucket counts
        self.count = count
        self.served = served
        self.failed = failed
        self.base = base
        self.growth = growth


def _window_quantile(counts: List[int], q: float, base: float,
                     growth: float) -> float:
    """Quantile over a delta bucket-count vector, interpolated inside
    the crossing bucket (same estimator as LogHistogram.percentile,
    minus the min/max clamp a delta view cannot know)."""
    total = sum(counts)
    if not total:
        return 0.0
    target = q * total
    seen = 0
    for i, c in enumerate(counts):
        if not c:
            continue
        if seen + c >= target:
            lo = base * (growth ** i)
            hi = lo * growth
            return lo + (hi - lo) * (target - seen) / c
        seen += c
    return base * (growth ** len(counts))


class SLOTracker:
    """Evaluate declared objectives over a sliding window of registry
    state. Thread-safe; `evaluate()` is internally rate-limited (at most
    one fresh evaluation per `min_interval_s` — healthz polls and the
    reporter can both call it freely)."""

    def __init__(self, objectives: SLOObjectives, registry=None,
                 min_interval_s: float = 1.0):
        from analytics_zoo_tpu.observability.registry import get_registry
        self.objectives = objectives.validate()
        self.registry = registry if registry is not None else get_registry()
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._ring: List[_Sample] = []
        self._last: Optional[Dict[str, Any]] = None
        self._last_t = 0.0
        self._was_met = True
        self._auto_stop = threading.Event()
        self._auto_thread: Optional[threading.Thread] = None

    # -- self-driving evaluation ------------------------------------------
    def start_auto(self, interval_s: Optional[float] = None
                   ) -> "SLOTracker":
        """Keep the window warm from a daemon thread: without one, SLO
        detection silently depends on something polling /metrics or
        /healthz more often than `window_s` — scrapes farther apart
        than the window would empty the ring and every evaluation would
        be vacuously met. `ClusterServing.start()` drives this when
        objectives are configured; the interval defaults to window_s/4
        capped at 15 s."""
        if self._auto_thread is not None:
            return self
        interval = interval_s if interval_s is not None \
            else min(self.objectives.window_s / 4.0, 15.0)
        self._auto_stop.clear()

        def loop():
            while not self._auto_stop.wait(interval):
                try:
                    self.evaluate(force=True)
                except Exception as e:  # noqa: BLE001 — keep sampling
                    log.debug("slo auto-evaluation failed: %s: %s",
                              type(e).__name__, e)

        self._auto_thread = threading.Thread(target=loop,
                                             name="slo-evaluator",
                                             daemon=True)
        self._auto_thread.start()
        return self

    def stop_auto(self):
        self._auto_stop.set()
        if self._auto_thread is not None:
            self._auto_thread.join(timeout=5)
            self._auto_thread = None

    # -- raw state ---------------------------------------------------------
    def _histogram_state(self) -> Tuple[List[int], int, float, float]:
        """Summed bucket counts across every series of the latency
        family (plus geometry); zeros when the family doesn't exist."""
        from analytics_zoo_tpu.observability.registry import Histogram
        fam = self.registry.get(self.objectives.latency_family)
        if not isinstance(fam, Histogram):
            return [], 0, 1e-3, 1.2
        counts: List[int] = []
        total = 0
        base, growth = 1e-3, 1.2
        for key in fam.label_keys():
            h = fam.child(**dict(key))
            with fam._lock:
                base, growth = h.base, h.growth
                if not counts:
                    counts = list(h.counts)
                else:
                    counts = [a + b for a, b in zip(counts, h.counts)]
                total += h.count
        return counts, total, base, growth

    def _record_state(self) -> Tuple[float, float]:
        fam = self.registry.get("serving_records_total")
        if fam is None:
            return 0.0, 0.0
        return fam.value(outcome="served"), fam.value(outcome="failed")

    # -- evaluation --------------------------------------------------------
    def evaluate(self, force: bool = False) -> Dict[str, Any]:
        with self._lock:
            now = time.monotonic()
            if (not force and self._last is not None
                    and now - self._last_t < self.min_interval_s):
                return self._last
            counts, count, base, growth = self._histogram_state()
            served, failed = self._record_state()
            cur = _Sample(now, counts, count, served, failed,
                          base=base, growth=growth)
            window = self.objectives.window_s
            # baseline: the oldest sample still inside the window
            self._ring = [s for s in self._ring if now - s.t <= window]
            old = self._ring[0] if self._ring else None
            self._ring.append(cur)
            result = self._evaluate_pair(old, cur)
            self._publish(result)
            # one WARNING per met → violated edge, owned HERE so every
            # driver (auto thread, reporter, healthz, scrape) shares a
            # single edge detector instead of each logging its own
            met = bool(result.get("met", True))
            if not met and self._was_met:
                log.warning(
                    "SLO violated: burn rates %s",
                    {k: v.get("burn_rate") for k, v in result.items()
                     if isinstance(v, dict) and "burn_rate" in v})
            self._was_met = met
            self._last, self._last_t = result, now
            return result

    def _evaluate_pair(self, old: Optional[_Sample],
                       cur: _Sample) -> Dict[str, Any]:
        obj = self.objectives
        out: Dict[str, Any] = {
            "met": True,
            "window_s": round(cur.t - old.t, 1) if old else 0.0,
        }
        if obj.latency_ms is not None:
            if old is None:
                # no baseline yet: process-lifetime cumulative counts are
                # NOT a window — a first /healthz poll hours after an old,
                # recovered outage must not report it as a live violation
                dcounts, n = [], 0
            elif old.counts and cur.counts:
                dcounts = [c - o for c, o in zip(cur.counts, old.counts)]
                n = cur.count - old.count
            else:
                dcounts, n = list(cur.counts), cur.count
            base, growth = cur.base, cur.growth
            lat: Dict[str, Any] = {"target_ms": obj.latency_ms,
                                   "quantile": obj.latency_quantile,
                                   "count": max(0, n)}
            if n > 0:
                observed = _window_quantile(dcounts, obj.latency_quantile,
                                            base, growth)
                # observations strictly above the target's bucket are
                # over target; the crossing bucket itself counts pro
                # rata of where the target falls inside it
                over = 0.0
                for i, c in enumerate(dcounts):
                    if c <= 0:
                        continue
                    lo = base * (growth ** i)
                    hi = lo * growth
                    if lo >= obj.latency_ms:
                        over += c
                    elif hi > obj.latency_ms:
                        over += c * (hi - obj.latency_ms) / (hi - lo)
                frac_over = min(1.0, over / n)
                burn = frac_over / max(1e-9, 1.0 - obj.latency_quantile)
                lat.update(observed_ms=round(observed, 3),
                           frac_over_target=round(frac_over, 6),
                           burn_rate=round(burn, 3),
                           met=burn <= 1.0)
            else:
                lat.update(observed_ms=None, frac_over_target=0.0,
                           burn_rate=0.0, met=True)   # no data: vacuous
            out["latency"] = lat
            out["met"] = out["met"] and lat["met"]
        if obj.availability is not None:
            # same no-baseline rule as latency: the first sample only
            # seeds the ring
            dserved = cur.served - old.served if old else 0.0
            dfailed = cur.failed - old.failed if old else 0.0
            avail: Dict[str, Any] = {"target": obj.availability,
                                     "served": dserved,
                                     "failed": dfailed}
            if dserved > 0:
                observed = max(0.0, (dserved - dfailed) / dserved)
                budget = max(1e-9, 1.0 - obj.availability)
                burn = (1.0 - observed) / budget
                avail.update(observed=round(observed, 6),
                             burn_rate=round(burn, 3),
                             met=burn <= 1.0)
            else:
                avail.update(observed=None, burn_rate=0.0, met=True)
            out["availability"] = avail
            out["met"] = out["met"] and avail["met"]
        return out

    def _publish(self, result: Dict[str, Any]) -> None:
        reg = self.registry
        burn_g = reg.gauge(
            "slo_burn_rate",
            "error-budget burn rate per objective (1.0 = spending "
            "exactly the budget; >1 = burning it down)")
        met_g = reg.gauge(
            "slo_met", "1 when the objective holds over the window, "
            "else 0, per objective (and 'all')")
        lat = result.get("latency")
        if lat is not None:
            reg.gauge("slo_latency_target_ms",
                      "declared latency objective").set(lat["target_ms"])
            if lat.get("observed_ms") is not None:
                reg.gauge(
                    "slo_latency_ms",
                    "observed windowed latency at the objective's "
                    "quantile").set(lat["observed_ms"],
                                    quantile=str(lat["quantile"]))
            burn_g.set(lat["burn_rate"], objective="latency")
            met_g.set(1.0 if lat["met"] else 0.0, objective="latency")
        avail = result.get("availability")
        if avail is not None:
            reg.gauge("slo_availability_target",
                      "declared availability objective"
                      ).set(avail["target"])
            if avail.get("observed") is not None:
                reg.gauge("slo_availability",
                          "observed windowed availability "
                          "(non-degraded fraction of served records)"
                          ).set(avail["observed"])
            burn_g.set(avail["burn_rate"], objective="availability")
            met_g.set(1.0 if avail["met"] else 0.0,
                      objective="availability")
        met_g.set(1.0 if result["met"] else 0.0, objective="all")
