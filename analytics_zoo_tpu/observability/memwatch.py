"""Device-memory telemetry (ISSUE 6 tentpole, part 3).

HBM is the scarcest resource on a chip and nothing in PR 1-5 watched
it: a leaked executable table or an un-dropped device reference shows
up today as an OOM three hours into a run. This module publishes
per-device live/peak byte gauges and gives tests a leak-check
assertion.

Sources, best first:

- `device.memory_stats()` (real TPU runtimes): `bytes_in_use`,
  `peak_bytes_in_use`, `bytes_limit`.
- fallback (CPU/forced-host backends return None there): sum of
  `jax.live_arrays()` nbytes grouped by committed device, with the peak
  tracked by the watcher across samples. Same gauges either way, so
  dashboards don't care which backend is under them.

`DeviceMemoryWatcher` is the periodic publisher (a daemon thread, like
`MetricsReporter`); `sample()` is the one-shot used by the watcher, the
`/healthz` payload, and `leak_check()` — the context manager tests wrap
around a workload to assert it returns device memory to baseline.
"""

from __future__ import annotations

import gc
import logging
import threading
from typing import Dict, Optional

log = logging.getLogger("analytics_zoo_tpu.observability")


def _device_label(d) -> str:
    return f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"


def device_memory_snapshot(devices=None) -> Dict[str, Dict[str, float]]:
    """{device label: {live_bytes, peak_bytes?, limit_bytes?, source}}.
    Never raises: a backend without either source reports live_bytes=0
    with source "none"."""
    import jax
    devs = list(devices) if devices is not None else jax.local_devices()
    out: Dict[str, Dict[str, float]] = {}
    live_fallback: Optional[Dict[int, float]] = None
    for d in devs:
        label = _device_label(d)
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend without the API
            stats = None
        if stats:
            entry = {"live_bytes": float(stats.get("bytes_in_use", 0.0)),
                     "source": "memory_stats"}
            if "peak_bytes_in_use" in stats:
                entry["peak_bytes"] = float(stats["peak_bytes_in_use"])
            if "bytes_limit" in stats:
                entry["limit_bytes"] = float(stats["bytes_limit"])
            out[label] = entry
            continue
        if live_fallback is None:
            live_fallback = {}
            try:
                for a in jax.live_arrays():
                    # per-device bytes come from the array's ACTUAL
                    # shards: a replicated array stores a FULL copy on
                    # every device (N × nbytes total), an fsdp-sharded
                    # one stores nbytes/N per device — dividing nbytes
                    # evenly (the old accounting) made those two read
                    # identical, hiding exactly the footprint the
                    # sharded fit exists to shrink
                    # per-array staging dict, merged only on success:
                    # a shard read that fails partway (e.g. a buffer
                    # donated mid-sample by a concurrent train step)
                    # must not leave half the array counted AND then be
                    # fully re-added by the fallback
                    per_array: Dict[int, float] = {}
                    try:
                        for sh in a.addressable_shards:
                            key = getattr(sh.device, "id", 0)
                            per_array[key] = per_array.get(key, 0.0) \
                                + sh.data.nbytes
                    except Exception:  # noqa: BLE001 — no shards API
                        per_array = {}
                        for shard_dev in getattr(a, "devices",
                                                 lambda: [])():
                            key = getattr(shard_dev, "id", 0)
                            per_array[key] = per_array.get(key, 0.0) \
                                + a.nbytes / max(1, len(a.devices()))
                    for key, b in per_array.items():
                        live_fallback[key] = live_fallback.get(
                            key, 0.0) + b
            except Exception:  # noqa: BLE001 — diagnostics only
                live_fallback = {}
        out[label] = {"live_bytes": live_fallback.get(
            getattr(d, "id", 0), 0.0), "source": "live_arrays"}
    return out


def tree_device_bytes(tree) -> Dict[str, float]:
    """Exact per-device bytes of one pytree's leaves, from their ACTUAL
    shards: {device label: bytes}. A replicated leaf contributes its
    full nbytes to every device it lives on; an fsdp-sharded leaf
    contributes nbytes/fsdp per device. This is the focused footprint
    probe the sharded-training bench/tests assert 1/fsdp memory with —
    `device_memory_snapshot` reports the whole process, this reports
    one tree."""
    import jax
    out: Dict[str, float] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            continue                       # host leaf: no device bytes
        for sh in shards:
            label = _device_label(sh.device)
            out[label] = out.get(label, 0.0) + sh.data.nbytes
    return out


class DeviceMemoryWatcher:
    """Daemon thread publishing per-device memory gauges every
    `interval_s`:

    - `device_memory_live_bytes{device}` — bytes in use now
    - `device_memory_peak_bytes{device}` — high-water mark (runtime's
      when available, else the max this watcher has observed)
    - `device_memory_limit_bytes{device}` — capacity, when the runtime
      reports one

    `sample()` publishes once and returns the snapshot, so the watcher
    is equally usable one-shot (healthz, bench teardown)."""

    def __init__(self, interval_s: float = 10.0, registry=None,
                 devices=None):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        from analytics_zoo_tpu.observability.registry import get_registry
        self.registry = registry if registry is not None else get_registry()
        self.interval_s = float(interval_s)
        self.devices = devices
        self._peaks: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def sample(self) -> Dict[str, Dict[str, float]]:
        snap = device_memory_snapshot(self.devices)
        live_g = self.registry.gauge(
            "device_memory_live_bytes",
            "device memory in use, per device (memory_stats or live "
            "array accounting)")
        peak_g = self.registry.gauge(
            "device_memory_peak_bytes",
            "device memory high-water mark, per device")
        limit_g = self.registry.gauge(
            "device_memory_limit_bytes",
            "device memory capacity, per device (when the runtime "
            "reports it)")
        for label, entry in snap.items():
            live = entry["live_bytes"]
            live_g.set(live, device=label)
            peak = entry.get("peak_bytes")
            if peak is None:
                # fallback source: track the max WE have seen
                peak = max(self._peaks.get(label, 0.0), live)
                entry["peak_bytes"] = peak
            self._peaks[label] = max(self._peaks.get(label, 0.0), peak)
            peak_g.set(self._peaks[label], device=label)
            if "limit_bytes" in entry:
                limit_g.set(entry["limit_bytes"], device=label)
        return snap

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception as e:  # noqa: BLE001 — the watcher must
                # outlive any backend hiccup it is watching
                log.debug("memory sample failed: %s: %s",
                          type(e).__name__, e)

    def start(self) -> "DeviceMemoryWatcher":
        if self._thread is not None:
            raise RuntimeError("watcher already started")
        self.sample()                       # gauges exist from t0
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="device-memory-watcher",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "DeviceMemoryWatcher":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class DeviceMemoryLeak(AssertionError):
    """Raised by `leak_check` when live device bytes grew past the
    tolerance — an AssertionError so pytest renders it as a failure."""


class leak_check:  # noqa: N801 — context-manager, used like a function
    """Assert a workload returns device memory to baseline:

        with leak_check(tolerance_bytes=1 << 20):
            model.predict(batch)           # everything it allocates
                                           # must be released again

    Live bytes are measured (after a `gc.collect()` — dropped Python
    refs must not read as device leaks) before and after; growth beyond
    `tolerance_bytes` raises `DeviceMemoryLeak` naming the per-device
    deltas. The `grew` attribute carries the measured growth either
    way, for tests that want the number."""

    def __init__(self, tolerance_bytes: float = 1 << 20, devices=None):
        self.tolerance_bytes = float(tolerance_bytes)
        self.devices = devices
        self.before: Dict[str, float] = {}
        self.grew: Dict[str, float] = {}

    @staticmethod
    def _live(devices) -> Dict[str, float]:
        gc.collect()
        return {label: e["live_bytes"]
                for label, e in device_memory_snapshot(devices).items()}

    def __enter__(self) -> "leak_check":
        self.before = self._live(self.devices)
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is not None:
            return False               # the workload failed; report THAT
        after = self._live(self.devices)
        self.grew = {label: after.get(label, 0.0) - b
                     for label, b in self.before.items()
                     if after.get(label, 0.0) - b > 0}
        leaked = {label: g for label, g in self.grew.items()
                  if g > self.tolerance_bytes}
        if leaked:
            detail = ", ".join(f"{label}: +{g:,.0f} B"
                               for label, g in sorted(leaked.items()))
            raise DeviceMemoryLeak(
                f"device memory grew past the {self.tolerance_bytes:,.0f}"
                f" B tolerance ({detail})")
        return False
