"""Request-scoped span tracing with Chrome trace-event export.

The reference's only tracing is `Supportive.timing` log lines — spans
that exist for one `grep` and die. This tracer keeps them: finished
spans land in a bounded ring buffer and export as Chrome trace-event
JSON (`chrome://tracing` / Perfetto's legacy JSON loader), so "where did
this request spend its time" is answerable per request, per stage.

Two ways to produce spans:

- `with tracer.span("decode", trace_id=uri): ...` — scoped, nests via a
  thread-local stack (children inherit the enclosing span's trace_id and
  record their parent's name).
- `tracer.add_span("queue_wait", t0, t1, ...)` — explicit timestamps,
  for intervals that start in one thread and end in another (the
  inter-stage queue waits in `serving/server.py`).

Request-ID propagation: a span carries `trace_id` (one request) or
`trace_ids` (a batch span covering many records — the serving pipeline
batches, so per-stage spans tag every record they carried instead of
multiplying span count by batch size). `tracer.spans(trace_id=uri)`
matches both. Timestamps are `time.perf_counter()` seconds rebased to
the tracer's epoch, so spans from different threads order correctly.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple


class Span:
    __slots__ = ("name", "cat", "start", "duration", "trace_id",
                 "trace_ids", "tid", "parent", "args")

    def __init__(self, name: str, cat: str, start: float, duration: float,
                 trace_id: Optional[str] = None,
                 trace_ids: Optional[Tuple[str, ...]] = None,
                 tid: str = "", parent: Optional[str] = None,
                 args: Optional[Dict[str, Any]] = None):
        self.name = name
        self.cat = cat
        self.start = start            # perf_counter seconds
        self.duration = duration     # seconds
        self.trace_id = trace_id
        self.trace_ids = trace_ids
        self.tid = tid
        self.parent = parent
        self.args = args or {}

    @property
    def end(self) -> float:
        return self.start + self.duration

    def covers(self, trace_id: str) -> bool:
        return (self.trace_id == trace_id
                or (self.trace_ids is not None
                    and trace_id in self.trace_ids))

    def __repr__(self):
        return (f"Span({self.name} {self.duration * 1e3:.3f}ms "
                f"trace_id={self.trace_id})")


class _ScopedSpan:
    """Context manager returned by `Tracer.span`."""

    __slots__ = ("_tracer", "name", "cat", "trace_id", "trace_ids",
                 "args", "_t0", "_parent")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 trace_id: Optional[str],
                 trace_ids: Optional[Sequence[str]],
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.trace_ids = tuple(trace_ids) if trace_ids else None
        self.args = args

    def __enter__(self) -> "_ScopedSpan":
        stack = self._tracer._stack()
        self._parent = stack[-1] if stack else None
        if self.trace_id is None and self._parent is not None:
            self.trace_id = self._parent.trace_id
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        end = time.perf_counter()
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        self._tracer._emit(Span(
            self.name, self.cat, self._t0, end - self._t0,
            trace_id=self.trace_id, trace_ids=self.trace_ids,
            tid=threading.current_thread().name,
            parent=self._parent.name if self._parent else None,
            args=self.args))
        return False


class Tracer:
    """Bounded span collector. `max_spans` caps memory: a serving
    process tracing forever keeps the most recent window (the Chrome
    JSON is a debugging view, not an archive).

    `engine` names the producing process (engine id / gateway id) and
    namespaces the Chrome-trace `tid` as ``engine:thread`` so merged
    multi-process views never interleave unrelated stages onto one row.
    `registry` mirrors ring overflow into
    `observability_spans_dropped_total` so an unscraped long-running
    engine's span loss is visible on a scrape, not only in `.dropped`.
    `add_sink(fn)` registers a callable invoked with every finished span
    (the fleet span exporter taps the flow here); sink errors are
    swallowed — telemetry must never fail the serving path."""

    def __init__(self, max_spans: int = 20000,
                 registry=None, engine: Optional[str] = None):
        self._spans: "collections.deque[Span]" = collections.deque(
            maxlen=max_spans)
        self._lock = threading.Lock()
        self._local = threading.local()
        self.epoch = time.perf_counter()
        self.dropped = 0
        self.engine = engine
        self._sinks: List[Any] = []
        self._dropped_counter = None
        if registry is not None:
            self._dropped_counter = registry.counter(
                "observability_spans_dropped_total",
                "finished spans evicted from the tracer's bounded ring "
                "(the trace window is smaller than the traffic it saw)")

    def _stack(self) -> List[_ScopedSpan]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def add_sink(self, fn) -> None:
        """Register `fn(span)` to observe every finished span."""
        self._sinks.append(fn)

    def remove_sink(self, fn) -> None:
        try:
            self._sinks.remove(fn)
        except ValueError:
            pass

    def _emit(self, span: Span):
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
                if self._dropped_counter is not None:
                    labels = {"engine": self.engine} if self.engine else {}
                    self._dropped_counter.inc(**labels)
            self._spans.append(span)
        for sink in self._sinks:
            try:
                sink(span)
            except Exception:  # noqa: BLE001 — a broken exporter must
                pass           # never fail the traced code path

    # -- producing ---------------------------------------------------------
    def span(self, name: str, trace_id: Optional[str] = None,
             cat: str = "serving",
             trace_ids: Optional[Sequence[str]] = None,
             args: Optional[Dict[str, Any]] = None) -> _ScopedSpan:
        return _ScopedSpan(self, name, cat, trace_id, trace_ids, args)

    def add_span(self, name: str, start: float, end: float,
                 trace_id: Optional[str] = None, cat: str = "serving",
                 trace_ids: Optional[Sequence[str]] = None,
                 tid: Optional[str] = None,
                 args: Optional[Dict[str, Any]] = None):
        """Record a span from explicit `time.perf_counter()` endpoints —
        the cross-thread case (queue waits begin at the producer's `put`
        and end at the consumer's `get`)."""
        self._emit(Span(name, cat, start, max(0.0, end - start),
                        trace_id=trace_id,
                        trace_ids=tuple(trace_ids) if trace_ids else None,
                        tid=tid or threading.current_thread().name,
                        args=args))

    # -- consuming ---------------------------------------------------------
    def spans(self, trace_id: Optional[str] = None) -> List[Span]:
        with self._lock:
            spans = list(self._spans)
        if trace_id is None:
            return spans
        return [s for s in spans if s.covers(trace_id)]

    def clear(self):
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def chrome_trace(self, trace_id: Optional[str] = None
                     ) -> Dict[str, Any]:
        """Chrome trace-event JSON (the `traceEvents` array form): open
        in Perfetto (ui.perfetto.dev → legacy JSON) or chrome://tracing.
        Complete events (`ph: "X"`), microsecond timestamps rebased to
        the tracer epoch, one row per producing thread."""
        events = []
        pid = os.getpid()
        for s in self.spans(trace_id):
            args: Dict[str, Any] = dict(s.args)
            if s.trace_id is not None:
                args["trace_id"] = s.trace_id
            if s.trace_ids is not None:
                args["trace_ids"] = list(s.trace_ids)
            if s.parent is not None:
                args["parent"] = s.parent
            events.append({
                "name": s.name,
                "cat": s.cat,
                "ph": "X",
                "ts": round((s.start - self.epoch) * 1e6, 3),
                "dur": round(s.duration * 1e6, 3),
                "pid": pid,
                "tid": (f"{self.engine}:{s.tid}" if self.engine
                        else s.tid),
                "args": args,
            })
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_chrome_trace(self, path: str,
                           trace_id: Optional[str] = None) -> str:
        with open(path, "w") as fh:
            json.dump(self.chrome_trace(trace_id), fh)
        return path


def span_to_dict(span: Span, epoch: float = 0.0) -> Dict[str, Any]:
    """Wire form of a span: start rebased to `epoch` (the producing
    tracer's epoch, so exported times are process-relative seconds),
    empty fields omitted. Inverse of `span_from_dict`."""
    d: Dict[str, Any] = {"name": span.name, "cat": span.cat,
                         "s": round(span.start - epoch, 9),
                         "d": round(span.duration, 9)}
    if span.trace_id is not None:
        d["id"] = span.trace_id
    if span.trace_ids:
        d["ids"] = list(span.trace_ids)
    if span.tid:
        d["tid"] = span.tid
    if span.parent is not None:
        d["parent"] = span.parent
    if span.args:
        d["args"] = span.args
    return d


def span_from_dict(d: Dict[str, Any]) -> Span:
    ids = d.get("ids")
    return Span(d.get("name", ""), d.get("cat", "serving"),
                float(d.get("s", 0.0)), float(d.get("d", 0.0)),
                trace_id=d.get("id"),
                trace_ids=tuple(ids) if ids else None,
                tid=d.get("tid", ""), parent=d.get("parent"),
                args=d.get("args"))


def span_coverage(spans: Iterable[Span], start: float, end: float) -> float:
    """Fraction of [start, end] (perf_counter seconds) covered by the
    union of the spans' intervals — the acceptance metric for "spans
    cover >= 95% of the request's measured end-to-end latency"."""
    if end <= start:
        return 0.0
    ivals = sorted((max(s.start, start), min(s.end, end)) for s in spans)
    covered = 0.0
    cur_lo = cur_hi = None
    for lo, hi in ivals:
        if hi <= lo:
            continue
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                covered += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    if cur_hi is not None:
        covered += cur_hi - cur_lo
    return covered / (end - start)
