"""Periodic one-line metrics digest — the "is it healthy" glance.

`MetricsReporter` wakes every `interval_s`, snapshots the registry, and
logs one INFO line: counters as value with rate-since-last-report,
gauges as current value, histograms as `n/p50/p99`. Optionally mirrors
the snapshot into a TensorBoard `SummaryWriter`
(`utils/tensorboard.write_metrics_snapshot`), so long trainings get the
same numbers in TB that the log line shows.

Used by `learn/trainer.fit_keras(metrics_report_s=...)` and available
standalone around any workload:

    with MetricsReporter(interval_s=30):
        serve_forever()
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional

from analytics_zoo_tpu.observability.registry import (MetricsRegistry,
                                                      get_registry)

log = logging.getLogger("analytics_zoo_tpu.observability")


def digest(snapshot: Dict[str, Dict[str, Any]],
           delta: Optional[Dict[str, Dict[str, Any]]] = None,
           interval_s: Optional[float] = None) -> str:
    """Compress a registry snapshot into one log line. `delta` (from
    `MetricsRegistry.delta`) plus `interval_s` adds per-second rates to
    counters. Empty families are skipped."""
    parts = []
    for name, fam in snapshot.items():
        dseries = {}
        if delta and name in delta:
            dseries = {tuple(sorted(s["labels"].items())): s
                       for s in delta[name].get("series", [])}
        for s in fam.get("series", []):
            lbl = "".join(
                f"[{v}]" for _, v in sorted(s["labels"].items()))
            if fam["kind"] == "counter":
                txt = f"{name}{lbl}={s['value']:g}"
                d = dseries.get(tuple(sorted(s["labels"].items())))
                if d is not None and interval_s:
                    txt += f"({d['value'] / interval_s:.1f}/s)"
                parts.append(txt)
            elif fam["kind"] == "gauge":
                parts.append(f"{name}{lbl}={s['value']:g}")
            else:  # histogram
                if not s["count"]:
                    continue
                parts.append(
                    f"{name}{lbl}=n{s['count']}"
                    f"/p50:{s['p50']:g}/p99:{s['p99']:g}")
    return " ".join(parts) if parts else "(no metrics)"


class MetricsReporter:
    """Daemon thread logging a digest every `interval_s`. `start()` is
    idempotent-ish (a second start raises); `stop()` joins and logs one
    final digest so short runs still leave a record."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 interval_s: float = 30.0,
                 logger: Optional[logging.Logger] = None,
                 writer=None, slo=None):
        """`slo`: an `observability.slo.SLOTracker` — evaluated on every
        report BEFORE the digest, so the burn-rate/`slo_met` gauges are
        fresh in the logged line and for any scrape that follows the
        same cadence. The tracker itself owns the one-WARNING-per-
        (met → violated)-edge logging, so it fires whichever driver
        evaluates first."""
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.registry = registry if registry is not None else get_registry()
        self.interval_s = interval_s
        self.log = logger or log
        self.writer = writer       # optional tensorboard SummaryWriter
        self.slo = slo
        self._prev: Optional[Dict[str, Dict[str, Any]]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._step = 0

    def _evaluate_slo(self):
        if self.slo is None:
            return
        try:
            self.slo.evaluate()
        except Exception as e:  # noqa: BLE001 — SLO math must never
            # take down the digest thread it rides on
            self.log.debug("slo evaluation failed: %s: %s",
                           type(e).__name__, e)

    def _report(self):
        self._evaluate_slo()
        snap = self.registry.snapshot()
        d = self.registry.delta(self._prev) if self._prev else None
        self.log.info("metrics: %s", digest(snap, d, self.interval_s))
        if self.writer is not None:
            from analytics_zoo_tpu.utils.tensorboard import \
                write_metrics_snapshot
            self._step += 1
            write_metrics_snapshot(self.writer, snap, self._step)
        self._prev = snap

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self._report()

    def start(self) -> "MetricsReporter":
        if self._thread is not None:
            raise RuntimeError("reporter already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="metrics-reporter",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5)
        self._thread = None
        self._report()             # final digest: short runs still report

    def __enter__(self) -> "MetricsReporter":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
