"""Process-wide metrics registry — the telemetry spine (ISSUE 2 tentpole).

Before this layer, every subsystem kept private, incompatible counters:
the serving `Timer` window (`serving/timer.py`), the trainer's ad-hoc
throughput print (`learn/trainer.py`), the frontend's request timer, and
`StepTimer` in `utils/profiling.py`. The reference platform is no better —
`Supportive.timing` span logs and a per-batch window print
(`serving/utils/Supportive.scala`, `http/FrontEndApp.scala:131,241`) are
its whole observability story. This module gives them ONE API:

- `Counter` — monotonic, `_total`-suffixed (Prometheus convention).
- `Gauge` — last-write-wins scalar, or a live callable evaluated at
  snapshot time (queue depths).
- `Histogram` — the log-bucketed streaming histogram already proven in
  `serving/timer.py` (O(1) memory, O(1) record, ~9% bounded relative
  error from the bucket growth factor), generalized to any unit.

All three support labels (bounded-cardinality key=value pairs → one
child series per distinct label set) and are thread-safe. `snapshot()`
returns a plain-dict view; `delta(prev)` subtracts counter/histogram
accumulation so reporters can log rates. Prometheus text exposition
lives in `observability/prometheus.py`; span tracing in
`observability/tracing.py`.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

_NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)*$")

# Histogram geometry (shared with serving/timer.py, which uses base=1e-6
# for seconds): bucket i covers [base*growth^i, base*growth^(i+1)).
# The default base=1e-3 suits millisecond-valued metrics: 1 µs .. ~300 s.
DEFAULT_HIST_BASE = 1e-3
DEFAULT_HIST_GROWTH = 1.2
DEFAULT_HIST_BUCKETS = 107


class LogHistogram:
    """Streaming log-bucketed histogram: geometrically-spaced buckets,
    percentiles interpolated within the bucket crossing the target rank
    and clamped to the observed min/max. NOT thread-safe on its own —
    owners (`Histogram` family, serving `Timer`) hold their own lock."""

    __slots__ = ("base", "growth", "_log_growth", "n_buckets", "counts",
                 "count", "total", "vmin", "vmax")

    def __init__(self, base: float = DEFAULT_HIST_BASE,
                 growth: float = DEFAULT_HIST_GROWTH,
                 n_buckets: int = DEFAULT_HIST_BUCKETS):
        self.base = base
        self.growth = growth
        self._log_growth = math.log(growth)
        self.n_buckets = n_buckets
        self.clear()

    def clear(self):
        self.counts = [0] * self.n_buckets
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = 0.0

    def bucket_index(self, v: float) -> int:
        if v <= self.base:
            return 0
        i = int(math.log(v / self.base) / self._log_growth)
        return min(i, self.n_buckets - 1)

    def bucket_upper(self, i: int) -> float:
        return self.base * (self.growth ** (i + 1))

    def observe(self, v: float):
        self.count += 1
        self.total += v
        self.vmin = min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        self.counts[self.bucket_index(v)] += 1

    def percentile(self, q: float) -> float:
        """Value at quantile q in [0, 1]: find the bucket crossing rank
        q*count, interpolate linearly inside it, clamp to min/max so
        bucket-edge estimates never exceed reality."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            if seen + c >= target:
                lo = self.base * (self.growth ** i)
                hi = lo * self.growth
                est = lo + (hi - lo) * (target - seen) / c
                return min(max(est, self.vmin), self.vmax)
            seen += c
        return self.vmax

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _Metric:
    """Base family: child series keyed by sorted (label, value) tuples."""

    kind = "untyped"

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], Any] = {}

    def label_keys(self) -> List[Tuple[Tuple[str, str], ...]]:
        with self._lock:
            return list(self._series)

    def _series_snapshot(self) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def snapshot(self) -> Dict[str, Any]:
        return {"kind": self.kind, "description": self.description,
                "series": self._series_snapshot()}


class Counter(_Metric):
    """Monotonic counter. `inc()` with labels creates the child series on
    first use; negative increments raise (monotonicity is what makes
    rate() well-defined downstream)."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels):
        if value < 0:
            raise ValueError(
                f"counter {self.name} cannot decrease (inc {value})")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)

    def _series_snapshot(self):
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in sorted(self._series.items())]


class Gauge(_Metric):
    """Last-write-wins scalar. `set_function` installs a zero-argument
    callable evaluated at snapshot time — live views (queue depths,
    pool sizes) without a writer thread.

    Callback hardening (ISSUE 6 satellite): a raising callback can
    never propagate out of `snapshot()`, `value()`, the Prometheus
    render, or the reporter digest — the series reads NaN for that
    evaluation and the failure is counted
    (`observability_gauge_errors_total{gauge=...}` via the registry's
    `_on_error` hook), so one bad gauge degrades to one bad series
    instead of killing every scrape."""

    kind = "gauge"
    _on_error: Optional[Callable[[str], None]] = None   # registry hook

    def _callback_failed(self, exc: BaseException):
        hook = self._on_error
        if hook is None:
            return
        try:
            hook(self.name)
        except Exception:  # noqa: BLE001 — error accounting must never
            pass           # become a second error

    def set(self, value: float, **labels):
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def inc(self, value: float = 1.0, **labels):
        key = _label_key(labels)
        with self._lock:
            cur = self._series.get(key, 0.0)
            if callable(cur):
                raise ValueError(
                    f"gauge {self.name}{dict(key)} is callable-backed")
            self._series[key] = cur + value

    def dec(self, value: float = 1.0, **labels):
        self.inc(-value, **labels)

    def set_function(self, fn: Callable[[], float], **labels):
        with self._lock:
            self._series[_label_key(labels)] = fn

    def release_function(self, fn: Callable[[], float],
                         freeze: bool = False, **labels):
        """Compare-and-release the closure installed by `set_function` —
        the uninstall: a retiring provider (a stopped server, a closed
        replica pool) must not leave a closure pinning it in the
        process-wide registry. A no-op when another provider has since
        replaced the series (label keys are process-global, so an
        unconditional removal would destroy the NEWER owner's live
        telemetry). With ``freeze=True`` the series keeps its final
        float value instead of disappearing."""
        key = _label_key(labels)
        with self._lock:
            if self._series.get(key) is not fn:
                return
            if freeze:
                try:
                    self._series[key] = float(fn())
                    return
                except Exception:  # noqa: BLE001 — dead provider:
                    pass           # drop rather than freeze a NaN
            self._series.pop(key, None)

    def value(self, **labels) -> float:
        with self._lock:
            v = self._series.get(_label_key(labels), 0.0)
        if not callable(v):
            return v
        try:
            return float(v())
        except Exception as e:  # noqa: BLE001 — same contract as
            # snapshot: a raising provider reads NaN, never raises
            self._callback_failed(e)
            return float("nan")

    def _series_snapshot(self):
        with self._lock:
            items = sorted(self._series.items())
        out = []
        for k, v in items:
            if callable(v):
                try:
                    v = float(v())
                except Exception as e:  # noqa: BLE001 — a dead provider
                    # (e.g. a stopped server's queue) must not break
                    # snapshots; counted so the failure is visible
                    self._callback_failed(e)
                    v = float("nan")
            out.append({"labels": dict(k), "value": v})
        return out


class Histogram(_Metric):
    """Labeled family of `LogHistogram`s. Observations are in the unit
    the name's suffix declares (`_ms`, `_bytes`); the default bucket
    geometry spans 1e-3 .. ~3e5 in that unit."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 base: float = DEFAULT_HIST_BASE,
                 growth: float = DEFAULT_HIST_GROWTH,
                 n_buckets: int = DEFAULT_HIST_BUCKETS):
        super().__init__(name, description)
        self._geometry = (base, growth, n_buckets)

    def observe(self, value: float, **labels):
        key = _label_key(labels)
        with self._lock:
            h = self._series.get(key)
            if h is None:
                h = self._series[key] = LogHistogram(*self._geometry)
            h.observe(value)

    def percentile(self, q: float, **labels) -> float:
        with self._lock:
            h = self._series.get(_label_key(labels))
            return h.percentile(q) if h is not None else 0.0

    def child(self, **labels) -> LogHistogram:
        """The raw LogHistogram for one label set (exposition needs the
        bucket counts; mutate only under this family's lock)."""
        key = _label_key(labels)
        with self._lock:
            h = self._series.get(key)
            if h is None:
                h = self._series[key] = LogHistogram(*self._geometry)
            return h

    def _series_snapshot(self):
        with self._lock:
            return [{"labels": dict(k),
                     "count": h.count,
                     "sum": round(h.total, 6),
                     "min": round(h.vmin, 6) if h.count else 0.0,
                     "max": round(h.vmax, 6),
                     "p50": round(h.percentile(0.50), 6),
                     "p95": round(h.percentile(0.95), 6),
                     "p99": round(h.percentile(0.99), 6)}
                    for k, h in sorted(self._series.items())]


_COUNTER_SUFFIX = ("_total",)
_HIST_SUFFIXES = ("_ms", "_bytes", "_seconds")


class MetricsRegistry:
    """Name → metric family. Registration is get-or-create: two
    subsystems asking for the same (name, kind) converge on one family
    (that is the point — process-wide convergence); a kind conflict
    raises. Naming is validated at registration so a bad name fails at
    import/construction, not in a Grafana query:

    - snake_case (`^[a-z][a-z0-9_]*$`, no leading/trailing/double `_`)
    - counters end `_total`
    - histograms end with a unit suffix (`_ms`, `_bytes`, `_seconds`)
    - gauges must NOT end `_total` (that claims monotonicity)

    `scripts/check_metric_names.py` enforces the same rules statically
    across the codebase as a tier-1 test."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    # -- registration ------------------------------------------------------
    def _get_or_create(self, cls, name: str, description: str, **kwargs):
        if not _NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} is not snake_case "
                "([a-z0-9_], segments separated by single underscores)")
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, requested {cls.kind}")
                return existing
            m = cls(name, description, **kwargs)
            if cls is Gauge:
                m._on_error = self._count_gauge_error
            self._metrics[name] = m
            return m

    def _count_gauge_error(self, gauge_name: str):
        """One bad callback = one counted error, not a dead scrape. The
        counter itself is get-or-create, so it exists from the first
        failure on (and survives a test's clear())."""
        if gauge_name == "observability_gauge_errors_total":
            return          # never recurse into our own accounting
        self.counter(
            "observability_gauge_errors_total",
            "gauge callbacks that raised during evaluation (the series "
            "read NaN for that snapshot)").inc(gauge=gauge_name)

    def counter(self, name: str, description: str = "") -> Counter:
        if not name.endswith(_COUNTER_SUFFIX):
            raise ValueError(
                f"counter {name!r} must end with '_total' "
                "(unit-suffix convention)")
        return self._get_or_create(Counter, name, description)

    def gauge(self, name: str, description: str = "") -> Gauge:
        if name.endswith(_COUNTER_SUFFIX):
            raise ValueError(
                f"gauge {name!r} must not end with '_total' "
                "(that suffix claims a monotonic counter)")
        return self._get_or_create(Gauge, name, description)

    def histogram(self, name: str, description: str = "",
                  base: float = DEFAULT_HIST_BASE,
                  growth: float = DEFAULT_HIST_GROWTH,
                  n_buckets: int = DEFAULT_HIST_BUCKETS) -> Histogram:
        if not name.endswith(_HIST_SUFFIXES):
            raise ValueError(
                f"histogram {name!r} must carry a unit suffix "
                f"({', '.join(_HIST_SUFFIXES)})")
        return self._get_or_create(Histogram, name, description,
                                   base=base, growth=growth,
                                   n_buckets=n_buckets)

    # -- introspection -----------------------------------------------------
    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def families(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def unregister(self, name: str):
        with self._lock:
            self._metrics.pop(name, None)

    def clear(self):
        """Drop every family — test isolation for the process-global
        registry."""
        with self._lock:
            self._metrics.clear()

    # -- views -------------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        return {m.name: m.snapshot() for m in self.families()}

    def delta(self, prev: Optional[Dict[str, Dict[str, Any]]]
              ) -> Dict[str, Dict[str, Any]]:
        """Current snapshot with counter values and histogram count/sum
        reduced by `prev` (a prior `snapshot()`). Gauges pass through
        (they are levels, not accumulations); series absent from `prev`
        keep their full value."""
        cur = self.snapshot()
        if not prev:
            return cur
        for name, fam in cur.items():
            pfam = prev.get(name)
            if not pfam or pfam.get("kind") != fam["kind"]:
                continue
            pseries = {_label_key(s["labels"]): s
                       for s in pfam.get("series", [])}
            for s in fam["series"]:
                p = pseries.get(_label_key(s["labels"]))
                if p is None:
                    continue
                if fam["kind"] == "counter":
                    s["value"] = max(0.0, s["value"] - p["value"])
                elif fam["kind"] == "histogram":
                    s["count"] = max(0, s["count"] - p["count"])
                    s["sum"] = round(max(0.0, s["sum"] - p["sum"]), 6)
        return cur


# The process-wide default: serving, training and the HTTP frontend all
# publish here unless handed an explicit registry.
_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry
