from analytics_zoo_tpu.utils import tensorboard  # noqa: F401
