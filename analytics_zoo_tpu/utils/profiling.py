"""Profiling & timing utilities (SURVEY §2.12/§5 tracing).

The reference has no general tracer — only `Supportive.timing` span logs
(`serving/utils/Supportive.scala`, `InferenceSupportive.timing`) and serving
`Timer` windows. The TPU build supplies both and adds what the reference
lacks: real device profiling via the jax profiler (xprof traces viewable in
TensorBoard/Perfetto) and step-level throughput/MFU accounting."""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, Iterator, Optional

import jax

log = logging.getLogger("analytics_zoo_tpu.profiling")


@contextlib.contextmanager
def timing(name: str, logger: Optional[logging.Logger] = None
           ) -> Iterator[None]:
    """`Supportive.timing` span: logs `name time [s]` at INFO."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        (logger or log).info("%s time %.4fs", name,
                             time.perf_counter() - t0)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """jax profiler trace (xprof): open in TensorBoard's profile plugin or
    Perfetto. Wrap a few training steps, not a whole run."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (`jax.profiler.TraceAnnotation`)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Per-step wall-clock + throughput accounting; the `Throughput` scalar
    the reference writes to its train summary (`Topology.scala:224`)."""

    def __init__(self, flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None):
        self.flops_per_step = flops_per_step
        self.peak_flops = peak_flops
        self.steps = 0
        self.total_s = 0.0
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.total_s += time.perf_counter() - self._t0
        self.steps += 1
        return False

    @property
    def step_ms(self) -> float:
        return self.total_s / max(self.steps, 1) * 1e3

    def samples_per_sec(self, batch_size: int) -> float:
        return batch_size * self.steps / max(self.total_s, 1e-9)

    @property
    def mfu(self) -> Optional[float]:
        if not (self.flops_per_step and self.peak_flops and self.total_s):
            return None
        return (self.flops_per_step * self.steps / self.total_s
                / self.peak_flops)

    def summary(self, batch_size: Optional[int] = None) -> Dict[str, float]:
        out = {"steps": self.steps, "step_ms": round(self.step_ms, 3)}
        if batch_size:
            out["samples_per_sec"] = round(self.samples_per_sec(batch_size),
                                           1)
        if self.mfu is not None:
            out["mfu"] = round(self.mfu, 4)
        return out

    def publish(self, registry=None, batch_size: Optional[int] = None):
        """Push this timer's accounting into the metrics registry, under
        the SAME family names the trainer loop uses
        (`training_step_ms`/`training_samples_per_sec`/`training_mfu`) —
        hand-rolled loops built on StepTimer land on the unified spine
        without their own naming. Safe to call repeatedly: the step
        counter only advances by steps recorded since the last publish."""
        from analytics_zoo_tpu.observability import get_registry
        reg = registry if registry is not None else get_registry()
        published = getattr(self, "_published_steps", 0)
        if self.steps > published:
            # one observation per publish WINDOW (the average step time
            # of the steps recorded since the last publish) — repeated
            # per-step publish() calls then histogram the step-time
            # distribution instead of re-observing a running mean
            pub_total = getattr(self, "_published_total_s", 0.0)
            window_ms = ((self.total_s - pub_total)
                         / (self.steps - published) * 1e3)
            reg.histogram(
                "training_step_ms",
                "per-step wall time, averaged over each epoch's device "
                "sync").observe(window_ms)
            reg.counter("training_steps_total",
                        "optimizer steps run").inc(self.steps - published)
            self._published_steps = self.steps
            self._published_total_s = self.total_s
        if batch_size:
            reg.gauge("training_samples_per_sec",
                      "last epoch's training throughput").set(
                self.samples_per_sec(batch_size))
        if self.mfu is not None:
            reg.gauge(
                "training_mfu",
                "model FLOPs utilization vs per-chip peak (needs "
                "flops_per_step)").set(self.mfu)
        return self


def transformer_train_flops(n_params_matmul: int, tokens: int,
                            n_layers: int, seq_len: int,
                            hidden: int, batch: int) -> float:
    """Standard fwd+bwd FLOPs estimate: 6 per matmul-param per token plus
    attention score/context terms (the bench.py accounting, shared)."""
    return (6.0 * n_params_matmul * tokens
            + 12.0 * n_layers * seq_len ** 2 * hidden * batch)
