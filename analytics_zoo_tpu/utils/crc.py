"""Shared CRC32C (Castagnoli) + TFRecord masking — one implementation for
the TensorBoard event writer (`utils/tensorboard.py`) and the TFRecord
data path (`data/tfrecord.py`), both of which use the same length +
masked-crc framing."""

from __future__ import annotations

from typing import List


def _build_table() -> List[int]:
    poly = 0x82F63B78
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


_TABLE = _build_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    tbl = _TABLE
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    """TFRecord's masked CRC: rotate right by 15, add a constant."""
    crc = crc32c(data)
    return ((crc >> 15) | ((crc << 17) & 0xFFFFFFFF)) \
        + 0xA282EAD8 & 0xFFFFFFFF
