"""Self-contained TensorBoard event writer (no TF dependency).

The reference ships its own TF-event writer on the JVM
(`zoo/.../tensorboard/FileWriter.scala:32`, `EventWriter.scala`,
`Summary.scala`) so training summaries work without TensorFlow; this is the
same idea in pure Python: hand-encoded `Event`/`Summary` protobufs framed as
TFRecords (length + masked-crc32c). Readable by TensorBoard and by our own
`FileReader` (mirroring `get_train_summary` read-back,
`Topology.scala:224`).
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Dict, Iterator, List, Optional, Tuple

from analytics_zoo_tpu.utils.crc import crc32c  # noqa: F401 (re-export)
from analytics_zoo_tpu.utils.crc import masked_crc32c as _masked_crc


# ---------------------------------------------------------------------------
# Minimal protobuf wire encoding
# ---------------------------------------------------------------------------
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _pb_double(field: int, value: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", value)


def _pb_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


def _pb_int64(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value & 0xFFFFFFFFFFFFFFFF)


def _pb_bytes(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(value)) + value


def _pb_string(field: int, value: str) -> bytes:
    return _pb_bytes(field, value.encode("utf-8"))


def _encode_event(wall_time: float, step: Optional[int] = None,
                  summary: Optional[bytes] = None,
                  file_version: Optional[str] = None) -> bytes:
    # Event: wall_time=1(double), step=2(int64), file_version=3(string),
    #        summary=5(message)
    out = _pb_double(1, wall_time)
    if step is not None:
        out += _pb_int64(2, step)
    if file_version is not None:
        out += _pb_string(3, file_version)
    if summary is not None:
        out += _pb_bytes(5, summary)
    return out


def _encode_scalar_summary(tag: str, value: float) -> bytes:
    # Summary.Value: tag=1(string), simple_value=2(float); Summary: value=1
    v = _pb_string(1, tag) + _pb_float(2, value)
    return _pb_bytes(1, v)


def _frame_record(data: bytes) -> bytes:
    header = struct.pack("<Q", len(data))
    return (header + struct.pack("<I", _masked_crc(header)) + data
            + struct.pack("<I", _masked_crc(data)))


# ---------------------------------------------------------------------------
# Writer / reader
# ---------------------------------------------------------------------------
class SummaryWriter:
    """`FileWriter.scala:32` equivalent: append scalar events to an
    `events.out.tfevents.*` file."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}")
        self.path = os.path.join(log_dir, fname)
        self._fh = open(self.path, "ab")
        self._write_event(_encode_event(time.time(),
                                        file_version="brain.Event:2"))

    def _write_event(self, event: bytes):
        self._fh.write(_frame_record(event))
        self._fh.flush()

    def scalar(self, tag: str, value: float, step: int):
        summary = _encode_scalar_summary(tag, float(value))
        self._write_event(_encode_event(time.time(), step=step,
                                        summary=summary))

    def close(self):
        if not self._fh.closed:
            self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_metrics_snapshot(writer: "SummaryWriter",
                           snapshot: Dict[str, dict], step: int):
    """Mirror a `MetricsRegistry.snapshot()` into TensorBoard scalars:
    counters/gauges write their value, histograms write count/p50/p99.
    Label sets become tag suffixes (`serving_stage_ms/decode/p50`), so
    the TB run shows the same numbers a Prometheus scrape would."""
    for name, fam in snapshot.items():
        for s in fam.get("series", []):
            tag = name + "".join(
                f"/{v}" for _, v in sorted(s["labels"].items()))
            if fam["kind"] in ("counter", "gauge"):
                v = s["value"]
                if v == v:                       # skip NaN gauge reads
                    writer.scalar(tag, v, step)
            else:
                if not s["count"]:
                    continue
                writer.scalar(tag + "/count", s["count"], step)
                writer.scalar(tag + "/p50", s["p50"], step)
                writer.scalar(tag + "/p99", s["p99"], step)


def read_scalars(path_or_dir: str) -> Dict[str, List[Tuple[int, float]]]:
    """Read back scalars: tag -> [(step, value)]. Mirrors the reference's
    `FileReader` used by `get_train_summary`."""
    paths = []
    if os.path.isdir(path_or_dir):
        for f in sorted(os.listdir(path_or_dir)):
            if "tfevents" in f:
                paths.append(os.path.join(path_or_dir, f))
    else:
        paths = [path_or_dir]
    out: Dict[str, List[Tuple[int, float]]] = {}
    for p in paths:
        with open(p, "rb") as fh:
            data = fh.read()
        off = 0
        while off + 12 <= len(data):
            (length,) = struct.unpack_from("<Q", data, off)
            payload = data[off + 12:off + 12 + length]
            off += 12 + length + 4
            step, scalars = _decode_event(payload)
            for tag, value in scalars:
                out.setdefault(tag, []).append((step, value))
    return out


def _decode_event(buf: bytes) -> Tuple[int, List[Tuple[str, float]]]:
    step = 0
    scalars: List[Tuple[str, float]] = []
    for field, wire, value in _iter_fields(buf):
        if field == 2 and wire == 0:
            step = value
        elif field == 5 and wire == 2:
            for f2, w2, v2 in _iter_fields(value):
                if f2 == 1 and w2 == 2:  # Summary.Value
                    tag, sval = None, None
                    for f3, w3, v3 in _iter_fields(v2):
                        if f3 == 1 and w3 == 2:
                            tag = v3.decode("utf-8", "replace")
                        elif f3 == 2 and w3 == 5:
                            (sval,) = struct.unpack("<f", v3)
                    if tag is not None and sval is not None:
                        scalars.append((tag, sval))
    return step, scalars


def _iter_fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    off = 0
    while off < len(buf):
        key, off = _read_varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 0:
            value, off = _read_varint(buf, off)
        elif wire == 1:
            value = buf[off:off + 8]
            off += 8
        elif wire == 5:
            value = buf[off:off + 4]
            off += 4
        elif wire == 2:
            length, off = _read_varint(buf, off)
            value = buf[off:off + length]
            off += length
        else:
            return
        yield field, wire, value


def _read_varint(buf: bytes, off: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7


class InferenceSummary:
    """Serving-side TensorBoard summaries
    (`pipeline/inference/InferenceSummary.scala:24`): throughput and
    latency scalars written per serving window."""

    def __init__(self, log_dir: str, app_name: str = "serving"):
        self._writer = SummaryWriter(f"{log_dir.rstrip('/')}/{app_name}")
        self._step = 0

    def record(self, records: int, window_s: float,
               p50_ms: float = None, p99_ms: float = None):
        self._step += 1
        if window_s > 0:
            self._writer.scalar("Throughput", records / window_s,
                                self._step)
        if p50_ms is not None:
            self._writer.scalar("LatencyP50", p50_ms, self._step)
        if p99_ms is not None:
            self._writer.scalar("LatencyP99", p99_ms, self._step)

    def close(self):
        self._writer.close()
