"""Per-chip peak numbers for roofline/MFU accounting (docs/ROOFLINE.md).

Single source of truth for the benches (`bench.py`, `bench_ncf.py`) and
any profiling hook that wants achieved-vs-peak ratios. Values are the
published per-chip peaks; lookup is by `device_kind` substring."""

from __future__ import annotations

PEAK_BF16_FLOPS = [  # device_kind substring -> peak bf16 FLOP/s per chip
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5e", 197e12),
    ("v5 lite", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
]

PEAK_HBM_BYTES = [  # device_kind substring -> peak HBM bytes/s per chip
    ("v6", 1640e9),
    ("v5p", 2765e9),
    ("v5e", 819e9),
    ("v5 lite", 819e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
]


def _lookup(device, table, default: float) -> float:
    kind = getattr(device, "device_kind", "cpu").lower()
    for sub, peak in table:
        if sub in kind:
            return peak
    return default


def peak_flops(device) -> float:
    """Peak bf16 matmul FLOP/s; unknown TPUs assume v5e."""
    return _lookup(device, PEAK_BF16_FLOPS, 197e12)


def peak_hbm(device) -> float:
    """Peak HBM bytes/s; unknown TPUs assume v5e."""
    return _lookup(device, PEAK_HBM_BYTES, 819e9)
