"""Minimal protobuf wire-format decoder for ONNX ModelProto.

The environment carries no `onnx` package, so the loader decodes the wire
format directly against a hand-written schema of the (stable, frozen)
field numbers from onnx.proto. Only what the op mapper needs is modelled;
unknown fields are skipped per the protobuf spec, so models produced by any
exporter remain readable.

Schema entries: {field_number: (name, kind)} with kind one of
  "varint"   — int (also used for enums/bools; zigzag not needed for ONNX)
  "float"    — 32-bit float (wire type 5)
  "double"   — 64-bit float (wire type 1)
  "bytes"    — raw bytes
  "string"   — utf-8 string
  ("msg", schema) — nested message decoded recursively
Repeated fields simply accumulate into lists (the decoder always returns
lists; callers take [0] for singular fields). Packed repeated numerics are
detected by wire type 2 on a numeric kind.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple


def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long (corrupt protobuf)")


def decode(buf, schema: Dict[int, Tuple[str, Any]]) -> Dict[str, List]:
    """Decode one message; returns {field_name: [values...]}."""
    buf = memoryview(buf)
    out: Dict[str, List] = {}
    pos = 0
    end = len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field_no = tag >> 3
        wire_type = tag & 7
        entry = schema.get(field_no)

        if wire_type == 0:
            val, pos = _read_varint(buf, pos)
            if entry is not None:
                out.setdefault(entry[0], []).append(val)
        elif wire_type == 1:
            raw = bytes(buf[pos:pos + 8])
            pos += 8
            if entry is not None:
                out.setdefault(entry[0], []).append(
                    struct.unpack("<d", raw)[0]
                    if entry[1] == "double" else
                    int.from_bytes(raw, "little"))
        elif wire_type == 5:
            raw = bytes(buf[pos:pos + 4])
            pos += 4
            if entry is not None:
                out.setdefault(entry[0], []).append(
                    struct.unpack("<f", raw)[0]
                    if entry[1] == "float" else
                    int.from_bytes(raw, "little"))
        elif wire_type == 2:
            length, pos = _read_varint(buf, pos)
            chunk = buf[pos:pos + length]
            pos += length
            if entry is None:
                continue
            name, kind = entry
            if kind == "bytes":
                out.setdefault(name, []).append(bytes(chunk))
            elif kind == "string":
                out.setdefault(name, []).append(
                    bytes(chunk).decode("utf-8", "replace"))
            elif kind == "varint":                    # packed ints
                vals = []
                p = 0
                while p < len(chunk):
                    v, p = _read_varint(chunk, p)
                    vals.append(v)
                out.setdefault(name, []).extend(vals)
            elif kind == "float":                     # packed floats
                n = len(chunk) // 4
                out.setdefault(name, []).extend(
                    struct.unpack(f"<{n}f", bytes(chunk)))
            elif kind == "double":
                n = len(chunk) // 8
                out.setdefault(name, []).extend(
                    struct.unpack(f"<{n}d", bytes(chunk)))
            elif isinstance(kind, tuple) and kind[0] == "msg":
                out.setdefault(name, []).append(decode(chunk, kind[1]))
            else:
                raise ValueError(f"Bad schema kind for field {field_no}")
        else:
            raise ValueError(f"Unsupported wire type {wire_type}")
    return out


def _write_varint(out: bytearray, value: int) -> None:
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def encode(msg: Dict[str, Any], schema: Dict[int, Tuple[str, Any]]) -> bytes:
    """Inverse of `decode`: {field_name: [values...]} → wire bytes. Used by
    the test fixtures (the environment has no onnx package to produce
    reference files) and by `save_onnx`-style exports."""
    by_name = {name: (no, kind) for no, (name, kind) in schema.items()}
    out = bytearray()
    for name, values in msg.items():
        if name not in by_name:
            raise KeyError(f"Field {name!r} not in schema")
        field_no, kind = by_name[name]
        if not isinstance(values, (list, tuple)):
            values = [values]
        for v in values:
            if kind == "varint":
                _write_varint(out, field_no << 3 | 0)
                _write_varint(out, int(v))
            elif kind == "float":
                _write_varint(out, field_no << 3 | 5)
                out += struct.pack("<f", float(v))
            elif kind == "double":
                _write_varint(out, field_no << 3 | 1)
                out += struct.pack("<d", float(v))
            elif kind in ("bytes", "string"):
                data = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                _write_varint(out, field_no << 3 | 2)
                _write_varint(out, len(data))
                out += data
            elif isinstance(kind, tuple) and kind[0] == "msg":
                data = encode(v, kind[1])
                _write_varint(out, field_no << 3 | 2)
                _write_varint(out, len(data))
                out += data
            else:
                raise ValueError(f"Bad schema kind for field {name!r}")
    return bytes(out)


# ---------------------------------------------------------------------------
# ONNX schemas (field numbers from onnx/onnx.proto, frozen by the spec)
# ---------------------------------------------------------------------------
TENSOR = {
    1: ("dims", "varint"),
    2: ("data_type", "varint"),
    4: ("float_data", "float"),
    5: ("int32_data", "varint"),
    7: ("int64_data", "varint"),
    8: ("name", "string"),
    9: ("raw_data", "bytes"),
    10: ("double_data", "double"),
}

ATTRIBUTE: Dict[int, Tuple[str, Any]] = {
    1: ("name", "string"),
    2: ("f", "float"),
    3: ("i", "varint"),
    4: ("s", "bytes"),
    5: ("t", ("msg", TENSOR)),
    7: ("floats", "float"),
    8: ("ints", "varint"),
    9: ("strings", "bytes"),
    20: ("type", "varint"),
}

NODE = {
    1: ("input", "string"),
    2: ("output", "string"),
    3: ("name", "string"),
    4: ("op_type", "string"),
    5: ("attribute", ("msg", ATTRIBUTE)),
    7: ("domain", "string"),
}

DIM = {
    1: ("dim_value", "varint"),
    2: ("dim_param", "string"),
}

TENSOR_SHAPE = {
    1: ("dim", ("msg", DIM)),
}

TENSOR_TYPE = {
    1: ("elem_type", "varint"),
    2: ("shape", ("msg", TENSOR_SHAPE)),
}

TYPE = {
    1: ("tensor_type", ("msg", TENSOR_TYPE)),
}

VALUE_INFO = {
    1: ("name", "string"),
    2: ("type", ("msg", TYPE)),
}

GRAPH = {
    1: ("node", ("msg", NODE)),
    2: ("name", "string"),
    5: ("initializer", ("msg", TENSOR)),
    11: ("input", ("msg", VALUE_INFO)),
    12: ("output", ("msg", VALUE_INFO)),
    13: ("value_info", ("msg", VALUE_INFO)),
}

OPERATOR_SET_ID = {
    1: ("domain", "string"),
    2: ("version", "varint"),
}

MODEL = {
    1: ("ir_version", "varint"),
    2: ("producer_name", "string"),
    7: ("graph", ("msg", GRAPH)),
    8: ("opset_import", ("msg", OPERATOR_SET_ID)),
}
