"""ONNX → native Keras-graph importer.

Reference: `pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:141` + the mapper
classes under `pyzoo/zoo/pipeline/api/onnx/mapper/` — there, ONNX nodes map
onto Zoo Keras layers on the JVM; here they map onto the jax layer library
and the whole imported graph jit-compiles to one XLA program.

ONNX tensors are NCHW; the imported graph keeps that layout end-to-end by
instantiating conv/pool layers with `dim_ordering="th"` so torch-exported
weights (OIHW) and Flatten orderings stay bit-compatible. Weights from
graph initializers are pinned into the layers' `build`.

Supported ops (the set every torchvision-style classifier and the
reference's mapper suite need): Conv, Gemm, MatMul, Add, Sub, Mul, Div,
Relu, LeakyRelu, Elu, Sigmoid, Tanh, Softmax, LogSoftmax, MaxPool,
AveragePool, GlobalAveragePool, GlobalMaxPool, BatchNormalization, Flatten,
Reshape, Dropout, Identity, Concat, Constant, Unsqueeze, Squeeze, Pad.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.learn.torch_bridge import _with_weights
from analytics_zoo_tpu.onnx import wire
from analytics_zoo_tpu.ops.autograd import LambdaLayer
from analytics_zoo_tpu.ops.autograd import pad_lambda as _pad_lambda

# ONNX TensorProto.DataType → numpy
_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 6: np.int32,
           7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64}


def _tensor_to_ndarray(t: Dict) -> np.ndarray:
    dims = t.get("dims", [])
    dtype = _DTYPES.get(t.get("data_type", [1])[0], np.float32)
    if t.get("raw_data"):
        arr = np.frombuffer(t["raw_data"][0], dtype=dtype)
    elif t.get("float_data"):
        arr = np.asarray(t["float_data"], np.float32)
    elif t.get("int64_data"):
        arr = np.asarray(t["int64_data"], np.int64)
    elif t.get("int32_data"):
        arr = np.asarray(t["int32_data"], np.int32)
    elif t.get("double_data"):
        arr = np.asarray(t["double_data"], np.float64)
    else:
        arr = np.zeros(dims, dtype)
    return arr.reshape(dims) if dims else arr


def _attrs(node: Dict) -> Dict[str, Any]:
    out = {}
    for a in node.get("attribute", []):
        name = a["name"][0]
        if a.get("ints"):
            out[name] = list(a["ints"])
        elif a.get("i"):
            out[name] = a["i"][0]
        elif a.get("floats"):
            out[name] = list(a["floats"])
        elif a.get("f"):
            out[name] = a["f"][0]
        elif a.get("s"):
            out[name] = a["s"][0].decode("utf-8", "replace")
        elif a.get("t"):
            out[name] = _tensor_to_ndarray(a["t"][0])
        else:
            out[name] = a.get("i", [0])[0]
    return out


def _value_shape(vi: Dict) -> Optional[List[Optional[int]]]:
    try:
        tt = vi["type"][0]["tensor_type"][0]
        dims = tt["shape"][0].get("dim", [])
    except (KeyError, IndexError):
        return None
    shape: List[Optional[int]] = []
    for d in dims:
        if d.get("dim_value"):
            shape.append(int(d["dim_value"][0]))
        else:
            shape.append(None)
    return shape


def _sym_pads(pads: Sequence[int], rank: int):
    """ONNX pads = [x1_begin..xk_begin, x1_end..xk_end]."""
    begin = pads[:rank]
    end = pads[rank:]
    return list(zip(begin, end))




class _OnnxGraphBuilder:
    def __init__(self, graph: Dict):
        self.graph = graph
        self.inits = {t["name"][0]: _tensor_to_ndarray(t)
                      for t in graph.get("initializer", [])}
        self.consts: Dict[str, np.ndarray] = dict(self.inits)
        self.nodes: Dict[str, Any] = {}     # tensor name → symbolic Node
        self.inputs = []

    # -- helpers -----------------------------------------------------------
    def _node(self, name: str, op: str):
        """Resolve a runtime-tensor input; constants get a clear error
        (ops that can fold constants do so before calling this)."""
        if name in self.nodes:
            return self.nodes[name]
        if name in self.consts:
            raise NotImplementedError(
                f"ONNX {op} over a constant input is not supported "
                "(no constant folding for this op)")
        raise ValueError(f"Unknown tensor {name!r} feeding {op}")

    def _pool(self, node, attrs, cls):
        k = attrs.get("kernel_shape", [2, 2])
        strides = attrs.get("strides", [1] * len(k))  # ONNX default is 1
        pads = attrs.get("pads", [0] * 4)
        x = self._node(node["input"][0], "Pool")
        if any(pads):
            (pt, pb), (pl, pr) = _sym_pads(pads, 2)
            pad_cfg = ((0, 0), (0, 0), (pt, pb), (pl, pr))
            if cls is L.AveragePooling2D \
                    and not int(attrs.get("count_include_pad", 0)):
                # ONNX default excludes pad zeros from the average:
                # sum-pool(padded x) / sum-pool(padded ones)
                kk, ss = tuple(k), tuple(strides)

                def avg_exclude_pad(t, pc=pad_cfg, kk=kk, ss=ss):
                    import jax
                    import jax.numpy as jnp
                    tp = jnp.pad(t, pc)
                    cnt = jnp.pad(jnp.ones_like(t), pc)
                    win = (1, 1) + kk
                    st = (1, 1) + ss
                    s = jax.lax.reduce_window(tp, 0.0, jax.lax.add, win,
                                              st, "VALID")
                    n = jax.lax.reduce_window(cnt, 0.0, jax.lax.add, win,
                                              st, "VALID")
                    return s / n

                return LambdaLayer(avg_exclude_pad)(x)
            # ONNX MaxPool pads with -inf, not zeros
            x = _pad_lambda(pad_cfg,
                            value=-np.inf if cls is L.MaxPooling2D
                            else 0.0)(x)
        return cls(pool_size=tuple(k), strides=tuple(strides),
                   border_mode="valid", dim_ordering="th")(x)

    def _act(self, node, fn_name, **kw):
        layer = {"Relu": lambda: L.Activation("relu"),
                 "Sigmoid": lambda: L.Activation("sigmoid"),
                 "Tanh": lambda: L.Activation("tanh"),
                 "Softmax": lambda: L.Activation("softmax"),
                 "LogSoftmax": lambda: L.Activation("log_softmax"),
                 "LeakyRelu": lambda: L.LeakyReLU(kw.get("alpha", 0.01)),
                 "Elu": lambda: L.ELU(kw.get("alpha", 1.0))}[fn_name]()
        return layer(self._node(node["input"][0], fn_name))

    def _binop(self, node, op):
        a_name, b_name = node["input"][:2]
        if a_name in self.consts and b_name in self.consts:
            # fold (weight-prep chains, e.g. decomposed-BatchNorm
            # Add(var, eps) → Sqrt → Div)
            fns = {"Add": np.add, "Sub": np.subtract,
                   "Mul": np.multiply, "Div": np.divide}
            self.consts[node["output"][0]] = fns[op](
                self.consts[a_name], self.consts[b_name])
            return None
        if b_name in self.consts and a_name in self.nodes:
            c = self.consts[b_name].astype(np.float32)
            fns = {"Add": lambda x: x + c, "Sub": lambda x: x - c,
                   "Mul": lambda x: x * c, "Div": lambda x: x / c}
            return LambdaLayer(fns[op])(self._node(a_name, op))
        if a_name in self.consts and b_name in self.nodes:
            c = self.consts[a_name].astype(np.float32)
            fns = {"Add": lambda x: c + x, "Sub": lambda x: c - x,
                   "Mul": lambda x: c * x, "Div": lambda x: c / x}
            return LambdaLayer(fns[op])(self._node(b_name, op))
        # tensor-tensor with numpy broadcasting semantics
        fns = {"Add": lambda a, b: a + b, "Sub": lambda a, b: a - b,
               "Mul": lambda a, b: a * b, "Div": lambda a, b: a / b}
        return LambdaLayer(fns[op])([self._node(a_name, op),
                                     self._node(b_name, op)])

    # -- op dispatch -------------------------------------------------------
    def handle(self, node: Dict):
        op = node["op_type"][0]
        attrs = _attrs(node)
        out_name = node["output"][0]

        if op == "Constant":
            self.consts[out_name] = np.asarray(attrs["value"])
            return
        if op in ("Identity", "Dropout"):
            src = node["input"][0]
            if src in self.consts:
                self.consts[out_name] = self.consts[src]
            else:
                # inference-mode dropout/identity: pass-through node
                self.nodes[out_name] = self.nodes[src]
            return
        if op == "Conv":
            self.nodes[out_name] = self._conv(node, attrs)
        elif op == "Gemm":
            self.nodes[out_name] = self._gemm(node, attrs)
        elif op == "MatMul":
            self.nodes[out_name] = self._matmul(node)
        elif op in ("Add", "Sub", "Mul", "Div"):
            combined = self._binop(node, op)
            if combined is not None:       # None → constant-folded
                self.nodes[out_name] = combined
        elif op in ("Relu", "Sigmoid", "Tanh", "Softmax", "LogSoftmax"):
            self.nodes[out_name] = self._act(node, op)
        elif op in ("LeakyRelu", "Elu"):
            self.nodes[out_name] = self._act(node, op,
                                             alpha=attrs.get("alpha"))
        elif op == "MaxPool":
            self.nodes[out_name] = self._pool(node, attrs, L.MaxPooling2D)
        elif op == "AveragePool":
            self.nodes[out_name] = self._pool(node, attrs,
                                              L.AveragePooling2D)
        elif op == "GlobalAveragePool":
            self.nodes[out_name] = LambdaLayer(
                lambda x: x.mean(axis=(2, 3), keepdims=True))(
                    self._node(node["input"][0], op))
        elif op == "GlobalMaxPool":
            self.nodes[out_name] = LambdaLayer(
                lambda x: x.max(axis=(2, 3), keepdims=True))(
                    self._node(node["input"][0], op))
        elif op == "BatchNormalization":
            self.nodes[out_name] = self._batchnorm(node, attrs)
        elif op == "Flatten":
            self.nodes[out_name] = L.Flatten()(
                self._node(node["input"][0], op))
        elif op == "Reshape":
            self.nodes[out_name] = self._reshape(node, attrs)
        elif op == "Concat":
            axis = int(attrs.get("axis", 1))
            self.nodes[out_name] = L.Merge(mode="concat", concat_axis=axis)(
                [self._node(i, op) for i in node["input"]])
        elif op == "Unsqueeze":
            axes = attrs.get("axes") or \
                self.consts[node["input"][1]].reshape(-1).tolist()
            node_out = self._node(node["input"][0], op)
            for ax in sorted(int(a) for a in axes):   # ascending keeps
                node_out = L.ExpandDim(ax)(node_out)  # later axes valid
            self.nodes[out_name] = node_out
        elif op == "Squeeze":
            axes = attrs.get("axes") or \
                self.consts[node["input"][1]].reshape(-1).tolist()
            node_out = self._node(node["input"][0], op)
            for ax in sorted((int(a) for a in axes), reverse=True):
                node_out = L.Squeeze(ax)(node_out)
            self.nodes[out_name] = node_out
        elif op == "Pad":
            self.nodes[out_name] = self._pad(node, attrs)
        elif op in ("Abs", "Exp", "Log", "Sqrt", "Neg"):
            src = node["input"][0]
            if src in self.consts:      # weight-prep chains: fold
                npfn = {"Abs": np.abs, "Exp": np.exp, "Log": np.log,
                        "Sqrt": np.sqrt, "Neg": np.negative}[op]
                self.consts[out_name] = npfn(self.consts[src])
                return
            import jax.numpy as jnp
            fn = {"Abs": jnp.abs, "Exp": jnp.exp, "Log": jnp.log,
                  "Sqrt": jnp.sqrt, "Neg": jnp.negative}[op]
            self.nodes[out_name] = LambdaLayer(fn)(self._node(src, op))
        elif op == "HardSigmoid":
            import jax.numpy as jnp
            alpha = float(attrs.get("alpha", 0.2))
            beta = float(attrs.get("beta", 0.5))
            self.nodes[out_name] = LambdaLayer(
                lambda x, a=alpha, b=beta: jnp.clip(a * x + b, 0.0, 1.0))(
                self._node(node["input"][0], op))
        elif op == "Clip":
            self.nodes[out_name] = self._clip(node, attrs)
        elif op == "Pow":
            powed = self._pow(node)
            if powed is not None:         # None → constant-folded
                self.nodes[out_name] = powed
        elif op == "Cast":
            src = node["input"][0]
            dtype = self._CAST_DTYPES.get(int(attrs.get("to", 1)))
            if dtype is None:
                raise NotImplementedError(
                    f"Cast to ONNX dtype {attrs.get('to')}")
            if src in self.consts:
                self.consts[out_name] = self.consts[src].astype(dtype)
            else:
                self.nodes[out_name] = LambdaLayer(
                    lambda x, d=dtype: x.astype(d))(
                    self._node(src, "Cast"))
        elif op == "Gather":
            gathered = self._gather(node, attrs)
            if gathered is not None:      # None → constant-folded
                self.nodes[out_name] = gathered
        elif op == "Greater":
            gt = self._greater(node)
            if gt is not None:            # None → constant-folded
                self.nodes[out_name] = gt
        elif op == "LRN":
            self.nodes[out_name] = L.LRN2D(
                alpha=float(attrs.get("alpha", 1e-4)),
                beta=float(attrs.get("beta", 0.75)),
                k=float(attrs.get("bias", 1.0)),
                n=int(attrs.get("size", 5)), dim_ordering="th")(
                self._node(node["input"][0], op))
        elif op in ("ReduceMean", "ReduceSum"):
            self.nodes[out_name] = self._reduce(node, attrs, op)
        elif op == "Shape":
            src = node["input"][0]
            if src in self.consts:
                self.consts[out_name] = np.asarray(
                    self.consts[src].shape, np.int64)
                return
            self.nodes[out_name] = L.GetShape()(self._node(src, op))
        elif op == "Slice":
            self.nodes[out_name] = self._slice(node, attrs)
        elif op == "Transpose":
            perm = attrs.get("perm")
            src = node["input"][0]
            if src in self.consts:      # weight pre-transpose: fold
                c = self.consts[src]
                self.consts[out_name] = np.transpose(
                    c, tuple(int(i) for i in perm)
                    if perm is not None else None)
                return
            self.nodes[out_name] = LambdaLayer(
                lambda x, p=perm: x.transpose(
                    tuple(int(i) for i in p) if p is not None
                    else tuple(range(x.ndim))[::-1]))(self._node(src, op))
        else:
            raise NotImplementedError(
                f"ONNX op {op!r} is not supported by the importer")

    def _clip(self, node, attrs):
        # opset<11 carries min/max attrs; >=11 as optional const inputs
        lo = attrs.get("min")
        hi = attrs.get("max")
        ins = node["input"]

        def bound(i, current):
            if current is not None or len(ins) <= i or not ins[i]:
                return current
            if ins[i] not in self.consts:
                raise NotImplementedError(
                    "Clip with runtime (non-constant) min/max inputs")
            return float(np.asarray(self.consts[ins[i]]).reshape(-1)[0])
        lo = bound(1, lo)
        hi = bound(2, hi)
        import jax.numpy as jnp
        return LambdaLayer(
            lambda x, lo=lo, hi=hi: jnp.clip(
                x, -np.inf if lo is None else lo,
                np.inf if hi is None else hi))(self._node(ins[0], "Clip"))

    def _pow(self, node):
        a, b = node["input"][:2]
        if a in self.consts and b in self.consts:
            # promote like the runtime branches do — int**-1 would raise
            self.consts[node["output"][0]] = np.power(
                self.consts[a].astype(np.float32),
                self.consts[b].astype(np.float32))
            return None
        if b in self.consts:
            c = self.consts[b].astype(np.float32)
            return LambdaLayer(lambda x, c=c: x ** c)(
                self._node(a, "Pow"))
        if a in self.consts:
            c = self.consts[a].astype(np.float32)
            return LambdaLayer(lambda x, c=c: c ** x)(
                self._node(b, "Pow"))
        return LambdaLayer(lambda x, y: x ** y)([self._node(a, "Pow"),
                                                 self._node(b, "Pow")])

    _CAST_DTYPES = {1: np.float32, 6: np.int32, 7: np.int64,
                    9: np.bool_, 10: np.float16, 11: np.float64}

    def _gather(self, node, attrs):
        axis = int(attrs.get("axis", 0))
        data, indices = node["input"][:2]
        import jax.numpy as jnp
        if data in self.consts and indices in self.consts:
            # constant fold (shape-computation subgraphs)
            self.consts[node["output"][0]] = np.take(
                self.consts[data],
                self.consts[indices].astype(np.int64), axis=axis)
            return None
        if data in self.consts and indices in self.nodes:
            # embedding-style: const table gathered by a runtime tensor
            # (keep the table dtype — int64 id tables must not round-trip
            # through float32)
            table = self.consts[data]
            return LambdaLayer(
                lambda idx, t=table, ax=axis: jnp.take(
                    t, idx.astype(jnp.int32), axis=ax))(
                self.nodes[indices])
        if indices in self.consts and data in self.nodes:
            idx = self.consts[indices].astype(np.int64)
            return LambdaLayer(
                lambda x, i=idx, ax=axis: jnp.take(x, i, axis=ax))(
                self.nodes[data])
        return LambdaLayer(
            lambda x, idx, ax=axis: jnp.take(x, idx.astype(jnp.int32),
                                             axis=ax))(
            [self.nodes[data], self.nodes[indices]])

    def _greater(self, node):
        a, b = node["input"][:2]
        if a in self.consts and b in self.consts:
            self.consts[node["output"][0]] = np.greater(
                self.consts[a], self.consts[b])
            return None
        if b in self.consts:
            c = self.consts[b].astype(np.float32)
            return LambdaLayer(lambda x, c=c: x > c)(
                self._node(a, "Greater"))
        if a in self.consts:
            c = self.consts[a].astype(np.float32)
            return LambdaLayer(lambda x, c=c: c > x)(
                self._node(b, "Greater"))
        return LambdaLayer(lambda x, y: x > y)([self._node(a, "Greater"),
                                                self._node(b, "Greater")])

    def _reduce(self, node, attrs, op):
        axes = attrs.get("axes")
        if axes is None and len(node["input"]) > 1 and node["input"][1]:
            if node["input"][1] not in self.consts:
                raise NotImplementedError(
                    f"{op} with runtime (non-constant) axes input")
            axes = self.consts[node["input"][1]].reshape(-1).tolist()
        axes = None if axes is None else tuple(int(a) for a in axes)
        keep = bool(int(attrs.get("keepdims", 1)))
        import jax.numpy as jnp
        fn = jnp.mean if op == "ReduceMean" else jnp.sum
        return LambdaLayer(
            lambda x, ax=axes, k=keep: fn(x, axis=ax, keepdims=k))(
            self._node(node["input"][0], op))

    def _slice(self, node, attrs):
        ins = node["input"]
        if "starts" in attrs:                   # opset < 10
            starts = [int(v) for v in attrs["starts"]]
            ends = [int(v) for v in attrs["ends"]]
            axes = [int(v) for v in attrs.get(
                "axes", range(len(starts)))]
            steps = [1] * len(starts)
        else:                                   # opset >= 10: const inputs
            def const(i, default=None, required=False):
                if len(ins) > i and ins[i]:
                    if ins[i] not in self.consts:
                        raise NotImplementedError(
                            "Slice with runtime (non-constant) "
                            "starts/ends/axes/steps inputs")
                    return self.consts[ins[i]].reshape(-1).tolist()
                if required:
                    raise NotImplementedError("Slice without starts/ends")
                return default
            starts = [int(v) for v in const(1, required=True)]
            ends = [int(v) for v in const(2, required=True)]
            axes = [int(v) for v in
                    const(3, list(range(len(starts))))]
            steps = [int(v) for v in const(4, [1] * len(starts))]

        def do_slice(x, starts=tuple(starts), ends=tuple(ends),
                     axes=tuple(axes), steps=tuple(steps)):
            sl = [slice(None)] * x.ndim
            for s, e, a, st in zip(starts, ends, axes, steps):
                sl[a] = slice(s, None if e >= 2**31 - 1 else e, st)
            return x[tuple(sl)]
        return LambdaLayer(do_slice)(self._node(ins[0], "Slice"))

    def _conv(self, node, attrs):
        w = self.consts[node["input"][1]]          # OIHW
        b = self.consts.get(node["input"][2]) if len(node["input"]) > 2 \
            else None
        group = int(attrs.get("group", 1))
        strides = attrs.get("strides", [1, 1])
        dilations = attrs.get("dilations", [1, 1])
        pads = attrs.get("pads", [0, 0, 0, 0])
        x = self._node(node["input"][0], "Conv")
        if any(pads):
            (pt, pb), (pl, pr) = _sym_pads(pads, 2)
            x = _pad_lambda(((0, 0), (0, 0), (pt, pb), (pl, pr)))(x)
        out_ch, _, kh, kw = w.shape
        if list(dilations) != [1, 1]:
            layer = L.AtrousConvolution2D(
                out_ch, kh, kw, atrous_rate=tuple(dilations),
                subsample=tuple(strides), border_mode="valid",
                dim_ordering="th", use_bias=b is not None, groups=group)
        else:
            layer = L.Convolution2D(
                out_ch, kh, kw, subsample=tuple(strides),
                border_mode="valid", dim_ordering="th",
                use_bias=b is not None, groups=group)
        params = {"kernel": np.transpose(w, (2, 3, 1, 0)).copy()}  # → HWIO
        if b is not None:
            params["bias"] = b
        return _with_weights(layer, params)(x)

    def _gemm(self, node, attrs):
        w = self.consts[node["input"][1]]
        b = self.consts.get(node["input"][2]) if len(node["input"]) > 2 \
            else None
        if int(attrs.get("transB", 0)):
            w = w.T
        if int(attrs.get("transA", 0)):
            raise NotImplementedError("Gemm transA")
        alpha = float(attrs.get("alpha", 1.0))
        beta = float(attrs.get("beta", 1.0))
        layer = L.Dense(w.shape[1], use_bias=b is not None)
        params = {"kernel": (w * alpha).astype(w.dtype)
                  if alpha != 1.0 else w.copy()}
        if b is not None:
            params["bias"] = (b * beta).astype(b.dtype) if beta != 1.0 else b
        return _with_weights(layer, params)(self.nodes[node["input"][0]])

    def _matmul(self, node):
        a, b = node["input"][:2]
        if b in self.consts and a in self.nodes:
            w = self.consts[b]
            layer = L.Dense(w.shape[-1], use_bias=False)
            return _with_weights(layer, {"kernel": w.copy()})(self.nodes[a])
        if a in self.consts:
            c = self.consts[a].astype(np.float32)
            return LambdaLayer(lambda y, c=c: c @ y)(self.nodes[b])
        return LambdaLayer(lambda x, y: x @ y)([self.nodes[a],
                                                self.nodes[b]])

    def _batchnorm(self, node, attrs):
        gamma = self.consts[node["input"][1]]
        beta = self.consts[node["input"][2]]
        mean = self.consts[node["input"][3]]
        var = self.consts[node["input"][4]]
        layer = L.BatchNormalization(
            epsilon=float(attrs.get("epsilon", 1e-5)), axis=1)
        return _with_weights(layer, {
            "gamma": gamma, "beta": beta,
            "moving_mean": mean, "moving_var": var,
        })(self.nodes[node["input"][0]])

    def _reshape(self, node, attrs):
        shape = self.consts[node["input"][1]].astype(np.int64).tolist()
        if int(attrs.get("allowzero", 0)) and 0 in shape:
            raise NotImplementedError("Reshape allowzero=1 with a 0 dim")
        # ONNX shape includes batch; 0 = copy the corresponding input dim
        # (allowzero=0 default). Batch stays implicit in our Reshape.
        src = self.nodes[node["input"][0]]
        in_shape = list(getattr(src, "shape", ()) or ())  # (None, ...) batch
        target = []
        for i, d in enumerate(shape[1:]):   # in_shape[i + 1] is the match
            if d == 0:
                if i + 1 >= len(in_shape) or in_shape[i + 1] is None:
                    raise NotImplementedError(
                        "Reshape 0-dim with unknown input dimension")
                target.append(int(in_shape[i + 1]))
            else:
                target.append(int(d))
        return L.Reshape(tuple(target))(src)

    def _pad(self, node, attrs):
        pads = attrs.get("pads")
        if pads is None:
            pads = self.consts[node["input"][1]].astype(np.int64).tolist()
        rank = len(pads) // 2
        sym = _sym_pads(pads, rank)
        if rank == 4 and sym[0] == (0, 0) and sym[1] == (0, 0) \
                and all(a == b for a, b in sym[2:]):
            return L.ZeroPadding2D((sym[2][0], sym[3][0]),
                                   dim_ordering="th")(
                self.nodes[node["input"][0]])
        raise NotImplementedError(f"Pad config {pads}")

    # -- assembly ----------------------------------------------------------
    def build(self) -> Model:
        for vi in self.graph.get("input", []):
            name = vi["name"][0]
            if name in self.inits:
                continue
            shape = _value_shape(vi)
            if shape is None or len(shape) < 2:
                raise ValueError(f"Graph input {name} lacks a static shape")
            inp = Input(shape=tuple(int(d) if d else None
                                    for d in shape[1:]))
            self.nodes[name] = inp
            self.inputs.append(inp)
        for node in self.graph.get("node", []):
            self.handle(node)
        outs = [self.nodes[vi["name"][0]]
                for vi in self.graph.get("output", [])]
        model = Model(self.inputs if len(self.inputs) > 1
                      else self.inputs[0],
                      outs if len(outs) > 1 else outs[0])
        return model


def load_onnx(path_or_bytes) -> Model:
    """Load an .onnx file (or bytes) into a native Model with the exported
    weights pinned. Call `.predict(x)` / continue training with `compile` +
    `fit` as usual."""
    if isinstance(path_or_bytes, (bytes, bytearray, memoryview)):
        blob = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as fh:
            blob = fh.read()
    model_msg = wire.decode(blob, wire.MODEL)
    graph = model_msg["graph"][0]
    model = _OnnxGraphBuilder(graph).build()
    # materialize pinned weights immediately
    sample = []
    for inp in (model.inputs if isinstance(model.inputs, list)
                else [model.inputs]):
        shape = tuple(1 if d is None else d for d in inp.shape)
        sample.append(np.zeros(shape, np.float32))
    model.ensure_built(sample if len(sample) > 1 else sample[0])
    return model
