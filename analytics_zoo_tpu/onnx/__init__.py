from analytics_zoo_tpu.onnx.onnx_loader import load_onnx  # noqa: F401
