from analytics_zoo_tpu.keras.engine import (  # noqa: F401
    Input, Layer, Model, Node, Sequential)
from analytics_zoo_tpu.keras import layers  # noqa: F401
