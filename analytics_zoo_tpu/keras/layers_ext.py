"""Extended Keras1-parity layer set.

Completes the reference's layer inventory
(`zoo/.../pipeline/api/keras/layers/*.scala`, python mirror
`pyzoo/zoo/pipeline/api/keras/layers/`): advanced activations
(`Advanced_Activations.scala`-family: LeakyReLU/ELU/PReLU/SReLU/
ThresholdedReLU), noise & structured dropout (`GaussianNoise.scala`,
`GaussianDropout.scala`, `SpatialDropout*.scala`, `Masking.scala`), dense
variants (`Highway.scala`, `MaxoutDense.scala`), the remaining convolution
family (`SeparableConvolution2D.scala`, `Deconvolution2D.scala`,
`AtrousConvolution1D/2D.scala`, `LocallyConnected1D/2D.scala`,
`Cropping1D/2D/3D.scala`, `ZeroPadding1D/3D.scala`, `UpSampling1D/3D.scala`,
`MaxPooling3D/AveragePooling3D.scala`, global 3D pools), `ConvLSTM2D.scala`/
`ConvLSTM3D.scala`, `LRN2D.scala`/`WithinChannelLRN2D.scala`,
`ResizeBilinear.scala`, `GaussianSampler.scala` (VAE app), and the
torch-style elementwise layers of `pyzoo/.../keras/layers/torch.py` (Scale,
CAdd, CMul, AddConstant, MulConstant, Abs, Clamp/HardTanh, Exp, Log, Power,
Square, Sqrt, Negative, Identity, HardShrink, SoftShrink, Threshold).

All layers follow the same stateless contract as
`analytics_zoo_tpu.keras.layers`: `build` → param pytree, `call` →
jax-traceable fn; channels_last is native with `dim_ordering="th"` accepted
and transposed on the fly.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras.engine import Layer
from analytics_zoo_tpu.keras.layers import (
    _ConvND, _GlobalPool, _PoolND, _Recurrent, _from_channels_last,
    _match_param_dtype, _to_channels_last, get_activation, get_init)

__all__ = [
    "LeakyReLU", "ELU", "PReLU", "SReLU", "ThresholdedReLU",
    "GaussianNoise", "GaussianDropout", "SpatialDropout1D", "SpatialDropout2D",
    "SpatialDropout3D", "Masking",
    "Highway", "MaxoutDense",
    "SeparableConvolution2D", "SeparableConv2D", "Deconvolution2D",
    "Conv2DTranspose", "AtrousConvolution1D", "AtrousConvolution2D",
    "LocallyConnected1D", "LocallyConnected2D",
    "Cropping1D", "Cropping2D", "Cropping3D",
    "ZeroPadding1D", "ZeroPadding3D", "UpSampling1D", "UpSampling3D",
    "MaxPooling3D", "AveragePooling3D", "GlobalMaxPooling3D",
    "GlobalAveragePooling3D",
    "ConvLSTM2D", "ConvLSTM3D",
    "LRN2D", "WithinChannelLRN2D", "ResizeBilinear", "GaussianSampler",
    "Scale", "CAdd", "CMul", "AddConstant", "MulConstant", "Abs", "Clamp",
    "HardTanh", "Exp", "Log", "Power", "Square", "Sqrt", "Negative",
    "Identity", "HardShrink", "SoftShrink", "Threshold",
    "Softmax", "BinaryThreshold", "Mul", "Max", "RReLU", "SelectTable",
    "SplitTensor", "Expand", "GetShape", "ShareConvolution2D",
    "SparseDense", "SparseEmbedding",
]


# ---------------------------------------------------------------------------
# Advanced activations
# ---------------------------------------------------------------------------
class LeakyReLU(Layer):
    """`keras/layers/advanced_activations` LeakyReLU(alpha)."""

    def __init__(self, alpha: float = 0.3, **kw):
        super().__init__(**kw)
        self.alpha = float(alpha)

    def call(self, params, x, *, training=False, rng=None):
        return jax.nn.leaky_relu(x, self.alpha)


class ELU(Layer):
    def __init__(self, alpha: float = 1.0, **kw):
        super().__init__(**kw)
        self.alpha = float(alpha)

    def call(self, params, x, *, training=False, rng=None):
        return jax.nn.elu(x, self.alpha)


class ThresholdedReLU(Layer):
    def __init__(self, theta: float = 1.0, **kw):
        super().__init__(**kw)
        self.theta = float(theta)

    def call(self, params, x, *, training=False, rng=None):
        return x * (x > self.theta).astype(x.dtype)


class PReLU(Layer):
    """Learnable per-element leaky slope (Keras1 default: alphas have the
    full non-batch input shape)."""

    def build(self, rng, input_shape):
        return {"alpha": jnp.zeros(tuple(input_shape[1:]), jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        a = params["alpha"]
        return jnp.maximum(x, 0.0) + a * jnp.minimum(x, 0.0)


class SReLU(Layer):
    """S-shaped ReLU (`SReLU.scala`): two learnable thresholds + slopes."""

    def build(self, rng, input_shape):
        shape = tuple(input_shape[1:])
        return {"t_left": jnp.zeros(shape, jnp.float32),
                "a_left": jnp.zeros(shape, jnp.float32),
                "t_right": jnp.ones(shape, jnp.float32),
                "a_right": jnp.ones(shape, jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        tl, al = params["t_left"], params["a_left"]
        tr, ar = params["t_right"], params["a_right"]
        y_left = tl + al * (x - tl)
        y_right = tr + ar * (x - tr)
        return jnp.where(x < tl, y_left, jnp.where(x > tr, y_right, x))


# ---------------------------------------------------------------------------
# Noise / structured dropout / masking
# ---------------------------------------------------------------------------
class GaussianNoise(Layer):
    def __init__(self, sigma: float, **kw):
        super().__init__(**kw)
        self.sigma = float(sigma)

    def call(self, params, x, *, training=False, rng=None):
        if not training or self.sigma <= 0.0:
            return x
        if rng is None:
            raise ValueError(f"{self.name}: needs an rng in training")
        return x + self.sigma * jax.random.normal(rng, jnp.shape(x), x.dtype)


class GaussianDropout(Layer):
    """Multiplicative 1-mean gaussian noise with std sqrt(p/(1-p))."""

    def __init__(self, p: float, **kw):
        super().__init__(**kw)
        self.rate = float(p)

    def call(self, params, x, *, training=False, rng=None):
        if not training or self.rate <= 0.0:
            return x
        if rng is None:
            raise ValueError(f"{self.name}: needs an rng in training")
        std = float(np.sqrt(self.rate / (1.0 - self.rate)))
        return x * (1.0 + std * jax.random.normal(rng, jnp.shape(x), x.dtype))


class _SpatialDropout(Layer):
    """Drops whole feature maps; mask broadcasts over spatial axes."""
    spatial_rank = 2

    def __init__(self, p: float = 0.5, dim_ordering: str = "tf", **kw):
        super().__init__(**kw)
        self.rate = float(p)
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        if not training or self.rate <= 0.0:
            return x
        if rng is None:
            raise ValueError(f"{self.name}: needs an rng in training")
        keep = 1.0 - self.rate
        shape = list(jnp.shape(x))
        if self.dim_ordering == "tf":
            for ax in range(1, 1 + self.spatial_rank):
                shape[ax] = 1
        else:
            for ax in range(2, 2 + self.spatial_rank):
                shape[ax] = 1
        mask = jax.random.bernoulli(rng, keep, tuple(shape))
        return jnp.where(mask, x / keep, 0.0)


class SpatialDropout1D(_SpatialDropout):
    spatial_rank = 1


class SpatialDropout2D(_SpatialDropout):
    spatial_rank = 2


class SpatialDropout3D(_SpatialDropout):
    spatial_rank = 3


class Masking(Layer):
    """`Masking.scala`: zero timesteps whose features all equal
    mask_value."""

    def __init__(self, mask_value: float = 0.0, **kw):
        super().__init__(**kw)
        self.mask_value = float(mask_value)

    def call(self, params, x, *, training=False, rng=None):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return x * keep.astype(x.dtype)


# ---------------------------------------------------------------------------
# Dense variants
# ---------------------------------------------------------------------------
class Highway(Layer):
    """`Highway.scala`: y = t·h(x) + (1−t)·x; requires out_dim == in_dim."""

    def __init__(self, activation="tanh", use_bias: bool = True,
                 init="glorot_uniform", **kw):
        super().__init__(**kw)
        self.activation = get_activation(activation)
        self.use_bias = use_bias
        self.init = get_init(init)

    def build(self, rng, input_shape):
        d = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        p = {"kernel": self.init(k1, (d, d), jnp.float32),
             "transform_kernel": self.init(k2, (d, d), jnp.float32)}
        if self.use_bias:
            p["bias"] = jnp.zeros((d,), jnp.float32)
            # negative transform bias ≈ carry-by-default (highway paper)
            p["transform_bias"] = jnp.full((d,), -2.0, jnp.float32)
        return p

    def call(self, params, x, *, training=False, rng=None):
        x = _match_param_dtype(x, params["kernel"])
        h = x @ params["kernel"]
        t = x @ params["transform_kernel"]
        if self.use_bias:
            h = h + params["bias"]
            t = t + params["transform_bias"]
        h = self.activation(h)
        t = jax.nn.sigmoid(t)
        return t * h + (1.0 - t) * x


class MaxoutDense(Layer):
    """`MaxoutDense.scala`: max over nb_feature affine maps."""

    def __init__(self, output_dim: int, nb_feature: int = 4,
                 use_bias: bool = True, init="glorot_uniform", **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.nb_feature = nb_feature
        self.use_bias = use_bias
        self.init = get_init(init)

    def build(self, rng, input_shape):
        d = input_shape[-1]
        p = {"kernel": self.init(
            rng, (self.nb_feature, d, self.output_dim), jnp.float32)}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.nb_feature, self.output_dim),
                                  jnp.float32)
        return p

    def call(self, params, x, *, training=False, rng=None):
        x = _match_param_dtype(x, params["kernel"])
        y = jnp.einsum("bd,fdo->bfo", x, params["kernel"])
        if self.use_bias:
            y = y + params["bias"]
        return jnp.max(y, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.output_dim)


# ---------------------------------------------------------------------------
# Convolution family
# ---------------------------------------------------------------------------
class SeparableConvolution2D(Layer):
    """`SeparableConvolution2D.scala`: depthwise (feature_group_count) then
    1×1 pointwise — both MXU-tileable convs."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample=(1, 1), border_mode="valid",
                 depth_multiplier: int = 1, dim_ordering="tf",
                 use_bias: bool = True, init="glorot_uniform", **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.kernel_size = (nb_row, nb_col)
        self.activation = get_activation(activation)
        self.strides = tuple(subsample)
        self.padding = border_mode.upper()
        self.depth_multiplier = depth_multiplier
        self.dim_ordering = dim_ordering
        self.use_bias = use_bias
        self.init = get_init(init)

    def build(self, rng, input_shape):
        in_ch = input_shape[1] if self.dim_ordering == "th" \
            else input_shape[-1]
        k1, k2 = jax.random.split(rng)
        p = {
            "depthwise": self.init(
                k1, self.kernel_size + (1, in_ch * self.depth_multiplier),
                jnp.float32),
            "pointwise": self.init(
                k2, (1, 1, in_ch * self.depth_multiplier, self.nb_filter),
                jnp.float32),
        }
        if self.use_bias:
            p["bias"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return p

    def call(self, params, x, *, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, 2)
        x = _match_param_dtype(x, params["depthwise"])
        in_ch = x.shape[-1]
        y = jax.lax.conv_general_dilated(
            x, params["depthwise"], window_strides=self.strides,
            padding=self.padding, feature_group_count=in_ch,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = jax.lax.conv_general_dilated(
            y, params["pointwise"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + params["bias"]
        y = self.activation(y)
        return _from_channels_last(y, self.dim_ordering, 2)

    def _out(self, size, k, s):
        if size is None:
            return None
        return -(-size // s) if self.padding == "SAME" \
            else (size - k) // s + 1

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            h, w = input_shape[2:4]
            return (input_shape[0], self.nb_filter,
                    self._out(h, self.kernel_size[0], self.strides[0]),
                    self._out(w, self.kernel_size[1], self.strides[1]))
        h, w = input_shape[1:3]
        return (input_shape[0],
                self._out(h, self.kernel_size[0], self.strides[0]),
                self._out(w, self.kernel_size[1], self.strides[1]),
                self.nb_filter)


SeparableConv2D = SeparableConvolution2D


class Deconvolution2D(Layer):
    """`Deconvolution2D.scala` (transposed conv / Conv2DTranspose)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample=(1, 1), border_mode="valid",
                 dim_ordering="tf", use_bias: bool = True,
                 init="glorot_uniform", **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.kernel_size = (nb_row, nb_col)
        self.activation = get_activation(activation)
        self.strides = tuple(subsample)
        self.padding = border_mode.upper()
        self.dim_ordering = dim_ordering
        self.use_bias = use_bias
        self.init = get_init(init)

    def build(self, rng, input_shape):
        in_ch = input_shape[1] if self.dim_ordering == "th" \
            else input_shape[-1]
        p = {"kernel": self.init(
            rng, self.kernel_size + (in_ch, self.nb_filter), jnp.float32)}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return p

    def call(self, params, x, *, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, 2)
        x = _match_param_dtype(x, params["kernel"])
        # Scatter (gradient-of-conv) semantics — matches Keras/BigDL. jax's
        # conv_transpose correlates, so flip the spatial dims.
        y = jax.lax.conv_transpose(
            x, jnp.flip(params["kernel"], (0, 1)), strides=self.strides,
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + params["bias"]
        y = self.activation(y)
        return _from_channels_last(y, self.dim_ordering, 2)

    def _out(self, size, k, s):
        if size is None:
            return None
        return size * s if self.padding == "SAME" else (size - 1) * s + k

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            h, w = input_shape[2:4]
            return (input_shape[0], self.nb_filter,
                    self._out(h, self.kernel_size[0], self.strides[0]),
                    self._out(w, self.kernel_size[1], self.strides[1]))
        h, w = input_shape[1:3]
        return (input_shape[0],
                self._out(h, self.kernel_size[0], self.strides[0]),
                self._out(w, self.kernel_size[1], self.strides[1]),
                self.nb_filter)


Conv2DTranspose = Deconvolution2D


class AtrousConvolution2D(_ConvND):
    """`AtrousConvolution2D.scala`: dilated conv."""

    def __init__(self, nb_filter, nb_row, nb_col, atrous_rate=(1, 1), **kw):
        super().__init__(nb_filter, (nb_row, nb_col), **kw)
        self.atrous_rate = tuple(atrous_rate)

    def call(self, params, x, *, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, self.spatial_rank)
        x = _match_param_dtype(x, params["kernel"])
        y = jax.lax.conv_general_dilated(
            x, params["kernel"], window_strides=self.strides,
            padding=self.padding, rhs_dilation=self.atrous_rate,
            dimension_numbers=self.dn,
            feature_group_count=self.groups)
        if self.use_bias:
            y = y + params["bias"]
        y = self.activation(y)
        return _from_channels_last(y, self.dim_ordering, self.spatial_rank)

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            spatial = input_shape[2:]
        else:
            spatial = input_shape[1:-1]
        out = []
        for d, k, s, r in zip(spatial, self.kernel_size, self.strides,
                              self.atrous_rate):
            if d is None:
                out.append(None)
            elif self.padding == "SAME":
                out.append(-(-d // s))
            else:
                eff = (k - 1) * r + 1
                out.append((d - eff) // s + 1)
        if self.dim_ordering == "th":
            return (input_shape[0], self.nb_filter) + tuple(out)
        return (input_shape[0],) + tuple(out) + (self.nb_filter,)


class AtrousConvolution1D(AtrousConvolution2D):
    spatial_rank = 1
    dn = ("NWC", "WIO", "NWC")

    def __init__(self, nb_filter, filter_length, atrous_rate: int = 1, **kw):
        _ConvND.__init__(self, nb_filter, (filter_length,), **kw)
        self.atrous_rate = (atrous_rate,)


class LocallyConnected1D(Layer):
    """`LocallyConnected1D.scala`: unshared conv — per-position kernels.
    Implemented as patch extraction + batched einsum (one big contraction,
    not a python loop over positions)."""

    spatial_rank = 1

    def __init__(self, nb_filter: int, filter_length: int, activation=None,
                 subsample_length: int = 1, use_bias: bool = True,
                 init="glorot_uniform", **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.kernel_size = (filter_length,)
        self.strides = (subsample_length,)
        self.activation = get_activation(activation)
        self.use_bias = use_bias
        self.init = get_init(init)

    def _out_len(self, size):
        return (size - self.kernel_size[0]) // self.strides[0] + 1

    def build(self, rng, input_shape):
        in_ch = input_shape[-1]
        out_len = self._out_len(input_shape[1])
        p = {"kernel": self.init(
            rng, (out_len, self.kernel_size[0] * in_ch, self.nb_filter),
            jnp.float32)}
        if self.use_bias:
            p["bias"] = jnp.zeros((out_len, self.nb_filter), jnp.float32)
        return p

    def call(self, params, x, *, training=False, rng=None):
        x = _match_param_dtype(x, params["kernel"])
        # [B, L, C] → patches [B, out_len, k*C]
        k = self.kernel_size[0]
        s = self.strides[0]
        out_len = self._out_len(x.shape[1])
        idx = jnp.arange(out_len)[:, None] * s + jnp.arange(k)[None, :]
        patches = x[:, idx, :]                      # [B, out_len, k, C]
        patches = patches.reshape(x.shape[0], out_len, -1)
        y = jnp.einsum("bok,okf->bof", patches, params["kernel"])
        if self.use_bias:
            y = y + params["bias"]
        return self.activation(y)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self._out_len(input_shape[1]),
                self.nb_filter)


class LocallyConnected2D(Layer):
    """`LocallyConnected2D.scala`."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample=(1, 1), use_bias: bool = True,
                 dim_ordering="tf", init="glorot_uniform", **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.kernel_size = (nb_row, nb_col)
        self.strides = tuple(subsample)
        self.activation = get_activation(activation)
        self.use_bias = use_bias
        self.dim_ordering = dim_ordering
        self.init = get_init(init)

    def _out(self, size, k, s):
        return (size - k) // s + 1

    def build(self, rng, input_shape):
        if self.dim_ordering == "th":
            in_ch, h, w = input_shape[1], input_shape[2], input_shape[3]
        else:
            h, w, in_ch = input_shape[1], input_shape[2], input_shape[3]
        oh = self._out(h, self.kernel_size[0], self.strides[0])
        ow = self._out(w, self.kernel_size[1], self.strides[1])
        kdim = self.kernel_size[0] * self.kernel_size[1] * in_ch
        p = {"kernel": self.init(
            rng, (oh * ow, kdim, self.nb_filter), jnp.float32)}
        if self.use_bias:
            p["bias"] = jnp.zeros((oh, ow, self.nb_filter), jnp.float32)
        return p

    def call(self, params, x, *, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, 2)
        x = _match_param_dtype(x, params["kernel"])
        b, h, w, c = x.shape
        kh, kw = self.kernel_size
        sh, sw = self.strides
        oh = self._out(h, kh, sh)
        ow = self._out(w, kw, sw)
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        # patches: [B, oh, ow, C*kh*kw] with channel-major ordering →
        # reorder to kh*kw*C to match kernel layout
        patches = patches.reshape(b, oh, ow, c, kh, kw)
        patches = jnp.transpose(patches, (0, 1, 2, 4, 5, 3))
        patches = patches.reshape(b, oh * ow, kh * kw * c)
        y = jnp.einsum("bok,okf->bof", patches, params["kernel"])
        y = y.reshape(b, oh, ow, self.nb_filter)
        if self.use_bias:
            y = y + params["bias"]
        y = self.activation(y)
        return _from_channels_last(y, self.dim_ordering, 2)

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            h, w = input_shape[2], input_shape[3]
            return (input_shape[0], self.nb_filter,
                    self._out(h, self.kernel_size[0], self.strides[0]),
                    self._out(w, self.kernel_size[1], self.strides[1]))
        h, w = input_shape[1], input_shape[2]
        return (input_shape[0],
                self._out(h, self.kernel_size[0], self.strides[0]),
                self._out(w, self.kernel_size[1], self.strides[1]),
                self.nb_filter)


# ---------------------------------------------------------------------------
# Cropping / padding / upsampling
# ---------------------------------------------------------------------------
class Cropping1D(Layer):
    def __init__(self, cropping=(1, 1), **kw):
        super().__init__(**kw)
        self.cropping = tuple(cropping)

    def call(self, params, x, *, training=False, rng=None):
        a, b = self.cropping
        return x[:, a:x.shape[1] - b, :]

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        s[1] -= sum(self.cropping)
        return tuple(s)


class _CroppingND(Layer):
    spatial_rank = 2

    def __init__(self, cropping=None, dim_ordering="tf", **kw):
        super().__init__(**kw)
        self.cropping = tuple(tuple(c) for c in (
            cropping or ((1, 1),) * self.spatial_rank))
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, self.spatial_rank)
        idx = [slice(None)]
        for ax, (a, b) in enumerate(self.cropping):
            idx.append(slice(a, x.shape[1 + ax] - b))
        idx.append(slice(None))
        y = x[tuple(idx)]
        return _from_channels_last(y, self.dim_ordering, self.spatial_rank)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        off = 2 if self.dim_ordering == "th" else 1
        for ax, (a, b) in enumerate(self.cropping):
            s[off + ax] -= a + b
        return tuple(s)


class Cropping2D(_CroppingND):
    spatial_rank = 2


class Cropping3D(_CroppingND):
    spatial_rank = 3

    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)), **kw):
        super().__init__(cropping, **kw)


class ZeroPadding1D(Layer):
    def __init__(self, padding=1, **kw):
        super().__init__(**kw)
        self.padding = (padding, padding) if isinstance(padding, int) \
            else tuple(padding)

    def call(self, params, x, *, training=False, rng=None):
        a, b = self.padding
        return jnp.pad(x, ((0, 0), (a, b), (0, 0)))

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        s[1] += sum(self.padding)
        return tuple(s)


class ZeroPadding3D(Layer):
    def __init__(self, padding=(1, 1, 1), dim_ordering="tf", **kw):
        super().__init__(**kw)
        self.padding = tuple(padding)
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        p1, p2, p3 = self.padding
        if self.dim_ordering == "tf":
            return jnp.pad(x, ((0, 0), (p1, p1), (p2, p2), (p3, p3), (0, 0)))
        return jnp.pad(x, ((0, 0), (0, 0), (p1, p1), (p2, p2), (p3, p3)))

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        off = 2 if self.dim_ordering == "th" else 1
        for i, p in enumerate(self.padding):
            s[off + i] += 2 * p
        return tuple(s)


class UpSampling1D(Layer):
    def __init__(self, length: int = 2, **kw):
        super().__init__(**kw)
        self.length = length

    def call(self, params, x, *, training=False, rng=None):
        return jnp.repeat(x, self.length, axis=1)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        s[1] *= self.length
        return tuple(s)


class UpSampling3D(Layer):
    def __init__(self, size=(2, 2, 2), dim_ordering="tf", **kw):
        super().__init__(**kw)
        self.size = tuple(size)
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        off = 2 if self.dim_ordering == "th" else 1
        y = x
        for i, s in enumerate(self.size):
            y = jnp.repeat(y, s, axis=off + i)
        return y

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        off = 2 if self.dim_ordering == "th" else 1
        for i, f in enumerate(self.size):
            s[off + i] *= f
        return tuple(s)


class MaxPooling3D(_PoolND):
    spatial_rank = 3


class AveragePooling3D(_PoolND):
    spatial_rank = 3
    reducer = "avg"


class GlobalMaxPooling3D(_GlobalPool):
    spatial_axes = (1, 2, 3)


class GlobalAveragePooling3D(_GlobalPool):
    spatial_axes = (1, 2, 3)
    reducer = "avg"


# ---------------------------------------------------------------------------
# ConvLSTM
# ---------------------------------------------------------------------------
class ConvLSTM2D(_Recurrent):
    """`ConvLSTM2D.scala`: LSTM whose gates are N-D convs. Input
    [B, T, *spatial, C] (channels_last). Gates computed in one fused conv
    (4·filters output channels) per step under `lax.scan`. border_mode is
    forced "same" so the state keeps its spatial shape (reference
    behavior). `ConvLSTM3D.scala` is the spatial_rank=3 subclass."""

    n_gates = 4
    spatial_rank = 2
    dn = ("NHWC", "HWIO", "NHWC")

    def __init__(self, nb_filter: int, nb_kernel: int, activation="tanh",
                 inner_activation="hard_sigmoid", return_sequences=False,
                 go_backwards=False, border_mode="same", subsample=None,
                 init="glorot_uniform", inner_init="orthogonal", **kw):
        super().__init__(nb_filter, activation=activation,
                         inner_activation=inner_activation,
                         return_sequences=return_sequences,
                         go_backwards=go_backwards, init=init,
                         inner_init=inner_init, **kw)
        if border_mode != "same":
            raise ValueError(
                f"{type(self).__name__} supports border_mode='same' only")
        self.kernel_size = (nb_kernel,) * self.spatial_rank \
            if isinstance(nb_kernel, int) else tuple(nb_kernel)
        self.strides = tuple(subsample or (1,) * self.spatial_rank)
        self._state_spatial: Optional[Tuple[int, ...]] = None

    def _out_spatial(self, spatial):
        return tuple(-(-d // s) for d, s in zip(spatial, self.strides))

    def build(self, rng, input_shape):
        # input_shape: [B, T, *spatial, C]
        spatial = input_shape[2:2 + self.spatial_rank]
        in_ch = input_shape[-1]
        self._state_spatial = self._out_spatial(spatial)
        k1, k2 = jax.random.split(rng)
        return {
            "kernel": self.init(
                k1, self.kernel_size + (in_ch, 4 * self.output_dim),
                jnp.float32),
            "recurrent": self.inner_init(
                k2, self.kernel_size + (self.output_dim,
                                        4 * self.output_dim), jnp.float32),
            "bias": jnp.zeros((4 * self.output_dim,), jnp.float32),
        }

    def initial_state(self, batch):
        z = jnp.zeros((batch,) + self._state_spatial + (self.output_dim,),
                      jnp.float32)
        return (z, z)

    def step(self, params, carry, x_t):
        h, c = carry
        zx = jax.lax.conv_general_dilated(
            x_t, params["kernel"], window_strides=self.strides,
            padding="SAME", dimension_numbers=self.dn)
        zh = jax.lax.conv_general_dilated(
            h, params["recurrent"],
            window_strides=(1,) * self.spatial_rank, padding="SAME",
            dimension_numbers=self.dn)
        z = zx + zh + params["bias"]
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = self.inner_activation(i)
        f = self.inner_activation(f)
        o = self.inner_activation(o)
        g = self.activation(g)
        c_new = f * c + i * g
        h_new = o * self.activation(c_new)
        return (h_new, c_new), h_new

    def call(self, params, x, *, training=False, rng=None):
        if self._state_spatial is None:
            self._state_spatial = self._out_spatial(
                x.shape[2:2 + self.spatial_rank])
        return super().call(params, x, training=training, rng=rng)

    def compute_output_shape(self, input_shape):
        b, t = input_shape[:2]
        out = self._out_spatial(input_shape[2:2 + self.spatial_rank])
        if self.return_sequences:
            return (b, t) + out + (self.output_dim,)
        return (b,) + out + (self.output_dim,)


class ConvLSTM3D(ConvLSTM2D):
    """`ConvLSTM3D.scala`: volumetric ConvLSTM, input [B, T, D, H, W, C]."""

    spatial_rank = 3
    dn = ("NDHWC", "DHWIO", "NDHWC")


# ---------------------------------------------------------------------------
# Normalization / resize / sampling
# ---------------------------------------------------------------------------
class LRN2D(Layer):
    """`LRN2D.scala`: cross-channel local response normalization
    (AlexNet/GoogLeNet): x / (k + alpha/n · Σ x²)^beta over a channel
    window."""

    def __init__(self, alpha: float = 1e-4, k: float = 1.0, beta: float = 0.75,
                 n: int = 5, dim_ordering: str = "tf", **kw):
        super().__init__(**kw)
        self.alpha, self.k, self.beta, self.n = alpha, k, beta, n
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, 2)
        half = self.n // 2
        sq = jnp.square(x)
        window = (1, 1, 1, self.n)
        summed = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add, window, (1, 1, 1, 1),
            [(0, 0), (0, 0), (0, 0), (half, self.n - 1 - half)])
        y = x / jnp.power(self.k + (self.alpha / self.n) * summed, self.beta)
        return _from_channels_last(y, self.dim_ordering, 2)


class WithinChannelLRN2D(Layer):
    """`WithinChannelLRN2D.scala`: LRN over a spatial window within each
    channel."""

    def __init__(self, size: int = 5, alpha: float = 1.0,
                 beta: float = 0.75, **kw):
        super().__init__(**kw)
        self.size, self.alpha, self.beta = size, alpha, beta

    def call(self, params, x, *, training=False, rng=None):
        n = self.size
        half = n // 2
        pad = [(0, 0), (half, n - 1 - half), (half, n - 1 - half), (0, 0)]
        sq = jnp.square(x)
        summed = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add, (1, n, n, 1), (1, 1, 1, 1), pad)
        mean_sq = summed / float(n * n)
        return x / jnp.power(1.0 + self.alpha * mean_sq, self.beta)


class ResizeBilinear(Layer):
    """`ResizeBilinear.scala`: bilinear spatial resize (NHWC).
    `align_corners=True` uses corner-aligned source coordinates
    (out_i · (in−1)/(out−1)), matching TF's align_corners grid; False uses
    jax.image's half-pixel-centered grid."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, **kw):
        super().__init__(**kw)
        self.out_hw = (output_height, output_width)
        self.align_corners = align_corners

    @staticmethod
    def _interp_axis(x, out_size, axis):
        in_size = x.shape[axis]
        if out_size == 1 or in_size == 1:
            coords = jnp.zeros((out_size,))
        else:
            coords = jnp.linspace(0.0, in_size - 1.0, out_size)
        lo = jnp.clip(jnp.floor(coords).astype(jnp.int32), 0, in_size - 1)
        hi = jnp.clip(lo + 1, 0, in_size - 1)
        w = (coords - lo).astype(x.dtype)
        shape = [1] * x.ndim
        shape[axis] = out_size
        w = w.reshape(shape)
        return (jnp.take(x, lo, axis=axis) * (1 - w)
                + jnp.take(x, hi, axis=axis) * w)

    def call(self, params, x, *, training=False, rng=None):
        b, _, _, c = x.shape
        oh, ow = self.out_hw
        if not self.align_corners:
            return jax.image.resize(x, (b, oh, ow, c), "bilinear")
        y = self._interp_axis(x, oh, axis=1)
        return self._interp_axis(y, ow, axis=2)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + self.out_hw + (input_shape[-1],)


class GaussianSampler(Layer):
    """`GaussianSampler.scala` (VAE reparameterization): input
    [mean, log_var] → mean + exp(log_var/2)·ε in training; the mean at
    inference."""

    def call(self, params, xs, *, training=False, rng=None):
        mean, log_var = xs
        if not training:
            return mean
        if rng is None:
            raise ValueError(f"{self.name}: needs an rng in training "
                             "(reparameterization noise)")
        eps = jax.random.normal(rng, jnp.shape(mean), mean.dtype)
        return mean + jnp.exp(log_var * 0.5) * eps

    def compute_output_shape(self, input_shapes):
        return input_shapes[0]


# ---------------------------------------------------------------------------
# Torch-style elementwise layers (`pyzoo/.../keras/layers/torch.py`)
# ---------------------------------------------------------------------------
class Scale(Layer):
    """Learnable per-channel affine y = a·x + b (`Scale` in torch.py)."""

    def build(self, rng, input_shape):
        d = input_shape[-1]
        return {"alpha": jnp.ones((d,), jnp.float32),
                "beta": jnp.zeros((d,), jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        return x * params["alpha"] + params["beta"]


class CAdd(Layer):
    """Learnable bias of arbitrary broadcastable shape."""

    def __init__(self, size: Sequence[int], **kw):
        super().__init__(**kw)
        self.size = tuple(size)

    def build(self, rng, input_shape):
        return {"bias": jnp.zeros(self.size, jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        return x + params["bias"]


class CMul(Layer):
    def __init__(self, size: Sequence[int], **kw):
        super().__init__(**kw)
        self.size = tuple(size)

    def build(self, rng, input_shape):
        return {"weight": jnp.ones(self.size, jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        return x * params["weight"]


class _Elementwise(Layer):
    fn = staticmethod(lambda x: x)

    def call(self, params, x, *, training=False, rng=None):
        return type(self).fn(x)


class AddConstant(Layer):
    def __init__(self, constant_scalar: float, **kw):
        super().__init__(**kw)
        self.c = constant_scalar

    def call(self, params, x, *, training=False, rng=None):
        return x + self.c


class MulConstant(Layer):
    def __init__(self, constant_scalar: float, **kw):
        super().__init__(**kw)
        self.c = constant_scalar

    def call(self, params, x, *, training=False, rng=None):
        return x * self.c


class Abs(_Elementwise):
    fn = staticmethod(jnp.abs)


class Exp(_Elementwise):
    fn = staticmethod(jnp.exp)


class Log(_Elementwise):
    fn = staticmethod(jnp.log)


class Square(_Elementwise):
    fn = staticmethod(jnp.square)


class Sqrt(_Elementwise):
    fn = staticmethod(jnp.sqrt)


class Negative(_Elementwise):
    fn = staticmethod(jnp.negative)


class Identity(_Elementwise):
    pass


class Power(Layer):
    """y = (scale·x + shift)^power."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0,
                 **kw):
        super().__init__(**kw)
        self.power, self.scale, self.shift = power, scale, shift

    def call(self, params, x, *, training=False, rng=None):
        return jnp.power(self.scale * x + self.shift, self.power)


class Clamp(Layer):
    def __init__(self, min: float, max: float, **kw):
        super().__init__(**kw)
        self.min_v, self.max_v = float(min), float(max)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.clip(x, self.min_v, self.max_v)


class HardTanh(Clamp):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0, **kw):
        super().__init__(min_value, max_value, **kw)


class HardShrink(Layer):
    def __init__(self, value: float = 0.5, **kw):
        super().__init__(**kw)
        self.value = value

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(jnp.abs(x) > self.value, x, 0.0)


class SoftShrink(Layer):
    def __init__(self, value: float = 0.5, **kw):
        super().__init__(**kw)
        self.value = value

    def call(self, params, x, *, training=False, rng=None):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.value, 0.0)


class Threshold(Layer):
    """y = x if x > th else v."""

    def __init__(self, th: float = 1e-6, v: float = 0.0, **kw):
        super().__init__(**kw)
        self.th, self.v = th, v

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(x > self.th, x, self.v)


# ---------------------------------------------------------------------------
# Long-tail parity layers (`keras/layers/*.scala` remaining inventory)
# ---------------------------------------------------------------------------
class Softmax(Layer):
    """Softmax as a layer (`Softmax.scala`); axis defaults to last."""

    def __init__(self, axis: int = -1, **kw):
        super().__init__(**kw)
        self.axis = int(axis)

    def call(self, params, x, *, training=False, rng=None):
        return jax.nn.softmax(x, axis=self.axis)


class BinaryThreshold(Layer):
    """`BinaryThreshold.scala`: element < th → 0 else 1."""

    def __init__(self, th: float = 1e-6, **kw):
        super().__init__(**kw)
        self.th = float(th)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.where(x < self.th, 0.0, 1.0)


class Mul(Layer):
    """`Mul.scala`: multiply the input by ONE learnable scalar."""

    def build(self, rng, input_shape):
        return {"weight": jax.random.uniform(rng, (1,), jnp.float32,
                                             -0.05, 0.05)}

    def call(self, params, x, *, training=False, rng=None):
        return x * params["weight"]


class Max(Layer):
    """`Max.scala`: max over dimension `dim` (1-based over the batched
    array, i.e. dim=1 is the first non-batch dim); `return_value=False`
    returns the argmax indices instead."""

    def __init__(self, dim: int, return_value: bool = True, **kw):
        super().__init__(**kw)
        if dim < 1:
            raise ValueError("Max cannot reduce the batch dimension")
        self.dim = int(dim)
        self.return_value = return_value

    def call(self, params, x, *, training=False, rng=None):
        if self.return_value:
            return jnp.max(x, axis=self.dim)
        return jnp.argmax(x, axis=self.dim).astype(jnp.int32)

    def compute_output_shape(self, input_shape):
        shape = list(input_shape)
        del shape[self.dim]
        return tuple(shape)


class RReLU(Layer):
    """`RReLU.scala`: randomized leaky ReLU — training slope ~ U(l, u)
    per element, eval slope = (l + u) / 2."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 **kw):
        super().__init__(**kw)
        self.lower, self.upper = float(lower), float(upper)

    def call(self, params, x, *, training=False, rng=None):
        if training:
            if rng is None:
                raise ValueError(f"{self.name} needs an rng in training")
            a = jax.random.uniform(rng, jnp.shape(x), jnp.float32,
                                   self.lower, self.upper)
        else:
            a = (self.lower + self.upper) / 2.0
        return jnp.maximum(x, 0.0) + a * jnp.minimum(x, 0.0)


class SelectTable(Layer):
    """`SelectTable.scala`: pick element `index` (0-based) from a list
    input."""

    def __init__(self, index: int, **kw):
        super().__init__(**kw)
        self.index = int(index)

    def call(self, params, x, *, training=False, rng=None):
        if not isinstance(x, (list, tuple)):
            raise ValueError("SelectTable expects a list input")
        return x[self.index]

    def compute_output_shape(self, input_shape):
        return input_shape[self.index]


class SplitTensor(Layer):
    """`SplitTensor.scala`: split along `dimension` (0-based counting the
    batch dim, matching the reference note) into `num` equal parts,
    output is a list."""

    def __init__(self, dimension: int, num: int, **kw):
        super().__init__(**kw)
        if dimension == 0:
            raise ValueError("SplitTensor cannot split the batch dimension")
        self.dimension, self.num = int(dimension), int(num)

    def call(self, params, x, *, training=False, rng=None):
        return list(jnp.split(x, self.num, axis=self.dimension))

    def compute_output_shape(self, input_shape):
        shape = list(input_shape)
        if shape[self.dimension] is not None:
            if shape[self.dimension] % self.num:
                raise ValueError(
                    f"SplitTensor: dim {self.dimension} size "
                    f"{shape[self.dimension]} not divisible by {self.num}")
            shape[self.dimension] //= self.num
        return [tuple(shape)] * self.num


class Expand(Layer):
    """`Expand.scala` (InternalExpand): broadcast singleton dims to
    `tgt_sizes` (full shape including batch; -1 keeps a dim)."""

    def __init__(self, tgt_sizes: Sequence[int], **kw):
        super().__init__(**kw)
        self.tgt_sizes = tuple(int(d) for d in tgt_sizes)

    def _target(self, in_shape):
        if len(self.tgt_sizes) != len(in_shape):
            raise ValueError(
                f"Expand tgt_sizes rank {len(self.tgt_sizes)} != input "
                f"rank {len(in_shape)} (shape {tuple(in_shape)})")
        return tuple(s if t == -1 else t
                     for t, s in zip(self.tgt_sizes, in_shape))

    def call(self, params, x, *, training=False, rng=None):
        return jnp.broadcast_to(x, self._target(x.shape))

    def compute_output_shape(self, input_shape):
        return self._target(input_shape)


class GetShape(Layer):
    """`GetShape.scala`: outputs the input's shape as an int tensor
    (batch dim included)."""

    def call(self, params, x, *, training=False, rng=None):
        return jnp.asarray(jnp.shape(x), jnp.int32)

    def compute_output_shape(self, input_shape):
        return (len(input_shape),)


class ShareConvolution2D(Layer):
    """`ShareConvolution2D.scala`: conv2d whose weights are intended for
    sharing across graph sites (weight sharing falls out of calling ONE
    layer object at several nodes in this engine); `propagate_back=False`
    stops the input gradient (the reference flag)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, subsample=(1, 1),
                 border_mode: str = "valid", propagate_back: bool = True,
                 **kw):
        super().__init__(**kw)
        from analytics_zoo_tpu.keras.layers import Convolution2D
        self._conv = Convolution2D(nb_filter, nb_row, nb_col,
                                   activation=activation,
                                   subsample=subsample,
                                   border_mode=border_mode)
        self.propagate_back = propagate_back

    def build(self, rng, input_shape):
        return self._conv.build(rng, input_shape)

    def call(self, params, x, *, training=False, rng=None):
        if not self.propagate_back:
            x = jax.lax.stop_gradient(x)
        return self._conv.call(params, x, training=training, rng=rng)

    def compute_output_shape(self, input_shape):
        return self._conv.compute_output_shape(input_shape)


class SparseDense(Layer):
    """`SparseDense.scala` semantics on dense-coded sparse rows: a Dense
    layer that does NOT backpropagate into its input by default (the
    reference's gradInput suppression; `backward_start/length` would
    select a slice — here the whole input grad is stopped unless
    `propagate_back=True`)."""

    def __init__(self, output_dim: int, activation=None,
                 propagate_back: bool = False, **kw):
        super().__init__(**kw)
        from analytics_zoo_tpu.keras.layers import Dense
        self._dense = Dense(output_dim, activation=activation)
        self.propagate_back = propagate_back

    def build(self, rng, input_shape):
        return self._dense.build(rng, input_shape)

    def call(self, params, x, *, training=False, rng=None):
        if not self.propagate_back:
            x = jax.lax.stop_gradient(x)
        return self._dense.call(params, x, training=training, rng=rng)

    def compute_output_shape(self, input_shape):
        return self._dense.compute_output_shape(input_shape)


class SparseEmbedding(Layer):
    """`SparseEmbedding.scala`: embedding lookup for id lists padded with
    0 (the sparse-tensor role); pad positions contribute zero vectors."""

    def __init__(self, input_dim: int, output_dim: int, **kw):
        super().__init__(**kw)
        self.input_dim, self.output_dim = int(input_dim), int(output_dim)

    def build(self, rng, input_shape):
        scale = 0.05
        return {"embeddings": jax.random.uniform(
            rng, (self.input_dim, self.output_dim), jnp.float32,
            -scale, scale)}

    def call(self, params, x, *, training=False, rng=None):
        idx = jnp.asarray(x, jnp.int32)
        vecs = params["embeddings"][idx]
        return vecs * (idx != 0)[..., None]

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)
