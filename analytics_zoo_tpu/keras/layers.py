"""Keras-style layer library on raw jax.lax/jax.nn.

TPU-native re-design of the reference's Keras1 layer set
(`zoo/.../pipeline/api/keras/layers/*.scala`, ~130 layers; python mirror
`pyzoo/zoo/pipeline/api/keras/layers/`). Layers are pure: `build` returns a
parameter pytree, `call` is a jax-traceable function — the whole model fuses
into one XLA program instead of the reference's per-layer JVM graph walk.

Shape conventions: channels_last (NHWC / NWC) is the default — it is the
layout the TPU MXU wants — with `dim_ordering="th"` accepted for source
compatibility and transposed on the fly. `input_shape` excludes the batch dim.
Weight init follows Keras: glorot_uniform kernels, orthogonal recurrent
kernels, zero biases.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras.engine import Layer, Params, Shape
from analytics_zoo_tpu.pallas.dropout import fused_dropout

# ---------------------------------------------------------------------------
# Initializers & activations
# ---------------------------------------------------------------------------
_INITS = {
    "glorot_uniform": jax.nn.initializers.glorot_uniform(),
    "glorot_normal": jax.nn.initializers.glorot_normal(),
    "he_normal": jax.nn.initializers.he_normal(),
    "he_uniform": jax.nn.initializers.he_uniform(),
    "lecun_normal": jax.nn.initializers.lecun_normal(),
    "orthogonal": jax.nn.initializers.orthogonal(),
    "zeros": jax.nn.initializers.zeros,
    "ones": jax.nn.initializers.ones,
    "uniform": jax.nn.initializers.uniform(0.05),
    "normal": jax.nn.initializers.normal(0.05),
}


def get_init(name_or_fn) -> Callable:
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _INITS:
        raise ValueError(f"Unsupported initializer: {name_or_fn}")
    return _INITS[key]


_ACTIVATIONS: Dict[str, Callable] = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
    "log_softmax": lambda x: jax.nn.log_softmax(x, axis=-1),
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "elu": jax.nn.elu,
    "selu": jax.nn.selu,
    "gelu": jax.nn.gelu,
    "swish": jax.nn.silu,
    "silu": jax.nn.silu,
    "leaky_relu": jax.nn.leaky_relu,
    "linear": lambda x: x,
}


def get_activation(name_or_fn) -> Callable:
    if name_or_fn is None:
        return lambda x: x
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in _ACTIVATIONS:
        raise ValueError(f"Unsupported activation: {name_or_fn}")
    return _ACTIVATIONS[key]


def _match_param_dtype(x, ref):
    """Float operands follow the parameter dtype so mixed-precision (bf16)
    params see matching MXU operands. Integer inputs pass through untouched
    — casting float-encoded ids to bf16 silently corrupts values > 256."""
    if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != ref.dtype:
        return x.astype(ref.dtype)
    return x


# ---------------------------------------------------------------------------
# Core layers
# ---------------------------------------------------------------------------
class Dense(Layer):
    """`keras/layers/Dense.scala`. Applies to the last axis (any rank)."""

    def __init__(self, output_dim: int, activation=None, use_bias: bool = True,
                 init="glorot_uniform", W_regularizer=None, b_regularizer=None,
                 **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.activation = get_activation(activation)
        self.use_bias = use_bias
        self.init = get_init(init)

    def build(self, rng, input_shape):
        in_dim = input_shape[-1]
        kernel = self.init(rng, (in_dim, self.output_dim), jnp.float32)
        p = {"kernel": kernel}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.output_dim,), jnp.float32)
        return p

    def call(self, params, x, *, training=False, rng=None):
        if "kernel_q" in params:   # int8 serving path (serving/quantization)
            from analytics_zoo_tpu.serving.quantization import int8_matmul
            y = int8_matmul(x, params["kernel_q"], params["kernel_scale"])
        else:
            x = _match_param_dtype(x, params["kernel"])
            y = x @ params["kernel"]
        if self.use_bias:
            y = y + params["bias"]
        return self.activation(y)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)


class Activation(Layer):
    def __init__(self, activation, **kw):
        super().__init__(**kw)
        self.activation = get_activation(activation)

    def call(self, params, x, *, training=False, rng=None):
        return self.activation(x)


class Dropout(Layer):
    """`keras/layers/Dropout.scala`: inverted dropout, active only in
    training."""

    def __init__(self, p: float, **kw):
        super().__init__(**kw)
        self.rate = float(p)

    def call(self, params, x, *, training=False, rng=None):
        if not training or self.rate <= 0.0:
            return x
        if rng is None:
            raise ValueError(f"{self.name}: dropout in training needs an rng")
        return fused_dropout(x, self.rate, rng=rng)


class Flatten(Layer):
    def call(self, params, x, *, training=False, rng=None):
        return x.reshape((x.shape[0], -1))

    def compute_output_shape(self, input_shape):
        return (input_shape[0], int(np.prod([d for d in input_shape[1:]])))


class Reshape(Layer):
    """`keras/layers/Reshape.scala`: target shape excludes batch; one -1
    allowed."""

    def __init__(self, target_shape: Sequence[int], **kw):
        super().__init__(**kw)
        self.target_shape = tuple(target_shape)

    def call(self, params, x, *, training=False, rng=None):
        return x.reshape((x.shape[0],) + self.target_shape)

    def compute_output_shape(self, input_shape):
        known = int(np.prod([d for d in input_shape[1:]]))
        tgt = list(self.target_shape)
        if -1 in tgt:
            fill = known // int(-np.prod(tgt))
            tgt[tgt.index(-1)] = fill
        return (input_shape[0],) + tuple(tgt)


class Permute(Layer):
    """Dims are 1-indexed over non-batch axes (Keras contract)."""

    def __init__(self, dims: Sequence[int], **kw):
        super().__init__(**kw)
        self.dims = tuple(dims)

    def call(self, params, x, *, training=False, rng=None):
        return jnp.transpose(x, (0,) + self.dims)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + tuple(input_shape[d] for d in self.dims)


class RepeatVector(Layer):
    def __init__(self, n: int, **kw):
        super().__init__(**kw)
        self.n = n

    def call(self, params, x, *, training=False, rng=None):
        return jnp.repeat(x[:, None, :], self.n, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.n, input_shape[1])


class Squeeze(Layer):
    """BigDL-style utility (`keras/layers/Squeeze.scala`); dim excludes
    batch (1-indexed over non-batch axes)."""

    def __init__(self, dim: int, **kw):
        super().__init__(**kw)
        self.dim = dim

    def call(self, params, x, *, training=False, rng=None):
        return jnp.squeeze(x, axis=self.dim)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        del s[self.dim]
        return tuple(s)


class ExpandDim(Layer):
    def __init__(self, dim: int, **kw):
        super().__init__(**kw)
        self.dim = dim

    def call(self, params, x, *, training=False, rng=None):
        return jnp.expand_dims(x, axis=self.dim)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        s.insert(self.dim, 1)
        return tuple(s)


class Select(Layer):
    """`keras/layers/Select.scala`: pick index `index` along `dim`."""

    def __init__(self, dim: int, index: int, **kw):
        super().__init__(**kw)
        self.dim, self.index = dim, index

    def call(self, params, x, *, training=False, rng=None):
        return jnp.take(x, self.index, axis=self.dim)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        del s[self.dim]
        return tuple(s)


class Narrow(Layer):
    """`keras/layers/Narrow.scala`: slice `length` elements from `offset`
    along `dim`."""

    def __init__(self, dim: int, offset: int, length: int = 1, **kw):
        super().__init__(**kw)
        self.dim, self.offset, self.length = dim, offset, length

    def call(self, params, x, *, training=False, rng=None):
        return jax.lax.slice_in_dim(x, self.offset, self.offset + self.length,
                                    axis=self.dim)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        s[self.dim] = self.length
        return tuple(s)


class Merge(Layer):
    """`keras/layers/Merge.scala`: combine a list of inputs.
    mode ∈ {sum, mul, ave, max, concat, dot, cos}."""

    def __init__(self, mode: str = "sum", concat_axis: int = -1, **kw):
        super().__init__(**kw)
        self.mode = mode
        self.concat_axis = concat_axis

    def call(self, params, xs, *, training=False, rng=None):
        if self.mode == "sum":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if self.mode == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if self.mode == "ave":
            return sum(xs) / len(xs)
        if self.mode == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if self.mode == "concat":
            return jnp.concatenate(xs, axis=self.concat_axis)
        if self.mode == "dot":
            a, b = xs
            return jnp.sum(a * b, axis=-1, keepdims=True)
        if self.mode == "cos":
            a, b = xs
            an = a / jnp.clip(jnp.linalg.norm(a, axis=-1, keepdims=True),
                              1e-7, None)
            bn = b / jnp.clip(jnp.linalg.norm(b, axis=-1, keepdims=True),
                              1e-7, None)
            return jnp.sum(an * bn, axis=-1, keepdims=True)
        raise ValueError(f"Unsupported merge mode: {self.mode}")

    def compute_output_shape(self, input_shapes):
        if self.mode in ("sum", "mul", "ave", "max"):
            return input_shapes[0]
        if self.mode == "concat":
            out = list(input_shapes[0])
            axis = self.concat_axis
            out[axis] = sum(s[axis] for s in input_shapes)
            return tuple(out)
        if self.mode in ("dot", "cos"):
            return (input_shapes[0][0], 1)
        raise ValueError(f"Unsupported merge mode: {self.mode}")


def merge(inputs, mode="sum", concat_axis=-1, name=None):
    """Functional helper matching pyzoo's `merge`
    (`keras/layers/topology.py`)."""
    return Merge(mode=mode, concat_axis=concat_axis, name=name)(inputs)


# ---------------------------------------------------------------------------
# Embeddings
# ---------------------------------------------------------------------------
class Embedding(Layer):
    """`keras/layers/Embedding.scala`: int ids → dense vectors. On TPU the
    lookup is a one-hot matmul for tiny vocabs or a gather for large ones —
    XLA picks; weights live f32, output follows compute dtype upstream."""

    def __init__(self, input_dim: int, output_dim: int, init="uniform",
                 weights: Optional[np.ndarray] = None, trainable: bool = True,
                 **kw):
        super().__init__(**kw)
        self.input_dim, self.output_dim = input_dim, output_dim
        self.init = get_init(init)
        self.weights = weights
        self.trainable = trainable

    def build(self, rng, input_shape):
        if self.weights is not None:
            table = jnp.asarray(self.weights, jnp.float32)
            if table.shape != (self.input_dim, self.output_dim):
                raise ValueError(
                    f"{self.name}: pretrained weights shape {table.shape} != "
                    f"({self.input_dim}, {self.output_dim})")
        else:
            table = self.init(rng, (self.input_dim, self.output_dim),
                              jnp.float32)
        return {"embeddings": table}

    def call(self, params, x, *, training=False, rng=None):
        ids = jnp.asarray(x, jnp.int32)
        if "embeddings_q" in params:   # int8 serving path
            from analytics_zoo_tpu.serving.quantization import \
                dequantize_rows
            return dequantize_rows(params["embeddings_q"],
                                   params["embeddings_scale"], ids)
        table = params["embeddings"]
        if not self.trainable:
            table = jax.lax.stop_gradient(table)
        return jnp.take(table, ids, axis=0)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)


class WordEmbedding(Embedding):
    """`keras/layers/WordEmbedding.scala`: frozen pretrained embeddings."""

    def __init__(self, embedding_matrix: np.ndarray, **kw):
        vocab, dim = np.shape(embedding_matrix)
        super().__init__(vocab, dim, weights=np.asarray(embedding_matrix),
                         trainable=False, **kw)


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------
class BatchNormalization(Layer):
    """`keras/layers/BatchNormalization.scala`. Moving stats are non-gradient
    state: training steps receive them back through `call_and_state` and the
    trainer merges them into params (outside the gradient path)."""

    stateful = True

    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 axis: int = -1, **kw):
        super().__init__(**kw)
        self.epsilon, self.momentum, self.axis = epsilon, momentum, axis

    def build(self, rng, input_shape):
        dim = input_shape[self.axis]
        return {"gamma": jnp.ones((dim,), jnp.float32),
                "beta": jnp.zeros((dim,), jnp.float32),
                "moving_mean": jnp.zeros((dim,), jnp.float32),
                "moving_var": jnp.ones((dim,), jnp.float32)}

    def _norm_axis(self, ndim):
        return ndim - 1 if self.axis == -1 else self.axis

    def _reshape_stat(self, s, ndim):
        """Broadcast (C,) stats against the normalized axis wherever it is."""
        shape = [1] * ndim
        shape[self._norm_axis(ndim)] = -1
        return s.reshape(shape)

    def _stats(self, params, x, training):
        axis = self._norm_axis(jnp.ndim(x))
        reduce_axes = tuple(i for i in range(jnp.ndim(x)) if i != axis)
        if training:
            mean = jnp.mean(x, axis=reduce_axes)
            var = jnp.var(x, axis=reduce_axes)
        else:
            mean, var = params["moving_mean"], params["moving_var"]
        return mean, var

    def _apply(self, params, x, mean, var):
        nd = jnp.ndim(x)
        inv = jax.lax.rsqrt(self._reshape_stat(var, nd) + self.epsilon)
        return ((x - self._reshape_stat(mean, nd)) * inv
                * self._reshape_stat(params["gamma"], nd)
                + self._reshape_stat(params["beta"], nd))

    def call(self, params, x, *, training=False, rng=None):
        mean, var = self._stats(params, x, training)
        return self._apply(params, x, mean, var)

    def call_and_state(self, params, x, *, training=False, rng=None):
        mean, var = self._stats(params, x, training)
        y = self._apply(params, x, mean, var)
        if not training:
            return y, {}
        m = self.momentum
        updates = {
            "moving_mean": m * params["moving_mean"]
            + (1.0 - m) * jax.lax.stop_gradient(mean),
            "moving_var": m * params["moving_var"]
            + (1.0 - m) * jax.lax.stop_gradient(var),
        }
        return y, updates


class LayerNormalization(Layer):
    """BERT-style layer norm over the last axis (`TransformerLayer.scala`
    LayerNorm)."""

    def __init__(self, epsilon: float = 1e-12, **kw):
        super().__init__(**kw)
        self.epsilon = epsilon

    def build(self, rng, input_shape):
        dim = input_shape[-1]
        return {"gamma": jnp.ones((dim,), jnp.float32),
                "beta": jnp.zeros((dim,), jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
        return y * params["gamma"] + params["beta"]


# ---------------------------------------------------------------------------
# Convolutions & pooling (channels_last native)
# ---------------------------------------------------------------------------
def _to_channels_last(x, dim_ordering, spatial_rank):
    if dim_ordering == "th":
        perm = (0,) + tuple(range(2, 2 + spatial_rank)) + (1,)
        return jnp.transpose(x, perm)
    return x


def _from_channels_last(x, dim_ordering, spatial_rank):
    if dim_ordering == "th":
        perm = (0, spatial_rank + 1) + tuple(range(1, spatial_rank + 1))
        return jnp.transpose(x, perm)
    return x


class _ConvND(Layer):
    spatial_rank = 2
    dn = ("NHWC", "HWIO", "NHWC")

    def __init__(self, nb_filter: int, kernel_size: Sequence[int],
                 activation=None, subsample: Sequence[int] = None,
                 border_mode: str = "valid", dim_ordering: str = "tf",
                 use_bias: bool = True, init="glorot_uniform",
                 groups: int = 1, **kw):
        super().__init__(**kw)
        self.nb_filter = nb_filter
        self.kernel_size = tuple(kernel_size)
        self.activation = get_activation(activation)
        self.strides = tuple(subsample or (1,) * self.spatial_rank)
        if border_mode not in ("valid", "same"):
            raise ValueError(f"Unsupported border_mode: {border_mode}")
        self.padding = border_mode.upper()
        self.dim_ordering = dim_ordering
        self.use_bias = use_bias
        self.init = get_init(init)
        self.groups = int(groups)

    def build(self, rng, input_shape):
        if self.dim_ordering == "th":
            in_ch = input_shape[1]
        else:
            in_ch = input_shape[-1]
        if in_ch % self.groups or self.nb_filter % self.groups:
            raise ValueError(
                f"groups={self.groups} must divide in_ch={in_ch} and "
                f"nb_filter={self.nb_filter}")
        kshape = self.kernel_size + (in_ch // self.groups, self.nb_filter)
        p = {"kernel": self.init(rng, kshape, jnp.float32)}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.nb_filter,), jnp.float32)
        return p

    def call(self, params, x, *, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, self.spatial_rank)
        if "kernel_q" in params:   # int8 serving path (serving/quantization)
            from analytics_zoo_tpu.serving.quantization import int8_conv
            y = int8_conv(x, params["kernel_q"], params["kernel_scale"],
                          window_strides=self.strides,
                          padding=self.padding, dimension_numbers=self.dn,
                          feature_group_count=self.groups)
        else:
            # conv requires matching operand dtypes; float inputs follow
            # the kernel (under mixed precision the params are bf16 while
            # e.g. an on-device normalization Lambda produces f32).
            # Integer inputs still error loudly — silently casting raw
            # uint8 images would train on unscaled 0-255 values.
            x = _match_param_dtype(x, params["kernel"])
            y = jax.lax.conv_general_dilated(
                x, params["kernel"], window_strides=self.strides,
                padding=self.padding, dimension_numbers=self.dn,
                feature_group_count=self.groups)
        if self.use_bias:
            y = y + params["bias"]
        y = self.activation(y)
        return _from_channels_last(y, self.dim_ordering, self.spatial_rank)

    def _spatial_out(self, size, k, s):
        if size is None:
            return None
        if self.padding == "SAME":
            return -(-size // s)
        return (size - k) // s + 1

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            spatial = input_shape[2:]
            out = tuple(self._spatial_out(d, k, s) for d, k, s in
                        zip(spatial, self.kernel_size, self.strides))
            return (input_shape[0], self.nb_filter) + out
        spatial = input_shape[1:-1]
        out = tuple(self._spatial_out(d, k, s) for d, k, s in
                    zip(spatial, self.kernel_size, self.strides))
        return (input_shape[0],) + out + (self.nb_filter,)


class Convolution2D(_ConvND):
    """`keras/layers/Convolution2D.scala`."""

    def __init__(self, nb_filter, nb_row, nb_col, **kw):
        super().__init__(nb_filter, (nb_row, nb_col), **kw)


class Convolution1D(_ConvND):
    spatial_rank = 1
    dn = ("NWC", "WIO", "NWC")

    def __init__(self, nb_filter, filter_length, **kw):
        super().__init__(nb_filter, (filter_length,), **kw)


class Convolution3D(_ConvND):
    spatial_rank = 3
    dn = ("NDHWC", "DHWIO", "NDHWC")

    def __init__(self, nb_filter, kernel_dim1, kernel_dim2, kernel_dim3, **kw):
        super().__init__(nb_filter, (kernel_dim1, kernel_dim2, kernel_dim3),
                         **kw)


# keras2-flavoured aliases (`keras2/layers/`)
Conv1D = Convolution1D
Conv2D = Convolution2D
Conv3D = Convolution3D


class _PoolND(Layer):
    spatial_rank = 2
    reducer = "max"

    def __init__(self, pool_size=None, strides=None, border_mode="valid",
                 dim_ordering="tf", **kw):
        super().__init__(**kw)
        self.pool_size = tuple(pool_size or (2,) * self.spatial_rank)
        self.strides = tuple(strides or self.pool_size)
        self.padding = border_mode.upper()
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        x = _to_channels_last(x, self.dim_ordering, self.spatial_rank)
        window = (1,) + self.pool_size + (1,)
        strides = (1,) + self.strides + (1,)
        if self.reducer == "max":
            y = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, window,
                                      strides, self.padding)
        else:
            ones = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                         window, strides, self.padding)
            y = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                      self.padding) / ones
        return _from_channels_last(y, self.dim_ordering, self.spatial_rank)

    def _spatial_out(self, size, k, s):
        if size is None:
            return None
        if self.padding == "SAME":
            return -(-size // s)
        return (size - k) // s + 1

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "th":
            spatial = input_shape[2:]
            out = tuple(self._spatial_out(d, k, s) for d, k, s in
                        zip(spatial, self.pool_size, self.strides))
            return input_shape[:2] + out
        spatial = input_shape[1:-1]
        out = tuple(self._spatial_out(d, k, s) for d, k, s in
                    zip(spatial, self.pool_size, self.strides))
        return (input_shape[0],) + out + (input_shape[-1],)


class MaxPooling2D(_PoolND):
    pass


class AveragePooling2D(_PoolND):
    reducer = "avg"


class MaxPooling1D(_PoolND):
    spatial_rank = 1

    def __init__(self, pool_length: int = 2, stride: Optional[int] = None,
                 **kw):
        super().__init__((pool_length,),
                         (stride,) if stride else None, **kw)


class AveragePooling1D(MaxPooling1D):
    reducer = "avg"


class _GlobalPool(Layer):
    spatial_axes: Tuple[int, ...] = (1, 2)
    reducer = "max"

    def __init__(self, dim_ordering="tf", **kw):
        super().__init__(**kw)
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        axes = self.spatial_axes if self.dim_ordering == "tf" else \
            tuple(a + 1 for a in self.spatial_axes)
        fn = jnp.max if self.reducer == "max" else jnp.mean
        return fn(x, axis=axes)

    def compute_output_shape(self, input_shape):
        if self.dim_ordering == "tf":
            return (input_shape[0], input_shape[-1])
        return (input_shape[0], input_shape[1])


class GlobalMaxPooling2D(_GlobalPool):
    pass


class GlobalAveragePooling2D(_GlobalPool):
    reducer = "avg"


class GlobalMaxPooling1D(_GlobalPool):
    spatial_axes = (1,)


class GlobalAveragePooling1D(_GlobalPool):
    spatial_axes = (1,)
    reducer = "avg"


class ZeroPadding2D(Layer):
    def __init__(self, padding=(1, 1), dim_ordering="tf", **kw):
        super().__init__(**kw)
        self.pad = tuple(padding)
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        ph, pw = self.pad
        if self.dim_ordering == "tf":
            return jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
        return jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        if self.dim_ordering == "tf":
            s[1] += 2 * self.pad[0]; s[2] += 2 * self.pad[1]
        else:
            s[2] += 2 * self.pad[0]; s[3] += 2 * self.pad[1]
        return tuple(s)


class UpSampling2D(Layer):
    def __init__(self, size=(2, 2), dim_ordering="tf", **kw):
        super().__init__(**kw)
        self.size = tuple(size)
        self.dim_ordering = dim_ordering

    def call(self, params, x, *, training=False, rng=None):
        sh, sw = self.size
        if self.dim_ordering == "tf":
            return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)
        return jnp.repeat(jnp.repeat(x, sh, axis=2), sw, axis=3)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        if self.dim_ordering == "tf":
            s[1] *= self.size[0]; s[2] *= self.size[1]
        else:
            s[2] *= self.size[0]; s[3] *= self.size[1]
        return tuple(s)


# ---------------------------------------------------------------------------
# Recurrent layers — lax.scan over time; weights packed per-gate for one
# fused matmul per step (MXU-friendly), unlike the reference's per-gate JVM
# tensor ops (`keras/layers/LSTM.scala`, `GRU.scala`, `SimpleRNN.scala`).
# ---------------------------------------------------------------------------
class _Recurrent(Layer):
    n_gates = 1

    def __init__(self, output_dim: int, activation="tanh",
                 inner_activation="hard_sigmoid", return_sequences=False,
                 go_backwards=False, init="glorot_uniform",
                 inner_init="orthogonal", **kw):
        super().__init__(**kw)
        self.output_dim = output_dim
        self.activation = get_activation(activation)
        self.inner_activation = get_activation(inner_activation)
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.init = get_init(init)
        self.inner_init = get_init(inner_init)

    def build(self, rng, input_shape):
        in_dim = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        return {
            "kernel": self.init(
                k1, (in_dim, self.n_gates * self.output_dim), jnp.float32),
            "recurrent": self.inner_init(
                k2, (self.output_dim, self.n_gates * self.output_dim),
                jnp.float32),
            "bias": jnp.zeros((self.n_gates * self.output_dim,), jnp.float32),
        }

    def initial_state(self, batch):
        return jnp.zeros((batch, self.output_dim), jnp.float32)

    def step(self, params, carry, x_t):
        raise NotImplementedError

    def call(self, params, x, *, training=False, rng=None):
        if self.go_backwards:
            x = jnp.flip(x, axis=1)
        x = _match_param_dtype(x, params["kernel"])
        batch = x.shape[0]
        xs = jnp.swapaxes(x, 0, 1)  # [T, B, F] for scan

        def body(carry, x_t):
            carry, out = self.step(params, carry, x_t)
            return carry, out

        carry0 = self.initial_state(batch)
        # carry must match the step output dtype for scan (bf16 params →
        # bf16 hidden state)
        carry0 = jax.tree_util.tree_map(
            lambda a: a.astype(params["kernel"].dtype), carry0)
        _, outs = jax.lax.scan(body, carry0, xs)
        if self.return_sequences:
            seq = jnp.swapaxes(outs, 0, 1)
            return jnp.flip(seq, axis=1) if self.go_backwards else seq
        return outs[-1]

    def compute_output_shape(self, input_shape):
        if self.return_sequences:
            return (input_shape[0], input_shape[1], self.output_dim)
        return (input_shape[0], self.output_dim)


class SimpleRNN(_Recurrent):
    n_gates = 1

    def step(self, params, h, x_t):
        h_new = self.activation(
            x_t @ params["kernel"] + h @ params["recurrent"] + params["bias"])
        return h_new, h_new


class LSTM(_Recurrent):
    """Gate order i, f, c, o (Keras convention)."""
    n_gates = 4

    def initial_state(self, batch):
        z = jnp.zeros((batch, self.output_dim), jnp.float32)
        return (z, z)

    def step(self, params, carry, x_t):
        h, c = carry
        z = x_t @ params["kernel"] + h @ params["recurrent"] + params["bias"]
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = self.inner_activation(i)
        f = self.inner_activation(f)
        o = self.inner_activation(o)
        g = self.activation(g)
        c_new = f * c + i * g
        h_new = o * self.activation(c_new)
        return (h_new, c_new), h_new


class GRU(_Recurrent):
    """Gate order z, r, h (Keras convention). `reset_after=True` applies the
    recurrent bias inside the reset gate product (torch/CuDNN semantics),
    needed for exact torch-weight conversion."""
    n_gates = 3

    def __init__(self, *args, reset_after: bool = False, **kw):
        super().__init__(*args, **kw)
        self.reset_after = reset_after

    def build(self, rng, input_shape):
        p = super().build(rng, input_shape)
        if self.reset_after:
            p["recurrent_bias"] = jnp.zeros(
                (self.n_gates * self.output_dim,), jnp.float32)
        return p

    def step(self, params, h, x_t):
        d = self.output_dim
        xz = x_t @ params["kernel"] + params["bias"]
        hz = h @ params["recurrent"]
        if self.reset_after:
            hz = hz + params["recurrent_bias"]
        z = self.inner_activation(xz[:, :d] + hz[:, :d])
        r = self.inner_activation(xz[:, d:2 * d] + hz[:, d:2 * d])
        hh = self.activation(xz[:, 2 * d:] + r * hz[:, 2 * d:])
        h_new = z * h + (1.0 - z) * hh
        return h_new, h_new


class Bidirectional(Layer):
    """`keras/layers/Bidirectional.scala`: wraps a recurrent layer;
    merge_mode ∈ {concat, sum, mul, ave}."""

    def __init__(self, layer: _Recurrent, merge_mode: str = "concat", **kw):
        super().__init__(**kw)
        import copy
        self.forward = layer
        self.backward = copy.deepcopy(layer)
        self.backward.name = layer.name + "_bwd"
        self.backward.go_backwards = not layer.go_backwards
        self.merge_mode = merge_mode

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        return {"forward": self.forward.build(k1, input_shape),
                "backward": self.backward.build(k2, input_shape)}

    def call(self, params, x, *, training=False, rng=None):
        f = self.forward.call(params["forward"], x, training=training)
        b = self.backward.call(params["backward"], x, training=training)
        if self.merge_mode == "concat":
            return jnp.concatenate([f, b], axis=-1)
        if self.merge_mode == "sum":
            return f + b
        if self.merge_mode == "mul":
            return f * b
        if self.merge_mode == "ave":
            return (f + b) / 2.0
        raise ValueError(f"Unsupported merge_mode: {self.merge_mode}")

    def compute_output_shape(self, input_shape):
        out = list(self.forward.compute_output_shape(input_shape))
        if self.merge_mode == "concat":
            out[-1] *= 2
        return tuple(out)


class TimeDistributed(Layer):
    """Apply an inner layer to every timestep (`keras/layers/
    TimeDistributed.scala`). Implemented by folding time into batch — one big
    matmul instead of T small ones."""

    def __init__(self, layer: Layer, **kw):
        super().__init__(**kw)
        self.layer = layer

    def build(self, rng, input_shape):
        inner_shape = (input_shape[0],) + tuple(input_shape[2:])
        return self.layer.build(rng, inner_shape)

    def call(self, params, x, *, training=False, rng=None):
        b, t = x.shape[0], x.shape[1]
        flat = x.reshape((b * t,) + x.shape[2:])
        y = self.layer.call(params, flat, training=training, rng=rng)
        return y.reshape((b, t) + y.shape[1:])

    def compute_output_shape(self, input_shape):
        inner_shape = (input_shape[0],) + tuple(input_shape[2:])
        inner_out = self.layer.compute_output_shape(inner_shape)
        return (input_shape[0], input_shape[1]) + tuple(inner_out[1:])


# `LayerNorm.scala` exposes layer normalization under this name too
LayerNorm = LayerNormalization

# Extended Keras1-parity set (advanced activations, noise, conv variants,
# ConvLSTM, LRN, torch-style elementwise, ...) lives in layers_ext but is
# part of this namespace — the reference exposes one flat layer namespace.
from analytics_zoo_tpu.keras.layers_ext import *  # noqa: E402,F401,F403
