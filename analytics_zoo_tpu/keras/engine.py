"""Keras-style model engine: Layer base, symbolic graph, Sequential/Model.

The TPU-native analogue of the reference's Keras API
(`zoo/.../pipeline/api/keras/models/Topology.scala`: `KerasNet` `:67`,
`compile` `:139`, `fit` `:347`, `evaluate` `:504`, `predict`, `Model` `:631`,
`Sequential` `:854`; python mirror `pyzoo/zoo/pipeline/api/keras/engine/
topology.py:200-246`). Design differences are deliberate and TPU-first:

- A layer is a *pure function* plus a parameter pytree — no mutable module
  state. `build(rng, input_shape) -> params`, `call(params, x)`.
- `Sequential`/`Model` compose layers into one pure `apply(params, inputs)`
  which jit-compiles to a single fused XLA program (the reference instead
  interprets a JVM graph node-by-node per minibatch).
- The same symbolic `Node` graph that powers the functional `Model` API also
  powers the autograd `Variable` DSL (`ops/autograd.py`), mirroring how the
  reference's autograd builds on its graph nodes (`autograd/math.scala:378`).
- `fit` delegates to the distributed trainer (`learn/trainer.py`): batch
  sharding over the mesh's data axes; one train step = one XLA program.

Keras semantics preserved: `input_shape` excludes the batch dim; compile
strings for loss/optimizer/metrics resolve through the reference registries
(`ops/objectives.py`, `ops/optimizers.py`, `ops/metrics.py`).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Shape = Tuple[Optional[int], ...]
Params = Dict[str, Any]

_name_counters: Dict[str, int] = collections.defaultdict(int)


def _auto_name(cls_name: str) -> str:
    _name_counters[cls_name] += 1
    return f"{cls_name.lower()}_{_name_counters[cls_name]}"


def reset_name_scope() -> None:
    _name_counters.clear()


class Layer:
    """Base layer. Subclasses implement `build`, `call`,
    `compute_output_shape`. Stateless: parameters live in the pytree returned
    by build and are passed back into call."""

    def __init__(self, input_shape: Optional[Shape] = None,
                 name: Optional[str] = None):
        self.name = name or _auto_name(type(self).__name__)
        # Keras contract: input_shape excludes the batch dimension.
        self.input_shape = (None,) + tuple(input_shape) if input_shape else None

    # True for layers carrying non-gradient state (e.g. BatchNorm moving
    # stats); they implement call_and_state.
    stateful = False

    # -- subclass API ------------------------------------------------------
    def build(self, rng: jax.Array, input_shape: Shape) -> Params:
        return {}

    def call(self, params: Params, x, *, training: bool = False,
             rng: Optional[jax.Array] = None):
        raise NotImplementedError

    def call_and_state(self, params: Params, x, *, training: bool = False,
                       rng: Optional[jax.Array] = None):
        """Stateful layers return (y, updated-param-entries); the trainer
        merges the updates back into params outside the gradient path."""
        return self.call(params, x, training=training, rng=rng), {}

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        return input_shape

    # -- graph building ----------------------------------------------------
    def __call__(self, inputs: Union["Node", Sequence["Node"]]) -> "Node":
        """Symbolic call: layer applied to graph node(s) yields a node.
        Node-wrapper objects (autograd Variables — anything exposing `.node`
        as a Node) are accepted; the result is re-wrapped in the same type."""
        raw = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        wrapper_cls = None
        nodes = []
        for item in raw:
            if isinstance(item, Node):
                nodes.append(item)
            elif isinstance(getattr(item, "node", None), Node):
                wrapper_cls = type(item)
                nodes.append(item.node)
            else:
                raise TypeError(
                    f"{self.name} called on non-Node inputs; use Input(shape) "
                    "to start a functional graph, or Sequential for linear "
                    "stacks")
        in_shapes = [n.shape for n in nodes]
        shape_in = in_shapes if len(in_shapes) > 1 else in_shapes[0]
        out_shape = self.compute_output_shape(shape_in)
        out = Node(layer=self, inputs=nodes, shape=out_shape)
        return wrapper_cls(node=out) if wrapper_cls is not None else out

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name})"


class Node:
    """A symbolic tensor in the layer graph (the reference's `ModuleNode`/
    autograd `Variable` substrate)."""

    def __init__(self, layer: Optional[Layer], inputs: List["Node"],
                 shape: Shape):
        self.layer = layer
        self.inputs = inputs
        self.shape = shape

    # Autograd DSL operators are attached by ops/autograd.py to avoid a
    # circular import; see `autograd._install_operators`.

    def __repr__(self):
        lname = self.layer.name if self.layer else "input"
        return f"Node({lname}, shape={self.shape})"


def Input(shape: Shape, name: Optional[str] = None) -> Node:
    """Entry node of a functional graph. `shape` excludes the batch dim
    (Keras contract, `keras/models/Topology.scala` Input)."""
    return Node(layer=None, inputs=[], shape=(None,) + tuple(shape))


def _topo_sort(outputs: Sequence[Node]) -> List[Node]:
    order: List[Node] = []
    seen: set = set()

    def visit(n: Node):
        if id(n) in seen:
            return
        seen.add(id(n))
        for i in n.inputs:
            visit(i)
        order.append(n)

    for out in outputs:
        visit(out)
    return order


class KerasNet:
    """Shared compile/fit/evaluate/predict surface (`Topology.scala:67`)."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or _auto_name(type(self).__name__)
        self.loss = None
        self.optimizer = None
        self.metrics: List[Any] = []
        self._tensorboard_dir: Optional[str] = None
        self._checkpoint_path: Optional[str] = None
        self.params: Optional[Params] = None
        self._built_shape: Optional[Shape] = None

    # -- subclass API ------------------------------------------------------
    def build(self, rng: jax.Array, input_shape) -> Params:
        raise NotImplementedError

    def apply(self, params: Params, inputs, *, training: bool = False,
              rng: Optional[jax.Array] = None):
        raise NotImplementedError

    def apply_and_state(self, params: Params, inputs, *,
                        training: bool = False,
                        rng: Optional[jax.Array] = None):
        """Like apply, but also returns {layer_name: updated entries} from
        stateful layers (BatchNorm moving stats)."""
        return self.apply(params, inputs, training=training, rng=rng), {}

    def compute_output_shape(self, input_shape):
        raise NotImplementedError

    # -- Keras surface -----------------------------------------------------
    def compile(self, optimizer, loss, metrics: Optional[Sequence] = None):
        """`Topology.scala:139`: resolve compile strings through the
        registries; `"accuracy"` dispatches on the loss string."""
        from analytics_zoo_tpu.ops import metrics as zmetrics
        from analytics_zoo_tpu.ops import objectives, optimizers
        # remembered so features that re-derive per-parameter update rules
        # (lazy embeddings) can check hyperparameter compatibility
        self._optimizer_spec = optimizer if isinstance(optimizer, str) \
            else None
        loss_str = loss if isinstance(loss, str) else None
        if isinstance(loss, (list, tuple)):
            # Keras multi-output contract: one loss per output, summed
            fns = [objectives.get(l) for l in loss]

            def _combined(y_true, y_pred):
                if not isinstance(y_pred, (list, tuple)) \
                        or len(y_pred) != len(fns):
                    n = len(y_pred) if isinstance(y_pred, (list, tuple)) \
                        else 1
                    raise ValueError(
                        f"compile() got {len(fns)} losses but the model "
                        f"produces {n} output(s)")
                if not isinstance(y_true, (list, tuple)) \
                        or len(y_true) != len(fns):
                    raise ValueError(
                        f"multi-output loss needs a list of {len(fns)} "
                        "label arrays (got a single array — it would zip "
                        "batch rows, not outputs)")
                return sum(fn(t, p)
                           for fn, t, p in zip(fns, y_true, y_pred))

            self.loss = _combined
        else:
            self.loss = objectives.get(loss)
        self.optimizer = optimizers.get(optimizer)
        self.metrics = zmetrics.resolve(metrics, loss_str)
        # recompiling invalidates any jitted closures built over the old
        # optimizer/loss/metrics (id() reuse after GC makes key checks
        # alone unreliable)
        for cache in ("_train_cache", "_eval_cache", "_predict_cache"):
            if hasattr(self, cache):
                delattr(self, cache)

    def set_tensorboard(self, log_dir: str, app_name: str):
        """`Topology.scala:208`."""
        self._tensorboard_dir = f"{log_dir.rstrip('/')}/{app_name}"

    def set_checkpoint(self, path: str, over_write: bool = True):
        """`Topology.scala:249`."""
        self._checkpoint_path = path

    def ensure_built(self, sample_input, rng: Optional[jax.Array] = None):
        """Initialise parameters from a sample batch (shape source)."""
        if self.params is not None:
            return self.params
        if rng is None:
            rng = jax.random.PRNGKey(0)
        shape = jax.tree_util.tree_map(
            lambda a: (None,) + tuple(np.shape(a))[1:], sample_input,
            is_leaf=lambda a: hasattr(a, "shape") or isinstance(a, np.ndarray))
        self.params = self.build(rng, shape)
        return self.params

    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 1,
            validation_data=None, distributed: bool = True, **kwargs):
        """`Topology.scala:347` / `topology.py:200`. Delegates to the
        distributed trainer; returns the history dict."""
        from analytics_zoo_tpu.learn.trainer import fit_keras
        return fit_keras(self, x, y, batch_size=batch_size, epochs=nb_epoch,
                         validation_data=validation_data,
                         distributed=distributed, **kwargs)

    def evaluate(self, x, y=None, batch_per_thread: int = 32, **kwargs):
        """`Topology.scala:504`: per-device batch for eval (the reference's
        batch-per-thread contract, `tf_dataset.py:116-157`)."""
        from analytics_zoo_tpu.learn.trainer import evaluate_keras
        return evaluate_keras(self, x, y, batch_per_thread=batch_per_thread,
                              **kwargs)

    def predict(self, x, batch_per_thread: int = 32, **kwargs):
        from analytics_zoo_tpu.learn.trainer import predict_keras
        return predict_keras(self, x, batch_per_thread=batch_per_thread,
                             **kwargs)

    # -- persistence (`models/common/ZooModel.scala` save/load) -----------
    def save_weights(self, path: str, params: Optional[Params] = None):
        """Persist `params` (default: this model's) + the layer-order
        sidecar. `params` lets derived trees (e.g. int8-quantized,
        serving/quantization.py) reuse the one artifact protocol."""
        import json
        from analytics_zoo_tpu.learn import checkpoint as ckpt
        if params is None:
            params = self.params
        if params is None:
            raise ValueError("Model has no parameters yet; call fit or "
                             "ensure_built first")
        ckpt.save_pytree(path, jax.device_get(params))
        order = self._layer_order()
        if order:
            with open(self._order_path(path), "w") as fh:
                json.dump(order, fh)

    def load_weights_tree(self, path: str) -> Params:
        """Read an artifact written by save_weights and remap it onto
        THIS instance's layer names — without assigning it. Callers that
        serve derived trees (int8 artifacts) use this; `load_weights`
        assigns the result."""
        import json
        import os
        from analytics_zoo_tpu.learn import checkpoint as ckpt
        loaded = ckpt.load_pytree(path)
        order = None
        if os.path.exists(self._order_path(path)):
            with open(self._order_path(path)) as fh:
                order = json.load(fh)
        return self._remap_loaded(loaded, order)

    def load_weights(self, path: str):
        self.params = self.load_weights_tree(path)
        return self

    @staticmethod
    def _order_path(path: str) -> str:
        base = path[:-4] if path.endswith(".npz") else path
        return base + ".layers.json"

    def _ordered_layers(self) -> List[Layer]:
        """Deterministic layer order for positional weight remapping;
        subclasses with named sub-layers override."""
        return []

    def _layer_order(self) -> List[str]:
        return [l.name for l in self._ordered_layers()]

    def _remap_loaded(self, loaded: Params,
                      order: Optional[List[str]] = None) -> Params:
        """Auto-generated layer names differ across instances; remap saved
        params onto this instance's names, recursing into nested
        Sequential/Model blocks. Matching is per-class-prefix by the numeric
        suffix of the auto names (creation order within a class equals
        structural order for identical architectures) — dict ordering is NOT
        relied on, since jax tree ops re-sort dict keys."""
        import re
        layers = self._ordered_layers()
        if not layers:
            return loaded
        if order is not None and (len(order) != len(loaded)
                                  or set(order) != set(loaded)):
            raise ValueError(
                f"Stale/mismatched layer-order sidecar: order has "
                f"{len(order)} names, saved params have {len(loaded)}")
        if len(loaded) != len(layers):
            raise ValueError(
                f"Saved weights have {len(loaded)} layers, model has "
                f"{len(layers)}")

        def remap_child(layer: Layer, value):
            if isinstance(layer, KerasNet):
                return layer._remap_loaded(value)
            return value

        if order is not None:
            # The sidecar records saved names in STRUCTURAL order — map
            # positionally onto this instance's structural order. Handles
            # custom layer names and same-class layers created out of
            # add() order (where prefix/suffix matching would mis-map).
            # Auto-generated names ("<class>_<n>") still carry their class:
            # cross-class positional assignment is an architecture mismatch.
            import re
            for layer, sname in zip(layers, order):
                saved_auto = re.match(r"^(.*)_(\d+)$", sname)
                cur_auto = re.match(r"^(.*)_(\d+)$", layer.name)
                if saved_auto and cur_auto \
                        and cur_auto.group(1) == type(layer).__name__.lower() \
                        and saved_auto.group(1) != cur_auto.group(1):
                    raise ValueError(
                        f"Saved layer {sname!r} does not match model layer "
                        f"{layer.name!r} ({type(layer).__name__}) at the "
                        "same structural position")
            return {layer.name: remap_child(layer, loaded[sname])
                    for layer, sname in zip(layers, order)}

        if set(loaded) == {l.name for l in layers}:
            return {l.name: remap_child(l, loaded[l.name]) for l in layers}

        def split(name: str):
            m = re.match(r"^(.*)_(\d+)$", name)
            return (m.group(1), int(m.group(2))) if m else (name, 0)

        saved_by_prefix: Dict[str, List] = {}
        for name in loaded:
            p, n = split(name)
            saved_by_prefix.setdefault(p, []).append((n, name))
        cur_by_prefix: Dict[str, List] = {}
        for layer in layers:
            p, n = split(layer.name)
            cur_by_prefix.setdefault(p, []).append((n, layer))
        if {p: len(v) for p, v in saved_by_prefix.items()} != \
                {p: len(v) for p, v in cur_by_prefix.items()}:
            raise ValueError(
                f"Saved layer classes {sorted(saved_by_prefix)} do not match "
                f"model layer classes {sorted(cur_by_prefix)}")
        result: Params = {}
        for p, cur_list in cur_by_prefix.items():
            for (_, layer), (_, sname) in zip(sorted(cur_list,
                                                     key=lambda t: t[0]),
                                              sorted(saved_by_prefix[p],
                                                     key=lambda t: t[0])):
                result[layer.name] = remap_child(layer, loaded[sname])
        return result

    def summary(self):
        lines = [f"Model: {self.name}", "-" * 60]
        for layer, shape, count in self._summary_rows():
            lines.append(f"{layer:<30} {str(shape):<20} {count}")
        lines.append("-" * 60)
        total = sum(r[2] for r in self._summary_rows())
        lines.append(f"Total params: {total}")
        text = "\n".join(lines)
        print(text)
        return text

    def _summary_rows(self):
        return []

    @staticmethod
    def _count(params) -> int:
        return sum(int(np.prod(np.shape(p)))
                   for p in jax.tree_util.tree_leaves(params))


class Sequential(KerasNet):
    """Linear stack (`Topology.scala:854`). First layer must carry
    `input_shape`, like Keras."""

    def __init__(self, layers: Optional[Sequence[Layer]] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.layers: List[Layer] = []
        for l in (layers or []):
            self.add(l)

    def add(self, layer: Layer) -> "Sequential":
        if not self.layers and layer.input_shape is None \
                and not isinstance(layer, (Sequential, Model)):
            # allowed: shape may come later via ensure_built(sample)
            pass
        self.layers.append(layer)
        return self

    def build(self, rng: jax.Array, input_shape: Shape) -> Params:
        if self.layers and self.layers[0].input_shape is not None:
            input_shape = self.layers[0].input_shape
        if input_shape is None:
            raise ValueError(
                "Cannot build Sequential: no input_shape on first layer")
        params: Params = {}
        shape = input_shape
        for layer in self.layers:
            rng, sub = jax.random.split(rng)
            params[layer.name] = layer.build(sub, shape)
            shape = layer.compute_output_shape(shape)
        self._built_shape = shape
        return params

    def apply(self, params: Params, inputs, *, training: bool = False,
              rng: Optional[jax.Array] = None):
        x = inputs
        for layer in self.layers:
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            x = layer.call(params[layer.name], x, training=training, rng=sub)
        return x

    def apply_and_state(self, params: Params, inputs, *,
                        training: bool = False,
                        rng: Optional[jax.Array] = None):
        x = inputs
        updates: Params = {}
        for layer in self.layers:
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            x, upd = layer.call_and_state(params[layer.name], x,
                                          training=training, rng=sub)
            if upd:
                updates[layer.name] = upd
        return x, updates

    def compute_output_shape(self, input_shape: Shape) -> Shape:
        shape = input_shape
        for layer in self.layers:
            shape = layer.compute_output_shape(shape)
        return shape

    # Sequential itself can be nested as a layer or called on a Node.
    def call(self, params, x, *, training=False, rng=None):
        return self.apply(params, x, training=training, rng=rng)

    def call_and_state(self, params, x, *, training=False, rng=None):
        return self.apply_and_state(params, x, training=training, rng=rng)

    stateful = True  # may contain stateful layers

    def __call__(self, inputs):
        return Layer.__call__(self, inputs)

    @property
    def input_shape(self):
        return self.layers[0].input_shape if self.layers else None

    @input_shape.setter
    def input_shape(self, v):
        pass  # satisfied by first layer

    def _summary_rows(self):
        rows = []
        if self.params:
            for layer in self.layers:
                rows.append((f"{layer.name} ({type(layer).__name__})",
                             "-", self._count(self.params.get(layer.name))))
        return rows

    def _ordered_layers(self):
        return self.layers


class Model(KerasNet):
    """Functional graph model (`Topology.scala:631`): built from `Input`
    nodes and symbolic layer calls."""

    def __init__(self, inputs: Union[Node, Sequence[Node]],
                 outputs: Union[Node, Sequence[Node]],
                 name: Optional[str] = None):
        super().__init__(name)

        def unwrap(x):  # accept autograd Variables interchangeably with Nodes
            return x.node if hasattr(x, "node") else x
        inputs = [unwrap(i) for i in inputs] \
            if isinstance(inputs, (list, tuple)) else [unwrap(inputs)]
        outputs = [unwrap(o) for o in outputs] \
            if isinstance(outputs, (list, tuple)) else [unwrap(outputs)]
        self.inputs = inputs
        self.outputs = outputs
        self._order = _topo_sort(self.outputs)
        # deduplicate shared layers (weight sharing): one param set per layer
        # *object*; two distinct layers with the same name is an error (Keras
        # raises too — silent aliasing would corrupt weights)
        self._layers: List[Layer] = []
        seen: Dict[int, Layer] = {}
        by_name: Dict[str, Layer] = {}
        for node in self._order:
            if node.layer is not None and id(node.layer) not in seen:
                dup = by_name.get(node.layer.name)
                if dup is not None and dup is not node.layer:
                    raise ValueError(
                        f"Duplicate layer name {node.layer.name!r} for two "
                        "distinct layers in one graph")
                seen[id(node.layer)] = node.layer
                by_name[node.layer.name] = node.layer
                self._layers.append(node.layer)

    def build(self, rng: jax.Array, input_shape=None) -> Params:
        params: Params = {}
        shapes: Dict[int, Shape] = {}
        for node in self._order:
            if node.layer is None:
                shapes[id(node)] = node.shape
            else:
                in_shapes = [shapes[id(i)] for i in node.inputs]
                # zero-input nodes are parameter/constant sources
                # (ops/autograd.py Parameter): build sees shape_in=None
                shape_in = in_shapes if len(in_shapes) > 1 else (
                    in_shapes[0] if in_shapes else None)
                if node.layer.name not in params:
                    rng, sub = jax.random.split(rng)
                    params[node.layer.name] = node.layer.build(sub, shape_in)
                shapes[id(node)] = node.layer.compute_output_shape(shape_in)
        return params

    def apply(self, params: Params, inputs, *, training: bool = False,
              rng: Optional[jax.Array] = None):
        out, _ = self.apply_and_state(params, inputs, training=training,
                                      rng=rng)
        return out

    def apply_and_state(self, params: Params, inputs, *,
                        training: bool = False,
                        rng: Optional[jax.Array] = None):
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if len(xs) != len(self.inputs):
            raise ValueError(
                f"Model {self.name} expects {len(self.inputs)} inputs, "
                f"got {len(xs)}")
        values: Dict[int, Any] = {id(n): x for n, x in zip(self.inputs, xs)}
        updates: Params = {}
        for node in self._order:
            if id(node) in values:
                continue
            if node.layer is None:
                raise ValueError("Disconnected input node in graph")
            args = [values[id(i)] for i in node.inputs]
            arg = args if len(args) > 1 else (args[0] if args else None)
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            y, upd = node.layer.call_and_state(
                params[node.layer.name], arg, training=training, rng=sub)
            values[id(node)] = y
            if upd:
                updates.setdefault(node.layer.name, {}).update(upd)
        outs = [values[id(o)] for o in self.outputs]
        return (outs if len(outs) > 1 else outs[0]), updates

    def compute_output_shape(self, input_shape):
        outs = [o.shape for o in self.outputs]
        return outs if len(outs) > 1 else outs[0]

    # nested-as-layer support
    def call(self, params, x, *, training=False, rng=None):
        return self.apply(params, x, training=training, rng=rng)

    def call_and_state(self, params, x, *, training=False, rng=None):
        return self.apply_and_state(params, x, training=training, rng=rng)

    stateful = True  # may contain stateful layers

    def __call__(self, inputs):
        return Layer.__call__(self, inputs)

    def _summary_rows(self):
        rows = []
        if self.params:
            for layer in self._layers:
                rows.append((f"{layer.name} ({type(layer).__name__})",
                             "-", self._count(self.params.get(layer.name))))
        return rows

    def _ordered_layers(self):
        return self._layers
