"""Transformer and BERT as Keras-style layers.

The reference ships a GPT-style `TransformerLayer`
(`keras/layers/TransformerLayer.scala:56`) and a full BERT encoder as a Keras
layer (`keras/layers/BERT.scala:66`), both assembled from per-gate JVM tensor
ops. This build is TPU-first:

- fused QKV projection — one [d, 3d] matmul per block feeds the MXU instead of
  three small ones;
- attention computed in bf16-friendly einsums with f32 softmax accumulation;
  the Pallas flash-attention kernel (`analytics_zoo_tpu/pallas/
  flash_attention.py`) drops in for long sequences;
- additive attention masks broadcast [B, 1, 1, T] so GSPMD can shard B and
  heads without re-layout;
- post-norm residual blocks matching BERT semantics (gelu FFN, LayerNorm
  eps 1e-12).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.keras.engine import Layer
from analytics_zoo_tpu.keras.layers import (LayerNormalization, get_activation,
                                            get_init)
from analytics_zoo_tpu.pallas.dropout import fused_dropout
from analytics_zoo_tpu.pallas.flash_attention import (_reference_attention,
                                                      flash_attention)
from analytics_zoo_tpu.serving.quantization import maybe_int8_matmul


def _dropout(rng, rate: float, x):
    """Shared inverted dropout (same semantics as layers.Dropout). On TPU
    this draws uint8 bytes instead of uint32 bits — 4x less unfusible RNG
    HBM traffic, which profiling shows is the entire dropout tax at
    BERT-base scale (docs/ROOFLINE.md)."""
    return fused_dropout(x, rate, rng=rng)


def dot_product_attention(q, k, v, mask=None, dropout_rng=None,
                          dropout_rate: float = 0.0, use_flash: bool = False):
    """q,k,v: [B, H, T, Dh]; mask: additive [B, 1, 1, T] or [B,1,T,T].
    Softmax statistics in f32 regardless of input dtype. With use_flash the
    Pallas kernel runs forward AND backward (custom VJP); attention dropout
    happens inside the kernel (bits regenerated in the backward pass)."""
    no_drop = dropout_rng is None or dropout_rate == 0.0
    if use_flash:
        seed = None
        if not no_drop:
            seed = jax.random.randint(dropout_rng, (), 0, 2 ** 31 - 1)
        return flash_attention(q, k, v, mask=mask,
                               dropout_rate=0.0 if no_drop
                               else dropout_rate,
                               dropout_seed=seed)
    if no_drop:
        return _reference_attention(q, k, v, mask)
    depth = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(depth)
    scores = scores.astype(jnp.float32)
    if mask is not None:
        scores = scores + mask
    weights = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    weights = _dropout(dropout_rng, dropout_rate, weights)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


class MultiHeadSelfAttention(Layer):
    """Fused-QKV self attention (`TransformerLayer.scala` attention part)."""

    def __init__(self, hidden_size: int, n_head: int,
                 attn_dropout: float = 0.0, output_dropout: float = 0.0,
                 use_flash: bool = False, **kw):
        super().__init__(**kw)
        if hidden_size % n_head:
            raise ValueError(f"hidden_size {hidden_size} not divisible by "
                             f"n_head {n_head}")
        self.hidden_size = hidden_size
        self.n_head = n_head
        self.head_dim = hidden_size // n_head
        self.attn_dropout = attn_dropout
        self.output_dropout = output_dropout
        self.use_flash = use_flash

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        init = get_init("glorot_uniform")
        return {
            "qkv_kernel": init(k1, (self.hidden_size, 3 * self.hidden_size),
                               jnp.float32),
            "qkv_bias": jnp.zeros((3 * self.hidden_size,), jnp.float32),
            "out_kernel": init(k2, (self.hidden_size, self.hidden_size),
                               jnp.float32),
            "out_bias": jnp.zeros((self.hidden_size,), jnp.float32),
        }

    def call(self, params, x, *, training=False, rng=None, mask=None):
        if isinstance(x, (list, tuple)):
            x, mask = x
        B, T, D = x.shape
        qkv = maybe_int8_matmul(x, params, "qkv_kernel") \
            + params["qkv_bias"]
        qkv = qkv.reshape(B, T, 3, self.n_head, self.head_dim)
        q, k, v = [jnp.transpose(qkv[:, :, i], (0, 2, 1, 3)) for i in range(3)]
        drop_rng = None
        if training and rng is not None and self.attn_dropout > 0:
            rng, drop_rng = jax.random.split(rng)
        ctx = dot_product_attention(q, k, v, mask=mask, dropout_rng=drop_rng,
                                    dropout_rate=self.attn_dropout,
                                    use_flash=self.use_flash)
        ctx = jnp.transpose(ctx, (0, 2, 1, 3)).reshape(B, T, D)
        out = maybe_int8_matmul(ctx, params, "out_kernel") \
            + params["out_bias"]
        if training and rng is not None and self.output_dropout > 0:
            out = _dropout(rng, self.output_dropout, out)
        return out

    def compute_output_shape(self, input_shape):
        if isinstance(input_shape, list):
            return input_shape[0]
        return input_shape


class TransformerEncoderBlock(Layer):
    """Post-norm BERT block: x + MHA → LN → x + FFN(gelu) → LN
    (`BERT.scala` block; `TransformerLayer.scala:56`)."""

    def __init__(self, hidden_size: int, n_head: int,
                 intermediate_size: Optional[int] = None,
                 hidden_dropout: float = 0.1, attn_dropout: float = 0.1,
                 hidden_act: str = "gelu", use_flash: bool = False, **kw):
        super().__init__(**kw)
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.attn = MultiHeadSelfAttention(
            hidden_size, n_head, attn_dropout=attn_dropout,
            output_dropout=hidden_dropout, use_flash=use_flash,
            name=self.name + "_attn")
        self.ln1 = LayerNormalization(name=self.name + "_ln1")
        self.ln2 = LayerNormalization(name=self.name + "_ln2")
        self.act = get_activation(hidden_act)
        self.hidden_dropout = hidden_dropout

    def build(self, rng, input_shape):
        shape = input_shape[0] if isinstance(input_shape, list) else input_shape
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        init = get_init("glorot_uniform")
        return {
            "attn": self.attn.build(k1, shape),
            "ln1": self.ln1.build(k2, shape),
            "ln2": self.ln2.build(k3, shape),
            "ffn_in_kernel": init(
                k4, (self.hidden_size, self.intermediate_size), jnp.float32),
            "ffn_in_bias": jnp.zeros((self.intermediate_size,), jnp.float32),
            "ffn_out_kernel": init(
                jax.random.fold_in(k4, 1),
                (self.intermediate_size, self.hidden_size), jnp.float32),
            "ffn_out_bias": jnp.zeros((self.hidden_size,), jnp.float32),
        }

    def call(self, params, x, *, training=False, rng=None, mask=None):
        if isinstance(x, (list, tuple)):
            x, mask = x
        r1 = r2 = None
        if rng is not None:
            rng, r1, r2 = jax.random.split(rng, 3)
        a = self.attn.call(params["attn"], x, training=training, rng=r1,
                           mask=mask)
        x = self.ln1.call(params["ln1"], x + a)
        h = self.act(maybe_int8_matmul(x, params, "ffn_in_kernel")
                     + params["ffn_in_bias"])
        h = maybe_int8_matmul(h, params, "ffn_out_kernel") \
            + params["ffn_out_bias"]
        if training and r2 is not None and self.hidden_dropout > 0:
            h = _dropout(r2, self.hidden_dropout, h)
        return self.ln2.call(params["ln2"], x + h)

    def compute_output_shape(self, input_shape):
        if isinstance(input_shape, list):
            return input_shape[0]
        return input_shape


class TransformerLayer(Layer):
    """Decoder-less transformer stack over embedded inputs
    (`TransformerLayer.scala:56`): word+position embeddings + N blocks."""

    def __init__(self, vocab: int, seq_len: int, n_block: int = 12,
                 hidden_size: int = 768, n_head: int = 12,
                 embedding_drop: float = 0.1, hidden_drop: float = 0.1,
                 attn_drop: float = 0.1, use_flash: bool = False, **kw):
        super().__init__(**kw)
        self.vocab, self.seq_len = vocab, seq_len
        self.hidden_size = hidden_size
        self.embedding_drop = embedding_drop
        self.blocks = [
            TransformerEncoderBlock(hidden_size, n_head,
                                    hidden_dropout=hidden_drop,
                                    attn_dropout=attn_drop,
                                    use_flash=use_flash,
                                    name=f"{self.name}_block{i}")
            for i in range(n_block)]

    def build(self, rng, input_shape):
        k0, k1, *ks = jax.random.split(rng, 2 + len(self.blocks))
        p = {
            "word_embeddings": jax.random.normal(
                k0, (self.vocab, self.hidden_size)) * 0.02,
            "position_embeddings": jax.random.normal(
                k1, (self.seq_len, self.hidden_size)) * 0.02,
        }
        h_shape = (None, self.seq_len, self.hidden_size)
        for blk, k in zip(self.blocks, ks):
            p[blk.name] = blk.build(k, h_shape)
        return p

    def call(self, params, x, *, training=False, rng=None):
        ids = jnp.asarray(x, jnp.int32)
        h = (jnp.take(params["word_embeddings"], ids, axis=0)
             + params["position_embeddings"][None, :ids.shape[1]])
        if training and rng is not None and self.embedding_drop > 0:
            rng, sub = jax.random.split(rng)
            h = _dropout(sub, self.embedding_drop, h)
        for blk in self.blocks:
            sub = None
            if rng is not None:
                rng, sub = jax.random.split(rng)
            h = blk.call(params[blk.name], h, training=training, rng=sub)
        return h

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.seq_len, self.hidden_size)


def stack_block_params(params: dict, n_block: int, prefix: str) -> dict:
    """Convert an UNSTACKED BERT param tree (per-block subtrees named
    `{prefix}_block{i}`) to the stacked layout (`blocks` = one [L, ...]
    buffer per tensor). Inverse: `unstack_block_params`. Used to move
    imported artifacts (TF-checkpoint weights load into the unstacked
    naming) onto a `stacked=True` encoder."""
    per_block = [params[f"{prefix}_block{i}"] for i in range(n_block)]
    out = {k: v for k, v in params.items()
           if not k.startswith(prefix + "_block")}
    out["blocks"] = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *per_block)
    return out


def unstack_block_params(params: dict, n_block: int, prefix: str) -> dict:
    """Inverse of `stack_block_params`."""
    out = {k: v for k, v in params.items() if k != "blocks"}
    for i in range(n_block):
        out[f"{prefix}_block{i}"] = jax.tree_util.tree_map(
            lambda x, _i=i: x[_i], params["blocks"])
    return out


class BERT(Layer):
    """BERT encoder as a layer (`keras/layers/BERT.scala:66`). Inputs:
    [token_ids, token_type_ids, attention_mask] (position ids are implicit);
    outputs (sequence_output, pooled_output) — or just pooled when
    `pooled_only=True` for graph use."""

    def __init__(self, vocab: int = 30522, hidden_size: int = 768,
                 n_block: int = 12, n_head: int = 12,
                 seq_len: int = 512, intermediate_size: int = 3072,
                 type_vocab: int = 2, hidden_drop: float = 0.1,
                 attn_drop: float = 0.1, pooled_only: bool = False,
                 use_flash: bool = False, remat: bool = False,
                 stacked: bool = False, **kw):
        super().__init__(**kw)
        self.vocab, self.hidden_size = vocab, hidden_size
        self.seq_len, self.type_vocab = seq_len, type_vocab
        self.hidden_drop = hidden_drop
        self.pooled_only = pooled_only
        self.remat = remat
        self.stacked = stacked
        self.n_block = n_block
        self.blocks = [
            TransformerEncoderBlock(hidden_size, n_head, intermediate_size,
                                    hidden_dropout=hidden_drop,
                                    attn_dropout=attn_drop,
                                    use_flash=use_flash,
                                    name=f"{self.name}_block{i}")
            for i in range(n_block)]
        self.emb_ln = LayerNormalization(name=self.name + "_emb_ln")

    def build(self, rng, input_shape):
        keys = jax.random.split(rng, 5 + len(self.blocks))
        p = {
            "word_embeddings": jax.random.normal(
                keys[0], (self.vocab, self.hidden_size)) * 0.02,
            "position_embeddings": jax.random.normal(
                keys[1], (self.seq_len, self.hidden_size)) * 0.02,
            "token_type_embeddings": jax.random.normal(
                keys[2], (self.type_vocab, self.hidden_size)) * 0.02,
            "emb_ln": self.emb_ln.build(
                keys[3], (None, None, self.hidden_size)),
            "pooler_kernel": get_init("glorot_uniform")(
                keys[4], (self.hidden_size, self.hidden_size), jnp.float32),
            "pooler_bias": jnp.zeros((self.hidden_size,), jnp.float32),
        }
        h_shape = (None, self.seq_len, self.hidden_size)
        per_block = [blk.build(k, h_shape)
                     for blk, k in zip(self.blocks, keys[5:])]
        if self.stacked:
            # ONE [L, ...] buffer per block tensor; `call` lax.scans the
            # block over dim 0. Why: (a) gradients are BORN stacked, so
            # the optimizer phase is ~15 big streaming fusions instead of
            # 12x13 small ones (the per-tensor Adam sweep measured 37
            # ms/step on BERT-base, 21% of the seq-128 step — and
            # repacking per-leaf grads after the fact costs the saving
            # back, docs/ROOFLINE.md round 5); (b) the block compiles
            # ONCE instead of 12 times. Same math, same init as the
            # unstacked form (`stack_block_params` converts either way).
            p["blocks"] = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs), *per_block)
        else:
            for blk, bp in zip(self.blocks, per_block):
                p[blk.name] = bp
        return p

    def _scan_blocks(self, stacked_params, h, mask, training, rng):
        """lax.scan the (single, shared-code) encoder block over the
        leading [L, ...] dim of the stacked params — identical math to
        the unstacked loop (per-layer weights, per-layer dropout keys),
        one compiled block body, gradients accumulated directly into the
        stacked buffers by scan's transpose."""
        blk = self.blocks[0]

        def run_block(bp, hh, key):
            fn = lambda p, a, m, r: blk.call(  # noqa: E731
                p, [a, m], training=training, rng=r)
            if self.remat:
                fn = jax.checkpoint(
                    fn, policy=jax.checkpoint_policies
                    .dots_with_no_batch_dims_saveable)
            return fn(bp, hh, mask, key)

        if rng is not None:
            layer_keys = jax.random.split(rng, self.n_block)

            def body(hh, xs):
                bp, key = xs
                return run_block(bp, hh, key), None

            h, _ = jax.lax.scan(body, h, (stacked_params, layer_keys))
        else:
            h, _ = jax.lax.scan(
                lambda hh, bp: (run_block(bp, hh, None), None),
                h, stacked_params)
        return h

    @staticmethod
    def make_mask(attention_mask) -> jax.Array:
        """[B, T] {0,1} → additive [B, 1, 1, T] (matches the reference's
        -10000 masked-logit convention, `BERT.scala`)."""
        m = jnp.asarray(attention_mask, jnp.float32)
        return (1.0 - m)[:, None, None, :] * -10000.0

    def call(self, params, x, *, training=False, rng=None):
        if isinstance(x, (list, tuple)):
            if len(x) == 3:
                ids, token_type, attn_mask = x
            elif len(x) == 2:
                ids, attn_mask = x
                token_type = jnp.zeros_like(ids)
            else:
                raise ValueError("BERT expects [ids, (token_type), mask]")
        else:
            ids = x
            token_type = jnp.zeros_like(ids)
            attn_mask = jnp.ones_like(ids)
        ids = jnp.asarray(ids, jnp.int32)
        token_type = jnp.asarray(token_type, jnp.int32)
        T = ids.shape[1]
        h = (jnp.take(params["word_embeddings"], ids, axis=0)
             + params["position_embeddings"][None, :T]
             + jnp.take(params["token_type_embeddings"], token_type, axis=0))
        h = self.emb_ln.call(params["emb_ln"], h)
        if training and rng is not None and self.hidden_drop > 0:
            rng, sub = jax.random.split(rng)
            h = _dropout(sub, self.hidden_drop, h)
        mask = self.make_mask(attn_mask)
        if self.stacked:
            h = self._scan_blocks(params["blocks"], h, mask, training, rng)
        else:
            for blk in self.blocks:
                sub = None
                if rng is not None:
                    rng, sub = jax.random.split(rng)
                if self.remat:
                    # activation rematerialization per block: save only
                    # the matmul outputs with no batch dims (i.e. nothing
                    # — all block dots carry the batch), recompute the
                    # rest in the backward pass. Trades ~1/3 more FLOPs
                    # on the block for O(1) blocks of live activations,
                    # unlocking batch sizes (and seq lengths) the
                    # non-remat program cannot fit.
                    h = jax.checkpoint(
                        lambda p, hh, mm, rr, _blk=blk: _blk.call(
                            p, [hh, mm], training=training, rng=rr),
                        policy=jax.checkpoint_policies
                        .dots_with_no_batch_dims_saveable)(
                            params[blk.name], h, mask, sub)
                else:
                    h = blk.call(params[blk.name], [h, mask],
                                 training=training, rng=sub)
        pooled = jnp.tanh(maybe_int8_matmul(h[:, 0], params,
                                            "pooler_kernel")
                          + params["pooler_bias"])
        if self.pooled_only:
            return pooled
        return h, pooled

    def compute_output_shape(self, input_shape):
        first = input_shape[0] if isinstance(input_shape, list) else input_shape
        if self.pooled_only:
            return (first[0], self.hidden_size)
        return [(first[0], first[1], self.hidden_size),
                (first[0], self.hidden_size)]
