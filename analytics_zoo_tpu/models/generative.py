"""Tiny causal-LM decoder — the generative model contract decode mode serves.

`serving/decode.py` and `InferenceModel.load_generative` are model-
agnostic; what they need from a model is the functional triple this
module defines (and any user model can supply):

- ``init_params(seed)`` — a host pytree of weights.
- ``init_kv(slots, max_kv_len)`` — the pooled KV cache: per layer a
  ``{"k","v"}: [slots, heads, max_kv_len, head_dim]`` pair, ONE device
  buffer per layer for the whole pool (the KVSlotPool leases rows of
  it, never reallocates).
- ``prefill_fn(params, kv, tokens, length, slot)`` — run the prompt
  (padded to a static prompt bucket) through the stack, write its KV
  into pool rows ``[slot, :, 0:len(tokens)]``, and return
  ``(kv, logits)`` with logits taken at position ``length - 1`` — the
  FIRST generated token comes out of prefill itself (that's what TTFT
  measures).
- ``step_fn(params, kv, tokens, positions, kv_bucket)`` — one decode
  step for every slot at once: embed ``tokens[s]`` at ``positions[s]``,
  append the new K/V at ``positions[s]``, attend over the first
  ``positions[s] + 1`` cached positions (via the Pallas decode kernel
  on TPU) and return ``(kv, logits[s])``. ``kv_bucket`` is a STATIC
  int — the per-step serving bucket the scheduler picked — so each
  bucket is its own executable (and its own compile-cache entry).

Per-slot math is row-independent end to end (embedding, layernorm and
matmuls act per row; attention only reads the slot's own KV rows), so a
sequence's token stream is bitwise-identical whatever else occupies the
other slots — the property the greedy-parity test asserts, and the
reason continuous batching is a pure scheduling win. Writes for dead
slots land in pool rows nobody reads (the engine passes position 0 and
their KV is overwritten by the next prefill into that slot).

The model itself is deliberately small (the serving stack is the
subject, not the LM): GPT-style pre-LN blocks, learned positions, tied
vocab kept untied for clarity, float32.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pallas.decode_attention import (
    _reference_decode_attention, _reference_paged_decode_attention,
    decode_attention, gather_kv_window, paged_decode_attention)


def _layer_norm(x, g, b, eps: float = 1e-5):
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


class TinyDecoder:
    """Minimal functional causal LM exposing the decode-mode contract."""

    def __init__(self, vocab: int = 64, n_layers: int = 2,
                 n_heads: int = 2, head_dim: int = 8,
                 max_len: int = 256, mlp_mult: int = 2,
                 use_pallas: bool = True):
        self.vocab = int(vocab)
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.embed_dim = self.n_heads * self.head_dim
        self.max_len = int(max_len)
        self.mlp_dim = self.embed_dim * int(mlp_mult)
        self.use_pallas = bool(use_pallas)

    # -- weights / cache ---------------------------------------------------
    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        E, M, V = self.embed_dim, self.mlp_dim, self.vocab

        def w(*shape, scale=0.08):
            return rng.normal(0.0, scale, shape).astype(np.float32)

        layers: List[Dict[str, np.ndarray]] = []
        for _ in range(self.n_layers):
            layers.append({
                "wq": w(E, E), "wk": w(E, E), "wv": w(E, E), "wo": w(E, E),
                "w1": w(E, M), "b1": np.zeros(M, np.float32),
                "w2": w(M, E), "b2": np.zeros(E, np.float32),
                "ln1_g": np.ones(E, np.float32),
                "ln1_b": np.zeros(E, np.float32),
                "ln2_g": np.ones(E, np.float32),
                "ln2_b": np.zeros(E, np.float32),
            })
        return {"embed": w(V, E, scale=0.5), "pos": w(self.max_len, E),
                "layers": layers,
                "lnf_g": np.ones(E, np.float32),
                "lnf_b": np.zeros(E, np.float32),
                "head": w(E, V, scale=0.3)}

    def init_kv(self, slots: int, max_kv_len: int):
        shape = (slots, self.n_heads, max_kv_len, self.head_dim)
        return [{"k": jnp.zeros(shape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.float32)}
                for _ in range(self.n_layers)]

    # -- prefill -----------------------------------------------------------
    def prefill_fn(self, params, kv, tokens, length, slot):
        """tokens: int32 [P] (bucket-padded prompt), length/slot: int32
        scalars. Returns (kv, logits[vocab]) — logits at the last REAL
        prompt position."""
        P = tokens.shape[0]
        H, D = self.n_heads, self.head_dim
        x = params["embed"][tokens] + params["pos"][:P]     # [P, E]
        causal = jnp.tril(jnp.ones((P, P), jnp.float32))
        mask = jnp.where(causal > 0, 0.0, -1e30)
        new_kv = []
        for lp, lkv in zip(params["layers"], kv):
            h = _layer_norm(x, lp["ln1_g"], lp["ln1_b"])
            q = (h @ lp["wq"]).reshape(P, H, D)
            k = (h @ lp["wk"]).reshape(P, H, D)
            v = (h @ lp["wv"]).reshape(P, H, D)
            scores = jnp.einsum("qhd,khd->hqk", q, k) / math.sqrt(D)
            scores = scores.astype(jnp.float32) + mask[None]
            w = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            att = jnp.einsum("hqk,khd->qhd", w, v).reshape(P, -1)
            x = x + att @ lp["wo"]
            h2 = _layer_norm(x, lp["ln2_g"], lp["ln2_b"])
            x = x + (jax.nn.gelu(h2 @ lp["w1"] + lp["b1"])
                     @ lp["w2"] + lp["b2"])
            # park this prompt's KV into the pool rows of `slot`
            k_upd = jnp.transpose(k, (1, 0, 2))[None]        # [1,H,P,D]
            v_upd = jnp.transpose(v, (1, 0, 2))[None]
            zero = jnp.int32(0)
            new_kv.append({
                "k": jax.lax.dynamic_update_slice(
                    lkv["k"], k_upd, (slot, zero, zero, zero)),
                "v": jax.lax.dynamic_update_slice(
                    lkv["v"], v_upd, (slot, zero, zero, zero))})
        x_last = jax.lax.dynamic_index_in_dim(
            x, length - 1, axis=0, keepdims=False)
        x_last = _layer_norm(x_last, params["lnf_g"], params["lnf_b"])
        return new_kv, x_last @ params["head"]

    # -- decode step -------------------------------------------------------
    def step_fn(self, params, kv, tokens, positions, kv_bucket: int):
        """tokens/positions: int32 [S]. One token per slot; the KV write
        lands at ``positions[s]`` and attention covers the first
        ``positions[s] + 1`` positions, windowed to the static
        ``kv_bucket``. Returns (kv, logits[S, vocab])."""
        S = tokens.shape[0]
        H, D = self.n_heads, self.head_dim
        rows = jnp.arange(S)[:, None]                        # [S, 1]
        heads = jnp.arange(H)[None, :]                       # [1, H]
        x = params["embed"][tokens] + params["pos"][positions]   # [S, E]
        lengths = positions.astype(jnp.int32) + 1
        new_kv = []
        for lp, lkv in zip(params["layers"], kv):
            h = _layer_norm(x, lp["ln1_g"], lp["ln1_b"])
            q = (h @ lp["wq"]).reshape(S, H, D)
            k = (h @ lp["wk"]).reshape(S, H, D)
            v = (h @ lp["wv"]).reshape(S, H, D)
            k_pool = lkv["k"].at[rows, heads, positions[:, None]].set(k)
            v_pool = lkv["v"].at[rows, heads, positions[:, None]].set(v)
            if self.use_pallas:
                att = decode_attention(q, k_pool, v_pool, lengths,
                                       kv_bucket)
            else:
                att = _reference_decode_attention(q, k_pool, v_pool,
                                                  lengths, kv_bucket)
            x = x + att.reshape(S, -1) @ lp["wo"]
            h2 = _layer_norm(x, lp["ln2_g"], lp["ln2_b"])
            x = x + (jax.nn.gelu(h2 @ lp["w1"] + lp["b1"])
                     @ lp["w2"] + lp["b2"])
            new_kv.append({"k": k_pool, "v": v_pool})
        x = _layer_norm(x, params["lnf_g"], params["lnf_b"])
        return new_kv, x @ params["head"]

    # -- paged contract (ISSUE 19) -----------------------------------------
    # Same math, block-pool memory layout: the cache is ONE pool of
    # ref-counted [heads, block_len, head_dim] blocks per layer and each
    # sequence owns an ordered block table. Greedy outputs stay bitwise
    # identical to the contiguous contract because every numeric op is
    # the same — only WHERE the KV bytes live changes.
    def init_kv_blocks(self, num_blocks: int, block_len: int):
        shape = (num_blocks, self.n_heads, block_len, self.head_dim)
        return [{"k": jnp.zeros(shape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.float32)}
                for _ in range(self.n_layers)]

    def paged_prefill_fn(self, params, kv, tokens, table, pre_len,
                         chunk_len, kv_bucket: int):
        """One prefill CHUNK of a prompt, KV parked through the block
        table.

        tokens: int32 [Cb] — this chunk, padded to a static chunk
        bucket. table: int32 [T] — the sequence's block table (covers
        at least ``pre_len + Cb`` logical positions). pre_len: int32
        scalar — tokens already in KV (adopted prefix blocks plus
        earlier chunks). chunk_len: int32 scalar — real tokens in this
        chunk. kv_bucket: STATIC context window covering ``pre_len``
        (0 on the fresh first chunk — by construction ``pre_len == 0``
        exactly when ``kv_bucket == 0``, since any cached or prior-chunk
        context needs a window to attend over).

        Returns (kv, logits[vocab]) at chunk position ``chunk_len - 1``
        — meaningful on the FINAL chunk (first generated token), ignored
        by the engine on intermediate ones.

        The ``kv_bucket == 0`` branch is op-for-op the contiguous
        ``prefill_fn`` (static ``pos[:Cb]`` slice, same causal-mask
        einsum walk), so a fresh single-chunk prompt produces bitwise-
        identical first-token logits — the paged-parity anchor."""
        Cb = tokens.shape[0]
        H, D = self.n_heads, self.head_dim
        bl = kv[0]["k"].shape[2]
        num_blocks = kv[0]["k"].shape[0]
        heads = jnp.arange(H)[None, :]                       # [1, H]
        pre_len = jnp.asarray(pre_len, jnp.int32)
        chunk_len = jnp.asarray(chunk_len, jnp.int32)
        table = table.astype(jnp.int32)
        idx = jnp.arange(Cb, dtype=jnp.int32)
        logical = pre_len + idx                              # [Cb]
        if kv_bucket == 0:
            x = params["embed"][tokens] + params["pos"][:Cb]
        else:
            # gather (not dynamic_slice) so real positions near max_len
            # are never shifted by start-clamping
            x = params["embed"][tokens] + params["pos"][
                jnp.clip(logical, 0, self.max_len - 1)]
        causal = jnp.tril(jnp.ones((Cb, Cb), jnp.float32))
        cmask = jnp.where(causal > 0, 0.0, -1e30)
        # KV scatter destinations: pad positions (idx >= chunk_len) are
        # routed out of bounds — JAX drops OOB scatter updates — so a
        # padded chunk never corrupts the next chunk's blocks
        blk = table[jnp.clip(logical // bl, 0, table.shape[0] - 1)]
        blk = jnp.where(idx < chunk_len, blk, num_blocks)    # [Cb]
        off = logical % bl
        new_kv = []
        for lp, lkv in zip(params["layers"], kv):
            h = _layer_norm(x, lp["ln1_g"], lp["ln1_b"])
            q = (h @ lp["wq"]).reshape(Cb, H, D)
            k = (h @ lp["wk"]).reshape(Cb, H, D)
            v = (h @ lp["wv"]).reshape(Cb, H, D)
            if kv_bucket == 0:
                scores = jnp.einsum("qhd,khd->hqk", q, k) / math.sqrt(D)
                scores = scores.astype(jnp.float32) + cmask[None]
                w = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
                att = jnp.einsum("hqk,khd->qhd", w, v).reshape(Cb, -1)
            else:
                # context (earlier logical positions, read through the
                # table BEFORE this chunk's writes) ++ in-chunk causal
                ctx_k = gather_kv_window(
                    lkv["k"], table[None], kv_bucket)[0]     # [H,kvb,D]
                ctx_v = gather_kv_window(lkv["v"], table[None],
                                         kv_bucket)[0]
                ctx_s = jnp.einsum("qhd,hkd->hqk", q, ctx_k) / math.sqrt(D)
                cpos = jnp.arange(kv_bucket, dtype=jnp.int32)
                ctx_s = jnp.where(cpos[None, None, :] < pre_len,
                                  ctx_s.astype(jnp.float32), -1e30)
                chn_s = jnp.einsum("qhd,khd->hqk", q, k) / math.sqrt(D)
                chn_s = chn_s.astype(jnp.float32) + cmask[None]
                scores = jnp.concatenate([ctx_s, chn_s], axis=-1)
                w = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
                att = (jnp.einsum("hqk,hkd->qhd", w[..., :kv_bucket],
                                  ctx_v)
                       + jnp.einsum("hqk,khd->qhd", w[..., kv_bucket:],
                                    v)).reshape(Cb, -1)
            x = x + att @ lp["wo"]
            h2 = _layer_norm(x, lp["ln2_g"], lp["ln2_b"])
            x = x + (jax.nn.gelu(h2 @ lp["w1"] + lp["b1"])
                     @ lp["w2"] + lp["b2"])
            new_kv.append({
                "k": lkv["k"].at[blk[:, None], heads, off[:, None]].set(k),
                "v": lkv["v"].at[blk[:, None], heads, off[:, None]].set(v)})
        x_last = jax.lax.dynamic_index_in_dim(
            x, chunk_len - 1, axis=0, keepdims=False)
        x_last = _layer_norm(x_last, params["lnf_g"], params["lnf_b"])
        return new_kv, x_last @ params["head"]

    def paged_step_fn(self, params, kv, tokens, positions, tables,
                      kv_bucket: int):
        """One decode step for every LANE, KV routed through per-lane
        block tables. tokens/positions: int32 [S]; tables: int32 [S, T].
        Dead lanes carry all-scratch tables and position 0, so their
        (discarded) KV write lands in the reserved scratch block and the
        fixed-shape executable never touches live blocks."""
        S = tokens.shape[0]
        H, D = self.n_heads, self.head_dim
        bl = kv[0]["k"].shape[2]
        heads = jnp.arange(H)[None, :]                       # [1, H]
        tables = tables.astype(jnp.int32)
        positions = positions.astype(jnp.int32)
        x = params["embed"][tokens] + params["pos"][positions]   # [S, E]
        lengths = positions + 1
        blk = jnp.take_along_axis(
            tables, (positions // bl)[:, None], axis=1)[:, 0]    # [S]
        off = positions % bl
        new_kv = []
        for lp, lkv in zip(params["layers"], kv):
            h = _layer_norm(x, lp["ln1_g"], lp["ln1_b"])
            q = (h @ lp["wq"]).reshape(S, H, D)
            k = (h @ lp["wk"]).reshape(S, H, D)
            v = (h @ lp["wv"]).reshape(S, H, D)
            k_pool = lkv["k"].at[blk[:, None], heads, off[:, None]].set(k)
            v_pool = lkv["v"].at[blk[:, None], heads, off[:, None]].set(v)
            if self.use_pallas:
                att = paged_decode_attention(q, k_pool, v_pool, tables,
                                             lengths, kv_bucket)
            else:
                att = _reference_paged_decode_attention(
                    q, k_pool, v_pool, tables, lengths, kv_bucket)
            x = x + att.reshape(S, -1) @ lp["wo"]
            h2 = _layer_norm(x, lp["ln2_g"], lp["ln2_b"])
            x = x + (jax.nn.gelu(h2 @ lp["w1"] + lp["b1"])
                     @ lp["w2"] + lp["b2"])
            new_kv.append({"k": k_pool, "v": v_pool})
        x = _layer_norm(x, params["lnf_g"], params["lnf_b"])
        return new_kv, x @ params["head"]
