"""Tiny causal-LM decoder — the generative model contract decode mode serves.

`serving/decode.py` and `InferenceModel.load_generative` are model-
agnostic; what they need from a model is the functional triple this
module defines (and any user model can supply):

- ``init_params(seed)`` — a host pytree of weights.
- ``init_kv(slots, max_kv_len)`` — the pooled KV cache: per layer a
  ``{"k","v"}: [slots, heads, max_kv_len, head_dim]`` pair, ONE device
  buffer per layer for the whole pool (the KVSlotPool leases rows of
  it, never reallocates).
- ``prefill_fn(params, kv, tokens, length, slot)`` — run the prompt
  (padded to a static prompt bucket) through the stack, write its KV
  into pool rows ``[slot, :, 0:len(tokens)]``, and return
  ``(kv, logits)`` with logits taken at position ``length - 1`` — the
  FIRST generated token comes out of prefill itself (that's what TTFT
  measures).
- ``step_fn(params, kv, tokens, positions, kv_bucket)`` — one decode
  step for every slot at once: embed ``tokens[s]`` at ``positions[s]``,
  append the new K/V at ``positions[s]``, attend over the first
  ``positions[s] + 1`` cached positions (via the Pallas decode kernel
  on TPU) and return ``(kv, logits[s])``. ``kv_bucket`` is a STATIC
  int — the per-step serving bucket the scheduler picked — so each
  bucket is its own executable (and its own compile-cache entry).

Per-slot math is row-independent end to end (embedding, layernorm and
matmuls act per row; attention only reads the slot's own KV rows), so a
sequence's token stream is bitwise-identical whatever else occupies the
other slots — the property the greedy-parity test asserts, and the
reason continuous batching is a pure scheduling win. Writes for dead
slots land in pool rows nobody reads (the engine passes position 0 and
their KV is overwritten by the next prefill into that slot).

The model itself is deliberately small (the serving stack is the
subject, not the LM): GPT-style pre-LN blocks, learned positions, tied
vocab kept untied for clarity, float32.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List

import numpy as np

import jax
import jax.numpy as jnp

from analytics_zoo_tpu.pallas.decode_attention import (
    _reference_decode_attention, decode_attention)


def _layer_norm(x, g, b, eps: float = 1e-5):
    mu = x.mean(axis=-1, keepdims=True)
    var = ((x - mu) ** 2).mean(axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


class TinyDecoder:
    """Minimal functional causal LM exposing the decode-mode contract."""

    def __init__(self, vocab: int = 64, n_layers: int = 2,
                 n_heads: int = 2, head_dim: int = 8,
                 max_len: int = 256, mlp_mult: int = 2,
                 use_pallas: bool = True):
        self.vocab = int(vocab)
        self.n_layers = int(n_layers)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.embed_dim = self.n_heads * self.head_dim
        self.max_len = int(max_len)
        self.mlp_dim = self.embed_dim * int(mlp_mult)
        self.use_pallas = bool(use_pallas)

    # -- weights / cache ---------------------------------------------------
    def init_params(self, seed: int = 0) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        E, M, V = self.embed_dim, self.mlp_dim, self.vocab

        def w(*shape, scale=0.08):
            return rng.normal(0.0, scale, shape).astype(np.float32)

        layers: List[Dict[str, np.ndarray]] = []
        for _ in range(self.n_layers):
            layers.append({
                "wq": w(E, E), "wk": w(E, E), "wv": w(E, E), "wo": w(E, E),
                "w1": w(E, M), "b1": np.zeros(M, np.float32),
                "w2": w(M, E), "b2": np.zeros(E, np.float32),
                "ln1_g": np.ones(E, np.float32),
                "ln1_b": np.zeros(E, np.float32),
                "ln2_g": np.ones(E, np.float32),
                "ln2_b": np.zeros(E, np.float32),
            })
        return {"embed": w(V, E, scale=0.5), "pos": w(self.max_len, E),
                "layers": layers,
                "lnf_g": np.ones(E, np.float32),
                "lnf_b": np.zeros(E, np.float32),
                "head": w(E, V, scale=0.3)}

    def init_kv(self, slots: int, max_kv_len: int):
        shape = (slots, self.n_heads, max_kv_len, self.head_dim)
        return [{"k": jnp.zeros(shape, jnp.float32),
                 "v": jnp.zeros(shape, jnp.float32)}
                for _ in range(self.n_layers)]

    # -- prefill -----------------------------------------------------------
    def prefill_fn(self, params, kv, tokens, length, slot):
        """tokens: int32 [P] (bucket-padded prompt), length/slot: int32
        scalars. Returns (kv, logits[vocab]) — logits at the last REAL
        prompt position."""
        P = tokens.shape[0]
        H, D = self.n_heads, self.head_dim
        x = params["embed"][tokens] + params["pos"][:P]     # [P, E]
        causal = jnp.tril(jnp.ones((P, P), jnp.float32))
        mask = jnp.where(causal > 0, 0.0, -1e30)
        new_kv = []
        for lp, lkv in zip(params["layers"], kv):
            h = _layer_norm(x, lp["ln1_g"], lp["ln1_b"])
            q = (h @ lp["wq"]).reshape(P, H, D)
            k = (h @ lp["wk"]).reshape(P, H, D)
            v = (h @ lp["wv"]).reshape(P, H, D)
            scores = jnp.einsum("qhd,khd->hqk", q, k) / math.sqrt(D)
            scores = scores.astype(jnp.float32) + mask[None]
            w = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            att = jnp.einsum("hqk,khd->qhd", w, v).reshape(P, -1)
            x = x + att @ lp["wo"]
            h2 = _layer_norm(x, lp["ln2_g"], lp["ln2_b"])
            x = x + (jax.nn.gelu(h2 @ lp["w1"] + lp["b1"])
                     @ lp["w2"] + lp["b2"])
            # park this prompt's KV into the pool rows of `slot`
            k_upd = jnp.transpose(k, (1, 0, 2))[None]        # [1,H,P,D]
            v_upd = jnp.transpose(v, (1, 0, 2))[None]
            zero = jnp.int32(0)
            new_kv.append({
                "k": jax.lax.dynamic_update_slice(
                    lkv["k"], k_upd, (slot, zero, zero, zero)),
                "v": jax.lax.dynamic_update_slice(
                    lkv["v"], v_upd, (slot, zero, zero, zero))})
        x_last = jax.lax.dynamic_index_in_dim(
            x, length - 1, axis=0, keepdims=False)
        x_last = _layer_norm(x_last, params["lnf_g"], params["lnf_b"])
        return new_kv, x_last @ params["head"]

    # -- decode step -------------------------------------------------------
    def step_fn(self, params, kv, tokens, positions, kv_bucket: int):
        """tokens/positions: int32 [S]. One token per slot; the KV write
        lands at ``positions[s]`` and attention covers the first
        ``positions[s] + 1`` positions, windowed to the static
        ``kv_bucket``. Returns (kv, logits[S, vocab])."""
        S = tokens.shape[0]
        H, D = self.n_heads, self.head_dim
        rows = jnp.arange(S)[:, None]                        # [S, 1]
        heads = jnp.arange(H)[None, :]                       # [1, H]
        x = params["embed"][tokens] + params["pos"][positions]   # [S, E]
        lengths = positions.astype(jnp.int32) + 1
        new_kv = []
        for lp, lkv in zip(params["layers"], kv):
            h = _layer_norm(x, lp["ln1_g"], lp["ln1_b"])
            q = (h @ lp["wq"]).reshape(S, H, D)
            k = (h @ lp["wk"]).reshape(S, H, D)
            v = (h @ lp["wv"]).reshape(S, H, D)
            k_pool = lkv["k"].at[rows, heads, positions[:, None]].set(k)
            v_pool = lkv["v"].at[rows, heads, positions[:, None]].set(v)
            if self.use_pallas:
                att = decode_attention(q, k_pool, v_pool, lengths,
                                       kv_bucket)
            else:
                att = _reference_decode_attention(q, k_pool, v_pool,
                                                  lengths, kv_bucket)
            x = x + att.reshape(S, -1) @ lp["wo"]
            h2 = _layer_norm(x, lp["ln2_g"], lp["ln2_b"])
            x = x + (jax.nn.gelu(h2 @ lp["w1"] + lp["b1"])
                     @ lp["w2"] + lp["b2"])
            new_kv.append({"k": k_pool, "v": v_pool})
        x = _layer_norm(x, params["lnf_g"], params["lnf_b"])
        return new_kv, x @ params["head"]
