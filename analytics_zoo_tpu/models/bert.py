"""BERT task models + TF-checkpoint import.

Reference: TFPark text estimators — `BERTClassifier`
(`pyzoo/zoo/tfpark/text/estimator/bert_classifier.py:64`), `BERTNER`,
`BERTSQuAD` over a shared BERT `model_fn` (`bert_base.py:115`). Here each is
a thin head over the native `keras.transformer.BERT` layer, trained by the
shared pjit trainer — no TF session, no estimator graph export.

`load_tf_checkpoint` imports Google-format BERT checkpoints (the reference
feeds `init_checkpoint` into its model_fn) by mapping TF1 variable names
(`bert/encoder/layer_0/attention/self/query/...`) onto the native fused-QKV
parameter tree; q/k/v kernels concatenate into the one [D, 3D] matmul the
MXU wants."""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras.engine import KerasNet
from analytics_zoo_tpu.keras.transformer import BERT, _dropout
from analytics_zoo_tpu.serving.quantization import maybe_int8_matmul


class _BERTTask(KerasNet):
    """Shared plumbing: BERT encoder + task head, optimizer defaults from
    the reference (AdamWeightDecay lr 5e-5)."""

    def __init__(self, bert: BERT, name=None):
        super().__init__(name)
        self.bert = bert

    def default_compile(self, lr: float = 5e-5, total_steps: int = -1,
                        loss: str = "sparse_categorical_crossentropy",
                        metrics=("accuracy",)):
        from analytics_zoo_tpu.ops.objectives import get as get_loss
        from analytics_zoo_tpu.ops.optimizers import adam_weight_decay
        self.compile(adam_weight_decay(lr, warmup_portion=0.1,
                                       total_steps=total_steps),
                     get_loss(loss, from_logits=True), list(metrics))
        return self

    def load_tf_checkpoint(self, ckpt_path: str) -> "_BERTTask":
        if self.params is None:
            raise RuntimeError("Build the model first (ensure_built or fit)")
        self.params[self.bert.name] = load_tf_checkpoint(
            self.bert, ckpt_path, self.params[self.bert.name])
        return self

    # No sidecar remap: param keys are stable (the encoder is always named
    # "bert" when constructed by the task classes), so saved trees load by
    # exact key. A custom-named user BERT must keep its name across
    # save/load.
    def _ordered_layers(self):
        return []


class BERTClassifier(_BERTTask):
    """Sequence classification (`bert_classifier.py:64`): pooled output ->
    dropout -> Dense(num_classes) logits."""

    def __init__(self, num_classes: int, bert: Optional[BERT] = None,
                 dropout: float = 0.1, **bert_kw):
        bert = bert or BERT(pooled_only=True, name="bert", **bert_kw)
        bert.pooled_only = True
        super().__init__(bert)
        self.num_classes = num_classes
        self.dropout = dropout

    def build(self, rng, input_shape=None):
        k1, k2 = jax.random.split(rng)
        seq = (None, self.bert.seq_len)
        return {
            self.bert.name: self.bert.build(k1, [seq, seq, seq]),
            "cls_kernel": jax.random.normal(
                k2, (self.bert.hidden_size, self.num_classes)) * 0.02,
            "cls_bias": jnp.zeros((self.num_classes,), jnp.float32),
        }

    def apply(self, params, inputs, *, training=False, rng=None):
        sub = None
        if rng is not None:
            rng, sub = jax.random.split(rng)
        pooled = self.bert.call(params[self.bert.name], inputs,
                                training=training, rng=sub)
        if training and rng is not None and self.dropout > 0:
            pooled = _dropout(rng, self.dropout, pooled)
        return maybe_int8_matmul(pooled, params, "cls_kernel") \
            + params["cls_bias"]

    def compute_output_shape(self, input_shape):
        return (None, self.num_classes)


class BERTNER(_BERTTask):
    """Token classification (`bert_ner.py`): sequence output ->
    per-token Dense(num_entities) logits."""

    def __init__(self, num_entities: int, bert: Optional[BERT] = None,
                 **bert_kw):
        bert = bert or BERT(name="bert", **bert_kw)
        bert.pooled_only = False
        super().__init__(bert)
        self.num_entities = num_entities

    def build(self, rng, input_shape=None):
        k1, k2 = jax.random.split(rng)
        seq = (None, self.bert.seq_len)
        return {
            self.bert.name: self.bert.build(k1, [seq, seq, seq]),
            "ner_kernel": jax.random.normal(
                k2, (self.bert.hidden_size, self.num_entities)) * 0.02,
            "ner_bias": jnp.zeros((self.num_entities,), jnp.float32),
        }

    def apply(self, params, inputs, *, training=False, rng=None):
        seq_out, _ = self.bert.call(params[self.bert.name], inputs,
                                    training=training, rng=rng)
        return maybe_int8_matmul(seq_out, params, "ner_kernel") \
            + params["ner_bias"]

    def compute_output_shape(self, input_shape):
        return (None, self.bert.seq_len, self.num_entities)


class BERTSQuAD(_BERTTask):
    """Extractive QA (`bert_squad.py`): sequence output -> start/end logits
    ([B, T] each)."""

    def __init__(self, bert: Optional[BERT] = None, **bert_kw):
        bert = bert or BERT(name="bert", **bert_kw)
        bert.pooled_only = False
        super().__init__(bert)

    def build(self, rng, input_shape=None):
        k1, k2 = jax.random.split(rng)
        seq = (None, self.bert.seq_len)
        return {
            self.bert.name: self.bert.build(k1, [seq, seq, seq]),
            "qa_kernel": jax.random.normal(
                k2, (self.bert.hidden_size, 2)) * 0.02,
            "qa_bias": jnp.zeros((2,), jnp.float32),
        }

    def apply(self, params, inputs, *, training=False, rng=None):
        seq_out, _ = self.bert.call(params[self.bert.name], inputs,
                                    training=training, rng=rng)
        logits = maybe_int8_matmul(seq_out, params, "qa_kernel") \
            + params["qa_bias"]
        return logits[..., 0], logits[..., 1]      # start, end

    def compute_output_shape(self, input_shape):
        T = self.bert.seq_len
        return [(None, T), (None, T)]


# ---------------------------------------------------------------------------
# Google TF1 BERT checkpoint import
# ---------------------------------------------------------------------------
def load_tf_checkpoint(bert: BERT, ckpt_path: str,
                       params: Dict) -> Dict:
    """Map `bert/...` TF1 variables onto the native param tree. Returns a
    new tree with imported weights (shapes validated); raises on missing
    variables."""
    import tensorflow as tf  # baked into the image; CPU-only use here
    reader = tf.train.load_checkpoint(ckpt_path)

    def get(name):
        full = f"bert/{name}"
        if not reader.has_tensor(full):
            raise KeyError(f"checkpoint missing {full}")
        return np.asarray(reader.get_tensor(full))

    p = jax.tree_util.tree_map(np.asarray, params)  # mutable copy
    if bert.stacked:
        # import targets the per-block naming; convert the stacked tree
        # out and back (`keras/transformer.py` converters)
        from analytics_zoo_tpu.keras.transformer import unstack_block_params
        p = unstack_block_params(p, bert.n_block, bert.name)
    p["word_embeddings"] = get("embeddings/word_embeddings")
    p["position_embeddings"] = get("embeddings/position_embeddings")
    p["token_type_embeddings"] = get("embeddings/token_type_embeddings")
    p["emb_ln"] = {"gamma": get("embeddings/LayerNorm/gamma"),
                   "beta": get("embeddings/LayerNorm/beta")}
    p["pooler_kernel"] = get("pooler/dense/kernel")
    p["pooler_bias"] = get("pooler/dense/bias")
    for i, blk in enumerate(bert.blocks):
        base = f"encoder/layer_{i}"
        q = get(f"{base}/attention/self/query/kernel")
        k = get(f"{base}/attention/self/key/kernel")
        v = get(f"{base}/attention/self/value/kernel")
        qb = get(f"{base}/attention/self/query/bias")
        kb = get(f"{base}/attention/self/key/bias")
        vb = get(f"{base}/attention/self/value/bias")
        bp = dict(p[blk.name])
        bp["attn"] = {
            "qkv_kernel": np.concatenate([q, k, v], axis=1),
            "qkv_bias": np.concatenate([qb, kb, vb]),
            "out_kernel": get(f"{base}/attention/output/dense/kernel"),
            "out_bias": get(f"{base}/attention/output/dense/bias"),
        }
        bp["ln1"] = {"gamma": get(f"{base}/attention/output/LayerNorm/gamma"),
                     "beta": get(f"{base}/attention/output/LayerNorm/beta")}
        bp["ffn_in_kernel"] = get(f"{base}/intermediate/dense/kernel")
        bp["ffn_in_bias"] = get(f"{base}/intermediate/dense/bias")
        bp["ffn_out_kernel"] = get(f"{base}/output/dense/kernel")
        bp["ffn_out_bias"] = get(f"{base}/output/dense/bias")
        bp["ln2"] = {"gamma": get(f"{base}/output/LayerNorm/gamma"),
                     "beta": get(f"{base}/output/LayerNorm/beta")}
        p[blk.name] = bp
    # shape validation against the existing tree
    if bert.stacked:
        from analytics_zoo_tpu.keras.transformer import stack_block_params
        p = stack_block_params(p, bert.n_block, bert.name)
    ref_shapes = jax.tree_util.tree_map(np.shape, params)
    new_shapes = jax.tree_util.tree_map(np.shape, p)
    if ref_shapes != new_shapes:
        raise ValueError("checkpoint shapes do not match the model config")
    return p
