"""Named-entity / POS / intent models (TFPark text.keras equivalents).

Reference: `pyzoo/zoo/tfpark/text/keras/` — NER (`ner.py:21`, BiLSTM-CRF
over word + char features), SequenceTagger (`pos_tagging.py:21`, 3×BiLSTM
with dual pos/chunk heads), IntentEntity (`intent_extraction.py:21`, joint
intent classification + slot filling). There the architectures come from
nlp-architect Keras models driven through TFPark; here they are built
directly on the native layer library — same input/output contracts:

- word indices [B, S]; char indices [B, S, W] (chars per word)
- NER → tags [B, S, num_entities]
- SequenceTagger → (pos [B, S, P], chunk [B, S, C])
- IntentEntity → (intent [B, I], tags [B, S, E])

`crf_mode`: the tag head emits scores; CRF training/decoding uses
`ops.crf.crf_loss` / `viterbi_decode` with the model's `transitions` param.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.models.common import ZooModel
from analytics_zoo_tpu.ops import crf as crf_ops


def _char_feature(chars_in, char_vocab: int, char_emb: int, lstm_dim: int,
                  name: str):
    """[B, S, W] → per-word char BiLSTM feature [B, S, 2·lstm_dim]."""
    emb = L.Embedding(char_vocab, char_emb, name=f"{name}_char_emb")(
        chars_in)
    return L.TimeDistributed(
        L.Bidirectional(L.LSTM(lstm_dim, name=f"{name}_char_lstm")),
        name=f"{name}_char_td")(emb)


class NER(ZooModel):
    """`ner.py:21`: word + char features → 2×BiLSTM tagger → entity
    scores. `crf_mode='reg'` adds a learnable transitions matrix used by
    `crf_loss`/`decode`."""

    def __init__(self, num_entities: int, word_vocab_size: int,
                 char_vocab_size: int, word_length: int = 12,
                 word_emb_dim: int = 100, char_emb_dim: int = 30,
                 tagger_lstm_dim: int = 100, dropout: float = 0.5,
                 crf_mode: str = "reg"):
        super().__init__()
        if crf_mode not in ("reg", "pad"):
            raise ValueError(f"Unsupported crf_mode: {crf_mode}")
        self._config = dict(num_entities=num_entities,
                            word_vocab_size=word_vocab_size,
                            char_vocab_size=char_vocab_size,
                            word_length=word_length,
                            word_emb_dim=word_emb_dim,
                            char_emb_dim=char_emb_dim,
                            tagger_lstm_dim=tagger_lstm_dim,
                            dropout=dropout, crf_mode=crf_mode)
        self.num_entities = num_entities
        self.crf_mode = crf_mode
        words = Input(shape=(None,))
        chars = Input(shape=(None, word_length))
        w = L.Embedding(word_vocab_size, word_emb_dim,
                        name="word_emb")(words)
        c = _char_feature(chars, char_vocab_size, char_emb_dim,
                          char_emb_dim, "ner")
        feats = L.merge([w, c], mode="concat", concat_axis=-1)
        feats = L.Dropout(dropout, name="ner_drop")(feats)
        h = L.Bidirectional(L.LSTM(tagger_lstm_dim, return_sequences=True,
                                   name="tagger1"))(feats)
        h = L.Bidirectional(L.LSTM(tagger_lstm_dim, return_sequences=True,
                                   name="tagger2"))(h)
        scores = L.TimeDistributed(
            L.Dense(num_entities, name="tag_dense"), name="tag_td")(h)
        self.model = Model([words, chars], scores)
        self._transitions: Optional[np.ndarray] = None

    @property
    def transitions(self) -> np.ndarray:
        if self._transitions is None:
            self._transitions = np.zeros(
                (self.num_entities, self.num_entities), np.float32)
        return self._transitions

    @transitions.setter
    def transitions(self, v):
        self._transitions = np.asarray(v, np.float32)

    def crf_loss(self, x, tags, mask=None) -> float:
        """Exact CRF NLL of `tags` under the current emissions."""
        emissions = self.model.predict(x, batch_per_thread=len(tags))
        return float(crf_ops.crf_loss(np.asarray(emissions), tags,
                                      self.transitions, mask))

    def decode(self, x, mask=None) -> np.ndarray:
        """Viterbi-decode tag paths (CRF head); emissions argmax when
        transitions are zero degenerates to per-step argmax."""
        emissions = np.asarray(self.model.predict(
            x, batch_per_thread=len(x[0]) if isinstance(x, list) else
            len(x)))
        tags, _ = crf_ops.viterbi_decode(emissions, self.transitions, mask)
        return np.asarray(tags)


class SequenceTagger(ZooModel):
    """`pos_tagging.py:21`: 3 stacked BiLSTMs; softmax pos head + chunk
    head conditioned on the pos features (nlp-architect chunker shape)."""

    def __init__(self, num_pos_labels: int, num_chunk_labels: int,
                 word_vocab_size: int, char_vocab_size: Optional[int] = None,
                 word_length: int = 12, feature_size: int = 100,
                 dropout: float = 0.2, classifier: str = "softmax"):
        super().__init__()
        classifier = classifier.lower()
        if classifier not in ("softmax", "crf"):
            raise ValueError("classifier should be either softmax or crf")
        self._config = dict(num_pos_labels=num_pos_labels,
                            num_chunk_labels=num_chunk_labels,
                            word_vocab_size=word_vocab_size,
                            char_vocab_size=char_vocab_size,
                            word_length=word_length,
                            feature_size=feature_size, dropout=dropout,
                            classifier=classifier)
        words = Input(shape=(None,))
        inputs = [words]
        w = L.Embedding(word_vocab_size, feature_size,
                        name="word_emb")(words)
        feats = w
        if char_vocab_size is not None:
            chars = Input(shape=(None, word_length))
            inputs.append(chars)
            c = _char_feature(chars, char_vocab_size, feature_size // 2,
                              feature_size // 2, "tagger")
            feats = L.merge([w, c], mode="concat", concat_axis=-1)
        h = feats
        for i in range(3):
            h = L.Bidirectional(L.LSTM(feature_size, return_sequences=True,
                                       name=f"bilstm{i}"))(h)
            h = L.Dropout(dropout, name=f"drop{i}")(h)
        pos = L.TimeDistributed(
            L.Dense(num_pos_labels, activation="softmax", name="pos_dense"),
            name="pos_td")(h)
        merged = L.merge([h, pos], mode="concat", concat_axis=-1)
        chunk = L.TimeDistributed(
            L.Dense(num_chunk_labels, activation="softmax",
                    name="chunk_dense"), name="chunk_td")(merged)
        self.model = Model(inputs if len(inputs) > 1 else inputs[0],
                           [pos, chunk])


POSTagger = SequenceTagger


class IntentEntity(ZooModel):
    """`intent_extraction.py:21`: joint intent + slots. Char BiLSTM word
    features + word embeddings → tagger BiLSTM; intent head pools the
    tagger states, entity head tags per step."""

    def __init__(self, num_intents: int, num_entities: int,
                 word_vocab_size: int, char_vocab_size: int,
                 word_length: int = 12, word_emb_dim: int = 100,
                 char_emb_dim: int = 30, char_lstm_dim: int = 30,
                 tagger_lstm_dim: int = 100, dropout: float = 0.2):
        super().__init__()
        self._config = dict(num_intents=num_intents,
                            num_entities=num_entities,
                            word_vocab_size=word_vocab_size,
                            char_vocab_size=char_vocab_size,
                            word_length=word_length,
                            word_emb_dim=word_emb_dim,
                            char_emb_dim=char_emb_dim,
                            char_lstm_dim=char_lstm_dim,
                            tagger_lstm_dim=tagger_lstm_dim,
                            dropout=dropout)
        words = Input(shape=(None,))
        chars = Input(shape=(None, word_length))
        w = L.Embedding(word_vocab_size, word_emb_dim,
                        name="word_emb")(words)
        c = _char_feature(chars, char_vocab_size, char_emb_dim,
                          char_lstm_dim, "intent")
        feats = L.merge([w, c], mode="concat", concat_axis=-1)
        feats = L.Dropout(dropout, name="in_drop")(feats)
        seq = L.Bidirectional(L.LSTM(tagger_lstm_dim, return_sequences=True,
                                     name="tagger"))(feats)
        seq = L.Dropout(dropout, name="tag_drop")(seq)
        intent_feat = L.GlobalMaxPooling1D()(seq)
        intent = L.Dense(num_intents, activation="softmax",
                         name="intent_dense")(intent_feat)
        tags = L.TimeDistributed(
            L.Dense(num_entities, activation="softmax", name="ent_dense"),
            name="ent_td")(seq)
        self.model = Model([words, chars], [intent, tags])
