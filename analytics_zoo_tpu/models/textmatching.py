"""KNRM — kernel-pooling neural ranking model for text matching.

Reference: `models/textmatching/KNRM.scala:75-103`. Takes the concatenation
[B, L1+L2] of query and doc ids (embedding weight sharing is expressed by
slicing one embedding output, as the reference notes), computes the
translation matrix via batched dot, applies `kernel_num` RBF kernels
(mu spaced over [-1, 1], exact-match kernel sigma), log-sum pools, and scores
with a Dense(1) head — sigmoid for classification, linear for ranking
(paired with the `rank_hinge` loss).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.models.common import Ranker, ZooModel
from analytics_zoo_tpu.ops.autograd import Lambda


class KNRM(ZooModel, Ranker):
    def __init__(self, text1_length: int, text2_length: int,
                 vocab_size: Optional[int] = None,
                 embed_size: int = 300,
                 embed_weights: Optional[np.ndarray] = None,
                 train_embed: bool = True, kernel_num: int = 21,
                 sigma: float = 0.1, exact_sigma: float = 0.001,
                 target_mode: str = "ranking"):
        super().__init__()
        if kernel_num < 2:
            raise ValueError("kernel_num must be >= 2")
        if target_mode not in ("ranking", "classification"):
            raise ValueError(f"Unsupported target_mode: {target_mode}")
        self.text1_length = text1_length
        self.text2_length = text2_length
        self.embed_weights = embed_weights
        self.vocab_size = vocab_size if embed_weights is None \
            else embed_weights.shape[0]
        self.embed_size = embed_size if embed_weights is None \
            else embed_weights.shape[1]
        # persist DERIVED sizes so a weights-constructed KNRM reloads (the
        # Embedding layer structure is identical either way; checkpoint
        # weights overwrite the fresh init)
        self._config = dict(text1_length=text1_length,
                            text2_length=text2_length,
                            vocab_size=int(self.vocab_size),
                            embed_size=int(self.embed_size),
                            train_embed=train_embed, kernel_num=kernel_num,
                            sigma=sigma, exact_sigma=exact_sigma,
                            target_mode=target_mode)
        self.train_embed = train_embed
        self.kernel_num = kernel_num
        self.sigma = sigma
        self.exact_sigma = exact_sigma
        self.target_mode = target_mode
        self.model = self.build_model()

    def build_model(self) -> Model:
        L1, L2 = self.text1_length, self.text2_length
        kernel_num = self.kernel_num
        sigma, exact_sigma = self.sigma, self.exact_sigma

        inp = Input(shape=(L1 + L2,))
        embed = L.Embedding(self.vocab_size, self.embed_size,
                            weights=self.embed_weights,
                            trainable=self.train_embed)(inp)

        def kernel_pooling(e):
            q = e[:, :L1]                       # [B, L1, D]
            d = e[:, L1:]                       # [B, L2, D]
            mm = jnp.einsum("bld,bmd->blm", q, d)   # translation matrix
            feats = []
            for i in range(kernel_num):
                mu = 1.0 / (kernel_num - 1) + (2.0 * i) / (kernel_num - 1) - 1.0
                s = sigma
                if mu > 1.0:  # exact-match kernel (`KNRM.scala:87-90`)
                    mu, s = 1.0, exact_sigma
                mm_exp = jnp.exp(-0.5 * (mm - mu) ** 2 / (s * s))
                mm_doc_sum = jnp.sum(mm_exp, axis=2)        # [B, L1]
                mm_log = jnp.log(mm_doc_sum + 1.0)
                feats.append(jnp.sum(mm_log, axis=1))       # [B]
            return jnp.stack(feats, axis=1)                  # [B, K]

        phi = Lambda(kernel_pooling)(embed)
        if self.target_mode == "ranking":
            out = L.Dense(1, init="uniform")(phi)
        else:
            out = L.Dense(1, init="uniform", activation="sigmoid")(phi)
        return Model(inp, out)
