from analytics_zoo_tpu.models.common import ZooModel  # noqa: F401
from analytics_zoo_tpu.models.recommendation import (  # noqa: F401
    NeuralCF, SessionRecommender, UserItemFeature, WideAndDeep)
from analytics_zoo_tpu.models.anomalydetection import (  # noqa: F401
    AnomalyDetector, detect_anomalies, unroll)
from analytics_zoo_tpu.models.textclassification import TextClassifier  # noqa: F401
from analytics_zoo_tpu.models.textmatching import KNRM  # noqa: F401
from analytics_zoo_tpu.models.seq2seq import Seq2seq  # noqa: F401
from analytics_zoo_tpu.models.image import ImageClassifier, resnet  # noqa: F401
