"""Pretrained-artifact interop for the model zoo (VERDICT r4 #4).

Parity target: the reference's zoo loads *published trained models* —
`ObjectDetector.scala` / `ImageClassifier.scala` pull artifacts whose
weights originated in Caffe (`models/caffe/CaffeLoader.scala:718`) or
other engines. Here the in-repo importers (`caffe/`, `onnx/`) decode the
foreign artifact into a native Model, and `transfer_weights` maps its
parameters onto the zoo architecture by shape-matched positional
assignment — so `load_image_classifier(..., weights_path="caffe:...")`
round-trips a pretrained artifact into the zoo entry point.

Spec grammar (the `weights_path` argument of the zoo loaders):
- `"caffe:<deploy.prototxt>,<weights.caffemodel>"`
- `"onnx:<model.onnx>"`
- anything without a scheme prefix → native checkpoint (load_weights)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np


def parse_weight_spec(spec: str):
    """→ ("caffe", (def_path, model_path)) | ("onnx", (path,)) | None
    (None = native checkpoint path, no scheme)."""
    if spec.startswith("caffe:"):
        rest = spec[len("caffe:"):]
        if "," not in rest:
            raise ValueError(
                "caffe weights spec is 'caffe:<deploy.prototxt>,"
                f"<weights.caffemodel>'; got {spec!r}")
        def_path, model_path = rest.split(",", 1)
        return "caffe", (def_path, model_path)
    if spec.startswith("onnx:"):
        return "onnx", (spec[len("onnx:"):],)
    return None


def load_foreign_model(kind: str, args: Tuple[str, ...]):
    """Import the artifact with the in-repo importers → native Model."""
    if kind == "caffe":
        from analytics_zoo_tpu.caffe import load_caffe
        return load_caffe(*args)
    if kind == "onnx":
        from analytics_zoo_tpu.onnx import load_onnx
        return load_onnx(*args)
    raise ValueError(f"Unknown foreign model kind {kind!r}")


def _natural_key(name: str):
    """'dense_10' sorts after 'dense_2' (jax tree ops re-sort dict keys
    LEXICOGRAPHICALLY — relying on insertion order silently shuffles
    10+ auto-numbered layers; same hazard `engine._remap_loaded`
    documents)."""
    import re
    m = re.match(r"^(.*)_(\d+)$", name)
    return (m.group(1), int(m.group(2))) if m else (name, -1)


def _ordered_leaves(model, params, prefix="") -> List[Tuple[str, Any]]:
    """(path, array) leaves in STRUCTURAL order: the model's layer order
    (`_ordered_layers`, recursing into nested Sequential/Model), natural-
    sorted keys inside each layer's subtree."""
    out: List[Tuple[str, Any]] = []

    def flat(tree, pfx):
        if isinstance(tree, dict):
            for k in sorted(tree, key=_natural_key):
                flat(tree[k], f"{pfx}/{k}" if pfx else k)
        else:
            out.append((pfx, np.asarray(tree)))

    layers = model._ordered_layers() \
        if hasattr(model, "_ordered_layers") else []
    if not layers:
        flat(params, prefix)
        return out
    for layer in layers:
        sub = params.get(layer.name)
        if sub is None:
            continue
        lp = f"{prefix}/{layer.name}" if prefix else layer.name
        if hasattr(layer, "_ordered_layers") and layer._ordered_layers() \
                and isinstance(sub, dict):
            out.extend(_ordered_leaves(layer, sub, lp))
        else:
            flat(sub, lp)
    return out


def _set_path(tree: Dict, path: List[str], value) -> None:
    node = tree
    for k in path[:-1]:
        node = node[k]
    node[path[-1]] = value


def transfer_weights(src_model, dst_model, strict: bool = True
                     ) -> Dict[str, int]:
    """Map src params onto dst by shape-matched positional assignment:
    walk both models' leaves in STRUCTURAL layer order, consume the first
    unused src leaf whose shape+dtype match. The importers already
    normalize layouts (caffe OIHW → HWIO etc.), so an architecture-equal
    artifact matches exactly.

    strict=True  → every dst leaf must match (full round-trip; identical
                   forward guaranteed for architecture-equal models).
    strict=False → unmatched dst leaves keep their initialization
                   (backbone-only transfer, the CaffeLoader fine-tune
                   pattern); returns counts for the caller to log.
    """
    if src_model.params is None:
        raise ValueError("source model has no parameters")
    if dst_model.params is None:
        raise ValueError("destination model must be built first")
    src = _ordered_leaves(src_model, jax.device_get(src_model.params))
    used = [False] * len(src)

    import copy
    new_params = copy.deepcopy(jax.device_get(dst_model.params))
    dst_leaves = _ordered_leaves(dst_model, new_params)

    matched = 0
    missing: List[str] = []
    for path, want in dst_leaves:
        for i, (_, arr) in enumerate(src):
            if not used[i] and arr.shape == want.shape \
                    and arr.dtype == want.dtype:
                used[i] = True
                matched += 1
                _set_path(new_params, path.split("/"), arr)
                break
        else:
            missing.append(f"{path}{tuple(want.shape)}")

    if missing and strict:
        raise ValueError(
            f"transfer_weights: {len(missing)} destination leaves have no "
            f"shape-matching source weight (first: {missing[:5]}); the "
            "artifact's architecture does not cover this zoo model — pass "
            "strict=False for a backbone-only transfer")
    dst_model.params = new_params
    return {"matched": matched, "unmatched_dst": len(missing),
            "unused_src": int(len(src) - sum(used))}


def apply_weight_spec(model, spec: str, strict: bool = True,
                      parsed: Optional[Tuple] = None):
    """Resolve a weights spec against a built native model. Returns the
    transfer stats dict for foreign artifacts, None for native paths
    (caller falls back to load_weights). Callers that already ran
    `parse_weight_spec` (the zoo loaders decide build-vs-load from it)
    pass the result as `parsed` so the grammar is evaluated once."""
    if parsed is None:
        parsed = parse_weight_spec(spec)
    if parsed is None:
        return None
    kind, args = parsed
    foreign = load_foreign_model(kind, args)
    return transfer_weights(foreign, model, strict=strict)
