"""Image classification: ResNet builder + ImageClassifier wrapper.

The reference ships pretrained-model *loaders* plus a ResNet-50 training
example (`zoo/.../examples/resnet/`, `models/image/imageclassification/`).
Zero-egress here, so the zoo provides the architectures natively: a ResNet
v1.5 family (18/34/50) built NHWC with BatchNorm — the layout/blocking the
MXU wants — and an `ImageClassifier` that pairs a model with its
preprocessing pipeline (`ImageClassifier.scala` + label-map surface).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.models.common import ZooModel

_CONFIGS = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
}


def _conv_bn(x, filters, k, stride=1, activation="relu"):
    x = L.Convolution2D(filters, k, k, subsample=(stride, stride),
                        border_mode="same", use_bias=False)(x)
    x = L.BatchNormalization()(x)
    if activation:
        x = L.Activation(activation)(x)
    return x


def _basic_block(x, filters, stride):
    shortcut = x
    y = _conv_bn(x, filters, 3, stride)
    y = _conv_bn(y, filters, 3, 1, activation=None)
    if stride != 1 or x.shape[-1] != filters:
        shortcut = _conv_bn(x, filters, 1, stride, activation=None)
    out = L.merge([y, shortcut], mode="sum")
    return L.Activation("relu")(out)


def _bottleneck_block(x, filters, stride):
    shortcut = x
    y = _conv_bn(x, filters, 1, 1)
    y = _conv_bn(y, filters, 3, stride)
    y = _conv_bn(y, 4 * filters, 1, 1, activation=None)
    if stride != 1 or x.shape[-1] != 4 * filters:
        shortcut = _conv_bn(x, 4 * filters, 1, stride, activation=None)
    out = L.merge([y, shortcut], mode="sum")
    return L.Activation("relu")(out)


def resnet(depth: int = 50, class_num: int = 1000,
           input_shape: Sequence[int] = (224, 224, 3),
           include_top: bool = True) -> Model:
    """ResNet v1.5 (stride-2 on the 3x3 conv of bottlenecks, the standard
    TPU/GPU variant)."""
    if depth not in _CONFIGS:
        raise ValueError(f"Unsupported depth {depth}; choose {list(_CONFIGS)}")
    kind, reps = _CONFIGS[depth]
    block = _basic_block if kind == "basic" else _bottleneck_block

    inp = Input(shape=tuple(input_shape))
    x = L.Convolution2D(64, 7, 7, subsample=(2, 2), border_mode="same",
                        use_bias=False)(inp)
    x = L.BatchNormalization()(x)
    x = L.Activation("relu")(x)
    x = L.MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                       border_mode="same")(x)
    filters = 64
    for stage, n in enumerate(reps):
        for i in range(n):
            stride = 2 if (i == 0 and stage > 0) else 1
            x = block(x, filters, stride)
        filters *= 2
    x = L.GlobalAveragePooling2D()(x)
    if include_top:
        x = L.Dense(class_num, activation="softmax")(x)
    return Model(inp, x)


def _inception_block(x, c1, c3r, c3, c5r, c5, pp):
    """One GoogLeNet inception module: 1x1 / 1x1→3x3 / 1x1→5x5 /
    pool→1x1 branches concatenated on channels."""
    b1 = _conv_bn(x, c1, 1)
    b3 = _conv_bn(_conv_bn(x, c3r, 1), c3, 3)
    b5 = _conv_bn(_conv_bn(x, c5r, 1), c5, 5)
    bp = L.MaxPooling2D(pool_size=(3, 3), strides=(1, 1),
                        border_mode="same")(x)
    bp = _conv_bn(bp, pp, 1)
    return L.merge([b1, b3, b5, bp], mode="concat", concat_axis=-1)


# (branch filter tables of GoogLeNet/Inception-v1, stage 3a..5b)
_INCEPTION_V1 = [
    ("3a", 64, 96, 128, 16, 32, 32), ("3b", 128, 128, 192, 32, 96, 64),
    ("pool", ),
    ("4a", 192, 96, 208, 16, 48, 64), ("4b", 160, 112, 224, 24, 64, 64),
    ("4c", 128, 128, 256, 24, 64, 64), ("4d", 112, 144, 288, 32, 64, 64),
    ("4e", 256, 160, 320, 32, 128, 128),
    ("pool", ),
    ("5a", 256, 160, 320, 32, 128, 128),
    ("5b", 384, 192, 384, 48, 128, 128),
]


def inception_v1(class_num: int = 1000,
                 input_shape: Sequence[int] = (224, 224, 3),
                 dropout: float = 0.4) -> Model:
    """GoogLeNet/Inception-v1 — the reference's headline ImageNet training
    model (`zoo/examples/inception/ImageNet2012.scala`, Train.scala;
    BigDL `Inception_v1_NoAuxClassifier`). NHWC with BatchNorm after every
    conv (the bn variant — plain v1 needs LRN, which buys nothing on TPU);
    no auxiliary heads (they exist to aid very deep pre-BN training)."""
    inp = Input(shape=tuple(input_shape))
    x = _conv_bn(inp, 64, 7, stride=2)
    x = L.MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                       border_mode="same")(x)
    x = _conv_bn(x, 64, 1)
    x = _conv_bn(x, 192, 3)
    x = L.MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                       border_mode="same")(x)
    for row in _INCEPTION_V1:
        if row[0] == "pool":
            x = L.MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                               border_mode="same")(x)
        else:
            _, c1, c3r, c3, c5r, c5, pp = row
            x = _inception_block(x, c1, c3r, c3, c5r, c5, pp)
    x = L.GlobalAveragePooling2D()(x)
    if dropout > 0:
        x = L.Dropout(dropout)(x)
    x = L.Dense(class_num, activation="softmax")(x)
    return Model(inp, x)


def lenet(class_num: int = 10,
          input_shape: Sequence[int] = (1, 28, 28)) -> Model:
    """LeNet-5 (BigDL `models/lenet`; the canonical Caffe artifact —
    conv20-pool-conv50-pool-fc500-fc10). Channels-FIRST like its Caffe
    lineage so an imported artifact's dense kernels transfer
    weight-for-weight — the flatten order matches
    (`models/pretrained.py` shape-matched transfer)."""
    inp = Input(shape=tuple(input_shape))
    x = L.Convolution2D(20, 5, 5, border_mode="valid",
                        dim_ordering="th")(inp)
    x = L.MaxPooling2D(pool_size=(2, 2), strides=(2, 2),
                       dim_ordering="th")(x)
    x = L.Convolution2D(50, 5, 5, border_mode="valid",
                        dim_ordering="th")(x)
    x = L.MaxPooling2D(pool_size=(2, 2), strides=(2, 2),
                       dim_ordering="th")(x)
    x = L.Flatten()(x)
    x = L.Dense(500, activation="relu")(x)
    x = L.Dense(class_num, activation="softmax")(x)
    return Model(inp, x)


class ImageClassifier(ZooModel):
    """Model + preprocessing + label map (`models/image/imageclassification/
    ImageClassifier.scala` surface)."""

    def __init__(self, depth: int = 50, class_num: int = 1000,
                 input_shape: Sequence[int] = (224, 224, 3),
                 label_map: Optional[Dict[int, str]] = None,
                 arch: str = "resnet"):
        super().__init__()
        # json keys are strings: normalize to int here, stringify in config
        self.label_map = {int(k): v for k, v in (label_map or {}).items()}
        self._config = dict(depth=depth, class_num=class_num,
                            input_shape=list(input_shape),
                            label_map={str(k): v
                                       for k, v in self.label_map.items()},
                            arch=arch)
        if arch == "inception-v1":
            self.model = inception_v1(class_num, input_shape)
        elif arch == "resnet":
            self.model = resnet(depth, class_num, input_shape)
        elif arch == "lenet":
            self.model = lenet(class_num, input_shape)
        else:
            raise ValueError(
                f"Unknown arch {arch!r}: resnet|inception-v1|lenet")

    def top_n(self, probs, top_n: int = 5) -> List[List]:
        """Per-row top-N (label, prob) via the label map — shared by
        predict_image_set and the classification_zoo config path."""
        out = []
        for p in np.asarray(probs):
            top = np.argsort(-p)[:top_n]
            out.append([(self.label_map.get(int(i), int(i)), float(p[i]))
                        for i in top])
        return out

    def predict_image_set(self, image_set, top_n: int = 5,
                          batch_per_thread: int = 8) -> List[List]:
        """Classify an ImageSet; returns per-image top-N (label, prob)."""
        x = np.stack(image_set.images).astype(np.float32)
        probs = self.predict(x, batch_per_thread=batch_per_thread)
        return self.top_n(probs, top_n)
