"""Seq2seq — RNN encoder/decoder with bridge, teacher forcing, and greedy
inference.

Reference: `models/seq2seq/Seq2seq.scala:59-103` (`RNNEncoder`/`RNNDecoder`
stacks, optional `Bridge` mapping encoder final states to decoder initial
states, optional generator head; `infer` feeds predictions back step by
step). The reference threads JVM state tables between graph nodes; here the
encoder/decoder are explicit `lax.scan`s over cell steps — states are just
pytree carries, and the whole (encode → bridge → teacher-forced decode)
train step is one XLA program.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.keras.engine import KerasNet, Params
from analytics_zoo_tpu.models.common import ZooModel


def _make_cells(rnn_type: str, hidden_sizes: Sequence[int], prefix: str
                ) -> List[L._Recurrent]:
    cls = {"lstm": L.LSTM, "gru": L.GRU, "simplernn": L.SimpleRNN}[
        rnn_type.lower()]
    return [cls(h, return_sequences=True, name=f"{prefix}_{i}")
            for i, h in enumerate(hidden_sizes)]


def _run_rnn(cell: L._Recurrent, params, x, carry=None):
    """Scan one recurrent layer over [B, T, F]; returns (seq, final_carry)."""
    if carry is None:
        carry = cell.initial_state(x.shape[0])
    xs = jnp.swapaxes(x, 0, 1)

    def body(c, x_t):
        c, out = cell.step(params, c, x_t)
        return c, out

    carry, outs = jax.lax.scan(body, carry, xs)
    return jnp.swapaxes(outs, 0, 1), carry


class _Seq2seqNet(KerasNet):
    """Internal KerasNet: apply([enc_input, dec_input]) -> decoder outputs."""

    def __init__(self, encoder_cells, decoder_cells, bridge: Optional[str],
                 generator_units: Optional[int]):
        super().__init__()
        self.encoder_cells = encoder_cells
        self.decoder_cells = decoder_cells
        self.bridge = bridge
        self.generator_units = generator_units

    def build(self, rng, input_shape):
        enc_shape, dec_shape = input_shape
        params: Params = {}
        shape = enc_shape
        for cell in self.encoder_cells:
            rng, sub = jax.random.split(rng)
            params[cell.name] = cell.build(sub, shape)
            shape = cell.compute_output_shape(shape)
        shape = dec_shape
        for cell in self.decoder_cells:
            rng, sub = jax.random.split(rng)
            params[cell.name] = cell.build(sub, shape)
            shape = cell.compute_output_shape(shape)
        if self.bridge == "dense":
            # one Dense per encoder state tensor per layer
            for i, (e, d) in enumerate(zip(self.encoder_cells,
                                           self.decoder_cells)):
                rng, sub = jax.random.split(rng)
                n_states = 2 if isinstance(e, L.LSTM) else 1
                ks = jax.random.split(sub, n_states)
                params[f"bridge_{i}"] = [
                    {"kernel": jax.nn.initializers.glorot_uniform()(
                        ks[j], (e.output_dim, d.output_dim), jnp.float32),
                     "bias": jnp.zeros((d.output_dim,), jnp.float32)}
                    for j in range(n_states)]
        elif self.bridge is not None:
            raise ValueError(f"Unsupported bridge: {self.bridge}")
        if self.generator_units:
            rng, sub = jax.random.split(rng)
            params["generator"] = {
                "kernel": jax.nn.initializers.glorot_uniform()(
                    sub, (self.decoder_cells[-1].output_dim,
                          self.generator_units), jnp.float32),
                "bias": jnp.zeros((self.generator_units,), jnp.float32)}
        return params

    # -- pieces ------------------------------------------------------------
    def encode(self, params, x):
        states = []
        for cell in self.encoder_cells:
            x, carry = _run_rnn(cell, params[cell.name], x)
            states.append(carry)
        return x, states

    def _bridge_states(self, params, states):
        if self.bridge is None:
            return states
        out = []
        for i, carry in enumerate(states):
            maps = params[f"bridge_{i}"]
            if isinstance(carry, tuple):
                out.append(tuple(
                    jnp.tanh(s @ m["kernel"] + m["bias"])
                    for s, m in zip(carry, maps)))
            else:
                out.append(jnp.tanh(carry @ maps[0]["kernel"]
                                    + maps[0]["bias"]))
        return out

    def decode(self, params, y_in, init_states):
        x = y_in
        for cell, carry in zip(self.decoder_cells, init_states):
            x, _ = _run_rnn(cell, params[cell.name], x, carry)
        if self.generator_units:
            g = params["generator"]
            x = x @ g["kernel"] + g["bias"]
        return x

    def apply(self, params, inputs, *, training=False, rng=None):
        enc_in, dec_in = inputs
        _, states = self.encode(params, enc_in)
        init = self._bridge_states(params, states)
        return self.decode(params, dec_in, init)

    def compute_output_shape(self, input_shape):
        return None


class Seq2seq(ZooModel):
    """`Seq2seq(rnn_type, encoder_hidden, decoder_hidden, bridge=...)`.
    Train with x = [encoder_seq, decoder_input_seq] (teacher forcing),
    y = decoder_target_seq."""

    def __init__(self, rnn_type: str = "lstm",
                 encoder_hidden: Sequence[int] = (32,),
                 decoder_hidden: Sequence[int] = (32,),
                 bridge: Optional[str] = None,
                 generator_units: Optional[int] = None):
        super().__init__()
        if len(encoder_hidden) != len(decoder_hidden):
            raise ValueError(
                "rnn encoder and decoder should have the same number of "
                "layers")  # `Seq2seq.scala:175-176`
        if bridge is None:
            for e, d in zip(encoder_hidden, decoder_hidden):
                if e != d:
                    raise ValueError("without a bridge, encoder/decoder "
                                     "hidden sizes must match")
        self._config = dict(rnn_type=rnn_type,
                            encoder_hidden=list(encoder_hidden),
                            decoder_hidden=list(decoder_hidden),
                            bridge=bridge, generator_units=generator_units)
        enc = _make_cells(rnn_type, encoder_hidden, "enc")
        dec = _make_cells(rnn_type, decoder_hidden, "dec")
        self.model = _Seq2seqNet(enc, dec, bridge, generator_units)

    def infer(self, enc_input: np.ndarray, start_sign: np.ndarray,
              max_seq_len: int = 30) -> np.ndarray:
        """Greedy autoregressive decode feeding predictions back
        (`Seq2seq.scala` infer). start_sign: [B, F] first decoder input."""
        net = self.model
        params = net.params
        if params is None:
            raise ValueError("Model has no parameters; fit or build first")
        _, states = net.encode(params, jnp.asarray(enc_input))
        carries = net._bridge_states(params, states)
        y_t = jnp.asarray(start_sign)
        outs = []
        for _ in range(max_seq_len):
            x_t = y_t
            new_carries = []
            for cell, carry in zip(net.decoder_cells, carries):
                carry, x_t = cell.step(params[cell.name], carry, x_t)
                new_carries.append(carry)
            carries = new_carries
            if net.generator_units:
                g = params["generator"]
                x_t = x_t @ g["kernel"] + g["bias"]
            outs.append(x_t)
            y_t = x_t
        return np.stack([np.asarray(o) for o in outs], axis=1)
