"""Object detection: anchors, box codec, NMS, MultiBox loss, SSD head,
ObjectDetector API.

Reference: `models/image/objectdetection/` — `BboxUtil.scala:1033` (box
encode/decode/jaccard), `SSDGraph.scala:220` (SSD assembly),
`MultiBoxLoss.scala:622` (matched smooth-L1 + hard-negative-mined CE),
`ObjectDetector` + postprocessing (`ScaleDetection`, label maps). TPU-first
choices: all postprocess math is batched jnp on fixed-size tensors (no
dynamic per-image box lists inside jit); NMS is the O(N^2) masked iterative
form with a static `max_out` — the XLA-friendly formulation — run per class
via vmap."""

from __future__ import annotations

import itertools
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.keras.engine import Layer


# ---------------------------------------------------------------------------
# Anchors (`BboxUtil` prior boxes) — corner-form [cx, cy, w, h] normalized
# ---------------------------------------------------------------------------
def multibox_priors(feature_sizes: Sequence[int],
                    scales: Sequence[float],
                    aspect_ratios: Sequence[float] = (1.0, 2.0, 0.5)
                    ) -> np.ndarray:
    """Per feature map of size SxS: one anchor per (cell, scale, ratio).
    Returns [A, 4] center-form normalized anchors."""
    if len(scales) != len(feature_sizes):
        raise ValueError("one scale per feature map")
    out = []
    for S, scale in zip(feature_sizes, scales):
        for i, j in itertools.product(range(S), range(S)):
            cx, cy = (j + 0.5) / S, (i + 0.5) / S
            for r in aspect_ratios:
                out.append([cx, cy, scale * math.sqrt(r),
                            scale / math.sqrt(r)])
    return np.asarray(out, np.float32)


def center_to_corner(boxes):
    cx, cy, w, h = jnp.split(boxes, 4, axis=-1)
    return jnp.concatenate(
        [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)


def corner_to_center(boxes):
    x1, y1, x2, y2 = jnp.split(boxes, 4, axis=-1)
    return jnp.concatenate(
        [(x1 + x2) / 2, (y1 + y2) / 2, x2 - x1, y2 - y1], axis=-1)


# ---------------------------------------------------------------------------
# Box codec (`BboxUtil.encodeBBox/decodeBBox`, SSD variances 0.1/0.2)
# ---------------------------------------------------------------------------
VARIANCES = (0.1, 0.1, 0.2, 0.2)


def encode_boxes(gt_corner, anchors_center,
                 variances: Sequence[float] = VARIANCES):
    """Ground-truth corner boxes [.., 4] vs anchors [.., 4] center-form ->
    regression targets."""
    gt = corner_to_center(gt_corner)
    vx, vy, vw, vh = variances
    acx, acy, aw, ah = jnp.split(anchors_center, 4, axis=-1)
    gcx, gcy, gw, gh = jnp.split(gt, 4, axis=-1)
    return jnp.concatenate([
        (gcx - acx) / (aw * vx),
        (gcy - acy) / (ah * vy),
        jnp.log(jnp.maximum(gw, 1e-8) / aw) / vw,
        jnp.log(jnp.maximum(gh, 1e-8) / ah) / vh,
    ], axis=-1)


def decode_boxes(loc, anchors_center,
                 variances: Sequence[float] = VARIANCES):
    """Regression outputs -> corner boxes (inverse of encode_boxes)."""
    vx, vy, vw, vh = variances
    acx, acy, aw, ah = jnp.split(anchors_center, 4, axis=-1)
    lx, ly, lw, lh = jnp.split(loc, 4, axis=-1)
    cx = lx * vx * aw + acx
    cy = ly * vy * ah + acy
    w = jnp.exp(lw * vw) * aw
    h = jnp.exp(lh * vh) * ah
    return center_to_corner(jnp.concatenate([cx, cy, w, h], axis=-1))


def iou_matrix(a_corner, b_corner):
    """[N,4] x [M,4] corner boxes -> [N,M] IoU (`BboxUtil.jaccard`)."""
    ax1, ay1, ax2, ay2 = jnp.split(a_corner, 4, axis=-1)       # [N,1]
    bx1, by1, bx2, by2 = [v[:, 0] for v in jnp.split(b_corner, 4, axis=-1)]
    ix1 = jnp.maximum(ax1, bx1[None, :])
    iy1 = jnp.maximum(ay1, by1[None, :])
    ix2 = jnp.minimum(ax2, bx2[None, :])
    iy2 = jnp.minimum(ay2, by2[None, :])
    inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
    area_a = jnp.clip(ax2 - ax1, 0) * jnp.clip(ay2 - ay1, 0)
    area_b = jnp.clip(bx2 - bx1, 0) * jnp.clip(by2 - by1, 0)
    return inter / jnp.maximum(area_a + area_b[None, :] - inter, 1e-8)


# ---------------------------------------------------------------------------
# NMS — static-shape masked iteration (XLA-friendly)
# ---------------------------------------------------------------------------
def _nms_from_iou(iou, scores, iou_threshold: float, max_out: int):
    n = scores.shape[0]

    def body(carry, _):
        alive, = carry
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        valid = masked[best] > -jnp.inf
        suppress = iou[best] > iou_threshold
        alive = alive & ~suppress & \
            ~jax.nn.one_hot(best, n, dtype=bool)
        return (alive,), (best, valid)

    (_, ), (idx, valid) = jax.lax.scan(
        body, (jnp.ones((n,), bool),), None, length=max_out)
    return idx, valid


def nms(boxes, scores, iou_threshold: float = 0.45, max_out: int = 100):
    """Returns (indices[max_out], valid[max_out]) — fixed-size outputs so
    the whole postprocess jits (`BboxUtil.nms` with maxOutputSize)."""
    max_out = min(max_out, boxes.shape[0])
    return _nms_from_iou(iou_matrix(boxes, boxes), scores, iou_threshold,
                         max_out)


def nms_multiclass(boxes, class_scores, iou_threshold: float = 0.45,
                   max_out: int = 100):
    """Per-class NMS sharing ONE IoU matrix: boxes [A,4],
    class_scores [C, A] -> (idx [C, max_out], valid [C, max_out])."""
    max_out = min(max_out, boxes.shape[0])
    iou = iou_matrix(boxes, boxes)
    return jax.vmap(
        lambda s: _nms_from_iou(iou, s, iou_threshold, max_out))(
            class_scores)


# ---------------------------------------------------------------------------
# Target assignment + MultiBox loss (`MultiBoxLoss.scala:622`)
# ---------------------------------------------------------------------------
def match_anchors(gt_boxes, gt_labels, anchors_center,
                  iou_threshold: float = 0.5):
    """Per-image assignment: each anchor takes the best-overlapping gt if
    IoU >= threshold (label 0 = background). gt_boxes [G,4] corner (padded
    rows w/ zeros allowed), gt_labels [G] int (0 for padding)."""
    anchors_corner = center_to_corner(anchors_center)
    iou = iou_matrix(anchors_corner, gt_boxes)          # [A, G]
    valid_gt = gt_labels > 0
    iou = jnp.where(valid_gt[None, :], iou, 0.0)
    best_gt = jnp.argmax(iou, axis=1)                   # [A]
    best_iou = jnp.max(iou, axis=1)
    # force-match: every valid gt claims its best anchor AND that anchor's
    # assignment is overridden to this gt (the reference's bipartite step,
    # `BboxUtil.matchBipartite`) — otherwise a low-IoU gt could be matched
    # nowhere while its claimed anchor regresses toward a different gt.
    best_anchor = jnp.argmax(iou, axis=0)               # [G]
    A = iou.shape[0]
    g_idx = jnp.arange(gt_labels.shape[0])
    upd = jnp.where(valid_gt, best_anchor, A)           # invalid -> dropped
    forced = jnp.zeros(A, bool).at[upd].set(True, mode="drop")
    best_gt = best_gt.at[upd].set(g_idx, mode="drop")
    matched = (best_iou >= iou_threshold) | forced
    labels = jnp.where(matched, gt_labels[best_gt], 0)
    target_boxes = gt_boxes[best_gt]                    # corner form
    loc_targets = encode_boxes(target_boxes, anchors_center)
    return labels, loc_targets, matched


def smooth_l1(x):
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


def multibox_loss(conf_logits, loc_preds, labels, loc_targets, matched,
                  neg_pos_ratio: float = 3.0):
    """Per-batch SSD loss: smooth-L1 on matched anchors + CE with hard
    negative mining at `neg_pos_ratio` (`MultiBoxLoss.scala` semantics).
    Shapes: conf [B,A,C], loc [B,A,4], labels [B,A], matched [B,A]."""
    pos = matched.astype(jnp.float32)
    n_pos = jnp.maximum(jnp.sum(pos, axis=1), 1.0)             # [B]

    loc_l = jnp.sum(smooth_l1(loc_preds - loc_targets), axis=-1)
    loc_loss = jnp.sum(loc_l * pos, axis=1) / n_pos

    logp = jax.nn.log_softmax(conf_logits, axis=-1)
    ce = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    # hard negative mining: top-k background losses per image
    neg_ce = jnp.where(matched, -jnp.inf, ce)
    rank = jnp.argsort(jnp.argsort(-neg_ce, axis=1), axis=1)   # rank of each
    n_neg = jnp.minimum(neg_pos_ratio * n_pos,
                        jnp.sum(~matched, axis=1))             # [B]
    neg_mask = rank < n_neg[:, None]
    conf_loss = (jnp.sum(ce * pos, axis=1)
                 + jnp.sum(jnp.where(neg_mask, ce, 0.0), axis=1)) / n_pos
    return jnp.mean(loc_loss + conf_loss)


# ---------------------------------------------------------------------------
# SSD head + detector
# ---------------------------------------------------------------------------
class _SSDHead(Layer):
    """Conv head over a feature map: per-cell loc(4*K) + conf(C*K)."""

    def __init__(self, n_anchors_per_cell: int, n_classes: int, **kw):
        super().__init__(**kw)
        self.K, self.C = n_anchors_per_cell, n_classes

    def build(self, rng, input_shape):
        cin = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        def conv_init(key, co):
            return (jax.random.normal(key, (3, 3, cin, co))
                    / math.sqrt(9 * cin)).astype(jnp.float32)
        return {"loc_w": conv_init(k1, 4 * self.K),
                "loc_b": jnp.zeros((4 * self.K,), jnp.float32),
                "conf_w": conv_init(k2, self.C * self.K),
                "conf_b": jnp.zeros((self.C * self.K,), jnp.float32)}

    def call(self, params, x, *, training=False, rng=None):
        def conv(w, b):
            y = jax.lax.conv_general_dilated(
                x, w, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            return y + b
        B = x.shape[0]
        loc = conv(params["loc_w"], params["loc_b"]).reshape(B, -1, 4)
        conf = conv(params["conf_w"], params["conf_b"]).reshape(
            B, -1, self.C)
        return jnp.concatenate([loc.reshape(B, -1),
                                conf.reshape(B, -1)], axis=-1)

    def compute_output_shape(self, input_shape):
        S = input_shape[1]
        return (input_shape[0], S * S * self.K * (4 + self.C))


def build_ssd(n_classes: int, image_size: int = 64,
              feature_sizes: Optional[Sequence[int]] = None,
              scales: Sequence[float] = (0.3, 0.6),
              aspect_ratios: Sequence[float] = (1.0, 2.0, 0.5)):
    """Small trainable SSD (`SSDGraph.scala:220` shape): shared conv trunk,
    one head per scale. Returns (model, anchors[A,4] center-form). `apply`
    output: [B, A*4 + A*C] (loc || conf), split by `split_ssd_output`."""
    trunk_sizes = (image_size // 8, image_size // 16)
    if feature_sizes is None:
        feature_sizes = trunk_sizes
    elif tuple(feature_sizes) != trunk_sizes:
        raise ValueError(
            f"feature_sizes {tuple(feature_sizes)} do not match the trunk's "
            f"/8 and /16 maps {trunk_sizes} for image_size={image_size}")
    K = len(aspect_ratios)
    inp = Input(shape=(image_size, image_size, 3))
    x = L.Convolution2D(16, 3, 3, border_mode="same", activation="relu")(inp)
    x = L.MaxPooling2D()(x)                              # /2
    x = L.Convolution2D(32, 3, 3, border_mode="same", activation="relu")(x)
    x = L.MaxPooling2D()(x)                              # /4
    x = L.Convolution2D(64, 3, 3, border_mode="same", activation="relu")(x)
    f1 = L.MaxPooling2D()(x)                             # /8 -> S=8 @ 64px
    head1 = _SSDHead(K, n_classes, name="ssd_head1")(f1)
    f2 = L.MaxPooling2D()(f1)                            # /16 -> S=4
    head2 = _SSDHead(K, n_classes, name="ssd_head2")(f2)
    out = L.merge([head1, head2], mode="concat", concat_axis=-1)
    model = Model(inp, out)
    anchors = multibox_priors(feature_sizes, scales, aspect_ratios)
    return model, anchors


def split_ssd_output(flat, n_anchors_per_map: Sequence[int], n_classes: int):
    """[B, sum_m Am*(4+C)] -> loc [B, A, 4], conf [B, A, C] (per-map chunks
    carry loc||conf contiguously)."""
    locs, confs = [], []
    off = 0
    for A in n_anchors_per_map:
        locs.append(flat[:, off:off + A * 4].reshape(-1, A, 4))
        off += A * 4
        confs.append(flat[:, off:off + A * n_classes]
                     .reshape(-1, A, n_classes))
        off += A * n_classes
    return jnp.concatenate(locs, axis=1), jnp.concatenate(confs, axis=1)


def decode_detections(flat, anchors, n_anchors_per_map: Sequence[int],
                      n_classes: int, score_threshold: float = 0.01,
                      iou_threshold: float = 0.45, max_out: int = 100
                      ) -> List[Dict[int, Tuple[np.ndarray, np.ndarray]]]:
    """Flat SSD output -> per-image {class: (scores desc, boxes [K,4])}
    (`BboxUtil.decodeBatchOutput` shape: per-image per-class RoiLabels).
    The decode + per-class NMS runs batched under jit; only the final
    ragged filtering is host-side."""
    loc, conf = split_ssd_output(jnp.asarray(flat), n_anchors_per_map,
                                 n_classes)
    boxes = decode_boxes(loc, anchors[None])                   # [B, A, 4]
    probs = jax.nn.softmax(conf, axis=-1)
    idx, valid = jax.vmap(
        lambda bx, pr: nms_multiclass(bx, pr.T[1:], iou_threshold,
                                      max_out))(boxes, probs)
    idx, valid = np.asarray(idx), np.asarray(valid)
    boxes_np, probs_np = np.asarray(boxes), np.asarray(probs)
    out: List[Dict[int, Tuple[np.ndarray, np.ndarray]]] = []
    for b in range(boxes_np.shape[0]):
        per_cls: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for c in range(1, n_classes):                          # skip bg
            ids = idx[b, c - 1][valid[b, c - 1]]
            if not len(ids):
                continue
            scores = probs_np[b, ids, c]
            keep = scores >= score_threshold
            if not keep.any():
                continue
            order = np.argsort(-scores[keep], kind="stable")
            per_cls[c] = (scores[keep][order], boxes_np[b, ids][keep][order])
        out.append(per_cls)
    return out


class ObjectDetector:
    """`ObjectDetector` surface: model + anchors + label map, with the
    `ScaleDetection`-style postprocess (decode, per-class NMS, score
    filter) returning per-image [label, score, x1, y1, x2, y2] rows."""

    def __init__(self, model, anchors: np.ndarray,
                 n_anchors_per_map: Sequence[int], n_classes: int,
                 label_map: Optional[Dict[int, str]] = None):
        self.model = model
        self.anchors = jnp.asarray(anchors)
        self.n_anchors_per_map = list(n_anchors_per_map)
        self.n_classes = n_classes
        self.label_map = label_map or {}

    def detect_raw(self, images: np.ndarray,
                   score_threshold: float = 0.01,
                   iou_threshold: float = 0.45, max_out: int = 100
                   ) -> List[Dict[int, Tuple[np.ndarray, np.ndarray]]]:
        """Per-image {class: (scores, boxes)} with a low score floor —
        the evaluator's input form (decoded batch output)."""
        flat = self.model.predict(np.asarray(images, np.float32),
                                  batch_per_thread=8)
        return decode_detections(flat, self.anchors,
                                 self.n_anchors_per_map, self.n_classes,
                                 score_threshold, iou_threshold, max_out)

    def predict(self, images: np.ndarray, score_threshold: float = 0.5,
                iou_threshold: float = 0.45, max_out: int = 20
                ) -> List[List[Tuple]]:
        dets = self.detect_raw(images, score_threshold, iou_threshold,
                               max_out)
        out = []
        for per_cls in dets:
            rows = []
            for c, (scores, boxes) in per_cls.items():
                for score, (x1, y1, x2, y2) in zip(scores, boxes):
                    rows.append((self.label_map.get(c, c), float(score),
                                 float(x1), float(y1), float(x2),
                                 float(y2)))
            rows.sort(key=lambda r: -r[1])
            out.append(rows)
        return out

    def evaluate(self, images: np.ndarray, gt,
                 classes: Optional[Sequence[str]] = None,
                 use_07_metric: bool = False, iou_threshold: float = 0.5,
                 nms_iou: float = 0.45, score_threshold: float = 0.01,
                 max_out: int = 100):
        """mAP over a batch (`MeanAveragePrecision` wired the way the
        reference's `ObjectDetector` evaluates with a ValidationMethod).
        `gt` is either flat [M,7] rows or the padded gt dict from
        `data/detection.py`. Returns a DetectionResult (print it for the
        per-class table; `.result()[0]` is the mAP)."""
        from analytics_zoo_tpu.models.detection_eval import \
            MeanAveragePrecision
        gt_rows = _gt_to_rows(gt)
        if classes is None:
            classes = ["__background__"] + [
                str(self.label_map.get(c, c))
                for c in range(1, self.n_classes)]
        evaluator = MeanAveragePrecision(
            classes, use_07_metric=use_07_metric,
            iou_threshold=iou_threshold)
        dets = self.detect_raw(images, score_threshold, nms_iou, max_out)
        return evaluator(dets, gt_rows)


def _gt_to_rows(gt) -> np.ndarray:
    if isinstance(gt, dict):
        from analytics_zoo_tpu.data.detection import gt_arrays_to_rows
        return gt_arrays_to_rows(
            {k: np.asarray(v) for k, v in gt.items()})
    return np.asarray(gt, np.float32).reshape(-1, 7)
