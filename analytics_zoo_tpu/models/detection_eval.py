"""Detection evaluation: VOC mean-average-precision.

Reference semantics: `common/evaluation/MeanAveragePrecision.scala` +
`EvalUtil.scala` (per-class tp/fp marking against greedily-claimed gts,
difficult gts excluded from both npos and fp, VOC07 11-point vs
area-under-envelope AP) and `PascalVocEvaluator.meanAveragePrecision`
(background excluded, mAP = unweighted class mean). Results are batch-
mergeable the way the reference's `DetectionResult.+` accumulates over a
validation epoch.

Class indices here are 0-based with 0 = background (the convention the
rest of `models/objectdetection.py` uses); gt rows use the
`SSDMiniBatch` layout `(img_id, label, difficult, x1, y1, x2, y2)`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def voc_ap(recall: np.ndarray, precision: np.ndarray,
           use_07_metric: bool = False) -> float:
    """AP from a PR curve: VOC07 11-point interpolation, or the corrected
    area under the monotone precision envelope (`EvalUtil.vocAp`)."""
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            mask = recall >= t
            p = float(precision[mask].max()) if mask.any() else 0.0
            ap += p / 11.0
        return ap
    # sentinel-pad, build the envelope, integrate where recall steps
    mrec = np.concatenate([[0.0], recall, [1.0]])
    mpre = np.concatenate([[0.0], precision, [0.0]])
    mpre = np.maximum.accumulate(mpre[::-1])[::-1]
    steps = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[steps + 1] - mrec[steps]) * mpre[steps + 1]))


def compute_ap(records: Sequence[Tuple[float, int, int]], npos: int,
               use_07_metric: bool = False) -> float:
    """(score, tp, fp) records -> AP (`EvalUtil.computeAP`): global sort by
    descending score, cumulate, precision/recall, `voc_ap`."""
    if npos == 0 or not len(records):
        return 0.0
    arr = np.asarray(records, np.float32)
    order = np.argsort(-arr[:, 0], kind="stable")
    tp = np.cumsum(arr[order, 1])
    fp = np.cumsum(arr[order, 2])
    recall = tp / float(npos)
    precision = tp / np.maximum(tp + fp, 1e-12)
    return voc_ap(recall, precision, use_07_metric)


def _iou_one_to_many(box: np.ndarray, gts: np.ndarray,
                     normalized: bool = True) -> np.ndarray:
    """One detection box vs [G,4] gt boxes (`BboxUtil.getMaxOverlaps`):
    un-normalized coords use the VOC +1 pixel-extent convention."""
    off = 0.0 if normalized else 1.0
    ix1 = np.maximum(gts[:, 0], box[0])
    iy1 = np.maximum(gts[:, 1], box[1])
    ix2 = np.minimum(gts[:, 2], box[2])
    iy2 = np.minimum(gts[:, 3], box[3])
    inter = np.clip(ix2 - ix1 + off, 0, None) \
        * np.clip(iy2 - iy1 + off, 0, None)
    area_d = (box[2] - box[0] + off) * (box[3] - box[1] + off)
    area_g = (gts[:, 2] - gts[:, 0] + off) * (gts[:, 3] - gts[:, 1] + off)
    return inter / np.maximum(area_d + area_g - inter, 1e-12)


def evaluate_class(detections: Dict[int, Tuple[np.ndarray, np.ndarray]],
                   gt_rows: np.ndarray, cls: int,
                   iou_threshold: float = 0.5, normalized: bool = True
                   ) -> Tuple[int, List[Tuple[float, int, int]]]:
    """Score one class over a batch (`EvalUtil.evaluateBatch`).

    detections: {img_id: (scores [K], boxes [K,4])} for THIS class, each
    image's detections in descending-score order (NMS output order).
    gt_rows: [M, 7] rows for all classes of the batch. Returns
    (npos, [(score, tp, fp)]): difficult gts count in neither npos nor
    fp; a gt already claimed by a higher-scoring detection turns later
    hits into fps (greedy claiming)."""
    npos = 0
    by_img: Dict[int, Dict[str, np.ndarray]] = {}
    if gt_rows.size:
        sel = gt_rows[gt_rows[:, 1].astype(np.int32) == cls]
        for img_id in np.unique(sel[:, 0].astype(np.int32)):
            rows = sel[sel[:, 0].astype(np.int32) == img_id]
            by_img[int(img_id)] = {
                "boxes": rows[:, 3:7],
                "difficult": rows[:, 2],
                "claimed": np.zeros(len(rows), bool)}
        npos = int(np.sum(sel[:, 2] == 0))
    records: List[Tuple[float, int, int]] = []
    for img_id, (scores, boxes) in detections.items():
        gts = by_img.get(int(img_id))
        for score, box in zip(np.asarray(scores), np.asarray(boxes)):
            if gts is None or not len(gts["boxes"]):
                records.append((float(score), 0, 1))
                continue
            ious = _iou_one_to_many(box, gts["boxes"], normalized)
            j = int(np.argmax(ious))
            if ious[j] > iou_threshold:
                if gts["difficult"][j] != 0:
                    continue                      # difficult: ignored
                if not gts["claimed"][j]:
                    gts["claimed"][j] = True
                    records.append((float(score), 1, 0))
                else:
                    records.append((float(score), 0, 1))
            else:
                records.append((float(score), 0, 1))
    return npos, records


class DetectionResult:
    """Per-class (npos, records) accumulator; `+` merges batches
    (`DetectionResult` in `MeanAveragePrecision.scala`)."""

    def __init__(self, results: List[Tuple[int, List[Tuple[float, int,
                                                           int]]]],
                 classes: Sequence[str], use_07_metric: bool):
        self.results = results
        self.classes = list(classes)
        self.use_07_metric = use_07_metric

    def __add__(self, other: "DetectionResult") -> "DetectionResult":
        merged = [(a[0] + b[0], list(a[1]) + list(b[1]))
                  for a, b in zip(self.results, other.results)]
        return DetectionResult(merged, self.classes, self.use_07_metric)

    def ap_by_class(self) -> List[Tuple[str, float]]:
        out = []
        for cls_name, (npos, records) in zip(self.classes, self.results):
            if cls_name != "__background__":
                out.append((cls_name,
                            compute_ap(records, npos, self.use_07_metric)))
        return out

    def result(self) -> Tuple[float, int]:
        aps = self.ap_by_class()
        mean = sum(ap for _, ap in aps) / max(len(aps), 1)
        return mean, 1

    def __str__(self):
        aps = self.ap_by_class()
        mean = sum(ap for _, ap in aps) / max(len(aps), 1)
        lines = ["~~~~~~~~", "Results:"]
        lines += [f"AP for {name} = {ap:.4f}" for name, ap in aps]
        lines += [f"Mean AP = {mean:.4f}", "~~~~~~~~"]
        return "\n".join(lines)


class MeanAveragePrecision:
    """`MeanAveragePrecision(use07metric, normalized, classes)` — call on
    (per-image per-class detections, gt rows) to get a mergeable
    DetectionResult."""

    name = "PascalMeanAveragePrecision"

    def __init__(self, classes: Sequence[str],
                 use_07_metric: bool = False, normalized: bool = True,
                 iou_threshold: float = 0.5):
        self.classes = list(classes)
        self.use_07_metric = use_07_metric
        self.normalized = normalized
        self.iou_threshold = iou_threshold

    def __call__(self,
                 detections: List[Dict[int, Tuple[np.ndarray, np.ndarray]]],
                 gt_rows: np.ndarray) -> DetectionResult:
        """detections: list over images; each entry maps class index ->
        (scores, boxes) in descending-score order. gt_rows: [M, 7]."""
        results = []
        for c, cls_name in enumerate(self.classes):
            if cls_name == "__background__":
                results.append((0, []))
                continue
            per_img = {i: d[c] for i, d in enumerate(detections) if c in d}
            results.append(evaluate_class(
                per_img, gt_rows, c, self.iou_threshold, self.normalized))
        return DetectionResult(results, self.classes, self.use_07_metric)


class DetectionMAP(MeanAveragePrecision):
    """`Estimator.evaluate(metrics=[DetectionMAP(...)])`-pluggable form:
    carries the SSD postprocess spec so it can decode the model's raw flat
    output itself (the reference passes `MeanAveragePrecision` as a BigDL
    ValidationMethod into `Estimator.evaluate`; here the decode that its
    `decodeBatchOutput` did lives in the metric)."""

    def __init__(self, anchors, n_anchors_per_map: Sequence[int],
                 n_classes: int, classes: Optional[Sequence[str]] = None,
                 score_threshold: float = 0.01, nms_iou: float = 0.45,
                 max_out: int = 100, **kw):
        if classes is None:
            classes = ["__background__"] + [str(i)
                                            for i in range(1, n_classes)]
        super().__init__(classes, **kw)
        self.anchors = np.asarray(anchors, np.float32)
        self.n_anchors_per_map = list(n_anchors_per_map)
        self.n_classes = n_classes
        self.score_threshold = score_threshold
        self.nms_iou = nms_iou
        self.max_out = max_out

    def evaluate_flat(self, flat_outputs, gt) -> DetectionResult:
        import jax.numpy as jnp

        from analytics_zoo_tpu.models.objectdetection import (
            _gt_to_rows, decode_detections)
        dets = decode_detections(
            flat_outputs, jnp.asarray(self.anchors),
            self.n_anchors_per_map, self.n_classes,
            self.score_threshold, self.nms_iou, self.max_out)
        return self(dets, _gt_to_rows(gt))
