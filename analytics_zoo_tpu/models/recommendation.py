"""Recommendation models: NeuralCF, WideAndDeep, SessionRecommender.

Architectures follow the reference exactly:
- NeuralCF (`models/recommendation/NeuralCF.scala:60-97`, py
  `neuralcf.py:30`): dual MLP embeddings concat → Dense relu stack, optional
  GMF branch (mf embeddings multiplied) concatenated before the softmax.
- WideAndDeep (`WideAndDeep.scala`, py `wide_and_deep.py:140-180`): wide
  linear over sparse-ish wide features + deep MLP over
  indicator/embedding/continuous columns, summed then softmax.
- SessionRecommender (`session_recommender.py:69-94`): GRU stack over session
  item embeddings, optional history MLP branch, summed logits → softmax.

The reference's inputs use 1-based ids (Embedding tables sized count+1);
kept here. On TPU the embedding lookups become gathers feeding fused MXU
matmuls; one jit program per model.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.models.common import ZooModel


class UserItemFeature:
    """(user_id, item_id, label) record used by the recommender helpers
    (`pyzoo/zoo/models/recommendation/utils.py`)."""

    def __init__(self, user_id: int, item_id: int, label: int = 0):
        self.user_id, self.item_id, self.label = user_id, item_id, label


class Recommender(ZooModel):
    """Shared ranking helpers (`Recommender` in
    `pyzoo/zoo/models/recommendation/__init__.py`)."""

    def predict_user_item_pair(self, features: Sequence[UserItemFeature],
                               batch_per_thread: int = 32) -> np.ndarray:
        x = np.array([[f.user_id, f.item_id] for f in features], np.int32)
        return self.predict(x, batch_per_thread=batch_per_thread)

    def recommend_for_user(self, features: Sequence[UserItemFeature],
                           max_items: int = 5):
        """Top-N items per user from candidate pairs."""
        probs = self.predict_user_item_pair(features)
        score = probs[:, -1] if probs.ndim > 1 else probs
        by_user = {}
        for f, s in zip(features, score):
            by_user.setdefault(f.user_id, []).append((f.item_id, float(s)))
        return {u: sorted(items, key=lambda t: -t[1])[:max_items]
                for u, items in by_user.items()}

    def recommend_for_item(self, features: Sequence[UserItemFeature],
                           max_users: int = 5):
        probs = self.predict_user_item_pair(features)
        score = probs[:, -1] if probs.ndim > 1 else probs
        by_item = {}
        for f, s in zip(features, score):
            by_item.setdefault(f.item_id, []).append((f.user_id, float(s)))
        return {i: sorted(users, key=lambda t: -t[1])[:max_users]
                for i, users in by_item.items()}


class NeuralCF(Recommender):
    """Neural Collaborative Filtering (`NeuralCF.scala:60`)."""

    def __init__(self, user_count: int, item_count: int, class_num: int,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20):
        super().__init__()
        self._config = dict(user_count=user_count, item_count=item_count,
                            class_num=class_num, user_embed=user_embed,
                            item_embed=item_embed,
                            hidden_layers=list(hidden_layers),
                            include_mf=include_mf, mf_embed=mf_embed)
        self.user_count, self.item_count = user_count, item_count
        self.class_num = class_num
        self.user_embed, self.item_embed = user_embed, item_embed
        self.hidden_layers = list(hidden_layers)
        self.include_mf, self.mf_embed = include_mf, mf_embed
        self.model = self.build_model()

    def build_model(self) -> Model:
        # input: [B, 2] of (user_id, item_id) — `neuralcf.py:55-57`
        inp = Input(shape=(2,))
        user = L.Select(1, 0)(inp)
        item = L.Select(1, 1)(inp)
        mlp_user = L.Flatten()(
            L.Embedding(self.user_count + 1, self.user_embed,
                        init="uniform", name="ncf_mlp_user")(user))
        mlp_item = L.Flatten()(
            L.Embedding(self.item_count + 1, self.item_embed,
                        init="uniform", name="ncf_mlp_item")(item))
        x = L.merge([mlp_user, mlp_item], mode="concat")
        for units in self.hidden_layers:
            x = L.Dense(units, activation="relu")(x)
        table_names = ["ncf_mlp_user", "ncf_mlp_item"]
        if self.include_mf:
            assert self.mf_embed > 0
            mf_user = L.Flatten()(
                L.Embedding(self.user_count + 1, self.mf_embed,
                            init="uniform", name="ncf_mf_user")(user))
            mf_item = L.Flatten()(
                L.Embedding(self.item_count + 1, self.mf_embed,
                            init="uniform", name="ncf_mf_item")(item))
            gmf = L.merge([mf_user, mf_item], mode="mul")
            x = L.merge([x, gmf], mode="concat")
            table_names += ["ncf_mf_user", "ncf_mf_item"]
        out = L.Dense(self.class_num, activation="softmax")(x)
        model = Model(inp, out)

        # Declare the embedding tables for the lazy row-sparse optimizer
        # path (`learn/lazy_embedding.py`; Estimator.fit
        # lazy_embeddings=True): the dense Adam sweep over these tables
        # is ~78% of device step time at MovieLens scale.
        import jax.numpy as jnp
        col = {"ncf_mlp_user": 0, "ncf_mlp_item": 1,
               "ncf_mf_user": 0, "ncf_mf_item": 1}

        def ids_fn(c):
            return lambda xb: jnp.asarray(xb[..., c], jnp.int32)

        def set_ids_fn(c):
            # write twin for the fused sparse backward (segment_update):
            # rewrite the id column so the model's gather reads
            # positions 0..B into a pre-gathered rows array instead of
            # vocabulary ids (B < 2^24, exact in the f32 input)
            return lambda xb, ids: xb.at[..., c].set(
                ids.astype(xb.dtype))

        from analytics_zoo_tpu.learn.lazy_embedding import LazyEmbeddingSpec
        model.lazy_embedding_specs = [
            LazyEmbeddingSpec((n, "embeddings"), ids_fn(col[n]),
                              set_ids_fn=set_ids_fn(col[n]))
            for n in table_names]
        return model


class WideAndDeep(Recommender):
    """Wide & Deep (`wide_and_deep.py:94,140-180`). Inputs (by model_type):
    wide [B, wide_dims], indicator [B, sum(indicator_dims)], embed ids
    [B, len(embed_in_dims)], continuous [B, len(continuous_cols)]."""

    def __init__(self, class_num: int, model_type: str = "wide_n_deep",
                 wide_base_dims: Sequence[int] = (),
                 wide_cross_dims: Sequence[int] = (),
                 indicator_dims: Sequence[int] = (),
                 embed_in_dims: Sequence[int] = (),
                 embed_out_dims: Sequence[int] = (),
                 continuous_cols: Sequence[str] = (),
                 hidden_layers: Sequence[int] = (40, 20, 10)):
        super().__init__()
        self._config = dict(class_num=class_num, model_type=model_type,
                            wide_base_dims=list(wide_base_dims),
                            wide_cross_dims=list(wide_cross_dims),
                            indicator_dims=list(indicator_dims),
                            embed_in_dims=list(embed_in_dims),
                            embed_out_dims=list(embed_out_dims),
                            continuous_cols=list(continuous_cols),
                            hidden_layers=list(hidden_layers))
        self.class_num = class_num
        self.model_type = model_type
        self.wide_dims = sum(wide_base_dims) + sum(wide_cross_dims)
        self.indicator_dims = list(indicator_dims)
        self.embed_in_dims = list(embed_in_dims)
        self.embed_out_dims = list(embed_out_dims)
        self.continuous_cols = list(continuous_cols)
        self.hidden_layers = list(hidden_layers)
        self.model = self.build_model()

    def _deep_branch(self):
        inputs, merged = [], []
        if self.indicator_dims:
            ind = Input(shape=(sum(self.indicator_dims),))
            inputs.append(ind)
            merged.append(ind)
        if self.embed_in_dims:
            emb_in = Input(shape=(len(self.embed_in_dims),))
            inputs.append(emb_in)
            for i, (vin, vout) in enumerate(zip(self.embed_in_dims,
                                                self.embed_out_dims)):
                col = L.Select(1, i)(emb_in)
                merged.append(L.Flatten()(
                    L.Embedding(vin + 1, vout, init="uniform")(col)))
        if self.continuous_cols:
            con = Input(shape=(len(self.continuous_cols),))
            inputs.append(con)
            merged.append(con)
        x = merged[0] if len(merged) == 1 else L.merge(merged, mode="concat")
        for units in self.hidden_layers:
            x = L.Dense(units, activation="relu")(x)
        # reference ends the deep tower with a relu Dense to class_num
        # (`wide_and_deep.py:179`)
        out = L.Dense(self.class_num, activation="relu")(x)
        return inputs, out

    def build_model(self) -> Model:
        if self.model_type == "wide":
            wide = Input(shape=(self.wide_dims,))
            out = L.Activation("softmax")(L.Dense(self.class_num)(wide))
            return Model(wide, out)
        if self.model_type == "deep":
            inputs, deep = self._deep_branch()
            out = L.Activation("softmax")(deep)
            return Model(inputs if len(inputs) > 1 else inputs[0], out)
        if self.model_type == "wide_n_deep":
            wide = Input(shape=(self.wide_dims,))
            wide_linear = L.Dense(self.class_num)(wide)
            inputs, deep = self._deep_branch()
            merged = L.merge([wide_linear, deep], mode="sum")
            out = L.Activation("softmax")(merged)
            return Model([wide] + inputs, out)
        raise TypeError(f"Unsupported model_type: {self.model_type}")


class SessionRecommender(Recommender):
    """Session-based GRU recommender (`session_recommender.py:30,69-94`)."""

    def __init__(self, item_count: int, item_embed: int = 100,
                 rnn_hidden_layers: Sequence[int] = (40, 20),
                 session_length: int = 0, include_history: bool = False,
                 mlp_hidden_layers: Sequence[int] = (40, 20),
                 history_length: int = 0):
        super().__init__()
        if session_length <= 0:
            raise ValueError("session_length must be positive")
        if include_history and history_length <= 0:
            raise ValueError("history_length must be positive with history")
        self._config = dict(item_count=item_count, item_embed=item_embed,
                            rnn_hidden_layers=list(rnn_hidden_layers),
                            session_length=session_length,
                            include_history=include_history,
                            mlp_hidden_layers=list(mlp_hidden_layers),
                            history_length=history_length)
        self.item_count = item_count
        self.item_embed = item_embed
        self.rnn_hidden_layers = list(rnn_hidden_layers)
        self.session_length = session_length
        self.include_history = include_history
        self.mlp_hidden_layers = list(mlp_hidden_layers)
        self.history_length = history_length
        self.model = self.build_model()

    def build_model(self) -> Model:
        inp_rnn = Input(shape=(self.session_length,))
        x = L.Embedding(self.item_count + 1, self.item_embed,
                        init="uniform")(inp_rnn)
        for units in self.rnn_hidden_layers[:-1]:
            x = L.GRU(units, return_sequences=True)(x)
        x = L.GRU(self.rnn_hidden_layers[-1], return_sequences=False)(x)
        rnn_logits = L.Dense(self.item_count)(x)
        if self.include_history:
            inp_mlp = Input(shape=(self.history_length,))
            h = L.Embedding(self.item_count + 1, self.item_embed,
                            init="uniform")(inp_mlp)
            from analytics_zoo_tpu.ops.autograd import Lambda
            import jax.numpy as jnp
            h = Lambda(lambda t: jnp.sum(t, axis=1))(h)
            for units in self.mlp_hidden_layers:
                h = L.Dense(units, activation="relu")(h)
            mlp_logits = L.Dense(self.item_count)(h)
            merged = L.merge([rnn_logits, mlp_logits], mode="sum")
            out = L.Activation("softmax")(merged)
            return Model([inp_rnn, inp_mlp], out)
        out = L.Activation("softmax")(rnn_logits)
        return Model(inp_rnn, out)

    def recommend_for_session(self, sessions: np.ndarray, max_items: int = 5,
                              zero_based_label: bool = True):
        probs = self.predict(sessions)
        top = np.argsort(-probs, axis=-1)[:, :max_items]
        shift = 0 if zero_based_label else 1
        return [list(zip((t + shift).tolist(), probs[i, t].tolist()))
                for i, t in enumerate(top)]
