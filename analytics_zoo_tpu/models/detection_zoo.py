"""Object-detection tooling around the SSD model: named model configs,
dataset label maps, and a box visualizer.

Reference components mirrored:
- `ObjectDetectionConfig.scala` — registry of model-name → (preprocess,
  postprocess, label map) configurations resolved by
  `ObjectDetector.load("ssd-...", dataset)`. The reference downloads
  pretrained weights from its model-zoo URL; this environment has no
  egress, so weights come from a local `weights_path` (saved by
  `model.save_weights`) and a config with no weights builds the
  architecture randomly-initialized for fine-tuning.
- `LabelReader.scala` / `ModelLabelReader` — VOC ("pascal") and COCO
  label maps, index 0 = background, plus file-based custom maps.
- `Visualizer.scala` — draw detection rows (label, score, box) onto the
  image; encode to PNG bytes or return the annotated array.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.models import objectdetection as od

# ---------------------------------------------------------------------------
# Label maps (`LabelReader.scala`): index 0 is background, matching the
# reference's 1-based class rows in detection outputs.
# ---------------------------------------------------------------------------
VOC_CLASSES: Tuple[str, ...] = (
    "__background__",
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor")

COCO_CLASSES: Tuple[str, ...] = (
    "__background__",
    "person", "bicycle", "car", "motorcycle", "airplane", "bus", "train",
    "truck", "boat", "traffic light", "fire hydrant", "stop sign",
    "parking meter", "bench", "bird", "cat", "dog", "horse", "sheep",
    "cow", "elephant", "bear", "zebra", "giraffe", "backpack", "umbrella",
    "handbag", "tie", "suitcase", "frisbee", "skis", "snowboard",
    "sports ball", "kite", "baseball bat", "baseball glove", "skateboard",
    "surfboard", "tennis racket", "bottle", "wine glass", "cup", "fork",
    "knife", "spoon", "bowl", "banana", "apple", "sandwich", "orange",
    "broccoli", "carrot", "hot dog", "pizza", "donut", "cake", "chair",
    "couch", "potted plant", "bed", "dining table", "toilet", "tv",
    "laptop", "mouse", "remote", "keyboard", "cell phone", "microwave",
    "oven", "toaster", "sink", "refrigerator", "book", "clock", "vase",
    "scissors", "teddy bear", "hair drier", "toothbrush")


def label_reader(dataset: str,
                 path: Optional[str] = None) -> Dict[int, str]:
    """`LabelReader(dataset)`: {class_index: name}. `dataset` ∈
    {"pascal", "coco"} or "file" with `path` to a one-name-per-line file
    (line order = class index, like the reference's resource files)."""
    key = dataset.lower()
    if key in ("pascal", "voc", "pascalvoc"):
        names: Sequence[str] = VOC_CLASSES
    elif key == "coco":
        names = COCO_CLASSES
    elif key == "file":
        if not path:
            raise ValueError('label_reader("file") needs a path')
        with open(path) as fh:
            names = [ln.strip() for ln in fh if ln.strip()]
    else:
        raise ValueError(
            f"Unknown label dataset {dataset!r}: use 'pascal', 'coco', or "
            "'file' with a path")
    return dict(enumerate(names))


# ---------------------------------------------------------------------------
# Model config registry (`ObjectDetectionConfig.scala`)
# ---------------------------------------------------------------------------
@dataclass
class DetectionConfig:
    """One named detector configuration: architecture shape + preprocess
    + postprocess parameters (`ImageConfigure` role)."""

    image_size: int
    scales: Sequence[float] = (0.3, 0.6)
    aspect_ratios: Sequence[float] = (1.0, 2.0, 0.5)
    # preprocess (`preprocessSsdVgg`: resize + channel-mean subtract)
    mean_rgb: Tuple[float, float, float] = (123.0, 117.0, 104.0)
    scale: float = 1.0
    # postprocess (`ScaleDetection`)
    score_threshold: float = 0.5
    iou_threshold: float = 0.45
    batch_per_partition: int = 2


# Reference model names resolve to the TPU-native SSD at the named input
# resolution (the reference's VGG/mobilenet backbones are pretrained Caffe
# artifacts; the backbone here is the trainable trunk of `build_ssd`).
MODELS: Dict[str, DetectionConfig] = {
    "ssd-vgg16-300x300": DetectionConfig(image_size=304),
    "ssd-vgg16-512x512": DetectionConfig(image_size=512),
    "ssd-mobilenet-300x300": DetectionConfig(image_size=304),
    "ssd-tpu-64x64": DetectionConfig(image_size=64, mean_rgb=(0, 0, 0),
                                     scale=1 / 255.0),
    "ssd-tpu-128x128": DetectionConfig(image_size=128, mean_rgb=(0, 0, 0),
                                       scale=1 / 255.0),
}


def load_object_detector(model_name: str, dataset: str = "pascal",
                         weights_path: Optional[str] = None,
                         label_path: Optional[str] = None
                         ) -> "ConfiguredDetector":
    """`ObjectDetector.load(name)` shape (`ObjectDetectionConfig.apply`):
    resolve the named config + dataset label map, build the detector, and
    load weights when given (no egress → weights are local files)."""
    if model_name not in MODELS:
        raise ValueError(
            f"Unknown detection model {model_name!r}; available: "
            f"{sorted(MODELS)}")
    cfg = MODELS[model_name]
    label_map = label_reader(dataset, label_path)
    n_classes = len(label_map)
    model, anchors = od.build_ssd(
        n_classes, image_size=cfg.image_size, scales=cfg.scales,
        aspect_ratios=cfg.aspect_ratios)
    from analytics_zoo_tpu.models.pretrained import (apply_weight_spec,
                                                     parse_weight_spec)
    spec = parse_weight_spec(weights_path) if weights_path else None
    if weights_path and spec is None:
        model.load_weights(weights_path)        # native ckpt: no throwaway
    else:
        import jax
        model.ensure_built(
            np.zeros((1, cfg.image_size, cfg.image_size, 3), np.float32),
            jax.random.PRNGKey(0))
        if spec is not None:
            # backbone-only transfer (strict=False): detection heads
            # rarely shape-match a foreign backbone artifact — the
            # CaffeLoader fine-tune pattern (`CaffeLoader.scala:718`)
            stats = apply_weight_spec(model, weights_path, strict=False,
                                      parsed=spec)
            import logging
            logging.getLogger("analytics_zoo_tpu").info(
                "load_object_detector(%s): foreign weight transfer %s",
                model_name, stats)
    k = len(cfg.aspect_ratios)
    sizes = (cfg.image_size // 8, cfg.image_size // 16)
    n_per_map = [s * s * k for s in sizes]
    det = od.ObjectDetector(model, anchors, n_per_map, n_classes,
                            label_map=label_map)
    return ConfiguredDetector(det, cfg, model_name)


class ConfiguredDetector:
    """A detector bound to its config: preprocess → predict → postprocess
    with the config's thresholds (the `ImageConfigure` composition)."""

    def __init__(self, detector: od.ObjectDetector, config: DetectionConfig,
                 name: str):
        self.detector = detector
        self.config = config
        self.name = name

    def preprocess(self, images) -> np.ndarray:
        """Resize to the config's input square + mean-subtract/scale
        (`preprocessSsdVgg`). Accepts one HWC image or a batch/list."""
        import cv2
        cfg = self.config
        if isinstance(images, np.ndarray) and images.ndim == 3:
            images = [images]
        out = []
        for img in images:
            img = np.asarray(img)
            if img.shape[:2] != (cfg.image_size, cfg.image_size):
                img = cv2.resize(img.astype(np.float32),
                                 (cfg.image_size, cfg.image_size))
            out.append((img.astype(np.float32)
                        - np.asarray(cfg.mean_rgb, np.float32))
                       * cfg.scale)
        return np.stack(out)

    def predict(self, images, score_threshold: Optional[float] = None,
                iou_threshold: Optional[float] = None, max_out: int = 20):
        """Raw images → detection rows [(label, score, x1, y1, x2, y2)]
        per image; box coords are normalized [0, 1]."""
        cfg = self.config
        batch = self.preprocess(images)
        return self.detector.predict(
            batch,
            score_threshold=(cfg.score_threshold if score_threshold is None
                             else score_threshold),
            iou_threshold=(cfg.iou_threshold if iou_threshold is None
                           else iou_threshold),
            max_out=max_out)


# ---------------------------------------------------------------------------
# Visualizer (`Visualizer.scala`): rows → boxes drawn on the image
# ---------------------------------------------------------------------------
class Visualizer:
    """Draw detection rows onto images. Rows are the `ObjectDetector.
    predict` output — (label, score, x1, y1, x2, y2) with normalized
    coords — or the reference's 1-based [class_id, score, x1..y2] with
    pixel coords (auto-detected by value range)."""

    PALETTE = [(204, 0, 0), (0, 153, 0), (0, 76, 204), (204, 153, 0),
               (153, 0, 153), (0, 153, 153), (102, 51, 0), (255, 102, 0)]

    def __init__(self, label_map: Optional[Dict[int, str]] = None,
                 thresh: float = 0.3, encoding: str = "png"):
        self.label_map = label_map or {}
        self.thresh = thresh
        self.encoding = encoding

    def draw(self, image: np.ndarray, rows) -> np.ndarray:
        """Return a copy of `image` (HWC uint8) with boxes + labels."""
        import cv2
        img = np.ascontiguousarray(np.asarray(image, np.uint8).copy())
        h, w = img.shape[:2]
        color_i = 0
        for row in rows:
            label, score, x1, y1, x2, y2 = row[:6]
            if score < self.thresh:
                continue
            if isinstance(label, (int, np.integer)) or (
                    isinstance(label, (float, np.floating))
                    and float(label).is_integer()):
                label = self.label_map.get(int(label), str(int(label)))
            if max(abs(float(x2)), abs(float(y2))) <= 1.5:  # normalized
                x1, x2 = x1 * w, x2 * w
                y1, y2 = y1 * h, y2 * h
            p1 = (int(round(float(x1))), int(round(float(y1))))
            p2 = (int(round(float(x2))), int(round(float(y2))))
            color = self.PALETTE[color_i % len(self.PALETTE)]
            color_i += 1
            cv2.rectangle(img, p1, p2, color, 2)
            cv2.putText(img, f"{label} {float(score):.2f}",
                        (p1[0], max(12, p1[1] - 4)),
                        cv2.FONT_HERSHEY_SIMPLEX, 0.4, color, 1,
                        cv2.LINE_AA)
        return img

    def encode(self, image: np.ndarray, rows) -> bytes:
        """`visualizeDetection`: annotated image → encoded bytes."""
        import cv2
        ok, buf = cv2.imencode(f".{self.encoding}", self.draw(image, rows))
        if not ok:
            raise ValueError(f"Failed to encode as {self.encoding}")
        return bytes(buf)

    def save(self, path: str, image: np.ndarray, rows) -> str:
        with open(path, "wb") as fh:
            fh.write(self.encode(image, rows))
        return path
