"""Anomaly detection over time series — LSTM forecaster + threshold detector.

Architecture per the reference (`models/anomalydetection/
AnomalyDetector.scala:40`, py `anomaly_detector.py:61-76`): stacked LSTMs
(return_sequences except last) with dropouts, Dense(1) head trained on MSE;
anomalies = top-N prediction errors (`anomaly_detector.py:126` `detect_anomalies`).
Also carries the unroll helper (`anomaly_detector.py:105`).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.models.common import ZooModel


class AnomalyDetector(ZooModel):
    def __init__(self, feature_shape: Tuple[int, int],
                 hidden_layers: Sequence[int] = (8, 32, 15),
                 dropouts: Sequence[float] = (0.2, 0.2, 0.2)):
        super().__init__()
        if len(hidden_layers) != len(dropouts):
            raise ValueError("hidden_layers and dropouts lengths differ")
        self._config = dict(feature_shape=list(feature_shape),
                            hidden_layers=list(hidden_layers),
                            dropouts=list(dropouts))
        self.feature_shape = tuple(feature_shape)
        self.hidden_layers = list(hidden_layers)
        self.dropouts = list(dropouts)
        self.model = self.build_model()

    def build_model(self) -> Sequential:
        m = Sequential()
        if len(self.hidden_layers) == 1:
            m.add(L.LSTM(self.hidden_layers[0],
                         input_shape=self.feature_shape,
                         return_sequences=False))
            m.add(L.Dropout(self.dropouts[0]))
        else:
            m.add(L.LSTM(self.hidden_layers[0],
                         input_shape=self.feature_shape,
                         return_sequences=True))
            m.add(L.Dropout(self.dropouts[0]))
            for units, drop in zip(self.hidden_layers[1:-1],
                                   self.dropouts[1:-1]):
                m.add(L.LSTM(units, return_sequences=True))
                m.add(L.Dropout(drop))
            m.add(L.LSTM(self.hidden_layers[-1], return_sequences=False))
            m.add(L.Dropout(self.dropouts[-1]))
        m.add(L.Dense(1))
        return m


def unroll(data: np.ndarray, unroll_length: int,
           predict_step: int = 1) -> Tuple[np.ndarray, np.ndarray]:
    """Sliding windows: x[i] = data[i : i+L], y[i] = data[i+L+step-1, 0]
    (`anomaly_detector.py:105` unroll semantics)."""
    data = np.asarray(data, np.float32)
    if data.ndim == 1:
        data = data[:, None]
    n = len(data) - unroll_length - predict_step + 1
    if n <= 0:
        raise ValueError("series too short for the requested unroll")
    x = np.stack([data[i:i + unroll_length] for i in range(n)])
    y = data[unroll_length + predict_step - 1:
             unroll_length + predict_step - 1 + n, 0]
    return x, y


def detect_anomalies(y_truth: np.ndarray, y_predict: np.ndarray,
                     anomaly_size: int) -> np.ndarray:
    """Indices of the `anomaly_size` largest absolute errors
    (`detect_anomalies`, `anomaly_detector.py:126`)."""
    err = np.abs(np.asarray(y_truth).reshape(-1)
                 - np.asarray(y_predict).reshape(-1))
    thresh = np.sort(err)[-anomaly_size]
    return np.where(err >= thresh)[0][:anomaly_size]


class ThresholdDetector:
    """`zouwu/model/anomaly.py` ThresholdDetector: fixed or percentile-based
    threshold on forecast error."""

    def __init__(self, threshold: Optional[float] = None,
                 ratio: float = 0.01):
        self.threshold = threshold
        self.ratio = ratio

    def fit(self, y_truth: np.ndarray, y_predict: np.ndarray):
        err = np.abs(np.asarray(y_truth) - np.asarray(y_predict)).reshape(-1)
        if self.threshold is None:
            self.threshold = float(np.quantile(err, 1.0 - self.ratio))
        return self

    def score(self, y_truth: np.ndarray, y_predict: np.ndarray) -> np.ndarray:
        if self.threshold is None:
            raise ValueError("fit() first or pass an explicit threshold")
        err = np.abs(np.asarray(y_truth) - np.asarray(y_predict)).reshape(-1)
        return (err > self.threshold).astype(np.int32)
