"""TextClassifier — CNN/LSTM/GRU encoders over (pretrained) embeddings.

Reference: `models/textclassification/TextClassifier.scala:43-67` — embedding
→ encoder (cnn: Conv1D(k=5, relu)+GlobalMaxPooling1D; lstm/gru: recurrent
final state) → Dense(128) → Dropout(0.2) → Dense(class_num, softmax).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.models.common import ZooModel


class TextClassifier(ZooModel):
    def __init__(self, class_num: int, embedding_dim: Optional[int] = None,
                 vocab_size: Optional[int] = None,
                 sequence_length: int = 500, encoder: str = "cnn",
                 encoder_output_dim: int = 256,
                 embedding_weights: Optional[np.ndarray] = None,
                 pretrained: bool = False):
        super().__init__()
        if embedding_weights is None and (embedding_dim is None
                                          or vocab_size is None):
            raise ValueError("Provide embedding_weights or "
                             "(vocab_size, embedding_dim)")
        self.class_num = class_num
        self.sequence_length = sequence_length
        self.encoder = encoder.lower()
        self.encoder_output_dim = encoder_output_dim
        if embedding_weights is None and pretrained:
            # reload path: rebuild the frozen-WordEmbedding structure with a
            # placeholder matrix; real weights come from the checkpoint
            embedding_weights = np.zeros((vocab_size, embedding_dim),
                                         np.float32)
        self.embedding_weights = embedding_weights
        self.vocab_size = vocab_size if embedding_weights is None \
            else embedding_weights.shape[0]
        self.embedding_dim = embedding_dim if embedding_weights is None \
            else embedding_weights.shape[1]
        # persist DERIVED sizes (+ pretrained flag) so load_model can rebuild
        # a weights-constructed instance
        self._config = dict(class_num=class_num,
                            embedding_dim=int(self.embedding_dim),
                            vocab_size=int(self.vocab_size),
                            sequence_length=sequence_length, encoder=encoder,
                            encoder_output_dim=encoder_output_dim,
                            pretrained=embedding_weights is not None)
        self.model = self.build_model()

    def build_model(self) -> Sequential:
        m = Sequential()
        if self.embedding_weights is not None:
            m.add(L.WordEmbedding(self.embedding_weights,
                                  input_shape=(self.sequence_length,)))
        else:
            m.add(L.Embedding(self.vocab_size, self.embedding_dim,
                              input_shape=(self.sequence_length,)))
        if self.encoder == "cnn":
            m.add(L.Convolution1D(self.encoder_output_dim, 5,
                                  activation="relu"))
            m.add(L.GlobalMaxPooling1D())
        elif self.encoder == "lstm":
            m.add(L.LSTM(self.encoder_output_dim))
        elif self.encoder == "gru":
            m.add(L.GRU(self.encoder_output_dim))
        else:
            raise ValueError(f"Unsupported encoder: {self.encoder} "
                             "(use cnn | lstm | gru)")
        m.add(L.Dense(128))
        m.add(L.Dropout(0.2))
        m.add(L.Activation("relu"))
        m.add(L.Dense(self.class_num, activation="softmax"))
        return m
