"""ZooModel — shared plumbing for the built-in model zoo.

Mirrors `zoo/.../models/common/ZooModel.scala` + `KerasZooModel` (save/load,
summary, predict) and the python `zoo.models.common` base. A ZooModel wraps a
constructed Keras-style graph plus its hyperparameters; `save_model`/
`load_model` persist config + weights so a model reloads without re-specifying
the architecture.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from analytics_zoo_tpu.keras.engine import KerasNet


class ZooModel:
    """Base: subclasses set `self.model` (a KerasNet) in build_model() and
    register their constructor kwargs via `self._config`."""

    def __init__(self):
        self.model: Optional[KerasNet] = None
        self._config: Dict[str, Any] = {}

    # -- Keras passthrough -------------------------------------------------
    def compile(self, optimizer, loss, metrics=None):
        self.model.compile(optimizer, loss, metrics)

    def fit(self, x, y=None, batch_size=32, nb_epoch=1, **kw):
        return self.model.fit(x, y, batch_size=batch_size, nb_epoch=nb_epoch,
                              **kw)

    def evaluate(self, x, y=None, batch_per_thread=32, **kw):
        return self.model.evaluate(x, y, batch_per_thread=batch_per_thread,
                                   **kw)

    def predict(self, x, batch_per_thread=32, **kw):
        return self.model.predict(x, batch_per_thread=batch_per_thread, **kw)

    def predict_classes(self, x, batch_per_thread=32, zero_based_label=True):
        """`Recommender.predict_classes`-style helper: argmax over the class
        axis; the reference's labels are 1-based by default."""
        probs = self.predict(x, batch_per_thread=batch_per_thread)
        cls = np.argmax(probs, axis=-1)
        return cls if zero_based_label else cls + 1

    def summary(self):
        return self.model.summary()

    # -- persistence -------------------------------------------------------
    def _save_config(self, path: str, over_write: bool):
        """Shared config-json step for the plain and encrypted savers."""
        os.makedirs(path, exist_ok=True)
        cfg_path = os.path.join(path, "config.json")
        if os.path.exists(cfg_path) and not over_write:
            raise FileExistsError(f"{path} exists; pass over_write=True")
        with open(cfg_path, "w") as fh:
            json.dump({"class": type(self).__name__,
                       "config": self._config}, fh)

    def save_model(self, path: str, over_write: bool = False):
        """`ZooModel.saveModel`: config json + weights."""
        self._save_config(path, over_write)
        self.model.save_weights(os.path.join(path, "weights"))

    def save_model_encrypted(self, path: str, secret: str, salt: str,
                             over_write: bool = False):
        """Encrypted save (`InferenceModel.scala:121-226` encrypted-model
        loaders): config json in clear, weights AES-GCM-sealed as
        weights.enc — loadable by `InferenceModel.load_keras_encrypted`
        and the serving `secure.model_encrypted` flow."""
        from analytics_zoo_tpu.learn.encrypted import save_encrypted_pytree
        self._save_config(path, over_write)
        save_encrypted_pytree(os.path.join(path, "weights.enc"),
                              self.model.params, secret, salt)

    @classmethod
    def load_model(cls, path: str) -> "ZooModel":
        with open(os.path.join(path, "config.json")) as fh:
            blob = json.load(fh)
        if blob["class"] != cls.__name__:
            raise ValueError(
                f"Checkpoint is a {blob['class']}, not {cls.__name__}")
        inst = cls(**blob["config"])
        inst.model.load_weights(os.path.join(path, "weights"))
        return inst

    def set_checkpoint(self, path: str):
        self.model.set_checkpoint(path)

    def set_tensorboard(self, log_dir: str, app_name: str):
        self.model.set_tensorboard(log_dir, app_name)


class Ranker:
    """Ranking-evaluation mixin (`models/common/Ranker.scala`): NDCG@k and
    MAP over per-query candidate lists. A "query" is one (x, y) pair where
    `x` is the model input for that query's candidates and `y` their
    relevance labels; metrics average over queries."""

    @staticmethod
    def ndcg_score(y_true, y_pred, k: int, threshold: float = 0.0) -> float:
        """One query (`Ranker.scala:113-146`): DCG over the top-k by
        predicted score / ideal DCG over the top-k by label, with gains
        2^g and only g > threshold contributing."""
        if k <= 0:
            raise ValueError(f"k for NDCG should be positive, got {k}")
        y_true = np.ravel(np.asarray(y_true, np.float64))
        y_pred = np.ravel(np.asarray(y_pred, np.float64))
        denom = np.log(2.0 + np.arange(len(y_true)))
        by_label = np.sort(y_true)[::-1][:k]
        idcg = float(np.sum(np.where(by_label > threshold,
                                     2.0 ** by_label, 0.0)
                            / denom[:len(by_label)]))
        by_pred = y_true[np.argsort(-y_pred)][:k]
        dcg = float(np.sum(np.where(by_pred > threshold,
                                    2.0 ** by_pred, 0.0)
                           / denom[:len(by_pred)]))
        return 0.0 if idcg == 0.0 else dcg / idcg

    @staticmethod
    def map_score(y_true, y_pred, threshold: float = 0.0) -> float:
        """One query (`Ranker.scala:148-173`): mean average precision —
        precision accumulated at each relevant (> threshold) position of
        the score-sorted list."""
        y_true = np.ravel(np.asarray(y_true, np.float64))
        y_pred = np.ravel(np.asarray(y_pred, np.float64))
        order = np.argsort(-y_pred)
        s, ipos = 0.0, 0
        for i, g in enumerate(y_true[order]):
            if g > threshold:
                ipos += 1
                s += ipos / (i + 1.0)
        return 0.0 if ipos == 0 else s / ipos

    def evaluate_ndcg(self, queries, k: int, threshold: float = 0.0,
                      batch_per_thread: int = 32) -> float:
        """`evaluateNDCG`: mean NDCG@k over `queries` =
        iterable of (x_candidates, y_relevance)."""
        vals = [self.ndcg_score(y, self.predict(
            x, batch_per_thread=batch_per_thread), k, threshold)
            for x, y in queries]
        return float(np.mean(vals)) if vals else 0.0

    def evaluate_map(self, queries, threshold: float = 0.0,
                     batch_per_thread: int = 32) -> float:
        """`evaluateMAP`: mean MAP over per-query candidate lists."""
        vals = [self.map_score(y, self.predict(
            x, batch_per_thread=batch_per_thread), threshold)
            for x, y in queries]
        return float(np.mean(vals)) if vals else 0.0
