"""ZooModel — shared plumbing for the built-in model zoo.

Mirrors `zoo/.../models/common/ZooModel.scala` + `KerasZooModel` (save/load,
summary, predict) and the python `zoo.models.common` base. A ZooModel wraps a
constructed Keras-style graph plus its hyperparameters; `save_model`/
`load_model` persist config + weights so a model reloads without re-specifying
the architecture.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from analytics_zoo_tpu.keras.engine import KerasNet


class ZooModel:
    """Base: subclasses set `self.model` (a KerasNet) in build_model() and
    register their constructor kwargs via `self._config`."""

    def __init__(self):
        self.model: Optional[KerasNet] = None
        self._config: Dict[str, Any] = {}

    # -- Keras passthrough -------------------------------------------------
    def compile(self, optimizer, loss, metrics=None):
        self.model.compile(optimizer, loss, metrics)

    def fit(self, x, y=None, batch_size=32, nb_epoch=1, **kw):
        return self.model.fit(x, y, batch_size=batch_size, nb_epoch=nb_epoch,
                              **kw)

    def evaluate(self, x, y=None, batch_per_thread=32, **kw):
        return self.model.evaluate(x, y, batch_per_thread=batch_per_thread,
                                   **kw)

    def predict(self, x, batch_per_thread=32, **kw):
        return self.model.predict(x, batch_per_thread=batch_per_thread, **kw)

    def predict_classes(self, x, batch_per_thread=32, zero_based_label=True):
        """`Recommender.predict_classes`-style helper: argmax over the class
        axis; the reference's labels are 1-based by default."""
        probs = self.predict(x, batch_per_thread=batch_per_thread)
        cls = np.argmax(probs, axis=-1)
        return cls if zero_based_label else cls + 1

    def summary(self):
        return self.model.summary()

    # -- persistence -------------------------------------------------------
    def _save_config(self, path: str, over_write: bool):
        """Shared config-json step for the plain and encrypted savers."""
        os.makedirs(path, exist_ok=True)
        cfg_path = os.path.join(path, "config.json")
        if os.path.exists(cfg_path) and not over_write:
            raise FileExistsError(f"{path} exists; pass over_write=True")
        with open(cfg_path, "w") as fh:
            json.dump({"class": type(self).__name__,
                       "config": self._config}, fh)

    def save_model(self, path: str, over_write: bool = False):
        """`ZooModel.saveModel`: config json + weights."""
        self._save_config(path, over_write)
        self.model.save_weights(os.path.join(path, "weights"))

    def save_model_encrypted(self, path: str, secret: str, salt: str,
                             over_write: bool = False):
        """Encrypted save (`InferenceModel.scala:121-226` encrypted-model
        loaders): config json in clear, weights AES-GCM-sealed as
        weights.enc — loadable by `InferenceModel.load_keras_encrypted`
        and the serving `secure.model_encrypted` flow."""
        from analytics_zoo_tpu.learn.encrypted import save_encrypted_pytree
        self._save_config(path, over_write)
        save_encrypted_pytree(os.path.join(path, "weights.enc"),
                              self.model.params, secret, salt)

    @classmethod
    def load_model(cls, path: str) -> "ZooModel":
        with open(os.path.join(path, "config.json")) as fh:
            blob = json.load(fh)
        if blob["class"] != cls.__name__:
            raise ValueError(
                f"Checkpoint is a {blob['class']}, not {cls.__name__}")
        inst = cls(**blob["config"])
        inst.model.load_weights(os.path.join(path, "weights"))
        return inst

    def set_checkpoint(self, path: str):
        self.model.set_checkpoint(path)

    def set_tensorboard(self, log_dir: str, app_name: str):
        self.model.set_tensorboard(log_dir, app_name)
