"""Image-classification tooling: named model configs + label maps around
`ImageClassifier` (the reference's
`models/image/imageclassification/ImageClassificationConfig.scala` +
`LabelReader.scala` role).

As with detection, this environment has no egress: named configs resolve
architecture + preprocess + label map, weights come from local files
(`model.save_weights`) or initialize randomly for fine-tuning.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.models.image import ImageClassifier

CIFAR10_CLASSES: Tuple[str, ...] = (
    "airplane", "automobile", "bird", "cat", "deer", "dog", "frog",
    "horse", "ship", "truck")

MNIST_CLASSES: Tuple[str, ...] = tuple(str(d) for d in range(10))


def classification_label_reader(dataset: str,
                                path: Optional[str] = None
                                ) -> Dict[int, str]:
    """`LabelReader.readImagenetlLabelMap` shape: {index: name}. Built-ins
    cover cifar10/mnist; "imagenet" (1000 names) and custom maps load from
    a one-name-per-line file, the reference's resource-file format."""
    key = dataset.lower()
    if key == "cifar10":
        return dict(enumerate(CIFAR10_CLASSES))
    if key == "mnist":
        return dict(enumerate(MNIST_CLASSES))
    if key in ("imagenet", "file"):
        if not path:
            raise ValueError(
                f"label dataset {dataset!r} needs a names file (one class "
                "name per line, line order = class index)")
        with open(path) as fh:
            return dict(enumerate(ln.strip() for ln in fh if ln.strip()))
    raise ValueError(
        f"Unknown label dataset {dataset!r}: cifar10, mnist, imagenet "
        "(with path), or file (with path)")


@dataclass
class ClassificationConfig:
    input_size: int
    class_num: int
    dataset: str
    arch: str = "resnet"        # "resnet" | "inception-v1" | "lenet"
    depth: int = 0              # resnet depth; unused by other archs
    # ImageNet-style preprocess: resize shorter side, center crop,
    # per-channel mean/std (RGB, 0-255 domain)
    resize: int = 256
    mean_rgb: Tuple[float, float, float] = (123.68, 116.78, 103.94)
    std_rgb: Tuple[float, float, float] = (58.4, 57.12, 57.38)
    channels: int = 3
    # caffe-lineage architectures run channels-first so pretrained
    # artifacts transfer weight-for-weight (flatten order matches)
    layout: str = "NHWC"        # "NHWC" | "NCHW"


CLASSIFICATION_MODELS: Dict[str, ClassificationConfig] = {
    "resnet-18-imagenet": ClassificationConfig(224, 1000, "imagenet",
                                               depth=18),
    "resnet-50-imagenet": ClassificationConfig(224, 1000, "imagenet",
                                               depth=50),
    "resnet-18-cifar10": ClassificationConfig(
        32, 10, "cifar10", depth=18, resize=32,
        mean_rgb=(125.3, 123.0, 113.9), std_rgb=(63.0, 62.1, 66.7)),
    # the reference's headline ImageNet trainer (examples/inception)
    "inception-v1-imagenet": ClassificationConfig(
        224, 1000, "imagenet", arch="inception-v1"),
    # the canonical Caffe artifact — pretrained-interop entry
    # (weights_path="caffe:deploy.prototxt,lenet.caffemodel")
    "lenet-mnist": ClassificationConfig(
        28, 10, "mnist", arch="lenet", resize=28,
        mean_rgb=(0.0, 0.0, 0.0), std_rgb=(255.0, 255.0, 255.0),
        channels=1, layout="NCHW"),
}


class ConfiguredClassifier:
    """Classifier bound to its config: preprocess → predict → top-N with
    names (the `ImageConfigure` composition for classification)."""

    def __init__(self, classifier: ImageClassifier,
                 config: ClassificationConfig, name: str):
        self.classifier = classifier
        self.config = config
        self.name = name

    def preprocess(self, images) -> np.ndarray:
        """Shorter-side resize + the shared ImageProcessing crop/normalize
        transforms from `data/image.py` (one implementation of the
        crop/normalize math across the pipeline and the zoo)."""
        import cv2

        from analytics_zoo_tpu.data.image import (ImageCenterCrop,
                                                  ImageChannelNormalize)
        cfg = self.config
        crop = ImageCenterCrop(cfg.input_size, cfg.input_size)
        if cfg.channels == 1:
            # single-channel (MNIST-style): scalar normalize — the RGB
            # normalizer's [3]-vector would broadcast HW1 → HW3
            norm = lambda im: ((im - cfg.mean_rgb[0])  # noqa: E731
                               / cfg.std_rgb[0])
        else:
            norm = ImageChannelNormalize(*cfg.mean_rgb, *cfg.std_rgb)
        if isinstance(images, np.ndarray):
            if images.ndim == 2:          # one grayscale image
                images = [images]
            elif images.ndim == 3:
                # HWC single image vs (N,H,W) stacked grayscale batch:
                # a trailing channel dim (3 or 1) means single image
                images = ([images]
                          if cfg.channels == 3 or images.shape[-1] == 1
                          else list(images))
            else:
                images = list(images)
        out = []
        for img in images:
            img = np.asarray(img).astype(np.float32)
            if cfg.channels == 1 and img.ndim == 3 and img.shape[-1] == 1:
                img = img[..., 0]   # 2-D throughout: cv2.resize drops
                                    # the (H,W,1) channel dim anyway
            h, w = img.shape[:2]
            # resize shorter side to cfg.resize (ImageResize is fixed WxH)
            if min(h, w) != cfg.resize:
                scale = cfg.resize / min(h, w)
                img = cv2.resize(img, (max(cfg.input_size,
                                           int(round(w * scale))),
                                       max(cfg.input_size,
                                           int(round(h * scale)))))
            out.append(norm(crop(img)))
        batch = np.stack(out).astype(np.float32)
        if cfg.channels == 1 and batch.ndim == 3:
            batch = batch[..., None]
        if cfg.layout == "NCHW":
            batch = batch.transpose(0, 3, 1, 2)
        return batch

    def predict_top_n(self, images, top_n: int = 5,
                      batch_per_thread: int = 8):
        probs = self.classifier.predict(self.preprocess(images),
                                        batch_per_thread=batch_per_thread)
        return self.classifier.top_n(probs, top_n)


def load_image_classifier(model_name: str,
                          weights_path: Optional[str] = None,
                          label_path: Optional[str] = None,
                          allow_missing_labels: bool = False
                          ) -> ConfiguredClassifier:
    """`ImageClassifier.loadModel(name)` shape: named config → architecture
    + label map (+ local weights when given). ImageNet-dataset configs
    need a `label_path` names file (no egress to fetch one); pass
    `allow_missing_labels=True` to skip the map (predictions then carry
    integer class indices), e.g. for fine-tuning workflows."""
    if model_name not in CLASSIFICATION_MODELS:
        raise ValueError(
            f"Unknown classification model {model_name!r}; available: "
            f"{sorted(CLASSIFICATION_MODELS)}")
    cfg = CLASSIFICATION_MODELS[model_name]
    if cfg.dataset == "imagenet" and not label_path:
        if not allow_missing_labels:
            raise ValueError(
                f"{model_name} needs a label_path names file (one class "
                "name per line) — or pass allow_missing_labels=True to "
                "predict integer class indices")
        label_map: Dict[int, str] = {}
    else:
        label_map = classification_label_reader(cfg.dataset, label_path)
    if cfg.layout == "NCHW":
        in_shape = (cfg.channels, cfg.input_size, cfg.input_size)
    else:
        in_shape = (cfg.input_size, cfg.input_size, cfg.channels)
    clf = ImageClassifier(
        depth=cfg.depth, class_num=cfg.class_num,
        input_shape=in_shape, label_map=label_map, arch=cfg.arch)
    from analytics_zoo_tpu.models.pretrained import (apply_weight_spec,
                                                     parse_weight_spec)
    spec = parse_weight_spec(weights_path) if weights_path else None
    if weights_path and spec is None:
        clf.model.load_weights(weights_path)    # native ckpt: no throwaway
    else:                                       # random init build
        import jax
        clf.model.ensure_built(
            np.zeros((1,) + in_shape, np.float32), jax.random.PRNGKey(0))
        if spec is not None:
            apply_weight_spec(clf.model, weights_path, strict=True,
                              parsed=spec)
    return ConfiguredClassifier(clf, cfg, model_name)
