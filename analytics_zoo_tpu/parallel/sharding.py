"""GSPMD sharding rules: parameter PartitionSpecs by tree path.

This replaces the reference's entire communication stack for model-scale
parallelism. The reference shards nothing but the batch (sync data parallel
over five transports, `docs/docs/wp-bigdl.md:150-166`); here a parameter tree
is annotated with `PartitionSpec`s per path-regex rule, `jax.jit` propagates
the shardings, and XLA emits the all-gathers/reduce-scatters over ICI. Tensor
parallelism is therefore a *table of specs*, not a rewrite of every layer —
the idiomatic-GSPMD design (scaling-book recipe: pick mesh, annotate, let XLA
insert collectives).

Megatron-style conventions for transformer blocks:
- column-parallel: QKV and FFN-in kernels split on the output dim ("tensor");
  their biases split likewise;
- row-parallel: attention-out and FFN-out kernels split on the input dim;
  outputs need a psum which XLA inserts; biases replicated;
- embeddings split on the hidden dim so the gather stays local;
- everything else falls through to FSDP sharding on its largest divisible dim
  (ZeRO-3: params all-gathered just-in-time per layer) or replication.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.common.mesh import BATCH_AXES, DeviceMesh


class ShardingRules:
    """Ordered (path-regex, PartitionSpec) table; first match wins.

    A parameter's path is its key chain joined with "/", e.g.
    "bert_1/bert_1_block0/attn/qkv_kernel". The same table annotates an
    OPTIMIZER state tree: optax state leaves flatten with paths that end
    in their parameter's path ("0/.mu/bert/.../qkv_kernel"), so each
    param's spec mirrors onto its moments and scalar leaves (step
    counters) fall through to replication — the match_partition_rules
    pattern, one table for params and opt_state, training and serving.
    """

    def __init__(self, rules: Sequence[Tuple[str, P]],
                 fsdp_fallback: bool = True):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.fsdp_fallback = fsdp_fallback

    def spec_for(self, path: str, shape: Tuple[int, ...],
                 mesh: DeviceMesh) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                trimmed = _trim_spec(spec, shape, mesh)
                if (len(spec) > 0
                        and not any(ax is not None for ax in trimmed)
                        and self.fsdp_fallback and mesh.size("fsdp") > 1):
                    # The rule WANTED this leaf sharded but none of its
                    # axes survived on this mesh (e.g. the embedding
                    # rule's 'tensor' axis on a pure data×fsdp mesh):
                    # fall through to ZeRO-style fsdp sharding rather
                    # than silently replicating a large table. An
                    # explicit P() rule (norm scales) stays replicated.
                    return _fsdp_spec(shape, mesh)
                return trimmed
        if self.fsdp_fallback and mesh.size("fsdp") > 1:
            return _fsdp_spec(shape, mesh)
        return P()

    def fingerprint(self) -> str:
        """Stable-across-processes content hash of the table — cache
        keys fold this in so two fits under different rule tables (or
        a replicated vs an fsdp fit) can never share an executable.
        Hashes the raw patterns + specs, NOT object identity."""
        import hashlib
        blob = ";".join(f"{pat.pattern}->{spec}" for pat, spec in self.rules)
        blob += f";fallback={self.fsdp_fallback}"
        return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _trim_spec(spec: P, shape: Tuple[int, ...], mesh: DeviceMesh) -> P:
    """Drop axes the mesh doesn't have (size 1) or that don't divide the dim
    — GSPMD would pad, but even sharding is both faster and exact."""
    out: List[Optional[str]] = []
    for i, ax in enumerate(spec):
        if ax is None or i >= len(shape):
            out.append(None)
            continue
        axes = ax if isinstance(ax, tuple) else (ax,)
        sizes = int(np.prod([mesh.size(a) for a in axes]))
        if sizes > 1 and shape[i] % sizes == 0:
            out.append(ax)
        else:
            out.append(None)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def _fsdp_spec(shape: Tuple[int, ...], mesh: DeviceMesh) -> P:
    """Shard the largest dim divisible by the fsdp axis; else replicate."""
    n = mesh.size("fsdp")
    dims = sorted(range(len(shape)), key=lambda i: -shape[i])
    for d in dims:
        if shape[d] >= n and shape[d] % n == 0:
            spec: List[Optional[str]] = [None] * len(shape)
            spec[d] = "fsdp"
            return P(*spec)
    return P()


# Megatron-style transformer table (matches keras/transformer.py param names).
TRANSFORMER_RULES = ShardingRules([
    (r"qkv_kernel$", P("fsdp", "tensor")),      # column-parallel
    (r"qkv_bias$", P("tensor")),
    (r"out_kernel$", P("tensor", "fsdp")),      # row-parallel
    (r"out_bias$", P()),
    (r"ffn_in_kernel$", P("fsdp", "tensor")),   # column-parallel
    (r"ffn_in_bias$", P("tensor")),
    (r"ffn_out_kernel$", P("tensor", "fsdp")),  # row-parallel
    (r"ffn_out_bias$", P()),
    (r"(word|position|token_type)_embeddings$", P(None, "tensor")),
    (r"pooler_kernel$", P(None, "tensor")),
    (r"(ln\d?|_ln|layernorm|emb_ln)/", P()),    # norm scales: replicated
])


def _tree_paths_and_leaves(tree) -> List[Tuple[str, Any]]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for keypath, leaf in flat:
        parts = []
        for k in keypath:
            parts.append(str(getattr(k, "key", getattr(k, "idx", k))))
        out.append(("/".join(parts), leaf))
    return out


def param_specs(params, mesh: DeviceMesh,
                rules: ShardingRules = TRANSFORMER_RULES):
    """Pytree of PartitionSpec matching `params`, per the rule table."""
    _, treedef = jax.tree_util.tree_flatten(params)
    specs = [rules.spec_for(path, tuple(np.shape(leaf)), mesh)
             for path, leaf in _tree_paths_and_leaves(params)]
    return jax.tree_util.tree_unflatten(treedef, specs)


def sharding_descriptor(mesh: DeviceMesh,
                        rules: "ShardingRules" = None,
                        devices=None) -> str:
    """Canonical layout string for compile-cache keys: mesh axis
    extents + the rule table's content fingerprint (+ device ids when
    the caller's executables pin a device assignment). ONE spelling for
    the trainer's step key and serving's forward key, so what counts as
    "the layout" can never drift between the two stacks."""
    rules = rules if rules is not None else TRANSFORMER_RULES
    desc = (repr(sorted(mesh.axis_sizes.items()))
            + "|rules=" + rules.fingerprint())
    if devices is not None:
        desc += f"|dev={sorted(d.id for d in devices)}"
    return desc


def tree_shardings(tree, mesh: DeviceMesh,
                   rules: ShardingRules = TRANSFORMER_RULES):
    """Pytree of NamedSharding matching `tree`, per the rule table.
    Works on parameter trees AND optimizer states (see ShardingRules:
    optax leaf paths carry the param path, so moments mirror their
    param's spec) — the layout contract shared by `fit_keras`'s sharded
    placement and serving's sharded placement."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh.mesh, s),
        param_specs(tree, mesh, rules))


def shard_params(params, mesh: DeviceMesh,
                 rules: ShardingRules = TRANSFORMER_RULES):
    """device_put each parameter with its rule's NamedSharding. A leaf
    that already carries the target sharding passes through device_put
    as the SAME buffer — a checkpoint restored straight onto the rule
    layout (or a live sharded fit's params) loads with zero resharding
    transfers. Host leaves go to device_put as-is: an eager
    jnp.asarray would materialize the full leaf on the default device
    first, defeating the bigger-than-one-chip case."""
    specs = param_specs(params, mesh, rules)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh.mesh, s)),
        params, specs)


def check_fsdp_divisibility(params, mesh: DeviceMesh,
                            rules: ShardingRules = TRANSFORMER_RULES,
                            min_size: int = 4096) -> None:
    """Validate that every LARGE parameter actually shards over the
    fsdp axis. The largest-dim fallback (`_fsdp_spec`) silently
    replicates a leaf none of whose dims divide `fsdp` — correct but
    defeating the 1/fsdp memory goal, so a big offender should fail
    loudly at config time, not OOM three layers later. Leaves smaller
    than `min_size` elements (biases, norm scales) legitimately
    replicate and are skipped."""
    n = mesh.size("fsdp")
    if n <= 1:
        return
    offenders: List[Tuple[str, Tuple[int, ...]]] = []
    for path, leaf in _tree_paths_and_leaves(params):
        shape = tuple(int(d) for d in np.shape(leaf))
        if not shape or int(np.prod(shape)) < max(min_size, n):
            continue
        spec = rules.spec_for(path, shape, mesh)
        if any(ax is not None for ax in spec):
            continue                      # sharded on some axis
        offenders.append((path, shape))
    if offenders:
        detail = ", ".join(f"{p} {s}" for p, s in offenders[:8])
        more = f" (+{len(offenders) - 8} more)" if len(offenders) > 8 else ""
        raise ValueError(
            f"{len(offenders)} large parameter(s) cannot shard over the "
            f"fsdp axis (size {n}) and would replicate on every device: "
            f"{detail}{more}. Fix by choosing an fsdp size that divides "
            "a dimension of each (e.g. a power of two matching the "
            "hidden size), padding the offending dimension, or adding "
            "an explicit ShardingRules entry for it.")


def shard_batch(batch, mesh: DeviceMesh, sequence_dim: Optional[int] = None):
    """Batch dim over the data axes; optionally the sequence dim over the
    'sequence' axis (sequence parallelism for long-context inputs)."""
    def put(a):
        a = jnp.asarray(a)
        spec: List[Any] = [BATCH_AXES] + [None] * (a.ndim - 1)
        if (sequence_dim is not None and mesh.size("sequence") > 1
                and a.ndim > sequence_dim
                and a.shape[sequence_dim] % mesh.size("sequence") == 0):
            spec[sequence_dim] = "sequence"
        return jax.device_put(a, NamedSharding(mesh.mesh, P(*spec)))
    return jax.tree_util.tree_map(put, batch)


def build_sharded_train_step(apply_fn, loss_fn,
                             optimizer: optax.GradientTransformation):
    """The multi-axis analogue of `trainer.build_train_step`: same pure
    function, but parameters arrive sharded (tensor/fsdp), the batch arrives
    split (data×fsdp, optionally sequence), and jit's sharding propagation +
    GSPMD turn the single program into DP gradient all-reduce + TP activation
    collectives + FSDP all-gathers — the whole reference comms stack
    (SURVEY §2.5) emitted by the compiler."""

    def train_step(params, opt_state, xb, yb, rng):
        def compute_loss(p):
            pred = apply_fn(p, xb, training=True, rng=rng)
            return loss_fn(yb, pred)
        loss, grads = jax.value_and_grad(compute_loss)(params)
        updates, opt_state2 = optimizer.update(grads, opt_state, params)
        params2 = optax.apply_updates(params, updates)
        return params2, opt_state2, loss

    return jax.jit(train_step, donate_argnums=(0, 1))
