"""Parallelism strategies over the device mesh.

The reference is data-parallel only (five transports, SURVEY §2.5); this
package supplies the parallelism the TPU build adds as first-class features:
tensor/FSDP sharding rules (GSPMD PartitionSpecs), sequence/context parallel
ring attention (`shard_map` + `ppermute`), and pipeline stages.
"""

from analytics_zoo_tpu.parallel.sharding import (  # noqa: F401
    ShardingRules, TRANSFORMER_RULES, param_specs, shard_params,
    shard_batch, build_sharded_train_step)
