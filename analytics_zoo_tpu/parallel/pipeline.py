"""Pipeline parallelism: GPipe-style microbatch schedule over the mesh's
"pipeline" axis.

Capability beyond the reference (data-parallel only, SURVEY §2.5): a stack of
S identical stages (the transformer-block case) is sharded one-stage-per-
device-group along "pipeline"; microbatches stream in and activations hop
stage-to-stage with `lax.ppermute` (neighbour transfers — the pattern that
tolerates DCN between slices, which is why "pipeline" is the outermost mesh
axis, `common/mesh.py`). The whole schedule is one `lax.scan` inside
`shard_map`, so it jits to a single XLA program and is differentiable (the
ppermute transposes to the reverse permutation in backward).

Schedule: T = n_micro + S - 1 ticks (fill + drain). At tick t, stage 0 eats
microbatch t (ticks >= n_micro recompute the last microbatch; their outputs
are discarded), stage p processes what stage p-1 produced at t-1, and the last
stage's outputs from ticks S-1..T-1 are the results, broadcast with a masked
psum.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.common.mesh import BATCH_AXES, DeviceMesh
from analytics_zoo_tpu.parallel.compat import pvary, shard_map


def _pipeline_shard(params, mbs, stage_fn: Callable, axis: str, n_stages: int):
    """Per-shard body. params: this stage's params (leading dim 1 stripped
    by caller's tree_map); mbs: [M, mb, ...] microbatches (replicated over
    the pipeline axis)."""
    M = mbs.shape[0]
    T = M + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
    idx = lax.axis_index(axis)

    def body(act, t):
        recv = lax.ppermute(act, axis, perm)
        mb_t = lax.dynamic_index_in_dim(
            mbs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        inp = jnp.where(idx == 0, mb_t, recv)
        out = stage_fn(params, inp)
        return out, out

    # carry becomes pipeline-varying after the first ppermute; mark the
    # initial value to match (shard_map vma typing; identity on jax
    # versions whose shard_map tracks replication instead — compat.pvary)
    act0 = pvary(jnp.zeros_like(mbs[0]), axis)
    _, ys = lax.scan(body, act0, jnp.arange(T))
    valid = ys[n_stages - 1:]                      # [M, mb, ...]
    out = jnp.where(idx == n_stages - 1, valid, jnp.zeros_like(valid))
    return lax.psum(out, axis)                     # broadcast final outputs


def pipeline_apply(stage_fn: Callable, stacked_params, microbatches,
                   mesh: DeviceMesh, axis: str = "pipeline",
                   seq_axis: str = None):
    """Run `stage_fn(params_s, x) -> y` (same x/y shape) for stages
    s = 0..S-1 as a pipeline.

    stacked_params: pytree whose leaves have leading dim S (one slice per
    stage), sharded over `axis`. microbatches: [n_micro, mb_size, ...];
    the batch dim shards over the data axes as usual. Returns
    [n_micro, mb_size, ...] outputs (identical on every pipeline rank).

    `seq_axis`: when the microbatches carry a sequence dim at position 2
    that is already sharded over a mesh axis (ring-attention output),
    name it here so the pipeline consumes it sharded instead of forcing
    an all-gather + full rematerialization between the two shard_maps
    (per-token stages never need the full sequence).
    """
    S = mesh.size(axis)
    n_stacked = {leaf.shape[0]
                 for leaf in jax.tree_util.tree_leaves(stacked_params)}
    if n_stacked != {S} and S != 1:
        raise ValueError(
            f"stacked_params leading dims {sorted(n_stacked)} must all equal "
            f"the pipeline axis size ({S})")
    if S == 1:
        def apply_all(x):
            def body(x, p):
                return stage_fn(p, x), None
            y, _ = lax.scan(body, x, stacked_params)
            return y
        return jax.vmap(apply_all)(microbatches)

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    mb_spec = P(None, BATCH_AXES, seq_axis) if seq_axis \
        else P(None, BATCH_AXES)

    def shard(params, mbs):
        params = jax.tree_util.tree_map(
            lambda p: jnp.squeeze(p, axis=0), params)
        return _pipeline_shard(params, mbs, stage_fn, axis, S)

    fn = shard_map(shard, mesh=mesh.mesh,
                   in_specs=(param_specs, mb_spec),
                   out_specs=mb_spec)
    return fn(stacked_params, microbatches)


def to_microbatches(x, n_micro: int):
    """[B, ...] -> [n_micro, B/n_micro, ...] by INTERLEAVING (microbatch i
    takes rows i::n_micro). A contiguous split of a data-sharded batch
    would land the n_micro dim on the data axis (forcing a reshard every
    pipeline tick); interleaving keeps the per-microbatch batch dim
    sharded exactly like the full batch."""
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by n_micro {n_micro}")
    return jnp.swapaxes(
        x.reshape((B // n_micro, n_micro) + x.shape[1:]), 0, 1)


def from_microbatches(y):
    """Inverse of `to_microbatches` (restores original row order)."""
    n_micro, mb = y.shape[0], y.shape[1]
    return jnp.swapaxes(y, 0, 1).reshape((n_micro * mb,) + y.shape[2:])
