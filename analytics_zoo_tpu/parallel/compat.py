"""jax API compatibility shims for the parallel package.

`shard_map` has moved twice across jax releases — born in
`jax.experimental.shard_map`, promoted to `jax.shard_map` (where the
`check_rep` kwarg became `check_vma`) — and the old spelling is removed
from versions that carry the new one, so no single import works
everywhere. `pcast`/`pvary` (marking a value as varying over a mesh
axis for the new shard_map's varying-axes type system) likewise exists
only where that type system does. One resolution point here keeps
`ring_attention.py` / `pipeline.py` / the trainer's fused-optimizer
shard_map working on both sides of the drift; everything resolves at
import time, so a broken jax fails loudly at import, not mid-dispatch.
"""

from __future__ import annotations

import inspect

import jax
from jax import lax


def _resolve_shard_map():
    impl = getattr(jax, "shard_map", None)
    if impl is None:
        from jax.experimental.shard_map import shard_map as impl
    return impl


_SHARD_MAP = _resolve_shard_map()
# `check_rep` (old) / `check_vma` (new) name the same knob: verify the
# body's claimed replication/varying types. Neither existing → drop it.
_CHECK_KW = next((k for k in ("check_vma", "check_rep")
                  if k in inspect.signature(_SHARD_MAP).parameters), None)


def shard_map(f, *, mesh, in_specs, out_specs, check: bool = False):
    """`shard_map` across jax spellings. `check` maps onto whichever of
    `check_vma`/`check_rep` this jax has; default False — the callers
    here use `ppermute` rings and masked `psum` broadcasts whose
    replication types the older checker cannot prove."""
    kw = {_CHECK_KW: check} if _CHECK_KW is not None else {}
    return _SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)


def pvary(x, axis_name: str):
    """Mark `x` as varying over `axis_name` inside a shard_map body —
    `lax.pvary` / `lax.pcast(..., to="varying")` where the varying-axes
    type system exists, identity where it does not (the old shard_map
    tracks replication, not variance, and needs no annotation)."""
    fn = getattr(lax, "pvary", None)
    if fn is not None:
        return fn(x, axis_name)
    fn = getattr(lax, "pcast", None)
    if fn is not None:
        return fn(x, axis_name, to="varying")
    return x
