"""Ring attention: exact attention over a sequence-sharded context.

Long-context capability the reference lacks entirely (SURVEY §5 "Long-context
/ sequence parallelism: absent"). The sequence dim of Q/K/V lives sharded over
the mesh's "sequence" axis; each device computes attention of its local query
block against every key/value block, rotating K/V around the ring with
`lax.ppermute` (one neighbour hop per step, riding ICI) while accumulating an
online (flash-style) softmax — so a T-length context needs only T/n per-device
memory and never materializes the [T, T] score matrix across devices.

The algorithm is the blockwise-parallel/ring formulation (Liu et al., ring
attention; same online-softmax update as the Pallas flash kernel in
`analytics_zoo_tpu/pallas/flash_attention.py`, which handles the *within
device* blocking — the two compose: ring over devices, flash within).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from analytics_zoo_tpu.common.mesh import BATCH_AXES, DeviceMesh
from analytics_zoo_tpu.parallel.compat import shard_map

NEG_INF = -1e30


def _ring_attention_shard(q, k, v, kmask, axis: str):
    """Per-shard body. q: [B, H, Tq, D] local; k/v: [B, H, Tk, D] local;
    kmask: [B, Tk] additive (0 / -inf-like) for local keys, or None."""
    axis_size = lax.psum(1, axis)
    scale = 1.0 / math.sqrt(q.shape[-1])
    qf = q.astype(jnp.float32) * scale
    B, H, Tq, D = q.shape

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def block_update(o, l, m, k, v, kmask):
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k.astype(jnp.float32))
        if kmask is not None:
            s = s + kmask[:, None, None, :]
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = (o * alpha[..., None]
                 + jnp.einsum("bhqk,bhkd->bhqd", p,
                              v.astype(jnp.float32)))
        return o_new, l_new, m_new

    # Derive initial accumulators from qf so they carry the same
    # varying-axes type as the loop outputs (shard_map vma check).
    o0 = jnp.zeros_like(qf)
    l0 = jnp.zeros_like(qf[..., 0])
    m0 = jnp.zeros_like(qf[..., 0]) + NEG_INF
    # Local block first, then rotate-and-accumulate n-1 times — the final
    # rotation (whose result would be discarded) never happens.
    o0, l0, m0 = block_update(o0, l0, m0, k, v, kmask)

    def step(carry, _):
        o, l, m, k, v, kmask = carry
        k = lax.ppermute(k, axis, perm)
        v = lax.ppermute(v, axis, perm)
        if kmask is not None:
            kmask = lax.ppermute(kmask, axis, perm)
        o, l, m = block_update(o, l, m, k, v, kmask)
        return (o, l, m, k, v, kmask), None

    (o, l, m, _, _, _), _ = lax.scan(
        step, (o0, l0, m0, k, v, kmask), None, length=axis_size - 1)
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked query rows -> zeros
    return (o / l[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mask: Optional[jax.Array] = None, *,
                   mesh: DeviceMesh, axis: str = "sequence",
                   head_axis: Optional[str] = "tensor"):
    """Exact attention with Q/K/V sequence-sharded over `axis`.

    q, k, v: [B, H, T, D]; mask: optional additive key mask [B, T]
    (0 for keep, large-negative for drop — the BERT convention,
    `keras/transformer.py make_mask` squeezed to 2D).
    Batch shards over the data axes, heads over `head_axis`, T over `axis`.
    """
    n = mesh.size(axis)
    if n == 1 and mesh.size(head_axis or "tensor") == 1:
        from analytics_zoo_tpu.pallas.flash_attention import (
            _reference_attention)
        m4 = None if mask is None else mask[:, None, None, :]
        return _reference_attention(q, k, v, m4)

    qkv_spec = P(BATCH_AXES, head_axis, axis, None)
    mask_spec = P(BATCH_AXES, axis)

    shard_fn = functools.partial(_ring_attention_shard, axis=axis)
    if mask is None:
        fn = shard_map(
            lambda q, k, v: shard_fn(q, k, v, None),
            mesh=mesh.mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec)
        return fn(q, k, v)
    fn = shard_map(
        shard_fn, mesh=mesh.mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec)
    return fn(q, k, v, mask)
