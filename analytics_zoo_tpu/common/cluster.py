"""Multi-process cluster bootstrap — the RayOnSpark-role launcher.

Reference: `RayContext` boots worker daemons across Spark executors with a
barrier-job master election (`pyzoo/zoo/ray/raycontext.py:262,210`), and
`ProcessMonitor`/`JVMGuard` reap leaked processes (`ray/process.py:90`). On
TPU, rendezvous is `jax.distributed.initialize` (one mechanism instead of
five, SURVEY §5) and pods are normally launched by the platform — so what
remains for the framework is (a) a worker entrypoint that wires coordinator
env into `init_zoo_context`, and (b) a local multi-process launcher that
simulates an N-host cluster on one machine (CPU devices), used for testing
the multi-host code path exactly like the reference tests multi-worker on
`local[N]` (SURVEY §4).

    # run fn in 2 "hosts" x 2 devices each:
    launch_local_cluster("my_module:main", num_processes=2,
                         devices_per_process=2)

Worker side (any real deployment):
    python -m analytics_zoo_tpu.common.cluster \
        --worker my_module:main --coordinator host0:29500 \
        --num-processes 8 --process-id $RANK
"""

from __future__ import annotations

import argparse
import importlib
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

__all__ = ["launch_local_cluster", "wait_all", "ProcessMonitor",
           "force_cpu_devices"]


def force_cpu_devices(n: int) -> None:
    """Force the CPU backend with `n` virtual devices, across jax
    versions: newer jax exposes a `jax_num_cpu_devices` config option;
    older ones reject it (`Unrecognized config option`) and need the
    `--xla_force_host_platform_device_count` XLA flag instead. Must run
    before the CPU backend initializes (both spellings are
    backend-construction-time knobs); the callers here sit at process
    start, before any device use."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", int(n))
    except AttributeError:
        import re
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={int(n)}"
        ).strip()
    try:
        # cross-process collectives on the CPU backend: jax versions
        # that gate them behind a collectives implementation raise
        # "Multiprocess computations aren't implemented on the CPU
        # backend" until one is selected; gloo ships in jaxlib. A no-op
        # for single-process runs and absent on jax trees that predate
        # (or retired) the option.
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ProcessMonitor:
    """Tracks spawned workers; kills the whole group on exit/failure
    (`ProcessMonitor`/`JVMGuard` semantics, `ray/process.py:90`)."""

    def __init__(self, procs: Sequence[subprocess.Popen]):
        self.procs = list(procs)

    def wait(self, timeout: Optional[float] = None) -> List[int]:
        """Wait for all; on any nonzero exit, terminate the rest (fail
        fast like a barrier job). Returns exit codes."""
        deadline = None if timeout is None else time.time() + timeout
        codes: Dict[int, int] = {}
        try:
            while len(codes) < len(self.procs):
                for i, p in enumerate(self.procs):
                    if i in codes:
                        continue
                    rc = p.poll()
                    if rc is not None:
                        codes[i] = rc
                        if rc != 0:
                            self.terminate()
                            raise RuntimeError(
                                f"worker {i} exited with {rc}; cluster "
                                "terminated")
                if deadline and time.time() > deadline:
                    self.terminate()
                    raise TimeoutError("cluster wait timed out")
                time.sleep(0.05)
        except BaseException:
            self.terminate()
            raise
        return [codes[i] for i in range(len(self.procs))]

    def terminate(self):
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        t0 = time.time()
        while time.time() - t0 < 5:
            if all(p.poll() is not None for p in self.procs):
                return
            time.sleep(0.05)
        for p in self.procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass


def launch_local_cluster(worker: str, num_processes: int,
                         devices_per_process: int = 1,
                         worker_args: Sequence[str] = (),
                         env: Optional[Dict[str, str]] = None,
                         platform: str = "cpu") -> ProcessMonitor:
    """Spawn `num_processes` local worker processes that rendezvous via
    jax.distributed and each see `devices_per_process` CPU devices —
    an N-host pod on one machine. `worker` is "module:function"."""
    coordinator = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(num_processes):
        cmd = [sys.executable, "-m", "analytics_zoo_tpu.common.cluster",
               "--worker", worker, "--coordinator", coordinator,
               "--num-processes", str(num_processes),
               "--process-id", str(pid),
               "--devices-per-process", str(devices_per_process),
               "--platform", platform, "--", *worker_args]
        penv = dict(os.environ)
        penv.update(env or {})
        procs.append(subprocess.Popen(cmd, env=penv))
    return ProcessMonitor(procs)


def wait_all(monitor: ProcessMonitor, timeout: Optional[float] = None):
    return monitor.wait(timeout)


def _worker_main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--worker", required=True, help="module:function")
    p.add_argument("--coordinator", required=True)
    p.add_argument("--num-processes", type=int, required=True)
    p.add_argument("--process-id", type=int, required=True)
    p.add_argument("--devices-per-process", type=int, default=1)
    p.add_argument("--platform", default=None)
    p.add_argument("rest", nargs="*")
    args = p.parse_args(argv)

    if args.platform == "cpu":
        force_cpu_devices(args.devices_per_process)
    else:
        import jax  # noqa: F401

    from analytics_zoo_tpu.common.config import ZooConfig
    from analytics_zoo_tpu.common.context import init_zoo_context
    cfg = ZooConfig()
    cfg.coordinator_address = args.coordinator
    cfg.num_processes = args.num_processes
    cfg.process_id = args.process_id
    init_zoo_context(cfg, cluster_mode="multi-host")

    mod_name, _, fn_name = args.worker.partition(":")
    fn = getattr(importlib.import_module(mod_name), fn_name or "main")
    result = fn(*args.rest)
    return int(result) if isinstance(result, int) else 0


if __name__ == "__main__":
    sys.exit(_worker_main())
