"""Device-mesh abstraction: the single communication substrate.

The reference maintains five data-parallel transports (Spark BlockManager
scatter-reduce `docs/docs/wp-bigdl.md:150-166`, Horovod-gloo, TF
MultiWorkerMirrored gRPC, torch.distributed gloo, MXNet kvstore — survey §2.5).
Here they all collapse into one object: a `jax.sharding.Mesh` whose axes map
onto the TPU interconnect. GSPMD emits `all-reduce`/`reduce-scatter`/
`all-gather`/`collective-permute` over ICI (and DCN for the outer axes), so the
"communication backend" is the XLA compiler itself.

Axis convention (outermost → innermost, i.e. DCN-most → ICI-most):
    pipeline — pipeline stages; activations `ppermute` stage-to-stage (DCN-ok).
    data     — data parallel; gradients all-reduce here.
    fsdp     — parameter/optimizer-state sharding (ZeRO-3 style all-gather).
    sequence — sequence/context parallel; ring attention `ppermute`s here.
    expert   — expert parallel; MoE all-to-all rides here.
    tensor   — tensor parallel; activation collectives need the fastest links.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from analytics_zoo_tpu.common.config import MeshConfig

# Outermost → innermost. Single source of truth for axis names/order.
AXIS_NAMES: Tuple[str, ...] = (
    "pipeline", "data", "fsdp", "sequence", "expert", "tensor")
# Axes over which the input batch is split.
BATCH_AXES: Tuple[str, ...] = ("data", "fsdp")


def _infer_axis_sizes(n_devices: int, cfg: MeshConfig) -> Dict[str, int]:
    sizes = {name: getattr(cfg, name) for name in AXIS_NAMES}
    for name, v in sizes.items():
        if v != -1 and v < 1:
            raise ValueError(
                f"Mesh axis {name}={v} invalid: must be >=1, or -1 to infer")
    fixed = math.prod(v for v in sizes.values() if v != -1)
    free = [k for k, v in sizes.items() if v == -1]
    if len(free) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {free}")
    if free:
        if n_devices % fixed != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes product {fixed}")
        sizes[free[0]] = n_devices // fixed
    if math.prod(sizes.values()) != n_devices:
        raise ValueError(
            f"Mesh {sizes} does not cover {n_devices} devices")
    return sizes


class DeviceMesh:
    """A named logical mesh over the available devices.

    >>> mesh = DeviceMesh()                       # all-data-parallel
    >>> mesh = DeviceMesh(MeshConfig(data=-1, tensor=4))
    >>> with mesh: ...                            # acts as jax Mesh context
    """

    def __init__(self,
                 config: Optional[MeshConfig] = None,
                 devices: Optional[Sequence[jax.Device]] = None):
        self.config = config or MeshConfig()
        devs = list(devices) if devices is not None else jax.devices()
        self.axis_sizes = _infer_axis_sizes(len(devs), self.config)
        shape = tuple(self.axis_sizes[a] for a in AXIS_NAMES)
        # Row-major reshape keeps 'tensor' innermost so tensor-parallel
        # collectives land on directly-connected neighbours; 'pipeline'/'data'
        # outermost so their (infrequent or overlappable) transfers may span
        # DCN in multi-slice deployments.
        dev_array = np.asarray(devs).reshape(shape)
        self.mesh = Mesh(dev_array, AXIS_NAMES)

    # -- mapping helpers ---------------------------------------------------
    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def data_parallel_size(self) -> int:
        return math.prod(self.axis_sizes[a] for a in BATCH_AXES)

    def size(self, axis: str) -> int:
        return self.axis_sizes[axis]

    def sharding(self, *spec) -> NamedSharding:
        """NamedSharding for a PartitionSpec over this mesh."""
        return NamedSharding(self.mesh, PartitionSpec(*spec))

    def batch_sharding(self) -> NamedSharding:
        """Canonical input-batch sharding: batch dim split over every
        batch-like axis (data × fsdp), rest replicated."""
        return NamedSharding(self.mesh, PartitionSpec(BATCH_AXES))

    def stacked_batch_sharding(self) -> NamedSharding:
        """Sharding for a (steps, batch, ...) stack of training batches:
        leading scan dim replicated, batch dim split like batch_sharding."""
        return NamedSharding(self.mesh, PartitionSpec(None, BATCH_AXES))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    # -- context manager ---------------------------------------------------
    def __enter__(self):
        self._ctx = self.mesh.__enter__()
        return self

    def __exit__(self, *exc):
        return self.mesh.__exit__(*exc)

    def __repr__(self):
        axes = ", ".join(f"{a}={self.axis_sizes[a]}"
                         for a in AXIS_NAMES if self.axis_sizes[a] != 1)
        return f"DeviceMesh({axes or 'single-device'})"


def validate_axis_names(axes) -> None:
    """THE axis-vocabulary check for config-driven mesh construction —
    serving-config load and `mesh_from_axes` both call it, so the
    vocabulary and its error can never drift between the two sites."""
    unknown = set(axes) - set(AXIS_NAMES)
    if unknown:
        raise ValueError(
            f"unknown mesh axis name(s) {sorted(unknown)}; valid axes: "
            f"{list(AXIS_NAMES)}")


def mesh_from_axes(axes: Dict[str, int],
                   devices: Optional[Sequence[jax.Device]] = None
                   ) -> DeviceMesh:
    """DeviceMesh from a plain axis→size mapping (the serving-config /
    CLI spelling, e.g. ``{"data": 1, "fsdp": 2, "tensor": 4}``) — ONE
    validation point for config-driven mesh construction, so a typo'd
    axis name fails with the axis vocabulary instead of a dataclass
    TypeError. Sizes follow MeshConfig semantics (-1 infers one axis
    from the device count; unlisted axes default per MeshConfig)."""
    validate_axis_names(axes)
    try:
        sizes = {k: int(v) for k, v in axes.items()}
    except (TypeError, ValueError):
        raise ValueError(
            f"mesh axis sizes must be integers, got {axes!r}") from None
    return DeviceMesh(MeshConfig(**sizes), devices)


def local_mirror_mesh(n: int = 1) -> DeviceMesh:
    """Single-host mesh over the first n local devices (testing helper)."""
    return DeviceMesh(MeshConfig(data=n), jax.local_devices()[:n])
