"""Unified typed configuration with environment-variable overrides.

The reference scatters configuration over six mechanisms — SparkConf keys from an
embedded properties file (`common/NNContext.scala:189-239`), Java system
properties (`bigdl.failure.retryTimes`), `init_orca_context` kwargs
(`orca/common.py:89`), `ZooContext`/`OrcaContext` class-property flags
(`orca/common.py:21-86`), the serving YAML, and per-example scopt CLIs. Here a
single dataclass hierarchy carries every knob; `ZOO_*` environment variables
override any field, and sub-configs serialize to/from plain dicts so the serving
YAML and CLI layers reuse the same schema.
"""

from __future__ import annotations

import dataclasses
import json
import os
import typing
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


def _coerce(value: str, typ: Any, env_key: str) -> Any:
    """Parse an env-var string into the annotated field type."""
    origin = typing.get_origin(typ)
    if origin is typing.Union:  # Optional[T] → first non-None arg
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        typ = args[0] if args else str
        origin = typing.get_origin(typ)
    try:
        if typ is bool:
            return value.lower() in ("1", "true", "yes", "on")
        if typ is int:
            return int(value)
        if typ is float:
            return float(value)
        if origin is tuple or typ is tuple:
            return tuple(int(v) for v in value.split(",") if v)
    except ValueError as e:
        raise ValueError(f"Bad value for env override {env_key}={value!r}: {e}")
    return value


@dataclass
class MeshConfig:
    """Logical device-mesh axes over ICI (fast, intra-slice) and DCN (slow,
    cross-slice). Axis sizes of -1 are inferred from the device count. Axis
    order/meaning is defined by `analytics_zoo_tpu.common.mesh.AXIS_NAMES`."""

    data: int = -1        # data parallel (outermost; may span DCN)
    fsdp: int = 1         # parameter/optimizer sharding (ZeRO-style)
    tensor: int = 1       # tensor/model parallel (innermost; rides ICI)
    sequence: int = 1     # sequence/context parallel (ring attention)
    pipeline: int = 1     # pipeline stages (spans DCN between slices)
    expert: int = 1       # expert parallel for MoE


@dataclass
class FailureConfig:
    """Retry/recovery semantics of the reference's training loop
    (`Topology.scala:1255-1337`): `bigdl.failure.retryTimes` default 5 within a
    120 s sliding window, restore from the latest snapshot on failure."""

    retry_times: int = 5
    retry_time_interval_s: int = 120


@dataclass
class CheckpointConfig:
    """Checkpoint layout compatible with the reference
    (`tf_optimizer.py:398-413`): `<dir>/<stamp>/model.<iteration>` plus
    `optimMethod-<name>.<iteration>`."""

    path: Optional[str] = None
    every_n_iterations: int = 0      # 0 → only on EveryEpoch trigger
    keep: int = 3
    async_save: bool = True


def _default_serving_config():
    # The canonical ServingConfig lives in serving/config.py (it also owns
    # YAML loading and model resolution); lazy factory keeps this base module
    # import-light and cycle-free.
    from analytics_zoo_tpu.serving.config import ServingConfig
    return ServingConfig()


@dataclass
class ZooConfig:
    """Top-level framework config. Build with `ZooConfig()` and override fields,
    or via `ZooConfig.from_env()` / `from_dict()`."""

    mesh: MeshConfig = field(default_factory=MeshConfig)
    failure: FailureConfig = field(default_factory=FailureConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    serving: Any = field(default_factory=_default_serving_config)

    log_level: str = "INFO"
    log_output: bool = False
    seed: int = 0
    # GSPMD-sharded training by default: fit_keras shards params and
    # optimizer state over the mesh's fsdp axis with the default
    # transformer rule table (the same table serving's sharded placement
    # uses). Equivalent to fit_keras(sharding_rules=True); the env
    # spelling is ZOO_SHARDED_FIT=1. Pair with a MeshConfig whose fsdp
    # axis is > 1 (e.g. ZOO_MESH_DATA=1 ZOO_MESH_FSDP=-1).
    sharded_fit: bool = False
    # Fused Pallas optimizer kernels (ISSUE 9): fit_keras swaps a
    # default-hyperparameter adam/adamw compile spec for the one-HBM-pass
    # fused update (`pallas/fused_adam.py`; with lazy_embeddings the
    # declared tables take the sparse segment path). Equivalent to
    # fit_keras(fused_optimizer=True); env spellings ZOO_FUSED_OPTIMIZER=1
    # (this field) or ZOO_FUSED_OPT=1 (short form, read at fit time).
    # Off-path optimizers and non-lowering backends degrade to plain
    # optax with one WARNING, so this is safe to set fleet-wide.
    fused_optimizer: bool = False
    # Parallel streaming input pipeline (ISSUE 15): worker threads for
    # file-backed dataset read+decode (`data/pipeline.py` — TFRecord /
    # parquet / csv shards decode concurrently behind a deterministic
    # reorder buffer, so any value yields the SAME batch stream). 0
    # keeps datasets single-threaded unless they pass their own
    # workers knob. Env spelling ZOO_PIPELINE_WORKERS.
    pipeline_workers: int = 0
    # Depth of the trainer's host→device prefetch queue (batches held
    # ready while the device runs the current step). Bounds host
    # memory: the input side never materializes more than
    # prefetch_depth batches + one decoded shard per pipeline worker.
    # Env spelling ZOO_PREFETCH_DEPTH.
    prefetch_depth: int = 2
    default_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    # pandas_read_backend flag of the reference (`nncontext.py:269`)
    pandas_read_backend: str = "pandas"
    # PRNG implementation. "rbg" generates random bits via the XLA RngBitGenerator
    # op, which is an order of magnitude faster than threefry on TPU (dropout in
    # a BERT-base train step is ~25% of wall time under threefry); keys remain
    # splittable. Set "threefry2x32" for cross-platform bit-exact streams.
    prng_impl: str = "rbg"
    # multi-host rendezvous (replaces the reference's five rendezvous schemes)
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    ENV_PREFIX = "ZOO_"

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ZooConfig":
        cfg = cls()
        fields = {f.name for f in dataclasses.fields(cfg)}
        for k, v in d.items():
            if k not in fields:
                raise ValueError(f"Unknown config key: {k}")
            cur = getattr(cfg, k)
            if dataclasses.is_dataclass(cur) and isinstance(v, dict):
                sub_fields = {f.name for f in dataclasses.fields(cur)}
                legacy = getattr(type(cur), "LEGACY_FIELDS", {})
                for sk, sv in v.items():
                    sk = legacy.get(sk, sk)
                    if sk not in sub_fields:
                        raise ValueError(f"Unknown config key: {k}.{sk}")
                    setattr(cur, sk, sv)
            else:
                setattr(cfg, k, v)
        return cfg

    @classmethod
    def from_env(cls, base: Optional["ZooConfig"] = None) -> "ZooConfig":
        """Apply `ZOO_<FIELD>` / `ZOO_<SECTION>_<FIELD>` env overrides, e.g.
        `ZOO_MESH_TENSOR=4`, `ZOO_LOG_LEVEL=DEBUG`. `base` is not mutated."""
        import copy
        cfg = copy.deepcopy(base) if base is not None else cls()
        hints = typing.get_type_hints(cls)
        for f in dataclasses.fields(cfg):
            cur = getattr(cfg, f.name)
            if dataclasses.is_dataclass(cur):
                sub_hints = typing.get_type_hints(type(cur))
                legacy = getattr(type(cur), "LEGACY_FIELDS", {})
                for old, new in legacy.items():
                    key = f"{cls.ENV_PREFIX}{f.name}_{old}".upper()
                    if key in os.environ:
                        setattr(cur, new,
                                _coerce(os.environ[key], sub_hints[new], key))
                for sf in dataclasses.fields(cur):
                    key = f"{cls.ENV_PREFIX}{f.name}_{sf.name}".upper()
                    if key in os.environ:
                        setattr(cur, sf.name,
                                _coerce(os.environ[key], sub_hints[sf.name], key))
            else:
                key = f"{cls.ENV_PREFIX}{f.name}".upper()
                if key in os.environ:
                    setattr(cfg, f.name,
                            _coerce(os.environ[key], hints[f.name], key))
        return cfg

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_dict(), fh, indent=2)

    @classmethod
    def load(cls, path: str) -> "ZooConfig":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))
