"""Training triggers — the `ZooTrigger` / BigDL `Trigger` family.

The reference gates epochs, validation, and checkpoints on trigger objects
(`zoo/.../common/ZooTrigger.scala`, used by `Topology.scala:354-365` and
`orca/learn/trigger.py:76`). Same composable semantics here, evaluated against
an immutable `TrainState` snapshot so they are safe to call from jit callbacks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence


@dataclass(frozen=True)
class TriggerState:
    """Loop counters a trigger may inspect."""
    epoch: int = 0            # completed epochs
    iteration: int = 0        # completed global steps
    loss: float = float("inf")
    score: float = float("-inf")
    epoch_finished: bool = False


class Trigger:
    def __call__(self, state: TriggerState) -> bool:
        raise NotImplementedError

    @staticmethod
    def from_string(spec: str) -> "Trigger":
        """Parse 'every_epoch' / 'max_epoch:10' / 'several_iteration:3' specs
        (string forms of orca's python trigger layer, `orca/learn/trigger.py`)."""
        s = spec.strip().lower().replace(" ", ":")
        if s in ("every_epoch", "everyepoch"):
            return EveryEpoch()
        name, _, arg = s.partition(":")
        table = {
            "max_epoch": MaxEpoch, "maxepoch": MaxEpoch,
            "max_iteration": MaxIteration, "maxiteration": MaxIteration,
            "several_iteration": SeveralIteration,
            "severaliteration": SeveralIteration,
        }
        if name in table and arg:
            return table[name](int(arg))
        raise ValueError(f"Cannot parse trigger spec: {spec!r}")


class EveryEpoch(Trigger):
    """Fires at each epoch boundary (`ZooTrigger.scala` EveryEpoch)."""

    def __call__(self, state: TriggerState) -> bool:
        return state.epoch_finished


class SeveralIteration(Trigger):
    def __init__(self, interval: int):
        if interval <= 0:
            raise ValueError("interval must be positive")
        self.interval = interval

    def __call__(self, state: TriggerState) -> bool:
        return state.iteration > 0 and state.iteration % self.interval == 0


class MaxEpoch(Trigger):
    """End-when trigger: stop after `max` epochs."""

    def __init__(self, max_epoch: int):
        self.max_epoch = max_epoch

    def __call__(self, state: TriggerState) -> bool:
        return state.epoch >= self.max_epoch


class MaxIteration(Trigger):
    def __init__(self, max_iteration: int):
        self.max_iteration = max_iteration

    def __call__(self, state: TriggerState) -> bool:
        return state.iteration >= self.max_iteration


class MinLoss(Trigger):
    def __init__(self, min_loss: float):
        self.min_loss = min_loss

    def __call__(self, state: TriggerState) -> bool:
        return state.loss < self.min_loss


class MaxScore(Trigger):
    def __init__(self, max_score: float):
        self.max_score = max_score

    def __call__(self, state: TriggerState) -> bool:
        return state.score > self.max_score


class And(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers: Sequence[Trigger] = triggers

    def __call__(self, state: TriggerState) -> bool:
        return all(t(state) for t in self.triggers)


class Or(Trigger):
    def __init__(self, *triggers: Trigger):
        self.triggers: Sequence[Trigger] = triggers

    def __call__(self, state: TriggerState) -> bool:
        return any(t(state) for t in self.triggers)
