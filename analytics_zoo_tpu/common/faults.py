"""Fault-injection harness (ISSUE 5) — test-addressable failure points.

The reference platform inherits its failure testing from its substrates
(Flink restarts the job, Spark re-runs the task); this reproduction has
no substrate, so the fault-tolerance layer (replica quarantine, broker
circuit breaker, training auto-resume) carries its own chaos harness.

Production code marks each place a real fault would land with ONE call:

    from analytics_zoo_tpu.common import faults
    faults.fire("broker.read_group", role="reader")

`fire` is a no-op (a single dict lookup) when nothing is injected, so
the hooks cost nothing in production. Tests and `bench_serving.py
--chaos` arm them:

    with faults.injected("replica.dispatch",
                         faults.Fault(mode="raise",
                                      match=lambda c: c["replica"] == 1)):
        ...                      # replica 1 now fails every batch

Well-known injection points (grep for `faults.fire` for the live list):

- ``broker.<op>``       every guarded op on a ResilientBroker-wrapped
                        serving connection (``role=reader|sink``)
- ``replica.dispatch``  one batch on one model replica
                        (``replica=<index>, batch=<count>``)
- ``trainer.step``      one training step, before device dispatch
                        (``iteration=<n>, attempt=<k>``)
- ``checkpoint.write``  a checkpoint artifact about to be committed
                        (``path=<temp file>``) — the truncate mode
                        simulates a crash mid-write
- ``decode.prefill``    one generative prefill (contiguous or one paged
                        chunk) about to dispatch
                        (``engine=<id>, uri=<uri>``) — raise simulates
                        an engine crash mid-admission, stall a wedged
                        prefill (the per-sequence watchdog's quarry)
- ``decode.step``       one batched decode step about to dispatch
                        (``engine=<id>``) — raise kills the engine loop
                        mid-decode, leaving records for the claim sweep
- ``decode.writeback``  the decode engine's fused row/final flush
                        (``engine=<id>``) — raise exercises the bounded
                        pending buffer (rows retained, loop keeps
                        stepping, drains on recovery)

Fault modes: ``raise`` (throw ``exc``), ``stall`` (sleep ``delay_s``
then proceed), ``truncate`` (cut the file at ``ctx["path"]`` to
``keep_fraction`` of its bytes). ``after`` skips the first N matching
calls; ``times`` bounds how often the fault trips (None = forever);
``match`` is a predicate over the call context. Thread-safe; faults
count their ``trips`` so tests can assert the site was actually hit.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, Optional

MODES = ("raise", "stall", "truncate")


class FaultError(ConnectionError):
    """Default exception an armed ``raise`` fault throws — a
    ConnectionError subclass so broker-shaped sites treat it exactly
    like a dead transport."""


class Fault:
    def __init__(self, mode: str = "raise",
                 exc: Optional[BaseException] = None,
                 delay_s: float = 0.1,
                 keep_fraction: float = 0.5,
                 after: int = 0,
                 times: Optional[int] = None,
                 match: Optional[Callable[[Dict[str, Any]], bool]] = None):
        if mode not in MODES:
            raise ValueError(f"fault mode {mode!r} not in {MODES}")
        self.mode = mode
        self.exc = exc
        self.delay_s = delay_s
        self.keep_fraction = keep_fraction
        self.after = after
        self.times = times
        self.match = match
        self.trips = 0            # how often the fault actually fired
        self._seen = 0            # matching calls, incl. skipped `after`
        self._lock = threading.Lock()

    def _should_trip(self, ctx: Dict[str, Any]) -> bool:
        if self.match is not None and not self.match(ctx):
            return False
        with self._lock:
            self._seen += 1
            if self._seen <= self.after:
                return False
            if self.times is not None and self.trips >= self.times:
                return False
            self.trips += 1
            return True

    def __call__(self, point: str, ctx: Dict[str, Any]):
        if not self._should_trip(ctx):
            return
        if self.mode == "stall":
            time.sleep(self.delay_s)
            return
        if self.mode == "truncate":
            path = ctx.get("path")
            if path and os.path.exists(path):
                keep = int(os.path.getsize(path) * self.keep_fraction)
                with open(path, "r+b") as fh:
                    fh.truncate(keep)
            return
        raise self.exc if self.exc is not None else FaultError(
            f"injected fault at {point} ({ctx})")


_faults: Dict[str, Fault] = {}
_mutate = threading.Lock()


def inject(point: str, fault: Fault) -> Fault:
    """Arm `fault` at `point` (replacing any previous fault there)."""
    with _mutate:
        _faults[point] = fault
    return fault


def clear(point: Optional[str] = None):
    """Disarm one point, or every point when None."""
    with _mutate:
        if point is None:
            _faults.clear()
        else:
            _faults.pop(point, None)


def active(point: str) -> Optional[Fault]:
    return _faults.get(point)


def fire(point: str, **ctx):
    """The production-side hook: evaluate the fault armed at `point`, if
    any. Reads race-free against inject/clear (CPython dict get is
    atomic); the common disarmed case is one failed lookup."""
    fault = _faults.get(point)
    if fault is not None:
        fault(point, ctx)


class injected:
    """Context manager: arm for the block, disarm on exit (even when the
    block raises — chaos tests must never leak a fault into the next
    test)."""

    def __init__(self, point: str, fault: Optional[Fault] = None, **kw):
        self.point = point
        self.fault = fault if fault is not None else Fault(**kw)

    def __enter__(self) -> Fault:
        return inject(self.point, self.fault)

    def __exit__(self, *exc):
        clear(self.point)
        return False
