"""`zoo-launch` — one-call multi-host training launcher.

Reference role: the one-call cluster bootstraps (`init_spark_on_yarn` /
`init_spark_standalone`, `pyzoo/zoo/common/nncontext.py:56,129,199`;
`scripts/standalone/start-standalone.sh`) that turn "a list of hosts"
into a running distributed job. TPU-native shape: every process runs the
SAME script; the launcher's whole job is to assign coordinator/world
env (`COORDINATOR_ADDRESS`, `ZOO_NUM_PROCESSES`, `ZOO_PROCESS_ID` —
read by `ZooConfig.from_env` inside
`init_orca_context(cluster_mode="multi-host")`) and to supervise the
process group fail-fast like `launch_local_cluster` does
(`common/cluster.py ProcessMonitor`).

    # 2 hosts x 4 processes, rendezvous on hostA:29400
    zoo-launch --hosts hostA,hostB --nproc 4 train.py --epochs 3

    # local simulation: 2 "hosts" on this machine, 4 CPU devices each
    zoo-launch --nproc 2 --simulate-devices 4 train.py

    # TPU pod slice: hosts come from the platform env; just
    zoo-launch train.py        # (TPU_WORKER_HOSTNAMES autodetected)

Remote processes start through `--ssh-cmd` (default `ssh`); anything
argv-shaped works (`--ssh-cmd "kubectl exec -i"` for GKE pods, a bash
shim in tests). Local hosts (`localhost`/`127.0.0.1`) spawn directly.
"""

from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from analytics_zoo_tpu.common.cluster import ProcessMonitor

_LOCAL_HOSTS = {"localhost", "127.0.0.1", "::1"}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _is_local(host: str) -> bool:
    return host.split("@")[-1] in _LOCAL_HOSTS


def detect_hosts() -> List[str]:
    """TPU pod-slice autodetect: the platform publishes the worker list
    (`TPU_WORKER_HOSTNAMES`, comma-separated). Fallback: this host."""
    names = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    hosts = [h.strip() for h in names.split(",") if h.strip()]
    return hosts or ["localhost"]


def build_commands(hosts: Sequence[str], nproc: int, coordinator: str,
                   script: str, script_args: Sequence[str],
                   python: str = sys.executable, ssh_cmd: str = "ssh",
                   extra_env: Optional[Dict[str, str]] = None,
                   simulate_devices: int = 0
                   ) -> List[Tuple[List[str], Optional[Dict[str, str]]]]:
    """One (argv, env) pair per process, ranks assigned host-major so
    rank r lives on host r // nproc (ICI-contiguous within a host).
    env is None for ssh'd commands (env rides inside the remote
    command line)."""
    world = len(hosts) * nproc
    out: List[Tuple[List[str], Optional[Dict[str, str]]]] = []
    # the launch cwd is importable on every worker (`python script.py`
    # only puts the SCRIPT's dir on sys.path) — the spark-submit
    # ships-the-project role
    pythonpath = os.pathsep.join(
        p for p in (os.getcwd(), os.environ.get("PYTHONPATH")) if p)
    # simulate mode must flip the backend via jax.config BEFORE the script
    # runs (env alone loses when a sitecustomize preimports jax), so the
    # script goes through this module's --bootstrap-devices runner
    runner: List[str] = []
    if simulate_devices:
        runner = ["-m", "analytics_zoo_tpu.common.launch",
                  "--bootstrap-devices", str(simulate_devices)]
    rank = 0
    for host in hosts:
        for _ in range(nproc):
            env_vars = {
                "COORDINATOR_ADDRESS": coordinator,
                "ZOO_NUM_PROCESSES": str(world),
                "ZOO_PROCESS_ID": str(rank),
                "PYTHONPATH": pythonpath,
                **(extra_env or {}),
            }
            if simulate_devices:
                # hermetic CPU workers: the dev rig's sitecustomize dials
                # its TPU relay when this var is set — a relay outage
                # would hang simulated (pure-CPU) clusters
                env_vars.setdefault("PALLAS_AXON_POOL_IPS", "")
            if _is_local(host):
                env = dict(os.environ)
                env.update(env_vars)
                out.append(([python, *runner, script, *script_args], env))
            else:
                assignments = " ".join(
                    f"{k}={shlex.quote(v)}" for k, v in env_vars.items())
                remote = (f"cd {shlex.quote(os.getcwd())} && "
                          f"env {assignments} {shlex.quote(python)} "
                          + " ".join(shlex.quote(a) for a in runner)
                          + (" " if runner else "")
                          + f"{shlex.quote(script)} "
                          + " ".join(shlex.quote(a) for a in script_args))
                # "{host}" placeholder lets exec styles that need args
                # AFTER the target work (kubectl >=1.22 requires
                # `exec POD -- cmd`): --ssh-cmd "kubectl exec -i {host} --"
                parts = shlex.split(ssh_cmd)
                if any("{host}" in p for p in parts):
                    argv = [p.replace("{host}", host) for p in parts]
                else:
                    argv = [*parts, host]
                out.append(([*argv, remote], None))
            rank += 1
    return out


def launch(hosts: Sequence[str], nproc: int, script: str,
           script_args: Sequence[str] = (),
           coordinator: Optional[str] = None, port: Optional[int] = None,
           python: str = sys.executable, ssh_cmd: str = "ssh",
           simulate_devices: int = 0,
           extra_env: Optional[Dict[str, str]] = None) -> ProcessMonitor:
    """Start the full host×nproc process group and return its monitor
    (fail-fast `.wait()`, group `.terminate()`).

    Remote coordinators default to a port DERIVED from the job identity
    (hash of script/hosts/nproc/cwd, range 29400-30399) — stable across
    re-launches of the same job, distinct for different jobs sharing a
    head host (a locally-probed free port says nothing about the remote
    head). Open that range on the head's firewall, or pass an explicit
    ``port``. Two concurrent IDENTICAL jobs still need distinct ports."""
    hosts = list(hosts)
    if coordinator is None:
        head = hosts[0].split("@")[-1]
        if _is_local(hosts[0]):
            # loopback: probe a genuinely free local port
            head = "127.0.0.1"
            coordinator = f"{head}:{port or _free_port()}"
        else:
            # remote coordinator: a port probed by binding LOCALLY says
            # nothing about the remote host. Derive a stable per-job port
            # from (script, hosts, nproc, cwd) in 29400-30399 so two
            # DIFFERENT jobs sharing a head host don't silently rendezvous
            # into one process group; identical re-launches keep the same
            # port (the conventional-fixed-port property that matters for
            # firewalls). Callers needing two concurrent identical jobs
            # must pass distinct ports.
            if port is None:
                import hashlib
                digest = hashlib.sha1(
                    f"{script}|{','.join(hosts)}|{nproc}|{os.getcwd()}"
                    .encode()).digest()
                port = 29400 + int.from_bytes(digest[:2], "big") % 1000
            coordinator = f"{head}:{port}"
    cmds = build_commands(hosts, nproc, coordinator, script, script_args,
                          python=python, ssh_cmd=ssh_cmd,
                          extra_env=extra_env,
                          simulate_devices=simulate_devices)
    procs = [subprocess.Popen(argv, env=env) for argv, env in cmds]
    return ProcessMonitor(procs)


def _bootstrap_devices(n: int, script: str, script_args: Sequence[str]):
    """Worker-side simulate-mode entry: force the CPU backend with n
    virtual devices via jax.config (env alone loses to a jax-preimporting
    sitecustomize), then run the user script as __main__."""
    import runpy

    from analytics_zoo_tpu.common.cluster import force_cpu_devices
    force_cpu_devices(n)
    sys.argv = [script, *script_args]
    runpy.run_path(script, run_name="__main__")


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["--bootstrap-devices"]:
        _bootstrap_devices(int(argv[1]), argv[2], argv[3:])
        return 0
    p = argparse.ArgumentParser(
        prog="zoo-launch",
        description="Launch a training script across hosts "
                    "(jax.distributed rendezvous env + supervision).")
    p.add_argument("--hosts", default=None,
                   help="comma-separated host list (default: TPU pod "
                        "autodetect, else localhost)")
    p.add_argument("--nproc", type=int, default=1,
                   help="processes per host")
    p.add_argument("--coordinator", default=None,
                   help="host:port rendezvous (default: first host + "
                        "free/default port)")
    p.add_argument("--port", type=int, default=None,
                   help="coordinator port when derived from --hosts")
    p.add_argument("--python", default=sys.executable)
    p.add_argument("--ssh-cmd", default="ssh",
                   help="remote-exec command (e.g. 'kubectl exec -i')")
    p.add_argument("--simulate-devices", type=int, default=0,
                   help="N>0: force JAX_PLATFORMS=cpu with N virtual "
                        "devices per process (local pod simulation)")
    p.add_argument("--timeout", type=float, default=None)
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)

    hosts = ([h.strip() for h in args.hosts.split(",") if h.strip()]
             if args.hosts else detect_hosts())
    mon = launch(hosts, args.nproc, args.script, args.script_args,
                 coordinator=args.coordinator, port=args.port,
                 python=args.python, ssh_cmd=args.ssh_cmd,
                 simulate_devices=args.simulate_devices)
    codes = mon.wait(args.timeout)
    return max(codes) if codes else 0


if __name__ == "__main__":
    sys.exit(main())
