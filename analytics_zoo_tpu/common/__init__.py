from analytics_zoo_tpu.common.config import ZooConfig  # noqa: F401
from analytics_zoo_tpu.common.context import (  # noqa: F401
    init_zoo_context,
    init_orca_context,
    stop_orca_context,
    ZooContext,
    OrcaContext,
)
from analytics_zoo_tpu.common.mesh import DeviceMesh  # noqa: F401
from analytics_zoo_tpu.common import triggers  # noqa: F401
