"""Context initialization — the `init_orca_context` / `init_nncontext` analogue.

The reference's context layer boots a SparkContext with BigDL engine config and
optionally a Ray cluster on top (`pyzoo/zoo/orca/common.py:89`,
`pyzoo/zoo/common/nncontext.py:319`, `pyzoo/zoo/ray/raycontext.py:262`). On TPU
there is no JVM and no two-level runtime: `init_orca_context` performs multi-host
rendezvous via `jax.distributed.initialize` (replacing barrier-mode master
election + redis_address handshakes), discovers the device mesh, seeds RNG, and
installs logging. `ZooContext`/`OrcaContext` keep the reference's global-flag
surface (`orca/common.py:21-86`).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

from analytics_zoo_tpu.common.config import MeshConfig, ZooConfig
from analytics_zoo_tpu.common.mesh import DeviceMesh

log = logging.getLogger("analytics_zoo_tpu")

_GLOBAL = {"context": None, "distributed_initialized": False}


class _ContextMeta(type):
    """Class-property global flags, mirroring `ZooContextMeta`
    (`nncontext.py:269`) / `OrcaContextMeta` (`orca/common.py:21`)."""

    _log_output = False
    _pandas_read_backend = "pandas"
    _serialize_data_creator = False
    _train_data_store = "DRAM"

    @property
    def log_output(cls) -> bool:
        return _ContextMeta._log_output

    @log_output.setter
    def log_output(cls, value: bool):
        # Only toggles output capture, never the configured verbosity
        # (matches the reference, where log_output redirects executor stdout,
        # `nncontext.py:274`).
        _ContextMeta._log_output = value

    @property
    def pandas_read_backend(cls) -> str:
        return _ContextMeta._pandas_read_backend

    @pandas_read_backend.setter
    def pandas_read_backend(cls, value: str):
        value = value.lower()
        if value not in ("pandas", "spark", "arrow"):
            raise ValueError(f"Unsupported pandas_read_backend: {value}")
        _ContextMeta._pandas_read_backend = value

    @property
    def train_data_store(cls) -> str:
        return _ContextMeta._train_data_store

    @train_data_store.setter
    def train_data_store(cls, value: str):
        value = value.upper()
        if value not in ("DRAM", "DISK", "DISK_AND_DRAM"):
            raise ValueError(f"Unsupported train_data_store: {value}")
        _ContextMeta._train_data_store = value


class ZooContext(metaclass=_ContextMeta):
    pass


class OrcaContext(metaclass=_ContextMeta):
    pass


def _configure_logging(level: str):
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    log.setLevel(getattr(logging, level.upper(), logging.INFO))


class Context:
    """The live runtime context: config + device mesh (+ rendezvous state)."""

    def __init__(self, config: ZooConfig, mesh: DeviceMesh):
        self.config = config
        self.mesh = mesh
        self.rng = jax.random.PRNGKey(config.seed)

    def next_rng(self) -> jax.Array:
        self.rng, sub = jax.random.split(self.rng)
        return sub

    def __repr__(self):
        return f"Context(mesh={self.mesh}, processes={jax.process_count()})"


def init_zoo_context(config: Optional[ZooConfig] = None,
                     cluster_mode: str = "local",
                     **mesh_axes) -> Context:
    """Initialise the runtime. Equivalent of `init_nncontext`
    (`nncontext.py:319`) + `NNContext.initNNContext` (`NNContext.scala:134`).

    cluster_mode:
      "local"      — this process's devices only (like Spark local[*]).
      "multi-host" — `jax.distributed.initialize` with coordinator settings
                     from config or TPU-pod env (like yarn/k8s modes).
    """
    config = ZooConfig.from_env(config)  # copies; caller's object untouched
    _configure_logging(config.log_level)
    # Wire config fields into the global context flags (setters validate).
    ZooContext.log_output = config.log_output
    ZooContext.pandas_read_backend = config.pandas_read_backend

    if cluster_mode in ("multi-host", "yarn", "k8s", "standalone"):
        # One rendezvous replaces the reference's five (survey §5): barrier
        # election, gloo, TF_CONFIG, tcp:// master, DMLC PS env. Must run
        # before anything touches the XLA backend, so we gate on our own flag
        # rather than jax.process_count().
        coordinator = (config.coordinator_address
                       or os.environ.get("COORDINATOR_ADDRESS"))
        if not _GLOBAL["distributed_initialized"]:
            if coordinator is None and "TPU_WORKER_HOSTNAMES" not in os.environ:
                raise ValueError(
                    "cluster_mode=multi-host needs a coordinator: set "
                    "ZooConfig.coordinator_address or COORDINATOR_ADDRESS "
                    "(on TPU pods jax.distributed can also auto-discover).")
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=config.num_processes,
                process_id=config.process_id,
            )
            _GLOBAL["distributed_initialized"] = True
    elif cluster_mode != "local":
        raise ValueError(f"Unknown cluster_mode: {cluster_mode}")

    # Fast TPU random bits for dropout et al. (rbg keys lower to the
    # hardware RngBitGenerator; threefry costs ~25% of a BERT train step on
    # v5e). TPU-only: on CPU/GPU threefry stays, keeping init draws stable.
    # The JAX_DEFAULT_PRNG_IMPL env var or a prior jax.config.update to a
    # non-threefry impl wins; to force threefry ON TPU set the env var or
    # ZooConfig.prng_impl="threefry2x32" (an explicit jax.config.update to
    # threefry is indistinguishable from the untouched default). Runs after
    # distributed init because default_backend() touches the XLA backend.
    if ("JAX_DEFAULT_PRNG_IMPL" not in os.environ
            and jax.config.jax_default_prng_impl == "threefry2x32"
            and jax.default_backend() == "tpu"):
        jax.config.update("jax_default_prng_impl", config.prng_impl)

    if mesh_axes:
        valid = set(MeshConfig.__dataclass_fields__)
        unknown = set(mesh_axes) - valid
        if unknown:
            raise TypeError(
                f"Unknown mesh axis kwarg(s) {sorted(unknown)}; "
                f"valid axes: {sorted(valid)}")
        for k, v in mesh_axes.items():
            setattr(config.mesh, k, v)
    mesh = DeviceMesh(config.mesh)
    ctx = Context(config, mesh)
    _GLOBAL["context"] = ctx
    log.info("Initialized %s on %d device(s) (%s), %d process(es)",
             mesh, mesh.n_devices,
             jax.devices()[0].platform, jax.process_count())
    return ctx


def init_orca_context(cluster_mode: str = "local",
                      cores: Optional[int] = None,
                      memory: Optional[str] = None,
                      num_nodes: int = 1,
                      init_ray_on_spark: bool = False,
                      config: Optional[ZooConfig] = None,
                      **kwargs) -> Context:
    """Drop-in analogue of `init_orca_context` (`orca/common.py:89`). The
    Spark-centric kwargs (cores/memory/num_nodes) are accepted for source
    compatibility; on TPU they are informational — the mesh is defined by the
    attached devices, not by executor sizing."""
    known_spark = {"driver_cores", "driver_memory", "num_executors",
                   "executor_cores", "executor_memory", "extra_python_lib",
                   "conf", "init_ray_on_spark"}
    mesh_axes = {}
    for k, v in kwargs.items():
        if k in MeshConfig.__dataclass_fields__:
            mesh_axes[k] = v
        elif k not in known_spark:
            raise TypeError(
                f"init_orca_context got unknown kwarg {k!r}; mesh axes are "
                f"{sorted(MeshConfig.__dataclass_fields__)}")
    if cluster_mode in ("yarn", "yarn-client", "yarn-cluster", "k8s",
                        "standalone"):
        cluster_mode = "multi-host"
    return init_zoo_context(config, cluster_mode=cluster_mode, **mesh_axes)


def get_context() -> Context:
    ctx = _GLOBAL["context"]
    if ctx is None:
        ctx = init_zoo_context()
    return ctx


def stop_orca_context() -> None:
    """Analogue of `stop_orca_context` (`orca/common.py:204`). Clears the
    global context; device runtime is managed by JAX and needs no teardown."""
    _GLOBAL["context"] = None
