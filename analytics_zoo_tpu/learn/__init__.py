from analytics_zoo_tpu.learn import checkpoint, trainer  # noqa: F401
