"""Encrypted model storage.

Reference: `InferenceModel.doLoadBigDL`/`doLoadTensorflow` accept
encrypted model files (`pipeline/inference/InferenceModel.scala:121-226`,
AES-CBC with PBKDF2-derived keys from a secret+salt pair; see
`EncryptSupportive`). Same contract here: `encrypt_file`/`decrypt_file`
derive an AES-128-GCM key with PBKDF2-HMAC-SHA256 and seal whole files;
`save_encrypted_pytree`/`load_encrypted_pytree` wrap checkpoint trees.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any

_MAGIC_V1 = b"AZTPUENC1"
_MAGIC = b"AZTPUENC2"
_ITERATIONS = 65536
_SALT_LEN = 16


def _derive_key(secret: str, salt: bytes) -> bytes:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.kdf.pbkdf2 import PBKDF2HMAC
    kdf = PBKDF2HMAC(algorithm=hashes.SHA256(), length=16, salt=salt,
                     iterations=_ITERATIONS)
    return kdf.derive(secret.encode("utf-8"))


def encrypt_bytes(data: bytes, secret: str, salt: str = "analytics-zoo"
                  ) -> bytes:
    """v2 format: MAGIC | random 16-byte file salt | 12-byte nonce | sealed.
    The KDF salt is the caller salt concatenated with the per-file random
    salt, so equal secrets never share a derived key across files."""
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    nonce = os.urandom(12)
    file_salt = os.urandom(_SALT_LEN)
    key = _derive_key(secret, salt.encode("utf-8") + file_salt)
    sealed = AESGCM(key).encrypt(nonce, data, _MAGIC)
    return _MAGIC + file_salt + nonce + sealed


def decrypt_bytes(blob: bytes, secret: str, salt: str = "analytics-zoo"
                  ) -> bytes:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    if blob.startswith(_MAGIC):
        off = len(_MAGIC)
        file_salt = blob[off:off + _SALT_LEN]
        nonce = blob[off + _SALT_LEN:off + _SALT_LEN + 12]
        sealed = blob[off + _SALT_LEN + 12:]
        key = _derive_key(secret, salt.encode("utf-8") + file_salt)
        return AESGCM(key).decrypt(nonce, sealed, _MAGIC)
    if blob.startswith(_MAGIC_V1):  # legacy fixed-salt files
        nonce = blob[len(_MAGIC_V1):len(_MAGIC_V1) + 12]
        sealed = blob[len(_MAGIC_V1) + 12:]
        key = _derive_key(secret, salt.encode("utf-8"))
        return AESGCM(key).decrypt(nonce, sealed, _MAGIC_V1)
    raise ValueError("Not an encrypted model blob (bad magic)")


def encrypt_file(src: str, dst: str, secret: str,
                 salt: str = "analytics-zoo") -> str:
    with open(src, "rb") as fh:
        data = fh.read()
    with open(dst, "wb") as fh:
        fh.write(encrypt_bytes(data, secret, salt))
    return dst


def decrypt_file(src: str, dst: str, secret: str,
                 salt: str = "analytics-zoo") -> str:
    with open(src, "rb") as fh:
        blob = fh.read()
    with open(dst, "wb") as fh:
        fh.write(decrypt_bytes(blob, secret, salt))
    return dst


def save_encrypted_pytree(path: str, tree: Any, secret: str,
                          salt: str = "analytics-zoo") -> str:
    """Serialize a param pytree (same npz+structure layout as
    `checkpoint.save_pytree`) into ONE encrypted file."""
    import json

    from analytics_zoo_tpu.learn.checkpoint import save_pytree
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "m")
        save_pytree(base, tree)
        with open(base + ".npz", "rb") as fh:
            npz = fh.read()
        with open(base + ".structure.json", "rb") as fh:
            struct = fh.read()
    payload = (len(struct).to_bytes(8, "little") + struct + npz)
    with open(path, "wb") as fh:
        fh.write(encrypt_bytes(payload, secret, salt))
    return path


def load_encrypted_pytree(path: str, secret: str,
                          salt: str = "analytics-zoo") -> Any:
    from analytics_zoo_tpu.learn.checkpoint import load_pytree
    with open(path, "rb") as fh:
        payload = decrypt_bytes(fh.read(), secret, salt)
    n = int.from_bytes(payload[:8], "little")
    struct = payload[8:8 + n]
    npz = payload[8 + n:]
    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "m")
        with open(base + ".structure.json", "wb") as fh:
            fh.write(struct)
        with open(base + ".npz", "wb") as fh:
            fh.write(npz)
        return load_pytree(base)
