"""Checkpointing with the reference's on-disk naming contract.

Layout follows `InternalDistriOptimizer` + `tf_optimizer.py:398-413`:
    <ckptDir>/<yyyyMMdd_HHmmss>/model.<iteration>
    <ckptDir>/<yyyyMMdd_HHmmss>/optimMethod-<name>.<iteration>
`load_checkpoint(path, version)` selects by version number like
`load_orca_checkpoint` (`orca/learn/tf/estimator.py:125`); resume restores
optimizer state so epoch continuation matches `Topology.scala:379-394`.

Format: each file is a numpy .npz of the flattened pytree plus a JSON sidecar
of the tree structure — portable, no pickle of code objects.
"""

from __future__ import annotations

import datetime
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np


# ---------------------------------------------------------------------------
# Pytree <-> flat ndarray dict
# ---------------------------------------------------------------------------
def _walk(tree: Any, path: List[List[Any]], paths: List[Any],
          leaves: List[np.ndarray]) -> None:
    """Record every node: leaves carry data; empty containers carry a marker
    so parameterless layers ({} in params) survive the roundtrip (jax's
    tree_flatten silently drops them)."""
    if isinstance(tree, dict):
        if not tree:
            paths.append({"path": path, "empty": "dict"})
            return
        for k in tree:  # preserve insertion order
            _walk(tree[k], path + [["k", k]], paths, leaves)
    elif isinstance(tree, (list, tuple)):
        if not tree:
            paths.append({"path": path, "empty": "list"})
            return
        for i, v in enumerate(tree):
            _walk(v, path + [["i", i]], paths, leaves)
    else:
        paths.append({"path": path, "leaf": len(leaves)})
        leaves.append(np.asarray(tree))


def save_pytree(path: str, tree: Any) -> None:
    """Write a pytree to `<path>` (npz + structure json)."""
    paths: List[Any] = []
    leaves: List[np.ndarray] = []
    _walk(tree, [], paths, leaves)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    flat = {f"leaf_{i}": l for i, l in enumerate(leaves)}
    np.savez(path if path.endswith(".npz") else path + ".npz", **flat)
    with open(_struct_path(path), "w") as fh:
        json.dump({"nodes": paths}, fh)


def _struct_path(path: str) -> str:
    base = path[:-4] if path.endswith(".npz") else path
    return base + ".structure.json"


def load_pytree(path: str) -> Any:
    """Load a pytree written by save_pytree; reconstructs nested
    dicts/lists (tuples come back as lists)."""
    npz = np.load(path if path.endswith(".npz") else path + ".npz")
    with open(_struct_path(path)) as fh:
        meta = json.load(fh)
    root: Any = None
    for node in meta["nodes"]:
        if "leaf" in node:
            value: Any = npz[f"leaf_{node['leaf']}"]
        else:
            value = {} if node["empty"] == "dict" else []
        root = _insert(root, node["path"], value)
    return root if root is not None else {}


def _insert(root, parts, value):
    if not parts:
        return value
    kind, key = parts[0]
    if kind == "i":
        key = int(key)
        if root is None:
            root = []
        while len(root) <= key:
            root.append(None)
        root[key] = _insert(root[key], parts[1:], value)
        return root
    if root is None:
        root = {}
    root[key] = _insert(root.get(key), parts[1:], value)
    return root


# ---------------------------------------------------------------------------
# Reference-layout training checkpoints
# ---------------------------------------------------------------------------
_STAMP_FMT = "%Y%m%d_%H%M%S"


class CheckpointManager:
    """Writes `model.<iter>` + `optimMethod-<name>.<iter>` into a timestamped
    subdir (created once per training run, `Topology.scala:1245-1252`)."""

    def __init__(self, root: str, optim_name: str = "default", keep: int = 3):
        self.root = root
        self.optim_name = optim_name
        self.keep = keep
        stamp = datetime.datetime.now().strftime(_STAMP_FMT)
        self.run_dir = os.path.join(root, stamp)
        os.makedirs(self.run_dir, exist_ok=True)
        self._saved: List[int] = []

    def save(self, iteration: int, params: Any, opt_state: Any = None,
             extra: Optional[Dict[str, Any]] = None) -> str:
        mpath = os.path.join(self.run_dir, f"model.{iteration}")
        save_pytree(mpath, params)
        if opt_state is not None:
            opath = os.path.join(self.run_dir,
                                 f"optimMethod-{self.optim_name}.{iteration}")
            save_pytree(opath, _optstate_to_tree(opt_state))
        if extra:
            with open(mpath + ".meta.json", "w") as fh:
                json.dump(extra, fh)
        self._saved.append(iteration)
        self._gc()
        return mpath

    def _gc(self):
        while len(self._saved) > self.keep:
            it = self._saved.pop(0)
            for pat in (f"model.{it}", f"optimMethod-{self.optim_name}.{it}"):
                for suffix in (".npz", ".structure.json", ".meta.json"):
                    p = os.path.join(self.run_dir, pat + suffix)
                    if os.path.exists(p):
                        os.remove(p)


def latest_checkpoint(root: str) -> Optional[Tuple[str, int]]:
    """Find (run_dir, version) of the newest model.<iter> under root —
    mirrors `find_latest_checkpoint` (`orca/learn/tf/utils.py`)."""
    best: Optional[Tuple[str, int]] = None
    if not os.path.isdir(root):
        return None
    candidates = [root] + [os.path.join(root, d) for d in sorted(os.listdir(root))
                           if os.path.isdir(os.path.join(root, d))]
    for run_dir in candidates:
        if not os.path.isdir(run_dir):
            continue
        for f in os.listdir(run_dir):
            m = re.match(r"model\.(\d+)\.npz$", f)
            if m:
                version = int(m.group(1))
                if best is None or version >= best[1]:
                    best = (run_dir, version)
    return best


def load_checkpoint(path: str, version: Optional[int] = None,
                    optim_name: str = "default"):
    """Load (params, opt_tree, meta) from a checkpoint dir. `path` may be the
    ckpt root or a run dir; `version=None` → latest."""
    if version is None:
        found = latest_checkpoint(path)
        if found is None:
            raise FileNotFoundError(f"No checkpoint under {path}")
        run_dir, version = found
    else:
        run_dir = path
        mfile = os.path.join(run_dir, f"model.{version}.npz")
        if not os.path.exists(mfile):
            found = latest_checkpoint(path)
            if found and os.path.exists(
                    os.path.join(found[0], f"model.{version}.npz")):
                run_dir = found[0]
            else:
                raise FileNotFoundError(f"No model.{version} under {path}")
    params = load_pytree(os.path.join(run_dir, f"model.{version}"))
    opt_tree = None
    opath = os.path.join(run_dir, f"optimMethod-{optim_name}.{version}")
    if os.path.exists(opath + ".npz"):
        opt_tree = load_pytree(opath)
    meta = {}
    mpath = os.path.join(run_dir, f"model.{version}.meta.json")
    if os.path.exists(mpath):
        with open(mpath) as fh:
            meta = json.load(fh)
    return params, opt_tree, meta


def _optstate_to_tree(opt_state: Any) -> Any:
    """Optax states are namedtuple pytrees; store leaves + paths only."""
    return jax.tree_util.tree_map(np.asarray, opt_state)


def restore_opt_state(template: Any, tree: Any) -> Any:
    """Pour saved leaves back into an optax state built by opt.init."""
    leaves_saved = jax.tree_util.tree_leaves(tree)
    treedef = jax.tree_util.tree_structure(template)
    leaves_tmpl = jax.tree_util.tree_leaves(template)
    if len(leaves_saved) != len(leaves_tmpl):
        raise ValueError(
            f"Optimizer state mismatch: saved {len(leaves_saved)} leaves, "
            f"template has {len(leaves_tmpl)}")
    cast = [np.asarray(s, dtype=np.asarray(t).dtype)
            for s, t in zip(leaves_saved, leaves_tmpl)]
    return jax.tree_util.tree_unflatten(treedef, cast)
