"""Checkpointing with the reference's on-disk naming contract.

Layout follows `InternalDistriOptimizer` + `tf_optimizer.py:398-413`:
    <ckptDir>/<yyyyMMdd_HHmmss>/model.<iteration>
    <ckptDir>/<yyyyMMdd_HHmmss>/optimMethod-<name>.<iteration>
`load_checkpoint(path, version)` selects by version number like
`load_orca_checkpoint` (`orca/learn/tf/estimator.py:125`); resume restores
optimizer state so epoch continuation matches `Topology.scala:379-394`.

Format: each file is a numpy .npz of the flattened pytree plus a JSON sidecar
of the tree structure — portable, no pickle of code objects.

Durability (ISSUE 5, mirroring the compile-cache store's discipline):
writes land in a same-directory temp file and `os.replace` into place,
so a crashed writer never leaves a half-written artifact under the
final name; the structure sidecar records the npz's CRC32C and is
written LAST, acting as the commit marker. `load_pytree` verifies the
CRC, and `latest_checkpoint` skips corrupt/truncated versions, falling
back to the newest intact one — a torn disk can cost a checkpoint, not
the run.
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.utils.crc import crc32c

log = logging.getLogger("analytics_zoo_tpu.checkpoint")


class CorruptCheckpointError(RuntimeError):
    """A checkpoint artifact failed its integrity check (missing
    sidecar, truncated npz, CRC mismatch)."""


# ---------------------------------------------------------------------------
# Pytree <-> flat ndarray dict
# ---------------------------------------------------------------------------
def gather_leaf(a: Any) -> np.ndarray:
    """Host copy of one checkpoint leaf, correct for sharded
    `jax.Array`s (the GSPMD fit's params/opt_state):

    - fully replicated → read ONE addressable shard; a bare np.asarray
      would be correct too but this makes the single-fetch explicit;
    - sharded but fully addressable (single-process mesh) → one
      device_get assembles every shard exactly once (np.asarray funnels
      through jax's single-gather conversion — shards are not fetched
      per-element or twice);
    - not fully addressable (multi-process) → actionable error: saving
      would silently write this host's partial view.

    Everything else (numpy, scalars) converts as before."""
    try:
        import jax
        if isinstance(a, jax.Array):
            if a.is_fully_replicated:
                return np.asarray(a.addressable_data(0))
            if not a.is_fully_addressable:
                raise NotImplementedError(
                    "checkpointing a cross-host sharded array: this "
                    "process cannot address every shard; gather to "
                    "host (e.g. multihost_utils.process_allgather) "
                    "before saving")
    except ImportError:          # jax-less tooling reading numpy trees
        pass
    return np.asarray(a)


def gather_tree(tree: Any) -> Any:
    """`gather_leaf` over a pytree — the host view a checkpoint
    stores."""
    import jax
    return jax.tree_util.tree_map(gather_leaf, tree)


def _walk(tree: Any, path: List[List[Any]], paths: List[Any],
          leaves: List[np.ndarray]) -> None:
    """Record every node: leaves carry data; empty containers carry a marker
    so parameterless layers ({} in params) survive the roundtrip (jax's
    tree_flatten silently drops them)."""
    if isinstance(tree, dict):
        if not tree:
            paths.append({"path": path, "empty": "dict"})
            return
        for k in tree:  # preserve insertion order
            _walk(tree[k], path + [["k", k]], paths, leaves)
    elif isinstance(tree, (list, tuple)):
        if not tree:
            paths.append({"path": path, "empty": "list"})
            return
        for i, v in enumerate(tree):
            _walk(v, path + [["i", i]], paths, leaves)
    else:
        paths.append({"path": path, "leaf": len(leaves)})
        leaves.append(gather_leaf(tree))


def save_pytree(path: str, tree: Any) -> None:
    """Write a pytree to `<path>` (npz + structure json), atomically:
    both files go through write-temp-then-rename, the npz first and the
    CRC-bearing sidecar last (the commit marker) — a reader can never
    observe a committed-looking checkpoint with torn bytes."""
    paths: List[Any] = []
    leaves: List[np.ndarray] = []
    _walk(tree, [], paths, leaves)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    flat = {f"leaf_{i}": l for i, l in enumerate(leaves)}
    npz_path = path if path.endswith(".npz") else path + ".npz"
    tmp_npz = npz_path + f".tmp-{os.getpid()}"
    tmp_struct = _struct_path(path) + f".tmp-{os.getpid()}"
    try:
        with open(tmp_npz, "wb") as fh:
            np.savez(fh, **flat)
        # CRC of the INTENDED bytes, read back before the commit point:
        # a crash (or injected truncation) between here and the rename
        # yields an artifact whose CRC cannot match
        with open(tmp_npz, "rb") as fh:
            crc = crc32c(fh.read())
        nbytes = os.path.getsize(tmp_npz)
        faults.fire("checkpoint.write", path=tmp_npz)
        os.replace(tmp_npz, npz_path)
        with open(tmp_struct, "w") as fh:
            json.dump({"nodes": paths, "npz_crc32c": crc,
                       "npz_bytes": nbytes}, fh)
        os.replace(tmp_struct, _struct_path(path))
    except BaseException:
        for tmp in (tmp_npz, tmp_struct):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        raise


def _struct_path(path: str) -> str:
    base = path[:-4] if path.endswith(".npz") else path
    return base + ".structure.json"


def verify_pytree(path: str) -> bool:
    """True when `<path>` is a complete, CRC-intact artifact. Legacy
    artifacts without a recorded CRC pass on existence alone."""
    npz_path = path if path.endswith(".npz") else path + ".npz"
    try:
        with open(_struct_path(path)) as fh:
            meta = json.load(fh)
        if not os.path.exists(npz_path):
            return False
        if "npz_crc32c" not in meta:
            return True
        if os.path.getsize(npz_path) != meta.get("npz_bytes"):
            return False
        with open(npz_path, "rb") as fh:
            return crc32c(fh.read()) == meta["npz_crc32c"]
    except (OSError, ValueError):
        return False


def load_pytree(path: str, verify: bool = True) -> Any:
    """Load a pytree written by save_pytree; reconstructs nested
    dicts/lists (tuples come back as lists). With `verify` (default)
    the npz's recorded CRC is checked against ONE read of the bytes
    (np.load then parses the same in-memory buffer — no second disk
    pass for multi-GB checkpoints) and a mismatch raises
    `CorruptCheckpointError` instead of feeding torn bytes to np.load."""
    import io
    npz_path = path if path.endswith(".npz") else path + ".npz"
    with open(_struct_path(path)) as fh:
        meta = json.load(fh)
    if verify and "npz_crc32c" in meta:
        with open(npz_path, "rb") as fh:
            raw = fh.read()
        if len(raw) != meta.get("npz_bytes") \
                or crc32c(raw) != meta["npz_crc32c"]:
            raise CorruptCheckpointError(
                f"checkpoint artifact {path} is corrupt or truncated")
        npz = np.load(io.BytesIO(raw))
    else:
        npz = np.load(npz_path)
    root: Any = None
    for node in meta["nodes"]:
        if "leaf" in node:
            value: Any = npz[f"leaf_{node['leaf']}"]
        else:
            value = {} if node["empty"] == "dict" else []
        root = _insert(root, node["path"], value)
    return root if root is not None else {}


def _insert(root, parts, value):
    if not parts:
        return value
    kind, key = parts[0]
    if kind == "i":
        key = int(key)
        if root is None:
            root = []
        while len(root) <= key:
            root.append(None)
        root[key] = _insert(root[key], parts[1:], value)
        return root
    if root is None:
        root = {}
    root[key] = _insert(root.get(key), parts[1:], value)
    return root


# ---------------------------------------------------------------------------
# Reference-layout training checkpoints
# ---------------------------------------------------------------------------
_STAMP_FMT = "%Y%m%d_%H%M%S"


class CheckpointManager:
    """Writes `model.<iter>` + `optimMethod-<name>.<iter>` into a timestamped
    subdir (created once per training run, `Topology.scala:1245-1252`)."""

    def __init__(self, root: str, optim_name: str = "default", keep: int = 3):
        self.root = root
        self.optim_name = optim_name
        self.keep = keep
        stamp = datetime.datetime.now().strftime(_STAMP_FMT)
        self.run_dir = os.path.join(root, stamp)
        os.makedirs(self.run_dir, exist_ok=True)
        self._saved: List[int] = []

    def save(self, iteration: int, params: Any, opt_state: Any = None,
             extra: Optional[Dict[str, Any]] = None) -> str:
        """Commit ORDER makes the checkpoint SET atomic, not just each
        artifact: optimizer state and metadata land first, the model
        artifact (whose CRC sidecar `checkpoint_intact` keys on) lands
        LAST as the commit marker. A crash anywhere before the final
        rename leaves no model.<iter>.npz, so the torn set is invisible
        to `latest_checkpoint`/resume — never a model that resumes with
        fresh optimizer state or epoch-0 metadata."""
        mpath = os.path.join(self.run_dir, f"model.{iteration}")
        if opt_state is not None:
            opath = os.path.join(self.run_dir,
                                 f"optimMethod-{self.optim_name}.{iteration}")
            save_pytree(opath, _optstate_to_tree(opt_state))
        if extra:
            tmp = mpath + f".meta.json.tmp-{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(extra, fh)
            os.replace(tmp, mpath + ".meta.json")
        save_pytree(mpath, params)
        self._saved.append(iteration)
        self._gc()
        return mpath

    def _gc(self):
        while len(self._saved) > self.keep:
            it = self._saved.pop(0)
            for pat in (f"model.{it}", f"optimMethod-{self.optim_name}.{it}"):
                # .int8.* is the quantization sidecar (ISSUE 12): it
                # lives and dies with its checkpoint version, or the
                # keep=N retention contract silently stops bounding the
                # directory
                for suffix in (".npz", ".structure.json", ".meta.json",
                               ".int8.npz", ".int8.structure.json"):
                    p = os.path.join(self.run_dir, pat + suffix)
                    if os.path.exists(p):
                        os.remove(p)


def list_checkpoints(root: str) -> List[Tuple[str, int]]:
    """Every (run_dir, version) under root, newest first (version desc,
    then run-dir stamp desc for ties across run dirs)."""
    found: List[Tuple[str, int]] = []
    if not os.path.isdir(root):
        return found
    candidates = [root] + [os.path.join(root, d)
                           for d in sorted(os.listdir(root))
                           if os.path.isdir(os.path.join(root, d))]
    for run_dir in candidates:
        if not os.path.isdir(run_dir):
            continue
        for f in os.listdir(run_dir):
            m = re.match(r"model\.(\d+)\.npz$", f)
            if m:
                found.append((run_dir, int(m.group(1))))
    return sorted(found, key=lambda rv: (rv[1], rv[0]), reverse=True)


def checkpoint_intact(run_dir: str, version: int) -> bool:
    """CRC/completeness check for one checkpoint version: the model
    artifact and (when present) its optimizer artifacts must all
    verify."""
    if not verify_pytree(os.path.join(run_dir, f"model.{version}")):
        return False
    for f in os.listdir(run_dir):
        if re.match(rf"optimMethod-.+\.{version}\.npz$", f):
            if not verify_pytree(os.path.join(run_dir, f)):
                return False
    return True


def latest_checkpoint(root: str,
                      verify: bool = True) -> Optional[Tuple[str, int]]:
    """Find (run_dir, version) of the newest INTACT model.<iter> under
    root — mirrors `find_latest_checkpoint` (`orca/learn/tf/utils.py`),
    plus the fallback discipline: a corrupt/truncated newest version is
    skipped (with a warning) in favor of the newest version that
    verifies. `verify=False` restores the raw newest-by-number scan."""
    for run_dir, version in list_checkpoints(root):
        if not verify or checkpoint_intact(run_dir, version):
            return (run_dir, version)
        log.warning(
            "checkpoint model.%d in %s is corrupt/truncated; falling "
            "back to an earlier version", version, run_dir)
    return None


def read_checkpoint_meta(run_dir: str, version: int) -> Dict[str, Any]:
    """The extra-metadata sidecar of one checkpoint ({} when absent or
    unreadable)."""
    mpath = os.path.join(run_dir, f"model.{version}.meta.json")
    try:
        with open(mpath) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def find_resume_checkpoint(root: str) -> Optional[Tuple[str, int,
                                                        Dict[str, Any]]]:
    """The checkpoint `fit_keras(auto_resume=True)` should continue
    from: the newest INTACT epoch-boundary checkpoint (mid-epoch and
    emergency saves are skipped — resuming from one would replay part
    of an epoch and break loss-identical continuation). Falls back to
    the newest intact checkpoint of any kind, with a warning, when no
    boundary checkpoint survives. Returns (run_dir, version, meta) or
    None."""
    fallback = None        # newest intact NON-boundary checkpoint
    # lazy: intactness CRC-reads whole artifacts, so verify candidates
    # newest-first only until a boundary hit instead of scanning every
    # version under every run dir up front
    for run_dir, version in list_checkpoints(root):
        if not checkpoint_intact(run_dir, version):
            continue
        meta = read_checkpoint_meta(run_dir, version)
        # legacy checkpoints predate the flag; treat them as boundaries
        if meta.get("epoch_finished", True):
            return (run_dir, version, meta)
        if fallback is None:
            fallback = (run_dir, version, meta)
    if fallback is not None:
        log.warning(
            "no epoch-boundary checkpoint under %s; resuming from "
            "mid-epoch model.%d (continuation will replay the partial "
            "epoch from its start)", root, fallback[1])
    return fallback


def resolve_checkpoint(path: str,
                       version: Optional[int] = None) -> Tuple[str, int]:
    """THE root-vs-run-dir resolution, shared by `load_checkpoint`,
    `InferenceModel.load_checkpoint` and the offline quantization
    script — one copy, so the sidecar probe and the param load can
    never resolve different directories. `version=None` → the newest
    INTACT checkpoint anywhere under `path`; an explicit version →
    `path` itself when it holds `model.<version>`, else the newest run
    dir under `path` that does. Raises FileNotFoundError."""
    if version is None:
        found = latest_checkpoint(path)
        if found is None:
            raise FileNotFoundError(f"No checkpoint under {path}")
        return found
    if os.path.exists(os.path.join(path, f"model.{version}.npz")):
        return path, version
    found = latest_checkpoint(path)
    if found and os.path.exists(
            os.path.join(found[0], f"model.{version}.npz")):
        return found[0], version
    raise FileNotFoundError(f"No model.{version} under {path}")


def load_checkpoint(path: str, version: Optional[int] = None,
                    optim_name: str = "default", verify: bool = True):
    """Load (params, opt_tree, meta) from a checkpoint dir. `path` may be the
    ckpt root or a run dir; `version=None` → latest. `verify=False` skips
    the CRC pass — for callers (auto-resume) that ran `checkpoint_intact`
    on this exact version moments earlier."""
    run_dir, version = resolve_checkpoint(path, version)
    params = load_pytree(os.path.join(run_dir, f"model.{version}"),
                         verify=verify)
    opt_tree = None
    opath = os.path.join(run_dir, f"optimMethod-{optim_name}.{version}")
    if os.path.exists(opath + ".npz"):
        opt_tree = load_pytree(opath, verify=verify)
    meta = {}
    mpath = os.path.join(run_dir, f"model.{version}.meta.json")
    if os.path.exists(mpath):
        with open(mpath) as fh:
            meta = json.load(fh)
    return params, opt_tree, meta


def _optstate_to_tree(opt_state: Any) -> Any:
    """Optax states are namedtuple pytrees; store leaves + paths only.
    Routed through `gather_leaf` so a GSPMD fit's sharded optimizer
    moments gather correctly (addressable shards fetched exactly
    once)."""
    return jax.tree_util.tree_map(gather_leaf, opt_state)


def restore_opt_state(template: Any, tree: Any) -> Any:
    """Pour saved leaves back into an optax state built by opt.init."""
    leaves_saved = jax.tree_util.tree_leaves(tree)
    treedef = jax.tree_util.tree_structure(template)
    leaves_tmpl = jax.tree_util.tree_leaves(template)
    if len(leaves_saved) != len(leaves_tmpl):
        raise ValueError(
            f"Optimizer state mismatch: saved {len(leaves_saved)} leaves, "
            f"template has {len(leaves_tmpl)}")
    cast = [np.asarray(s, dtype=np.asarray(t).dtype)
            for s, t in zip(leaves_saved, leaves_tmpl)]
    return jax.tree_util.tree_unflatten(treedef, cast)
