"""Checkpointing with the reference's on-disk naming contract.

Layout follows `InternalDistriOptimizer` + `tf_optimizer.py:398-413`:
    <ckptDir>/<yyyyMMdd_HHmmss>/model.<iteration>
    <ckptDir>/<yyyyMMdd_HHmmss>/optimMethod-<name>.<iteration>
`load_checkpoint(path, version)` selects by version number like
`load_orca_checkpoint` (`orca/learn/tf/estimator.py:125`); resume restores
optimizer state so epoch continuation matches `Topology.scala:379-394`.

Format: each file is a numpy .npz of the flattened pytree plus a JSON sidecar
of the tree structure — portable, no pickle of code objects.

Durability (ISSUE 5, mirroring the compile-cache store's discipline):
writes land in a same-directory temp file and `os.replace` into place,
so a crashed writer never leaves a half-written artifact under the
final name; the structure sidecar records the npz's CRC32C and is
written LAST, acting as the commit marker. `load_pytree` verifies the
CRC, and `latest_checkpoint` skips corrupt/truncated versions, falling
back to the newest intact one — a torn disk can cost a checkpoint, not
the run.
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from analytics_zoo_tpu.common import faults
from analytics_zoo_tpu.utils.crc import crc32c

log = logging.getLogger("analytics_zoo_tpu.checkpoint")


class CorruptCheckpointError(RuntimeError):
    """A checkpoint artifact failed its integrity check (missing
    sidecar, truncated npz, CRC mismatch)."""


# ---------------------------------------------------------------------------
# Pytree <-> flat ndarray dict
# ---------------------------------------------------------------------------
def gather_leaf(a: Any) -> np.ndarray:
    """Host copy of one checkpoint leaf, correct for sharded
    `jax.Array`s (the GSPMD fit's params/opt_state):

    - fully replicated → read ONE addressable shard; a bare np.asarray
      would be correct too but this makes the single-fetch explicit;
    - sharded but fully addressable (single-process mesh) → one
      device_get assembles every shard exactly once (np.asarray funnels
      through jax's single-gather conversion — shards are not fetched
      per-element or twice);
    - not fully addressable (multi-process) → actionable error: saving
      would silently write this host's partial view.

    Everything else (numpy, scalars) converts as before."""
    try:
        import jax
        if isinstance(a, jax.Array):
            if a.is_fully_replicated:
                return np.asarray(a.addressable_data(0))
            if not a.is_fully_addressable:
                raise NotImplementedError(
                    "checkpointing a cross-host sharded array: this "
                    "process cannot address every shard; gather to "
                    "host (e.g. multihost_utils.process_allgather) "
                    "before saving")
    except ImportError:          # jax-less tooling reading numpy trees
        pass
    return np.asarray(a)


def gather_tree(tree: Any) -> Any:
    """`gather_leaf` over a pytree — the host view a checkpoint
    stores."""
    import jax
    return jax.tree_util.tree_map(gather_leaf, tree)


def _walk(tree: Any, path: List[List[Any]], paths: List[Any],
          leaves: List[np.ndarray]) -> None:
    """Record every node: leaves carry data; empty containers carry a marker
    so parameterless layers ({} in params) survive the roundtrip (jax's
    tree_flatten silently drops them)."""
    if isinstance(tree, dict):
        if not tree:
            paths.append({"path": path, "empty": "dict"})
            return
        for k in tree:  # preserve insertion order
            _walk(tree[k], path + [["k", k]], paths, leaves)
    elif isinstance(tree, (list, tuple)):
        if not tree:
            paths.append({"path": path, "empty": "list"})
            return
        for i, v in enumerate(tree):
            _walk(v, path + [["i", i]], paths, leaves)
    else:
        paths.append({"path": path, "leaf": len(leaves)})
        leaves.append(gather_leaf(tree))


def save_pytree(path: str, tree: Any) -> None:
    """Write a pytree to `<path>` (npz + structure json), atomically:
    both files go through write-temp-then-rename, the npz first and the
    CRC-bearing sidecar last (the commit marker) — a reader can never
    observe a committed-looking checkpoint with torn bytes."""
    paths: List[Any] = []
    leaves: List[np.ndarray] = []
    _walk(tree, [], paths, leaves)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    flat = {f"leaf_{i}": l for i, l in enumerate(leaves)}
    npz_path = path if path.endswith(".npz") else path + ".npz"
    tmp_npz = npz_path + f".tmp-{os.getpid()}"
    tmp_struct = _struct_path(path) + f".tmp-{os.getpid()}"
    try:
        with open(tmp_npz, "wb") as fh:
            np.savez(fh, **flat)
        # CRC of the INTENDED bytes, read back before the commit point:
        # a crash (or injected truncation) between here and the rename
        # yields an artifact whose CRC cannot match
        with open(tmp_npz, "rb") as fh:
            crc = crc32c(fh.read())
        nbytes = os.path.getsize(tmp_npz)
        faults.fire("checkpoint.write", path=tmp_npz)
        os.replace(tmp_npz, npz_path)
        with open(tmp_struct, "w") as fh:
            json.dump({"nodes": paths, "npz_crc32c": crc,
                       "npz_bytes": nbytes}, fh)
        os.replace(tmp_struct, _struct_path(path))
    except BaseException:
        for tmp in (tmp_npz, tmp_struct):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        raise


def _struct_path(path: str) -> str:
    base = path[:-4] if path.endswith(".npz") else path
    return base + ".structure.json"


def verify_pytree(path: str) -> bool:
    """True when `<path>` is a complete, CRC-intact artifact. Legacy
    artifacts without a recorded CRC pass on existence alone."""
    npz_path = path if path.endswith(".npz") else path + ".npz"
    try:
        with open(_struct_path(path)) as fh:
            meta = json.load(fh)
        if not os.path.exists(npz_path):
            return False
        if "npz_crc32c" not in meta:
            return True
        if os.path.getsize(npz_path) != meta.get("npz_bytes"):
            return False
        with open(npz_path, "rb") as fh:
            return crc32c(fh.read()) == meta["npz_crc32c"]
    except (OSError, ValueError):
        return False


def load_pytree(path: str, verify: bool = True) -> Any:
    """Load a pytree written by save_pytree; reconstructs nested
    dicts/lists (tuples come back as lists). With `verify` (default)
    the npz's recorded CRC is checked against ONE read of the bytes
    (np.load then parses the same in-memory buffer — no second disk
    pass for multi-GB checkpoints) and a mismatch raises
    `CorruptCheckpointError` instead of feeding torn bytes to np.load."""
    import io
    npz_path = path if path.endswith(".npz") else path + ".npz"
    with open(_struct_path(path)) as fh:
        meta = json.load(fh)
    if verify and "npz_crc32c" in meta:
        with open(npz_path, "rb") as fh:
            raw = fh.read()
        if len(raw) != meta.get("npz_bytes") \
                or crc32c(raw) != meta["npz_crc32c"]:
            raise CorruptCheckpointError(
                f"checkpoint artifact {path} is corrupt or truncated")
        npz = np.load(io.BytesIO(raw))
    else:
        npz = np.load(npz_path)
    root: Any = None
    for node in meta["nodes"]:
        if "leaf" in node:
            value: Any = npz[f"leaf_{node['leaf']}"]
        else:
            value = {} if node["empty"] == "dict" else []
        root = _insert(root, node["path"], value)
    return root if root is not None else {}


def _insert(root, parts, value):
    if not parts:
        return value
    kind, key = parts[0]
    if kind == "i":
        key = int(key)
        if root is None:
            root = []
        while len(root) <= key:
            root.append(None)
        root[key] = _insert(root[key], parts[1:], value)
        return root
    if root is None:
        root = {}
    root[key] = _insert(root.get(key), parts[1:], value)
    return root


# ---------------------------------------------------------------------------
# Reference-layout training checkpoints
# ---------------------------------------------------------------------------
_STAMP_FMT = "%Y%m%d_%H%M%S"


class CheckpointManager:
    """Writes `model.<iter>` + `optimMethod-<name>.<iter>` into a timestamped
    subdir (created once per training run, `Topology.scala:1245-1252`)."""

    def __init__(self, root: str, optim_name: str = "default", keep: int = 3):
        self.root = root
        self.optim_name = optim_name
        self.keep = keep
        stamp = datetime.datetime.now().strftime(_STAMP_FMT)
        self.run_dir = os.path.join(root, stamp)
        os.makedirs(self.run_dir, exist_ok=True)
        self._saved: List[int] = []

    def save(self, iteration: int, params: Any, opt_state: Any = None,
             extra: Optional[Dict[str, Any]] = None) -> str:
        """Commit ORDER makes the checkpoint SET atomic, not just each
        artifact: optimizer state and metadata land first, the model
        artifact (whose CRC sidecar `checkpoint_intact` keys on) lands
        LAST as the commit marker. A crash anywhere before the final
        rename leaves no model.<iter>.npz, so the torn set is invisible
        to `latest_checkpoint`/resume — never a model that resumes with
        fresh optimizer state or epoch-0 metadata."""
        mpath = os.path.join(self.run_dir, f"model.{iteration}")
        if opt_state is not None:
            opath = os.path.join(self.run_dir,
                                 f"optimMethod-{self.optim_name}.{iteration}")
            save_pytree(opath, _optstate_to_tree(opt_state))
        if extra:
            tmp = mpath + f".meta.json.tmp-{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(extra, fh)
            os.replace(tmp, mpath + ".meta.json")
        save_pytree(mpath, params)
        self._saved.append(iteration)
        self._gc()
        return mpath

    def _gc(self):
        while len(self._saved) > self.keep:
            it = self._saved.pop(0)
            for pat in (f"model.{it}", f"optimMethod-{self.optim_name}.{it}"):
                # .int8.* is the quantization sidecar (ISSUE 12): it
                # lives and dies with its checkpoint version, or the
                # keep=N retention contract silently stops bounding the
                # directory
                # .published.json is the rollout marker (ISSUE 14):
                # retired with its version, or the watcher could keep
                # "seeing" a version whose artifacts are gone
                for suffix in (".npz", ".structure.json", ".meta.json",
                               ".int8.npz", ".int8.structure.json",
                               ".published.json"):
                    p = os.path.join(self.run_dir, pat + suffix)
                    if os.path.exists(p):
                        os.remove(p)


def list_checkpoints(root: str) -> List[Tuple[str, int]]:
    """Every (run_dir, version) under root, newest first (version desc,
    then run-dir stamp desc for ties across run dirs)."""
    found: List[Tuple[str, int]] = []
    if not os.path.isdir(root):
        return found
    candidates = [root] + [os.path.join(root, d)
                           for d in sorted(os.listdir(root))
                           if os.path.isdir(os.path.join(root, d))]
    for run_dir in candidates:
        if not os.path.isdir(run_dir):
            continue
        for f in os.listdir(run_dir):
            m = re.match(r"model\.(\d+)\.npz$", f)
            if m:
                found.append((run_dir, int(m.group(1))))
    return sorted(found, key=lambda rv: (rv[1], rv[0]), reverse=True)


def checkpoint_intact(run_dir: str, version: int) -> bool:
    """CRC/completeness check for one checkpoint version: the model
    artifact and (when present) its optimizer artifacts must all
    verify."""
    if not verify_pytree(os.path.join(run_dir, f"model.{version}")):
        return False
    for f in os.listdir(run_dir):
        if re.match(rf"optimMethod-.+\.{version}\.npz$", f):
            if not verify_pytree(os.path.join(run_dir, f)):
                return False
    return True


def latest_checkpoint(root: str,
                      verify: bool = True) -> Optional[Tuple[str, int]]:
    """Find (run_dir, version) of the newest INTACT model.<iter> under
    root — mirrors `find_latest_checkpoint` (`orca/learn/tf/utils.py`),
    plus the fallback discipline: a corrupt/truncated newest version is
    skipped (with a warning) in favor of the newest version that
    verifies. `verify=False` restores the raw newest-by-number scan."""
    for run_dir, version in list_checkpoints(root):
        if not verify or checkpoint_intact(run_dir, version):
            return (run_dir, version)
        log.warning(
            "checkpoint model.%d in %s is corrupt/truncated; falling "
            "back to an earlier version", version, run_dir)
    return None


def read_checkpoint_meta(run_dir: str, version: int) -> Dict[str, Any]:
    """The extra-metadata sidecar of one checkpoint ({} when absent or
    unreadable)."""
    mpath = os.path.join(run_dir, f"model.{version}.meta.json")
    try:
        with open(mpath) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return {}


def find_resume_checkpoint(root: str) -> Optional[Tuple[str, int,
                                                        Dict[str, Any]]]:
    """The checkpoint `fit_keras(auto_resume=True)` should continue
    from: the newest INTACT epoch-boundary checkpoint (mid-epoch and
    emergency saves are skipped — resuming from one would replay part
    of an epoch and break loss-identical continuation). Falls back to
    the newest intact checkpoint of any kind, with a warning, when no
    boundary checkpoint survives. Returns (run_dir, version, meta) or
    None."""
    fallback = None        # newest intact NON-boundary checkpoint
    # lazy: intactness CRC-reads whole artifacts, so verify candidates
    # newest-first only until a boundary hit instead of scanning every
    # version under every run dir up front
    for run_dir, version in list_checkpoints(root):
        if not checkpoint_intact(run_dir, version):
            continue
        meta = read_checkpoint_meta(run_dir, version)
        # legacy checkpoints predate the flag; treat them as boundaries
        if meta.get("epoch_finished", True):
            return (run_dir, version, meta)
        if fallback is None:
            fallback = (run_dir, version, meta)
    if fallback is not None:
        log.warning(
            "no epoch-boundary checkpoint under %s; resuming from "
            "mid-epoch model.%d (continuation will replay the partial "
            "epoch from its start)", root, fallback[1])
    return fallback


# ---------------------------------------------------------------------------
# Publish markers (ISSUE 14): the rollout contract between trainer and fleet
# ---------------------------------------------------------------------------
def _marker_path(run_dir: str, version: int) -> str:
    return os.path.join(run_dir, f"model.{version}.published.json")


def write_publish_marker(run_dir: str, version: int,
                         extra: Optional[Dict[str, Any]] = None) -> str:
    """Commit the PUBLISH marker for one checkpoint version — the
    rollout watcher's admission gate. Written LAST, after every
    artifact of the version (params, optimizer state, int8 sidecar) is
    durable: `latest_checkpoint` only proves the model artifact is
    intact, while a rollout must never serve a version whose sidecar
    (or opt state, for a warm A/B restart) is still mid-write. The
    marker records a CRC manifest of every artifact it vouches for, so
    `verify_publish_marker` can detect a version whose bytes changed
    (or vanished) after publication. Atomic write-then-rename like
    every other checkpoint artifact."""
    manifest: Dict[str, Dict[str, Any]] = {}
    prefix = f"model.{version}."
    optim_re = re.compile(rf"optimMethod-.+\.{version}\.")
    for f in sorted(os.listdir(run_dir)):
        if f.endswith(".published.json") or ".tmp-" in f:
            continue
        if not (f.startswith(prefix) or optim_re.match(f)):
            continue
        p = os.path.join(run_dir, f)
        with open(p, "rb") as fh:
            raw = fh.read()
        crc = crc32c(raw)
        if f.endswith(".npz"):
            # publishing asserts the WHOLE set verifies — checked in
            # THIS read pass (multi-GB checkpoints must not pay a
            # separate checkpoint_intact sweep per publish): each npz
            # must match the CRC its structure sidecar committed, so a
            # writer killed mid-write (or an injected truncation) can
            # never gain a marker
            try:
                with open(_struct_path(os.path.join(run_dir, f))) as sh:
                    meta = json.load(sh)
            except (OSError, ValueError):
                raise CorruptCheckpointError(
                    f"refusing to publish model.{version} in "
                    f"{run_dir}: {f} has no readable structure "
                    "sidecar") from None
            if "npz_crc32c" in meta and (
                    meta.get("npz_bytes") != len(raw)
                    or meta["npz_crc32c"] != crc):
                raise CorruptCheckpointError(
                    f"refusing to publish model.{version} in "
                    f"{run_dir}: {f} does not match its CRC sidecar")
        manifest[f] = {"bytes": len(raw), "crc32c": crc}
    if f"model.{version}.npz" not in manifest:
        raise FileNotFoundError(
            f"cannot publish model.{version} in {run_dir}: the model "
            "artifact is not on disk")
    marker = _marker_path(run_dir, version)
    tmp = marker + f".tmp-{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            json.dump({"version": version, "manifest": manifest,
                       "extra": extra or {}}, fh)
        os.replace(tmp, marker)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return marker


def read_publish_marker(run_dir: str,
                        version: int) -> Optional[Dict[str, Any]]:
    """The marker payload, or None when absent/unparseable (an
    unparseable marker is an UNPUBLISHED version, never an error — a
    crash mid-rename must not wedge the watcher)."""
    try:
        with open(_marker_path(run_dir, version)) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def verify_publish_marker(run_dir: str, version: int) -> bool:
    """True when the version carries a marker AND every artifact the
    marker's manifest vouches for still exists with matching
    bytes+CRC. A marked version whose artifacts were since torn (disk
    fault, partial restore) reads as unpublished."""
    marker = read_publish_marker(run_dir, version)
    if marker is None:
        return False
    for f, meta in (marker.get("manifest") or {}).items():
        p = os.path.join(run_dir, f)
        try:
            if os.path.getsize(p) != meta.get("bytes"):
                return False
            with open(p, "rb") as fh:
                if crc32c(fh.read()) != meta.get("crc32c"):
                    return False
        except OSError:
            return False
    return True


def _publish_stat_key(run_dir: str, version: int) -> Optional[tuple]:
    """Cheap cache key for a version's publish verdict: (mtime_ns,
    size) of the marker and of EVERY file its manifest vouches for —
    the marker JSON is small, so reading it per poll is cheap, and
    keying on the whole set means a verdict (True or False)
    invalidates the moment ANY artifact changes: a sidecar repaired
    in place re-verifies, a sidecar torn after the fact re-fails.
    None when the marker or any manifest file is absent (definitely
    unpublished — no verdict to cache)."""
    marker = read_publish_marker(run_dir, version)
    if marker is None:
        return None
    stats = []
    try:
        m = os.stat(_marker_path(run_dir, version))
        stats.append(("", m.st_mtime_ns, m.st_size))
        for f in sorted(marker.get("manifest") or {}):
            s = os.stat(os.path.join(run_dir, f))
            stats.append((f, s.st_mtime_ns, s.st_size))
    except OSError:
        return None
    return (run_dir, version, tuple(stats))


def published_intact(run_dir: str, version: int,
                     verify_cache: Optional[Dict] = None) -> bool:
    """The watcher's whole admission check, ONE read pass: the marker
    proves publication, and its manifest CRCs — which cover every
    artifact AND every structure sidecar, with npz↔sidecar consistency
    asserted at publish time by `write_publish_marker` — prove the set
    still holds the published bytes (a separate `checkpoint_intact`
    sweep would re-read the same multi-GB files to learn nothing new).
    With `verify_cache` (a caller-owned dict) the verdict is memoized
    per stat key, so a control loop polling every second costs stats
    plus one small JSON read per tick."""
    if verify_cache is None:
        return verify_publish_marker(run_dir, version)
    key = _publish_stat_key(run_dir, version)
    if key is None:
        return False
    verdict = verify_cache.get(key)
    if verdict is None:
        verdict = verify_publish_marker(run_dir, version)
        verify_cache[key] = verdict
    return verdict


def latest_published_checkpoint(
        root: str, skip_versions=(),
        verify_cache: Optional[Dict] = None) -> Optional[Tuple[str, int]]:
    """(run_dir, version) of the newest PUBLISHED checkpoint under
    `root` — what the rollout watcher acts on. Stricter than
    `latest_checkpoint`: a version without an intact publish marker
    (trainer still writing, crashed mid-commit, artifacts torn after
    the fact) is invisible, so a watcher polling a live training run
    can only ever observe versions whose whole artifact set is
    durable. `skip_versions` (the rollout controller's quarantine set)
    falls back to the newest published version not in it.

    `verify_cache` (a caller-owned dict) memoizes the full-CRC verdict
    per (run_dir, version, marker/model stat): verification reads and
    CRCs the WHOLE artifact set, which a control loop polling every
    second must not re-pay for a multi-GB checkpoint that hasn't
    changed — with the cache, an idle poll costs a dir listing and two
    stats. Entries for versions no longer listed are pruned."""
    skip = {int(v) for v in skip_versions}
    listed = list_checkpoints(root)
    if verify_cache is not None:
        live = {(rd, v) for rd, v in listed}
        for key in [k for k in verify_cache
                    if (k[0], k[1]) not in live]:
            verify_cache.pop(key, None)
    for run_dir, version in listed:
        if version in skip:
            continue
        if published_intact(run_dir, version, verify_cache=verify_cache):
            return (run_dir, version)
    return None


def resolve_checkpoint(path: str,
                       version: Optional[int] = None) -> Tuple[str, int]:
    """THE root-vs-run-dir resolution, shared by `load_checkpoint`,
    `InferenceModel.load_checkpoint` and the offline quantization
    script — one copy, so the sidecar probe and the param load can
    never resolve different directories. `version=None` → the newest
    INTACT checkpoint anywhere under `path`; an explicit version →
    `path` itself when it holds `model.<version>`, else the newest run
    dir under `path` that does. Raises FileNotFoundError."""
    if version is None:
        found = latest_checkpoint(path)
        if found is None:
            raise FileNotFoundError(f"No checkpoint under {path}")
        return found
    if os.path.exists(os.path.join(path, f"model.{version}.npz")):
        return path, version
    found = latest_checkpoint(path)
    if found and os.path.exists(
            os.path.join(found[0], f"model.{version}.npz")):
        return found[0], version
    raise FileNotFoundError(f"No model.{version} under {path}")


def load_checkpoint(path: str, version: Optional[int] = None,
                    optim_name: str = "default", verify: bool = True):
    """Load (params, opt_tree, meta) from a checkpoint dir. `path` may be the
    ckpt root or a run dir; `version=None` → latest. `verify=False` skips
    the CRC pass — for callers (auto-resume) that ran `checkpoint_intact`
    on this exact version moments earlier."""
    run_dir, version = resolve_checkpoint(path, version)
    params = load_pytree(os.path.join(run_dir, f"model.{version}"),
                         verify=verify)
    opt_tree = None
    opath = os.path.join(run_dir, f"optimMethod-{optim_name}.{version}")
    if os.path.exists(opath + ".npz"):
        opt_tree = load_pytree(opath, verify=verify)
    meta = {}
    mpath = os.path.join(run_dir, f"model.{version}.meta.json")
    if os.path.exists(mpath):
        with open(mpath) as fh:
            meta = json.load(fh)
    return params, opt_tree, meta


def _optstate_to_tree(opt_state: Any) -> Any:
    """Optax states are namedtuple pytrees; store leaves + paths only.
    Routed through `gather_leaf` so a GSPMD fit's sharded optimizer
    moments gather correctly (addressable shards fetched exactly
    once)."""
    return jax.tree_util.tree_map(gather_leaf, opt_state)


def restore_opt_state(template: Any, tree: Any) -> Any:
    """Pour saved leaves back into an optax state built by opt.init."""
    leaves_saved = jax.tree_util.tree_leaves(tree)
    treedef = jax.tree_util.tree_structure(template)
    leaves_tmpl = jax.tree_util.tree_leaves(template)
    if len(leaves_saved) != len(leaves_tmpl):
        raise ValueError(
            f"Optimizer state mismatch: saved {len(leaves_saved)} leaves, "
            f"template has {len(leaves_tmpl)}")
    cast = [np.asarray(s, dtype=np.asarray(t).dtype)
            for s, t in zip(leaves_saved, leaves_tmpl)]
    return jax.tree_util.tree_unflatten(treedef, cast)
