"""Lazy (row-sparse) embedding updates — the TPU-native answer to the
dense-Adam embedding sweep that dominates recommendation training.

Profiled on v5e (NCF, MovieLens scale, batch 8192): the dense Adam update
of the [138k, 64] user tables is ~78% of device step time — 7 full f32
passes (grad read; p, m, v read+write) over EVERY row each step, when a
batch touches at most 8192 of 138k rows (docs/ROOFLINE.md). The reference
has the same structure (dense gradient aggregation over the whole table).

This module updates ONLY the touched rows:

- the forward/backward stays the standard dense path (the gradient
  scatter-add is one zeros+scatter — cheap next to seven sweeps);
- the optimizer gathers the touched rows of (grad, p, m, v), applies
  row-wise Adam, and scatters the results back: O(batch·dim) optimizer
  traffic instead of O(table·dim);
- duplicate ids inside a batch are deduplicated by sort + neighbor
  compare, with duplicates redirected to an out-of-bounds index that
  `scatter(mode="drop")` discards — everything static-shape, jit/scan
  friendly;
- semantics are torch `SparseAdam`: momentum/variance decay advances
  only for touched rows (untouched rows are untouched bytes — that IS
  the optimization). Bias correction uses the global step count.

Wire-up: models expose `lazy_embedding_specs` (NeuralCF does);
`Estimator.fit(..., lazy_embeddings=True)` routes matching tables here
and every other parameter through the model's compiled optax optimizer
unchanged.
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax


class LazyEmbeddingSpec(NamedTuple):
    """One table: where it lives in the params pytree and how to read its
    batch ids from the model input. `lr=None` means "the model was
    compiled with the stock 'adam' string" — `resolve_specs` verifies
    that and fills optax.adam defaults; any other compiled optimizer
    must set the row-Adam hyperparameters here explicitly (the row
    updates are SparseAdam, independent of the dense-path optax chain).

    `set_ids_fn(xb, new_ids) -> xb` is the write twin of `ids_fn`: it
    rewrites the batch input so the model's gather reads `new_ids`
    instead. Declaring it unlocks the fully-sparse fused backward
    (`pallas/segment_update.py`): the trainer gathers the touched rows
    OUTSIDE the differentiated function and points the model at them
    through rewritten position ids, so a vocab-sized cotangent never
    materializes. Without it the fused path still does the in-place
    row-wise kernel update, but over a dense-materialized gradient."""
    path: Tuple[str, ...]                 # e.g. ("embedding_1", "embeddings")
    ids_fn: Callable                      # xb -> [B] int ids
    lr: float = None
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    set_ids_fn: Callable = None           # (xb, [B] ids) -> xb


def _get(tree, path):
    for k in path:
        tree = tree[k]
    return tree


def _set(tree, path, value):
    if len(path) == 1:
        return {**tree, path[0]: value}
    return {**tree, path[0]: _set(tree[path[0]], path[1:], value)}


def _key(spec: LazyEmbeddingSpec) -> str:
    return "/".join(spec.path)


def split_rest(params, specs: Sequence[LazyEmbeddingSpec]):
    """Params with table leaves replaced by None (a fixed treedef the
    rest-optimizer state is built over)."""
    rest = params
    for s in specs:
        rest = _set(rest, s.path, None)
    return rest


def init_state(params, specs: Sequence[LazyEmbeddingSpec],
               optimizer: optax.GradientTransformation):
    """(rest optax state, per-table (mu, nu), global step count)."""
    tables = {
        _key(s): (jnp.zeros_like(_get(params, s.path)),
                  jnp.zeros_like(_get(params, s.path)))
        for s in specs}
    return {"rest": optimizer.init(split_rest(params, specs)),
            "tables": tables, "t": jnp.zeros((), jnp.int32)}


def _dedup(ids, n_rows):
    """(safe_gather_idx, scatter_idx): duplicates keep an in-bounds gather
    index but scatter to n_rows (out of bounds → dropped)."""
    sids = jnp.sort(ids.astype(jnp.int32))
    dup = jnp.concatenate([jnp.zeros((1,), bool), sids[1:] == sids[:-1]])
    return jnp.where(dup, 0, sids), jnp.where(dup, n_rows, sids)


def row_adam_update(spec: LazyEmbeddingSpec, table, mu, nu, g_table, ids, t):
    """SparseAdam step over the rows `ids` touches; everything else is
    untouched bytes."""
    n_rows = table.shape[0]
    safe, scatter_idx = _dedup(ids, n_rows)
    g = g_table[safe]
    m = spec.b1 * mu[safe] + (1.0 - spec.b1) * g
    v = spec.b2 * nu[safe] + (1.0 - spec.b2) * g * g
    tf = t.astype(jnp.float32)
    mhat = m / (1.0 - spec.b1 ** tf)
    vhat = v / (1.0 - spec.b2 ** tf)
    p = table[safe] - spec.lr * mhat / (jnp.sqrt(vhat) + spec.eps)
    table = table.at[scatter_idx].set(p, mode="drop")
    mu = mu.at[scatter_idx].set(m, mode="drop")
    nu = nu.at[scatter_idx].set(v, mode="drop")
    return table, mu, nu


def make_lazy_one_step(apply_fn, loss_fn,
                       optimizer: optax.GradientTransformation,
                       specs: Sequence[LazyEmbeddingSpec],
                       apply_and_state_fn=None,
                       mixed_precision: bool = False):
    """Drop-in replacement for the trainer's one_step when lazy tables are
    declared: same (params, opt_state, xb, yb, rng) signature, with
    opt_state from `init_state`."""
    from analytics_zoo_tpu.learn.trainer import (_cast_tree, _merge_state)

    def one_step(params, opt_state, xb, yb, rng):
        def compute_loss(p):
            if mixed_precision:
                p = _cast_tree(p, jnp.bfloat16)
                # inputs stay uncast: ids_fn reads the same xb the model
                # sees, and bf16 cannot represent ids > 256 exactly
                # (see trainer.py one_step for the full rationale).
            if apply_and_state_fn is not None:
                pred, state_upd = apply_and_state_fn(p, xb, training=True,
                                                     rng=rng)
            else:
                pred, state_upd = apply_fn(p, xb, training=True,
                                           rng=rng), {}
            if mixed_precision:
                pred = jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.float32), pred)
            return loss_fn(yb, pred), state_upd

        (loss, state_upd), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        if mixed_precision:
            grads = _cast_tree(grads, jnp.float32, only=jnp.bfloat16)
            state_upd = _cast_tree(state_upd, jnp.float32,
                                   only=jnp.bfloat16)

        t = opt_state["t"] + 1
        tables = dict(opt_state["tables"])
        for s in specs:
            table, mu, nu = row_adam_update(
                s, _get(params, s.path), *tables[_key(s)],
                _get(grads, s.path), s.ids_fn(xb), t)
            params = _set(params, s.path, table)
            tables[_key(s)] = (mu, nu)

        rest_grads = split_rest(grads, specs)
        rest_params = split_rest(params, specs)
        updates, rest_state = optimizer.update(
            rest_grads, opt_state["rest"], rest_params)
        new_rest = optax.apply_updates(rest_params, updates)
        # graft the updated non-table leaves back in (table leaves are
        # None in new_rest and keep their row-updated values)
        params = jax.tree_util.tree_map(
            lambda new, old: old if new is None else new,
            new_rest, params, is_leaf=lambda x: x is None)
        params = _merge_state(params, state_upd)
        return params, {"rest": rest_state, "tables": tables, "t": t}, loss

    return one_step


def resolve_specs(model) -> Sequence[LazyEmbeddingSpec]:
    """Read `lazy_embedding_specs` off a model (attribute or zero-arg
    method); raises when absent so `lazy_embeddings=True` never silently
    falls back to the dense sweep. Specs with `lr=None` require the model
    to be compiled with the stock "adam" string (whose defaults they
    inherit) — any other optimizer silently training the tables with
    different hyperparameters than the rest of the model would be a trap.
    """
    specs = getattr(model, "lazy_embedding_specs", None)
    if callable(specs):
        specs = specs()
    if not specs:
        raise ValueError(
            "lazy_embeddings=True but the model declares no "
            "lazy_embedding_specs (path + ids_fn per table)")
    out = []
    okey = getattr(model, "_optimizer_spec", None)
    for s in specs:
        if s.lr is None:
            if str(okey).lower() != "adam":
                raise ValueError(
                    "lazy_embeddings: spec for " + "/".join(s.path) +
                    " inherits adam defaults but the model was compiled "
                    f"with {okey!r}; set lr/b1/b2/eps on the "
                    "LazyEmbeddingSpec to match the compiled optimizer")
            s = s._replace(lr=1e-3)
        out.append(s)
    return out
