"""Orca-style unified Estimator — the north-star `zoo.orca.learn` entry point.

Mirrors the surface of `Estimator.from_graph/from_keras/from_torch`
(`orca/learn/tf/estimator.py:148`, `orca/learn/tf2/tf_ray_estimator.py:183`,
`orca/learn/pytorch/estimator.py:50`) and the engine-agnostic Scala Estimator
(`zoo/.../pipeline/estimator/Estimator.scala:68`). One implementation instead
of the reference's five per-engine wrappers: everything lowers to the same
pjit'd train loop (`learn/trainer.py`).

- `from_keras(model)` — a `analytics_zoo_tpu.keras` model (Sequential/Model).
- `from_fn(forward_fn, init_fn, loss, optimizer)` — the `from_graph`
  analogue: a pure forward function + parameter initializer.
- `from_torch(model, loss, optimizer)` — converts a torch.nn module's
  architecture+weights to the native layer library (the reference instead
  embeds CPython in the JVM via JEP, `TorchModel.scala:34`; on TPU the model
  must become an XLA program, so conversion replaces embedding).

Failure handling reproduces `InternalDistriOptimizer.train`'s retry loop
(`Topology.scala:1255-1337`): on a training exception, reload the latest
snapshot and resume, up to `retry_times` failures within a sliding window.

Data: accepts TPUDataset, XShards of {"x","y"}, (x, y) ndarrays, pandas
DataFrame (+feature/label cols) — the `to_dataset` conversion surface
(`orca/learn/tf/estimator.py:225-276`).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from analytics_zoo_tpu.common import triggers as tg
from analytics_zoo_tpu.common.context import get_context
from analytics_zoo_tpu.data.dataset import TPUDataset
from analytics_zoo_tpu.data.shards import XShards
from analytics_zoo_tpu.keras.engine import KerasNet
from analytics_zoo_tpu.learn import checkpoint as ckpt_mod
from analytics_zoo_tpu.learn import trainer

log = logging.getLogger("analytics_zoo_tpu.estimator")


class QuantizationQualityError(ValueError):
    """The int8-quantized model's eval metrics drifted past the
    configured tolerance from the f32 baseline — the quality gate of
    `Estimator.evaluate(..., quantize="int8", quality_tolerance=...)`
    refusing to bless a quantized artifact for serving (the
    OpenVINOInt8Suite predict-equivalence contract, made a hard
    gate)."""


def to_dataset(data, batch_size: int = -1, batch_per_thread: int = -1,
               feature_cols: Optional[Sequence[str]] = None,
               label_cols: Optional[Sequence[str]] = None) -> TPUDataset:
    """Normalize any supported data form into a TPUDataset."""
    if isinstance(data, TPUDataset):
        return data
    if isinstance(data, XShards):
        return TPUDataset.from_xshards(data, batch_size, batch_per_thread)
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            if not feature_cols:
                raise ValueError("DataFrame input needs feature_cols")
            return TPUDataset.from_dataframe(data, feature_cols, label_cols,
                                             batch_size, batch_per_thread)
    except ImportError:
        pass
    return TPUDataset.from_ndarrays(data, batch_size, batch_per_thread)


class Estimator:
    """Unified estimator facade (`orca/learn/base_estimator.py:43`)."""

    def __init__(self, model: KerasNet, model_dir: Optional[str] = None):
        self.model = model
        self.model_dir = model_dir
        self._load_ckpt: Optional[Tuple[str, Optional[int]]] = None
        # (torch optimizer, torch scheduler) whose per-epoch schedule is
        # resolved at fit() time when steps_per_epoch was not given
        self._torch_optim_spec = None

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_keras(keras_model: KerasNet, model_dir: Optional[str] = None,
                   optimizer=None, loss=None, metrics=None) -> "Estimator":
        """`Estimator.from_keras`. The model may already be compiled; compile
        args given here override."""
        if optimizer is not None or loss is not None:
            keras_model.compile(optimizer or "adam", loss or "mse", metrics)
        return Estimator(keras_model, model_dir)

    @staticmethod
    def from_fn(forward_fn: Callable, init_fn: Callable,
                loss, optimizer, metrics=None,
                model_dir: Optional[str] = None) -> "Estimator":
        """`from_graph` analogue: forward_fn(params, x, training, rng) plus
        init_fn(rng, input_shape)->params."""
        model = _FnModel(forward_fn, init_fn)
        model.compile(optimizer, loss, metrics)
        return Estimator(model, model_dir)

    @staticmethod
    def from_model_fn(model_fn: Callable, init_fn: Callable,
                      optimizer="adam", metrics=None,
                      model_dir: Optional[str] = None) -> "Estimator":
        """`TFEstimator.from_model_fn` analogue (`tfpark/estimator.py:47`):
        model_fn(params, features, labels, mode, rng) returns a dict spec —
        {"loss": scalar} in "train"/"eval" mode, {"predictions": tree} in
        "predict" mode. The loss is computed INSIDE model_fn (the
        tf.estimator contract), so the compile loss is a pass-through."""
        model = _ModelFnModel(model_fn, init_fn)
        model.compile(optimizer, model._spec_loss, metrics)
        return Estimator(model, model_dir)

    @staticmethod
    def from_torch(model, loss=None, optimizer=None, metrics=None,
                   scheduler=None, steps_per_epoch: Optional[int] = None,
                   model_dir: Optional[str] = None) -> "Estimator":
        """Convert a torch.nn module (Sequential-style) into the native layer
        library, carrying its trained weights. Supported: Linear, Conv2d,
        ReLU/Tanh/Sigmoid/Softmax/GELU, MaxPool2d/AvgPool2d, Flatten,
        Dropout, BatchNorm1d/2d, Embedding, LSTM/GRU (single layer).

        `loss` may be a torch.nn loss module and `optimizer` a
        torch.optim.Optimizer (+ optional torch LR `scheduler`) — the
        reference's TorchLoss/TorchOptim interop (`TorchOptim.scala:41-60`);
        both convert once to jax/optax equivalents, so the hot path stays
        pure XLA. Per-epoch schedulers (torch's StepLR-stepped-per-epoch
        idiom) need `steps_per_epoch`; when omitted it is computed at
        fit() time from the dataset size and batch size."""
        from analytics_zoo_tpu.learn.torch_bridge import (
            convert_torch_loss, convert_torch_module,
            convert_torch_optimizer)
        native = convert_torch_module(model)
        # torch itself is importable here — convert_torch_module already ran
        import torch
        import torch.nn as nn
        if isinstance(loss, nn.Module):
            loss = convert_torch_loss(loss)
        torch_spec = None
        if isinstance(optimizer, torch.optim.Optimizer):
            if scheduler is not None and steps_per_epoch is None:
                # real steps/epoch known only at fit(); provisional now
                torch_spec = (optimizer, scheduler)
            optimizer = convert_torch_optimizer(
                optimizer, scheduler, steps_per_epoch or 1)
        elif scheduler is not None:
            raise ValueError("scheduler is only used with a torch optimizer")
        native.compile(optimizer or "adam", loss or "mse", metrics)
        est = Estimator(native, model_dir)
        est._torch_optim_spec = torch_spec
        return est

    # -- training with retry/resume ---------------------------------------
    def fit(self, data, epochs: int = 1, batch_size: Optional[int] = None,
            validation_data=None, checkpoint_trigger=None,
            feature_cols=None, label_cols=None, seed: int = 0,
            **fit_kwargs) -> Dict[str, List[float]]:
        """`fit_kwargs` pass through to the trainer loop: `steps_per_run=k`
        fuses k steps per dispatch, `mixed_precision=True` runs bf16
        compute with f32 masters, `prefetch=False` disables the
        background batch pipeline, `metrics_report_s=30` logs a periodic
        registry digest, `flops_per_step=...` enables the MFU gauge,
        `sharding_rules=True` (or a `parallel.sharding.ShardingRules`)
        runs the GSPMD-sharded fit — params/opt_state sharded over the
        mesh's fsdp axis with the same rule table serving's sharded
        placement consumes (`ZooConfig.sharded_fit` / ZOO_SHARDED_FIT=1
        is the config spelling; see
        docs/ProgrammingGuide/distributed-training.md),
        `fused_optimizer=True` swaps a stock adam/adamw for the fused
        Pallas update kernels (`ZooConfig.fused_optimizer` /
        ZOO_FUSED_OPT=1; one HBM pass per leaf, sparse segment path for
        declared embedding tables under `lazy_embeddings=True`).
        Step/loss/throughput telemetry lands in the process-wide
        `MetricsRegistry` either way (`observability/`)."""
        ds = to_dataset(data, batch_size=batch_size or 32,
                        feature_cols=feature_cols, label_cols=label_cols)
        # a pre-built TPUDataset's own batch/shuffle settings win over fit()
        # defaults (the dataset carries the contract, `tf_dataset.py:116`)
        if ds.batch_size != -1:
            batch_size = ds.batch_size
        elif batch_size is None:
            batch_size = 32
        dp = get_context().mesh.data_parallel_size
        lazy = ds.x is None  # disk-tier FeatureSet / TFRecord stream bridge
        if self._torch_optim_spec is not None:
            # per-epoch torch scheduler: now that the dataset + resolved
            # batch are known, rebuild the optax schedule with the true
            # steps/epoch. Lazy datasets step at global_batch (their
            # iter_train contract); in-memory data steps at the resolved
            # fit batch_size.
            from analytics_zoo_tpu.learn.torch_bridge import \
                convert_torch_optimizer
            topt, tsched = self._torch_optim_spec
            # multi-process fit_keras steps each process through its LOCAL
            # shard at batch_size/process_count per step, so steps/epoch is
            # n_local // per_process_batch — using the global batch here
            # would make the rebuilt schedule decay process_count× early.
            step_batch = (ds.global_batch(dp) if lazy
                          else max(1, batch_size // jax.process_count()))
            spe = max(1, ds.n_samples() // step_batch)
            self.model.optimizer = convert_torch_optimizer(
                topt, tsched, steps_per_epoch=spe)
            for cache in ("_train_cache", "_eval_cache", "_predict_cache"):
                if hasattr(self.model, cache):
                    delattr(self.model, cache)

        # callers may supply their own per-epoch batch source (nnframes
        # re-runs stochastic sample preprocessing each epoch this way
        # WITHOUT restarting fit — optimizer state must survive epochs)
        batch_iter_factory = fit_kwargs.pop("batch_iter_factory", None)
        if batch_iter_factory is None:
            batch_iter_factory = (
                (lambda epoch: ds.iter_train(dp, seed=seed + epoch))
                if lazy else None)
            if batch_iter_factory is not None:
                # datasets that read DISJOINT files per host (TFRecord
                # via pipeline.host_shard) declare it so fit_keras's
                # multi-process streaming-duplication guard admits them
                batch_iter_factory.shards_per_host = getattr(
                    ds, "shards_per_host", False)
        if lazy and self.model.params is None \
                and hasattr(ds, "first_sample"):
            # cheap shape probe: one record, not a shuffle-buffer fill
            sx, _ = ds.first_sample()
            batched = jax.tree_util.tree_map(
                lambda a: np.expand_dims(a, 0), sx)
            self.model.ensure_built(batched, jax.random.PRNGKey(seed))

        val = None
        if validation_data is not None:
            vds = to_dataset(validation_data, batch_size=batch_size,
                             feature_cols=feature_cols, label_cols=label_cols)
            val = vds.materialize()
        elif ds.val is not None:
            val = ds.val.materialize()

        cfg = get_context().config
        if self.model_dir:
            self.model.set_checkpoint(self.model_dir)
        if self._load_ckpt is not None:
            self._restore(*self._load_ckpt)
            self._load_ckpt = None

        failures: List[float] = []
        epoch_done = getattr(self, "_resume_epoch", 0)
        history: Dict[str, List[float]] = {}
        while epoch_done < epochs:
            try:
                h = trainer.fit_keras(
                    self.model, ds.x, ds.y, batch_size=batch_size,
                    epochs=epochs - epoch_done, validation_data=val,
                    shuffle=ds.shuffle,
                    checkpoint_trigger=checkpoint_trigger,
                    seed=seed + epoch_done,
                    batch_iter_factory=batch_iter_factory, **fit_kwargs)
                for k, v in h.items():
                    history.setdefault(k, []).extend(v)
                break
            except (KeyboardInterrupt, jax.errors.JaxRuntimeError):
                raise
            except ValueError:
                raise  # config errors are not retryable (IllegalArgument)
            except Exception as e:  # noqa: BLE001 — retry semantics
                now = time.time()
                failures = [t for t in failures
                            if now - t < cfg.failure.retry_time_interval_s]
                failures.append(now)
                if len(failures) > cfg.failure.retry_times:
                    log.error("Exceeded %d failures within %ds window; "
                              "giving up", cfg.failure.retry_times,
                              cfg.failure.retry_time_interval_s)
                    raise
                # counted only once the budget check passed: the final
                # fatal failure re-raises above and is NOT a recovery
                from analytics_zoo_tpu.observability import get_registry
                get_registry().counter(
                    "training_retries_total",
                    "training failures recovered by snapshot-restore "
                    "retry").inc()
                log.warning("Training failure (%s: %s); restoring latest "
                            "snapshot and retrying (%d/%d)",
                            type(e).__name__, e, len(failures),
                            cfg.failure.retry_times)
                epoch_done = self._restore_latest() or epoch_done
        self._resume_epoch = 0
        return history

    def _restore_latest(self) -> Optional[int]:
        if not self.model_dir:
            return None
        found = ckpt_mod.latest_checkpoint(self.model_dir)
        if found is None:
            return None
        params, _, meta = ckpt_mod.load_checkpoint(self.model_dir)
        self.model.params = self.model._remap_loaded(params)
        return int(meta.get("epoch", 0)) if meta else None

    def _restore(self, path: str, version: Optional[int]):
        params, _, meta = ckpt_mod.load_checkpoint(path, version)
        # remap saved layer names onto this instance's auto-generated names
        # (save order == stack order; the pytree store preserves dict order)
        self.model.params = self.model._remap_loaded(params)
        self._resume_epoch = int(meta.get("epoch", 0)) if meta else 0

    # -- inference ---------------------------------------------------------
    def predict(self, data, batch_per_thread: int = 32, feature_cols=None
                ) -> np.ndarray:
        ds = to_dataset(data, batch_per_thread=batch_per_thread,
                        feature_cols=feature_cols)
        x, _ = ds.materialize()
        preds = self.model.predict(x, batch_per_thread=batch_per_thread)
        return preds

    def evaluate(self, data, batch_per_thread: int = 32, metrics=None,
                 feature_cols=None, label_cols=None,
                 quantize: Optional[str] = None,
                 quality_tolerance: Optional[float] = None,
                 baseline_metrics: Optional[Dict[str, float]] = None
                 ) -> Dict[str, float]:
        """`quantize="int8"` evaluates the POST-TRAINING-QUANTIZED
        model (per-output-channel int8 weights,
        `serving/quantization.py`) instead of the f32 one, and — with
        `quality_tolerance` — enforces the quality gate: every metric
        must sit within `quality_tolerance` (absolute) of the f32
        baseline or the call raises `QuantizationQualityError`, so a
        quantized model that lost accuracy can never be blessed for
        serving. The baseline is evaluated on the spot unless
        `baseline_metrics` (a prior f32 `evaluate()` result) is
        passed; the return carries the quantized metrics plus the
        baseline as `baseline_<name>` entries."""
        if quantize is not None:
            return self._evaluate_quantized(
                data, batch_per_thread, metrics, feature_cols,
                label_cols, quantize, quality_tolerance,
                baseline_metrics)
        ds = to_dataset(data, batch_per_thread=batch_per_thread,
                        feature_cols=feature_cols, label_cols=label_cols)
        if metrics:
            # detection mAP is corpus-level (per-class global score sort) —
            # it cannot stream through the jitted metric accumulators, so
            # it takes the predict-then-evaluate path
            from analytics_zoo_tpu.models.detection_eval import DetectionMAP
            mlist = metrics if isinstance(metrics, (list, tuple)) \
                else [metrics]
            det = [m for m in mlist if isinstance(m, DetectionMAP)]
            if det:
                if len(det) != len(mlist):
                    raise ValueError(
                        "DetectionMAP cannot be mixed with streaming "
                        "metrics in one evaluate() call")
                x, y = ds.materialize()
                flat = self.model.predict(
                    x, batch_per_thread=batch_per_thread)
                out: Dict[str, float] = {}
                for i, m in enumerate(det):
                    # disambiguate repeated evaluators (e.g. VOC07 + area)
                    tag = m.name if len(det) == 1 else f"{m.name}_{i}"
                    res = m.evaluate_flat(flat, y)
                    out[tag] = res.result()[0]
                    out.update({f"AP_{n}" if len(det) == 1
                                else f"AP_{n}_{i}": ap
                                for n, ap in res.ap_by_class()})
                return out
        from analytics_zoo_tpu.ops import metrics as zmetrics
        ms = zmetrics.resolve(metrics) if metrics else None
        x, y = ds.materialize()
        if isinstance(self.model, _ModelFnModel) and not ms \
                and not self.model.metrics:
            # spec loss needs the raw features → dedicated eval path
            return self.model._evaluate_spec(x, y, batch_per_thread)
        return self.model.evaluate(x, y,
                                   batch_per_thread=batch_per_thread,
                                   metrics=ms)

    def _evaluate_quantized(self, data, batch_per_thread, metrics,
                            feature_cols, label_cols, quantize,
                            quality_tolerance,
                            baseline_metrics) -> Dict[str, float]:
        """The quantized leg of `evaluate`: f32 baseline (given or
        evaluated here), then the same evaluation with the model's
        params swapped for the int8 rewrite (the layers dispatch on the
        quantized keys; the f32 master params are restored whatever
        happens), then the tolerance gate."""
        if quantize != "int8":
            raise ValueError(
                f"Unsupported quantize={quantize!r}; only 'int8'")
        from analytics_zoo_tpu.serving.quantization import \
            quantize_model_params
        base = baseline_metrics if baseline_metrics is not None else \
            self.evaluate(data, batch_per_thread=batch_per_thread,
                          metrics=metrics, feature_cols=feature_cols,
                          label_cols=label_cols)
        if self.model.params is None:
            raise ValueError("Model has no parameters; fit or load first")
        f32_params = self.model.params
        q = quantize_model_params(self.model,
                                  jax.device_get(f32_params))
        try:
            self.model.params = q
            quantized = self.evaluate(
                data, batch_per_thread=batch_per_thread,
                metrics=metrics, feature_cols=feature_cols,
                label_cols=label_cols)
        finally:
            self.model.params = f32_params
        if quality_tolerance is not None:
            # `not (|Δ| <= tol)`, NOT `|Δ| > tol`: a NaN metric (an
            # int8 rewrite that overflowed) compares False either way,
            # and the gate must REFUSE what it cannot prove within
            # tolerance rather than bless it
            drifted = {
                name: (base[name], quantized[name])
                for name in quantized
                if name in base
                and not (abs(quantized[name] - base[name])
                         <= quality_tolerance)}
            if drifted:
                detail = ", ".join(
                    f"{n}: f32={b:.6g} int8={q_:.6g} "
                    f"(|Δ|={abs(q_ - b):.6g})"
                    for n, (b, q_) in sorted(drifted.items()))
                raise QuantizationQualityError(
                    f"int8 quantization drifted {len(drifted)} metric(s) "
                    f"past the quality gate (tolerance "
                    f"{quality_tolerance:g}): {detail}. Refusing to "
                    "bless the quantized model; raise the tolerance "
                    "only if this accuracy loss is acceptable, or keep "
                    "serving f32/bf16.")
        out = dict(quantized)
        out.update({f"baseline_{k}": v for k, v in base.items()})
        return out

    # -- persistence (`orca` save/load + load_orca_checkpoint) ------------
    def get_model(self):
        return self.model

    def save(self, path: str) -> str:
        self.model.save_weights(path)
        return path

    def load(self, path: str) -> "Estimator":
        self.model.load_weights(path)
        return self

    def load_orca_checkpoint(self, path: str,
                             version: Optional[int] = None) -> "Estimator":
        """Resume from a `model.<version>` checkpoint
        (`orca/learn/tf/estimator.py:125` semantics; version=None → latest)."""
        self._load_ckpt = (path, version)
        return self


class _ModelFnModel(KerasNet):
    """tf.estimator-style adapter: model_fn(params, features, labels, mode,
    rng) → spec dict. Training feeds labels through `apply` by closing over
    the batch (the trainer calls apply(params, x) then loss(y, out); here
    `apply` returns features untouched in predict mode and the loss path
    re-invokes model_fn with labels)."""

    def __init__(self, model_fn: Callable, init_fn: Callable):
        super().__init__()
        self.model_fn = model_fn
        self.init_fn = init_fn

    def build(self, rng, input_shape):
        return self.init_fn(rng, input_shape)

    def apply(self, params, inputs, *, training=False, rng=None):
        if training:
            # defer: loss path recombines with labels in _spec_loss via
            # the (params, features) closure the trainer maintains
            return _DeferredSpec(self, params, inputs, rng)
        spec = self.model_fn(params, inputs, None, "predict", rng)
        return spec["predictions"]

    def _spec_loss(self, y_true, deferred):
        if not isinstance(deferred, _DeferredSpec):
            # eval path delivers plain predictions; the spec loss needs the
            # raw features, so evaluation goes through evaluate() (which
            # dispatches to _evaluate_spec) or explicit compiled metrics
            raise ValueError(
                "from_model_fn: the spec loss is only computable in the "
                "training path; compile explicit metrics for validation "
                "(metrics=[...]) or call Estimator.evaluate()")
        spec = self.model_fn(deferred.params, deferred.features, y_true,
                             "train", deferred.rng)
        return spec["loss"]

    def _evaluate_spec(self, x, y, batch_per_thread: int = 32
                       ) -> Dict[str, float]:
        """Mean spec loss over batches — model_fn in eval mode."""
        import jax

        from analytics_zoo_tpu.learn import trainer as _trainer

        @jax.jit
        def batch_loss(params, xb, yb):
            spec = self.model_fn(params, xb, yb, "eval", None)
            return spec["loss"]

        total, n = 0.0, 0
        for xb, yb, _count in _trainer.iter_batches(
                x, y, batch_per_thread, shuffle=False,
                drop_remainder=False):
            total += float(batch_loss(self.params, xb, yb))
            n += 1
        return {"loss": total / max(n, 1)}

    def compute_output_shape(self, input_shape):
        return None


class _DeferredSpec:
    """Carries (params, features, rng) from apply to the loss call."""

    def __init__(self, model, params, features, rng):
        self.model = model
        self.params = params
        self.features = features
        self.rng = rng


class _FnModel(KerasNet):
    """Adapter: pure forward/init functions behave like a KerasNet so the
    shared trainer drives them (the `from_graph` lowering)."""

    def __init__(self, forward_fn: Callable, init_fn: Callable):
        super().__init__()
        self.forward_fn = forward_fn
        self.init_fn = init_fn

    def build(self, rng, input_shape):
        return self.init_fn(rng, input_shape)

    def apply(self, params, inputs, *, training=False, rng=None):
        return self.forward_fn(params, inputs, training=training, rng=rng)

    def compute_output_shape(self, input_shape):
        return None
