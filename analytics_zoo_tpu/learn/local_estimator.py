"""Single-host trainer (no mesh/context required).

Reference: `zoo/.../pipeline/estimator/LocalEstimator.scala` — a
single-JVM multi-thread trainer used by the `localEstimator` examples;
here a thin single-device wrapper over the Keras engine fit loop (XLA's
intra-op threading plays the multi-thread role).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.keras.engine import KerasNet


class LocalEstimator:
    """`LocalEstimator(model, criterion, optimizer)` then
    `fit(x, y, epochs, batch_size)` / `evaluate` / `predict`."""

    def __init__(self, model: KerasNet, criterion: Any = "mse",
                 optimizer: Any = "sgd",
                 metrics: Optional[Sequence] = None):
        self.model = model
        self.model.compile(optimizer, criterion, metrics)

    def fit(self, x, y, epochs: int = 1, batch_size: int = 32,
            validation_data=None) -> Dict[str, list]:
        return self.model.fit(x, y, batch_size=batch_size, nb_epoch=epochs,
                              validation_data=validation_data)

    def evaluate(self, x, y, batch_size: int = 32) -> Dict[str, float]:
        return self.model.evaluate(x, y, batch_per_thread=batch_size)

    def predict(self, x, batch_size: int = 32) -> np.ndarray:
        return np.asarray(self.model.predict(x, batch_per_thread=batch_size))
