"""The distributed training loop — TPU-native `InternalDistriOptimizer`.

The reference's hot loop (`Topology.scala:1160-1337`, via BigDL
DistriOptimizer) does, per iteration: broadcast weights from the BlockManager,
local forward/backward per executor thread, scatter-reduce gradient slices,
per-slice optimizer update, allgather weights. Here the whole iteration is ONE
jit-compiled XLA program: parameters live replicated (or fsdp-sharded) on the
mesh, the batch is split over the mesh's batch axes, and GSPMD inserts the
gradient all-reduce over ICI automatically. Triggers, checkpoints, metrics and
the retry/resume semantics (`Topology.scala:1255-1337`) are host-side around
that one program.

Batch-size contract (`tfpark/tf_dataset.py:116-157`): training takes a GLOBAL
`batch_size` that must divide by the data-parallel size; eval/predict take
per-device `batch_per_thread`.
"""

from __future__ import annotations

import functools
import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common.context import get_context
from analytics_zoo_tpu.common import triggers as tg
from analytics_zoo_tpu.observability.registry import get_registry

log = logging.getLogger("analytics_zoo_tpu.trainer")


class _TrainingMetrics:
    """Training telemetry published into the process-wide registry — the
    same spine the serving pipeline and HTTP frontend feed, so one
    `GET /metrics` scrape answers for both sides of the platform.
    Registration is get-or-create: repeated fits converge on the same
    families and counters accumulate across fits (that is the Prometheus
    model; per-fit views come from `MetricsRegistry.delta`)."""

    def __init__(self, registry=None):
        reg = registry if registry is not None else get_registry()
        self.step_ms = reg.histogram(
            "training_step_ms",
            "per-step wall time, averaged over each epoch's device sync")
        self.steps = reg.counter("training_steps_total",
                                 "optimizer steps run")
        self.samples = reg.counter("training_samples_total",
                                   "training samples consumed")
        self.epochs = reg.counter("training_epochs_total",
                                  "epochs completed")
        self.loss = reg.gauge("training_loss", "mean loss of the last epoch")
        self.throughput = reg.gauge("training_samples_per_sec",
                                    "last epoch's training throughput")
        self.mfu = reg.gauge(
            "training_mfu",
            "model FLOPs utilization vs per-chip peak (needs "
            "flops_per_step)")
        self.val = reg.gauge("training_validation_metric",
                             "last validation metrics, labeled by name")
        self.resumes = reg.counter(
            "training_resumes_total",
            "training runs continued from a checkpoint by auto_resume")
        self.step_retries = reg.counter(
            "training_step_retries_total",
            "failed/hung training steps retried by the step watchdog")
        self.mesh_axis = reg.gauge(
            "training_mesh_axis_size",
            "device-mesh axis extents of the sharded fit, labeled by "
            "axis (a tensor extent > 1 means column/row-parallel "
            "placement is live)")
        self.input_wait_ms = reg.histogram(
            "training_input_wait_ms",
            "per-step wall time the training loop sat blocked on the "
            "input-pipeline prefetch queue before dispatching (device "
            "idle, host decoding — the input-stall histogram)")
        self.input_bound = reg.gauge(
            "training_input_bound",
            "fraction of the last epoch's wall time the step loop "
            "spent blocked on the prefetch queue (0 = device-bound, "
            "1 = fully input-bound; the measured verdict on whether "
            "a fit needs more pipeline_workers)")
        self.fused_update_ms = reg.histogram(
            "training_fused_update_ms",
            "measured wall time of one fused-kernel optimizer sweep "
            "over the model's parameter tree (observed once per "
            "model/step-program build, not per fit — warm re-fits "
            "skip the probe)")

    def mesh_axes(self, mesh) -> None:
        """Publish the sharded fit's mesh factorization (one series per
        axis) so a scrape can tell a pure-fsdp fit from a tensor-
        parallel one without reading logs. `mesh=None` (a non-sharded
        fit) resets every axis to 1 — a later replicated fit must not
        leave a previous fit's factorization reading as live."""
        if mesh is None:
            from analytics_zoo_tpu.common.mesh import AXIS_NAMES
            sizes = {a: 1 for a in AXIS_NAMES}
        else:
            sizes = mesh.axis_sizes
        for ax, size in sizes.items():
            self.mesh_axis.set(size, axis=ax)

    def epoch(self, steps: int, n_seen: int, dt: float, mean_loss: float,
              flops_per_step: Optional[float] = None):
        step_ms = dt / max(steps, 1) * 1e3
        self.step_ms.observe(step_ms)
        self.steps.inc(steps)
        self.samples.inc(n_seen)
        self.epochs.inc()
        self.loss.set(mean_loss)
        self.throughput.set(n_seen / max(dt, 1e-9))
        if flops_per_step:
            from analytics_zoo_tpu.utils.roofline import peak_flops
            peak = peak_flops(jax.devices()[0]) * jax.device_count()
            self.mfu.set(flops_per_step * steps / max(dt, 1e-9) / peak)
        return step_ms

    def roofline(self, flops: float, bytes_: float, dt: float,
                 n_devices: int = 1):
        """Cost-analysis roofline for one epoch (ISSUE 6): publishes
        `roofline_mfu{kind="train"}` / `roofline_hbm_utilization` etc.
        from the XLA-counted FLOPs/bytes over the epoch's device wall
        time — no hand-supplied flops_per_step, and HBM utilization
        against the measured session roofline. `flops`/`bytes_` are
        GLOBAL (all participating devices); `n_devices` is the step
        program's device span, scaling the roofline denominator so a
        sharded fit's MFU reads against the whole slice's peak."""
        from analytics_zoo_tpu.observability.roofline import get_accountant
        get_accountant().account("train", flops, bytes_, dt,
                                 device=jax.devices()[0],
                                 n_devices=n_devices)


# ---------------------------------------------------------------------------
# Data plumbing: numpy structures -> shard-ready batches
# ---------------------------------------------------------------------------
def _tree_len(x) -> int:
    leaves = jax.tree_util.tree_leaves(x)
    if not leaves:
        raise ValueError("Empty input data")
    return int(np.shape(leaves[0])[0])


def _tree_take(x, idx):
    return jax.tree_util.tree_map(lambda a: np.asarray(a)[idx], x)


def _num_batches(n: int, batch: int, drop_remainder: bool) -> int:
    return n // batch if drop_remainder else -(-n // batch)


def iter_batches(x, y=None, batch_size: int = 32, shuffle: bool = False,
                 seed: int = 0, drop_remainder: bool = True,
                 pad_to_batch: bool = False):
    """Yield (x_batch, y_batch, real_count) of numpy arrays. Static batch
    shapes (pad or drop) keep jit from recompiling — the TPU analogue of the
    reference's `hard_code_batch_size` (`tf_dataset.py:158-173`)."""
    n = _tree_len(x)
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    nb = _num_batches(n, batch_size, drop_remainder and not pad_to_batch)
    for b in range(nb):
        sel = idx[b * batch_size:(b + 1) * batch_size]
        real = len(sel)
        if real < batch_size:
            if pad_to_batch:
                sel = np.concatenate([sel, np.repeat(sel[-1:],
                                                     batch_size - real)])
            else:
                continue
        xb = _tree_take(x, sel)
        yb = _tree_take(y, sel) if y is not None else None
        yield xb, yb, real


def check_global_batch(batch_size: int, dp: int, fsdp: int = 1) -> None:
    """`dp` is the full batch-splitting extent (data × fsdp — BOTH are
    batch axes, `common/mesh.BATCH_AXES`); `fsdp` names the fsdp part so
    the error can say which axis the caller actually configured."""
    if batch_size % dp != 0:
        if fsdp > 1:
            raise ValueError(
                f"global batch_size ({batch_size}) must be a multiple of "
                f"the batch-splitting extent {dp} = data ({dp // fsdp}) × "
                f"fsdp ({fsdp}) — the fsdp axis splits the batch too "
                f"(ZeRO-style sharding rides the data path). Use a "
                f"batch_size that is a multiple of {dp}, or shrink the "
                f"fsdp axis to a divisor of your batch.")
        raise ValueError(
            f"global batch_size ({batch_size}) must be a multiple of the "
            f"data-parallel size ({dp}) — the reference's total-core-number "
            f"contract (tf_dataset.py:142-147)")


def _put_batch(tree, mesh, stacked: bool = False):
    """mesh=None → single default device (non-distributed escape hatch).
    stacked=True for (steps, batch, ...) multi-step stacks.

    Multi-process (`jax.distributed`): each process passes its LOCAL batch
    shard (the per-executor-partition contract of the reference) and the
    global array is assembled across hosts — device_put cannot target
    non-addressable devices."""
    if mesh is None:
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.asarray(a)), tree)
    sharding = mesh.stacked_batch_sharding() if stacked \
        else mesh.batch_sharding()
    if jax.process_count() > 1:
        batch_dim = 1 if stacked else 0

        def put(a):
            a = np.asarray(a)
            gshape = list(a.shape)
            gshape[batch_dim] *= jax.process_count()
            return jax.make_array_from_process_local_data(
                sharding, a, tuple(gshape))
        return jax.tree_util.tree_map(put, tree)
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(jnp.asarray(a), sharding), tree)


def _materialize(x):
    """THE host-sync point of the training loop: every device→host readback
    in fit_keras funnels through here so tests can count syncs (one per
    logging interval, not one per step)."""
    return jax.device_get(x)


def _step_with_watchdog(step_fn, args, retries: int,
                        timeout_s: Optional[float], retry_counter,
                        iteration: int):
    """One training step under the fault-tolerance contract
    (`Topology.scala:1255-1337`'s retry role, made local): a failed step
    is retried up to `retries` times; with `timeout_s` the step runs
    under a watchdog thread so a hung dispatch surfaces as TimeoutError
    instead of a silent stall. The `trainer.step` fault-injection point
    fires before device dispatch, so an injected failure retries without
    touching the donated parameter buffers. A REAL mid-execution failure
    may consume them — then the retry fails too and the caller's
    emergency-checkpoint path takes over."""
    import threading
    from analytics_zoo_tpu.common import faults
    attempts = 0
    while True:
        try:
            if timeout_s is None:
                faults.fire("trainer.step", iteration=iteration,
                            attempt=attempts)
                return step_fn(*args)
            box: Dict[str, Any] = {}
            cancelled = threading.Event()
            done = threading.Event()

            def run():
                try:
                    faults.fire("trainer.step", iteration=iteration,
                                attempt=attempts)
                    if cancelled.is_set():
                        return          # timed out during the stall:
                    box["out"] = step_fn(*args)   # don't consume buffers
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    box["exc"] = e
                finally:
                    done.set()

            t = threading.Thread(target=run, daemon=True,
                                 name="train-step-watchdog")
            t.start()
            if not done.wait(timeout_s):
                cancelled.set()
                # grace window before declaring it hung: a step that is
                # merely SLOW (step 0 pays XLA compilation) completes
                # here and its result is perfectly valid — retrying
                # instead would race the still-running dispatch on the
                # donated parameter buffers and abort the run
                if done.wait(timeout_s) and "out" in box:
                    log.warning(
                        "training step %d exceeded the %ss watchdog but "
                        "completed in the grace window; using its result "
                        "(raise step_timeout_s if this recurs)",
                        iteration, timeout_s)
                    return box["out"]
                raise TimeoutError(
                    f"training step {iteration} exceeded the "
                    f"{timeout_s}s watchdog")
            if "exc" in box:
                raise box["exc"]
            if "out" not in box:
                raise RuntimeError(
                    f"training step {iteration} was cancelled by an "
                    "earlier watchdog timeout")
            return box["out"]
        except Exception as e:  # noqa: BLE001 — retry policy owns this
            attempts += 1
            if attempts > retries:
                raise
            retry_counter.inc()
            log.warning(
                "training step %d failed (%s: %s); retry %d/%d",
                iteration, type(e).__name__, e, attempts, retries)


class _StepCostTracker:
    """Per-fit accumulation of XLA cost-analysis FLOPs/bytes for the
    live train step (ISSUE 6 roofline). Two-phase per distinct argument
    signature:

    - `before(args)` (pre-dispatch): memo hit → accumulate; miss →
      record the signature as pending with a ShapeDtypeStruct skeleton
      (shape/dtype/sharding — the only parts lowering needs, and the
      only parts safe to keep once the call donates the buffers).
    - `after()` (post-dispatch): resolve pending signatures — prefer
      `cost_analysis()` straight off the executable the call just built
      (an `AOTFunctionCache` exposes it via `executables()`, so a warm
      AOT re-run never lowers at all); plain-jit steps fall back to one
      lowering of the SDS skeleton, which costs a trace but no compile.

    Any failure marks the signature un-costed and the roofline gauges
    simply stay absent — never an error in the hot loop. `memo` is the
    per-train-step sub-dict of the model's cost memo, selected by the
    SAME cache key the trainer's step cache uses (`id()`-keying the
    step object would resurrect a stale program's cost after CPython
    address reuse), so warm restarts and repeated bench fits never
    re-harvest.

    Units: XLA's cost analysis visits a While body ONCE (a k-step
    `lax.scan` run program and the whole-epoch device-cache program
    both report ≈ one step's flops/bytes — verified on this backend),
    and the single-step program trivially reports one step's. So the
    accumulated `flops`/`bytes` are PER-STEP costs × `calls`; the
    epoch accounting in `fit_keras` scales the per-call mean by the
    epoch's iteration count, which is exact for every program shape.

    Basis: harvested costs are the LOGICAL GLOBAL cost of one step
    (the ExecCost contract — model work counted once). A partitioned
    executable's `cost_analysis()` counts its per-device module, and
    per-device × span over-counts work that replicates across a mesh
    axis, so for multi-device programs the tracker ALWAYS harvests by
    lowering the SDS skeleton (one trace per signature, no compile);
    the zero-lowering executable fast path is kept for single-device
    programs, where the two bases agree. `self.devices` records the
    program span for the accountant's roofline denominator — classic
    MFU: model flops over the participating slice's peak."""

    def __init__(self, train_step, memo: Dict):
        self._step = train_step
        self._memo = memo
        self._pending: Dict[Tuple, Any] = {}   # sig -> (sds_args, calls)
        self.flops = 0.0
        self.bytes = 0.0
        self.calls = 0
        self.devices = 1
        self._span_known = False
        # per-step ExecCost DELTA for Pallas kernel regions (ISSUE 9):
        # cost analysis cannot see inside a pallas_call (Mosaic reports
        # ~0; the interpreter emulation over-counts), so the fit adds
        # (analytic − XLA-counted) for the fused sweep here
        self.correction = None

    def reset_epoch(self):
        self.flops = 0.0
        self.bytes = 0.0
        self.calls = 0

    @staticmethod
    def _sig(args) -> Tuple:
        from analytics_zoo_tpu.compile_cache.key import cheap_signature
        return cheap_signature(args)

    @staticmethod
    def _skeleton(args):
        """Avals of the live args, for a post-donation lowering
        fallback. Shardings are carried only for MULTI-device leaves
        (mesh-sharded params/batches — they change the program); a
        single-device leaf stays unconstrained, because pinning e.g.
        the rng key's device-0 placement next to 8-device params makes
        jit.lower reject the skeleton as incompatible devices, where
        the live (uncommitted) array resolved fine."""
        def sds(a):
            if not hasattr(a, "shape"):
                return a
            sharding = getattr(a, "sharding", None)
            try:
                multi = sharding is not None \
                    and len(sharding.device_set) > 1
            except Exception:  # noqa: BLE001 — exotic sharding object
                multi = False
            if multi:
                try:
                    return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                sharding=sharding)
                except TypeError:   # jax without the sharding kwarg
                    pass
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        return jax.tree_util.tree_map(sds, args)

    def _accumulate(self, cost, calls=1):
        if cost is not None:
            corr = self.correction
            cf = corr.flops if corr is not None else 0.0
            cb = corr.bytes if corr is not None else 0.0
            self.flops += max(cost.flops + cf, 0.0) * calls
            self.bytes += max(cost.bytes + cb, 0.0) * calls
            self.calls += calls

    def before(self, args):
        try:
            if not self._span_known:
                # one walk per fit: the step program's device span is
                # fixed by the (mesh, placement) the fit chose
                from analytics_zoo_tpu.observability.roofline import \
                    device_span
                self.devices = device_span(args)
                self._span_known = True
            key = self._sig(args)
            if key in self._memo:
                self._accumulate(self._memo[key])
                return
            entry = self._pending.get(key)
            if entry is not None:
                entry[1] += 1
            else:
                self._pending[key] = [self._skeleton(args), 1]
        except Exception:  # noqa: BLE001 — telemetry only
            pass

    def after(self):
        if not self._pending:
            return
        try:
            pending, self._pending = self._pending, {}
            for key, (sds_args, calls) in pending.items():
                if key not in self._memo:
                    self._memo[key] = self._harvest(key, sds_args)
                self._accumulate(self._memo[key], calls)
        except Exception as e:  # noqa: BLE001 — telemetry only
            log.debug("step cost harvest failed: %s: %s",
                      type(e).__name__, e)

    def _harvest(self, sig, sds_args):
        from analytics_zoo_tpu.observability.roofline import cost_of
        step = self._step
        try:
            execs_fn = getattr(step, "executables", None)
            if execs_fn is not None and self.devices == 1:
                # single-device: the executable answers directly (no
                # lowering at all on a warm AOT re-run)
                cost = cost_of(execs_fn().get(sig))
                if cost is not None:
                    return cost
            fn = getattr(step, "wrapped", step)
            # multi-device (and the plain-jit fallback): the lowered,
            # UNPARTITIONED module is the logical basis — a partitioned
            # executable's per-device count can't be scaled back
            # exactly (see ExecCost)
            return cost_of(fn.lower(*sds_args))
        except Exception as e:  # noqa: BLE001 — telemetry only
            log.debug("step cost harvest failed: %s: %s",
                      type(e).__name__, e)
            return None


class _Prefetcher:
    """Background-thread batch prefetch: prepares + device_puts the next
    item while the device runs the current one. Depth-bounded so host
    memory stays flat. The TPU analogue of the reference FeatureSet's
    prefetching cached tier.

    Stall accounting (ISSUE 15): every consumer `__next__` times how
    long it sat blocked on the queue — that wait IS the device's input
    stall (the step can't dispatch until the batch exists). `wait_s`
    accumulates the epoch total; `on_wait(seconds)` fires per get for
    the per-step histogram. An always-full queue reads ~0: the host
    pipeline is keeping up."""

    _END = object()

    def __init__(self, source_iter, transfer, depth: int = 2,
                 on_wait=None):
        import queue
        import threading
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._err = None
        self._stop = False
        self._queue_mod = queue
        self._on_wait = on_wait
        self.wait_s = 0.0

        def worker():
            try:
                for item in source_iter:
                    out = transfer(item)
                    while not self._stop:
                        try:
                            self._q.put(out, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._stop:
                        return
            except BaseException as e:   # propagate to consumer
                self._err = e
            finally:
                # blocking put with stop checks: a full queue must not
                # swallow the END sentinel (the consumer would hang)
                while not self._stop:
                    try:
                        self._q.put(self._END, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._t = threading.Thread(target=worker, daemon=True)
        self._t.start()

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter()
        item = self._q.get()
        waited = time.perf_counter() - t0
        self.wait_s += waited
        if self._on_wait is not None:
            try:
                self._on_wait(waited)
            except Exception:  # noqa: BLE001 — telemetry only
                pass
        if item is self._END:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item

    def close(self):
        """Unblock and retire the worker (early exit via end_trigger)."""
        self._stop = True
        try:
            while True:
                self._q.get_nowait()
        except self._queue_mod.Empty:
            pass


def _chunk_batches(it, k: int):
    """Group (xb, yb, real) triples into lists of up to k for multi-step
    runs. The final short group is emitted as-is (compiled separately at
    most once per distinct length)."""
    group = []
    for item in it:
        group.append(item)
        if len(group) == k:
            yield group
            group = []
    if group:
        yield group


def _stack_group(group, mesh):
    """Stack k (xb, yb, real) batches into device-resident (k, B, ...)
    arrays sharded so the batch dim stays split over the mesh."""
    xs = jax.tree_util.tree_map(lambda *a: np.stack(a),
                                *[g[0] for g in group])
    ys = None
    if group[0][1] is not None:
        ys = jax.tree_util.tree_map(lambda *a: np.stack(a),
                                    *[g[1] for g in group])
    real = sum(g[2] for g in group)
    return (_put_batch(xs, mesh, stacked=True),
            _put_batch(ys, mesh, stacked=True) if ys is not None else None,
            real, len(group))


def _resolve_sharding_rules(sharding_rules, ctx):
    """Normalize the fit's `sharding_rules` knob: None consults the
    config passthrough (`ZooConfig.sharded_fit` / env ZOO_SHARDED_FIT),
    True means the default transformer table, a `ShardingRules` passes
    through. Returns a ShardingRules or None (replicated fit)."""
    if sharding_rules is None and ctx is not None \
            and getattr(ctx.config, "sharded_fit", False):
        sharding_rules = True
    if sharding_rules is True:
        from analytics_zoo_tpu.parallel.sharding import TRANSFORMER_RULES
        return TRANSFORMER_RULES
    if sharding_rules is False:
        return None
    return sharding_rules


def _step_shardings(mesh, param_shardings, opt_shardings):
    """The layout dict `_jit_donated` pins into the step/run programs."""
    return {"params": param_shardings, "opt": opt_shardings,
            "batch": mesh.batch_sharding(),
            "stacked": mesh.stacked_batch_sharding(),
            "rep": mesh.replicated()}


def _put_with_shardings(tree, shardings):
    """device_put every leaf onto its rule-derived NamedSharding. A
    leaf already carrying the target sharding passes through as the
    same buffer, so re-placing live sharded state is free; a host leaf
    (checkpoint restore) lands DIRECTLY on the sharded layout — the
    host array goes to device_put as-is (an eager jnp.asarray would
    first materialize the FULL leaf on the default device, OOMing
    exactly the bigger-than-one-chip model this path exists for)."""
    return jax.tree_util.tree_map(
        lambda a, s: jax.device_put(a, s), tree, shardings)


def _put_replicated(tree, mesh):
    if mesh is None:
        return jax.tree_util.tree_map(lambda a: jax.device_put(a), tree)
    sharding = mesh.replicated()
    if jax.process_count() > 1:
        # every process holds the full value (same seed) → its local
        # shard of a replicated array IS the full array
        return jax.tree_util.tree_map(
            lambda a: jax.make_array_from_process_local_data(
                sharding, np.asarray(a), np.shape(a)), tree)
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding), tree)


# ---------------------------------------------------------------------------
# Core train/eval step builders
# ---------------------------------------------------------------------------
def _merge_state(params, state_updates):
    """Merge stateful-layer updates (nested dict subset) into params."""
    if not state_updates:
        return params
    merged = dict(params)
    for k, v in state_updates.items():
        if isinstance(v, dict) and isinstance(merged.get(k), dict):
            merged[k] = _merge_state(merged[k], v)
        else:
            merged[k] = v
    return merged


def _cast_tree(tree, dtype, only=jnp.float32):
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype) if a.dtype == only else a, tree)


def _shard_mapped_fused(fused_apply, shardings):
    """Run the fused optimizer sweep on fsdp-LOCAL shards: the whole
    `fused_apply` call goes through one `shard_map` whose specs are the
    rule table's own PartitionSpecs, so each device's kernels walk only
    its 1/fsdp slice of (params, moments, grads) and GSPMD never
    gathers state around the Pallas custom calls. The update is
    elementwise per leaf, so any partitioning is numerically exact;
    grads arrive already reduced across the batch axes (GSPMD inserts
    the all-reduce upstream to satisfy the entry specs)."""
    from analytics_zoo_tpu.parallel.compat import shard_map
    p_specs = jax.tree_util.tree_map(lambda s: s.spec, shardings["params"])
    o_specs = jax.tree_util.tree_map(lambda s: s.spec, shardings["opt"])
    mesh = jax.tree_util.tree_leaves(shardings["params"])[0].mesh
    return shard_map(fused_apply, mesh=mesh,
                     in_specs=(p_specs, o_specs, p_specs),
                     out_specs=(p_specs, o_specs), check=False)


def _fused_kernel_correction(optimizer, lazy_specs, params, opt_state,
                             shardings, batch: int):
    """Per-step ExecCost DELTA (analytic − XLA-counted) of the fused
    Pallas regions, for `_StepCostTracker.correction` (ISSUE 9).

    HLO cost analysis cannot see inside a `pallas_call`: a Mosaic
    custom call reports ~0 bytes, and the CPU interpreter's emulated
    block walk over-counts them ~10×. Each kernel carries the analytic
    `cost_estimate` (`fused_adam.update_cost` / `segment_adam_cost`),
    but the tracker harvests the WHOLE step module — so the honest
    count is: harvested − (what XLA counted for the kernel region
    alone) + (the analytic model). This lowers each kernel region once
    per fit (a trace, no compile) to get the subtraction term; any
    failure returns None and the gauges keep the uncorrected count."""
    from analytics_zoo_tpu.observability.roofline import ExecCost, cost_of

    def lowered(fn, *args):
        sds = _StepCostTracker._skeleton(args)
        return cost_of(jax.jit(fn).lower(*sds))

    flops = bytes_ = 0.0
    try:
        if lazy_specs:
            from analytics_zoo_tpu.learn.lazy_embedding import _get, _key
            from analytics_zoo_tpu.pallas.segment_update import (
                kernel_apply, segment_adam_cost)
            for s in lazy_specs:
                table = _get(params, s.path)
                mu, nu = opt_state["tables"][_key(s)]
                dim = table.shape[1]
                a_f, a_b = segment_adam_cost(batch, dim, table.dtype)
                raw = lowered(
                    functools.partial(kernel_apply, b1=s.b1, b2=s.b2),
                    table, mu, nu, jnp.zeros((batch,), jnp.int32),
                    jnp.zeros((batch,), jnp.int32),
                    jnp.zeros((batch, dim), jnp.float32),
                    jnp.zeros((3,), jnp.float32))
                if raw is None:
                    return None
                flops += a_f - raw.flops
                bytes_ += a_b - raw.bytes
        fused_apply = getattr(optimizer, "fused_apply", None)
        if fused_apply is not None:
            from analytics_zoo_tpu.learn.lazy_embedding import split_rest
            from analytics_zoo_tpu.pallas.fused_adam import update_cost
            if lazy_specs:
                sweep_params = split_rest(params, lazy_specs)
                sweep_state = opt_state["rest"]
            else:
                sweep_params = params
                sweep_state = opt_state
            if shardings is not None:
                fused_apply = _shard_mapped_fused(fused_apply, shardings)
            a_f, a_b = update_cost(sweep_params)
            raw = lowered(fused_apply, sweep_params, sweep_state,
                          sweep_params)
            if raw is None:
                return None
            flops += a_f - raw.flops
            bytes_ += a_b - raw.bytes
        return ExecCost(flops, bytes_)
    except Exception as e:  # noqa: BLE001 — telemetry only
        log.debug("fused roofline correction unavailable: %s: %s",
                  type(e).__name__, e)
        return None


def _make_one_step(apply_fn, loss_fn, optimizer, apply_and_state_fn,
                   mixed_precision, shardings=None):
    # fused-kernel optimizer (ISSUE 9): the transformation carries a
    # `fused_apply(grads, state, params) -> (params, state)` fast path
    # — the Pallas kernel writes new params/moments in place, so the
    # optax updates tree (and its extra HBM passes) never exists
    fused_apply = getattr(optimizer, "fused_apply", None)
    if fused_apply is not None and shardings is not None:
        fused_apply = _shard_mapped_fused(fused_apply, shardings)

    def one_step(params, opt_state, xb, yb, rng):
        def compute_loss(p):
            if mixed_precision:
                p = _cast_tree(p, jnp.bfloat16)
                # inputs are NOT cast here: float-encoded integer id
                # features (nnframes emits float32 ids) lose exactness
                # above 256 in bf16 → silently wrong embedding rows.
                # Matmul/conv layers cast their own float operands to the
                # param dtype instead (keras/layers.py _match_param_dtype).
            if apply_and_state_fn is not None:
                pred, state_upd = apply_and_state_fn(p, xb, training=True,
                                                     rng=rng)
            else:
                pred, state_upd = apply_fn(p, xb, training=True,
                                           rng=rng), {}
            if mixed_precision:
                pred = jax.tree_util.tree_map(
                    lambda a: a.astype(jnp.float32), pred)
            return loss_fn(yb, pred), state_upd

        (loss, state_upd), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        if mixed_precision:
            grads = _cast_tree(grads, jnp.float32, only=jnp.bfloat16)
            # stateful updates (BatchNorm moving stats) were computed from
            # the bf16-cast params — cast back so the f32 master tree never
            # picks up bf16 leaves (dtype drift + donation mismatch)
            state_upd = _cast_tree(state_upd, jnp.float32,
                                   only=jnp.bfloat16)
        if fused_apply is not None:
            params, opt_state = fused_apply(grads, opt_state, params)
        else:
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        params = _merge_state(params, state_upd)
        return params, opt_state, loss

    return one_step


def _jit_donated(fn, shardings, batch_key: str, n_extra_out: int):
    """jit with donated (params, opt_state) buffers. `shardings` (a
    sharded fit's rule-derived layout dict, see `_step_shardings`) pins
    explicit in/out shardings: params and opt_state arrive AND leave on
    the rule table's NamedShardings (so GSPMD cannot re-layout them and
    donation stays an in-place buffer reuse — in == out is the donation
    contract), the batch on the mesh's batch axes, rng and losses
    replicated. Without it, behavior is byte-for-byte the old jit."""
    if shardings is None:
        return jax.jit(fn, donate_argnums=(0, 1))
    bsh = shardings[batch_key]
    rep = shardings["rep"]
    in_sh = (shardings["params"], shardings["opt"], bsh, bsh, rep)
    out_sh = (shardings["params"], shardings["opt"]) + (rep,) * n_extra_out
    return jax.jit(fn, donate_argnums=(0, 1),
                   in_shardings=in_sh, out_shardings=out_sh)


def build_train_step(apply_fn: Callable, loss_fn: Callable,
                     optimizer: optax.GradientTransformation,
                     apply_and_state_fn: Optional[Callable] = None,
                     mixed_precision: bool = False,
                     lazy_specs=None, fused: bool = False,
                     shardings=None) -> Callable:
    """One iteration as a pure function. jit + sharded inputs → GSPMD emits
    the gradient all-reduce; donation reuses parameter buffers in HBM.
    Stateful layers (BatchNorm moving stats) return updates through the aux
    channel and are merged outside the gradient path.
    mixed_precision=True keeps f32 master params and runs the fwd/bwd
    matmuls in bf16 (MXU-native). `shardings` (from `_step_shardings`)
    pins the fsdp-sharded layout explicitly — the GSPMD fit. `fused`
    selects the Pallas fused-update paths (ISSUE 9): the segment
    one-step for declared embedding tables, `fused_apply` for the rest."""
    one_step = _pick_one_step(apply_fn, loss_fn, optimizer,
                              apply_and_state_fn, mixed_precision,
                              lazy_specs, fused, shardings)
    return _jit_donated(one_step, shardings, "batch", 1)


def build_train_run(apply_fn: Callable, loss_fn: Callable,
                    optimizer: optax.GradientTransformation,
                    apply_and_state_fn: Optional[Callable] = None,
                    mixed_precision: bool = False,
                    lazy_specs=None, fused: bool = False,
                    shardings=None) -> Callable:
    """Multi-step variant: one jit'd program `lax.scan`s over a
    (k, batch, ...) stack of batches, so k steps cost ONE dispatch and ONE
    loss readback. This is the framework's hot path — the analogue of the
    reference engine owning its hot loop (`Topology.scala:1160-1337`)."""
    one_step = _pick_one_step(apply_fn, loss_fn, optimizer,
                              apply_and_state_fn, mixed_precision,
                              lazy_specs, fused, shardings)

    def train_run(params, opt_state, xs, ys, rng):
        def body(carry, batch):
            params, opt_state, rng = carry
            rng, sub = jax.random.split(rng)
            xb, yb = batch
            params, opt_state, loss = one_step(params, opt_state, xb, yb,
                                               sub)
            return (params, opt_state, rng), loss

        (params, opt_state, rng), losses = jax.lax.scan(
            body, (params, opt_state, rng), (xs, ys))
        return params, opt_state, rng, losses

    return _jit_donated(train_run, shardings, "stacked", 2)


def build_device_epoch_run(apply_fn: Callable, loss_fn: Callable,
                           optimizer: optax.GradientTransformation,
                           apply_and_state_fn: Optional[Callable] = None,
                           mixed_precision: bool = False,
                           lazy_specs=None, fused: bool = False,
                           steps: int = 1,
                           batch: int = 1, shuffle: bool = True,
                           shardings=None) -> Callable:
    """Whole-epoch program over a DEVICE-RESIDENT dataset: shuffle
    (on-device permutation), batch (on-device gather) and all `steps`
    train steps run inside ONE `lax.scan` dispatch. Eliminates every
    per-step host→device transfer — on a tunnel-attached dev chip the
    batch stream otherwise dominates small-model steps (NCF: 4.4 of
    7.7 ms/step was host transfer; docs/ROOFLINE.md round-5 NCF
    breakdown)."""
    one_step = _pick_one_step(apply_fn, loss_fn, optimizer,
                              apply_and_state_fn, mixed_precision,
                              lazy_specs, fused, shardings)

    def epoch_run(params, opt_state, x, y, rng):
        n = _tree_len(x)
        shuffle_rng, step_rng0 = jax.random.split(rng)
        idx = (jax.random.permutation(shuffle_rng, n) if shuffle
               else jnp.arange(n))[:steps * batch].reshape(steps, batch)

        def body(carry, ids):
            params, opt_state, rng = carry
            rng, sub = jax.random.split(rng)
            xb = jax.tree_util.tree_map(lambda a: a[ids], x)
            yb = (jax.tree_util.tree_map(lambda a: a[ids], y)
                  if y is not None else None)
            params, opt_state, loss = one_step(params, opt_state, xb, yb,
                                               sub)
            return (params, opt_state, rng), loss

        (params, opt_state, _), losses = jax.lax.scan(
            body, (params, opt_state, step_rng0), idx)
        return params, opt_state, losses

    return _jit_donated(epoch_run, shardings, "batch", 1)


def _epoch_safe_trigger(trigger) -> bool:
    """Triggers that only need epoch-boundary state keep their exact
    semantics under the one-dispatch-per-epoch path."""
    return trigger is None or isinstance(trigger, (tg.EveryEpoch,
                                                   tg.MaxEpoch))


def _device_cache_eligible(x, y, mesh, n_proc: int, device_cache,
                           checkpoint_trigger=None,
                           end_trigger=None) -> bool:
    """Auto device-residency: single process, single device, in-memory
    arrays small enough to pin in HBM alongside the model, and no
    trigger that needs mid-epoch granularity (iteration counters and
    loss thresholds would silently stop checking mid-epoch — only the
    explicit opt-in accepts that trade)."""
    if device_cache is False or n_proc > 1:
        return False
    if device_cache is True:
        # explicit opt-in works on any local mesh (GSPMD resolves the
        # sharded in-jit gathers); AUTO stays single-device where it is
        # an unconditional win
        return True
    if mesh is not None and mesh.n_devices > 1:
        return False
    if not (_epoch_safe_trigger(checkpoint_trigger)
            and _epoch_safe_trigger(end_trigger)):
        return False
    limit_mb = float(os.environ.get("ZOO_DEVICE_CACHE_MB", "256"))
    nbytes = sum(np.asarray(a).nbytes
                 for a in jax.tree_util.tree_leaves((x, y)))
    return nbytes <= limit_mb * 1e6


def _data_fingerprint(tree) -> tuple:
    """Cheap content key for the device-data cache: identity alone would
    train on stale device copies after in-place mutation (per-round
    negative resampling mutates y in place). Hashes head/middle/tail
    slices of every leaf — O(KB) per leaf, catches realistic refreshes
    (a mutation confined entirely between the sampled slices can still
    alias; pass a fresh array to force a re-put)."""
    import zlib
    parts = []
    for leaf in jax.tree_util.tree_leaves(tree):
        a = np.asarray(leaf)
        if not a.flags["C_CONTIGUOUS"]:
            a = np.ascontiguousarray(a)
        raw = a.reshape(-1).view(np.uint8)
        k = min(len(raw), 4096)
        mid = len(raw) // 2
        parts.append((id(leaf), a.shape, str(a.dtype),
                      zlib.crc32(raw[:k].tobytes()),
                      zlib.crc32(raw[mid:mid + k].tobytes()),
                      zlib.crc32(raw[-k:].tobytes())))
    return tuple(parts)


def _device_cached_data(model, x, y, mesh):
    """device_put once per distinct (x, y) CONTENT; cached on the model
    so repeated fit calls (warm restarts, bench epochs) skip the
    transfer. Strong refs to the host arrays keep the key's ids valid."""
    key = _data_fingerprint((x, y))
    cached = getattr(model, "_device_data", None)
    if cached is not None and cached[0] == key:
        return cached[1], cached[2]
    x_dev = _put_batch(x, mesh)
    y_dev = _put_batch(y, mesh) if y is not None else None
    model._device_data = (key, x_dev, y_dev, (x, y))
    return x_dev, y_dev


def _pick_one_step(apply_fn, loss_fn, optimizer, apply_and_state_fn,
                   mixed_precision, lazy_specs, fused=False,
                   shardings=None):
    if lazy_specs:
        if fused:
            from analytics_zoo_tpu.pallas.segment_update import \
                make_fused_one_step
            return make_fused_one_step(apply_fn, loss_fn, optimizer,
                                       lazy_specs, apply_and_state_fn,
                                       mixed_precision)
        from analytics_zoo_tpu.learn.lazy_embedding import make_lazy_one_step
        return make_lazy_one_step(apply_fn, loss_fn, optimizer, lazy_specs,
                                  apply_and_state_fn, mixed_precision)
    return _make_one_step(apply_fn, loss_fn, optimizer, apply_and_state_fn,
                          mixed_precision, shardings=shardings)


def build_eval_step(apply_fn: Callable, metrics: Sequence) -> Callable:
    def eval_step(params, states, xb, yb):
        pred = apply_fn(params, xb, training=False)
        return [m.update(s, yb, pred) for m, s in zip(metrics, states)]

    return jax.jit(eval_step)


# ---------------------------------------------------------------------------
# Keras front-door: fit / evaluate / predict
# ---------------------------------------------------------------------------
def fit_keras(model, x, y=None, batch_size: int = 32, epochs: int = 1,
              validation_data=None, distributed: bool = True,
              shuffle: bool = True, checkpoint_trigger=None,
              end_trigger=None, seed: int = 0,
              batch_iter_factory: Optional[Callable] = None,
              steps_per_run: int = 1, mixed_precision: bool = False,
              prefetch: bool = True,
              prefetch_depth: Optional[int] = None,
              lazy_embeddings: bool = False,
              device_cache: Optional[bool] = None,
              flat_optimizer: bool = False,
              fused_optimizer: Optional[bool] = None,
              sharding_rules=None,
              flops_per_step: Optional[float] = None,
              metrics_report_s: Optional[float] = None,
              compile_cache_dir: Optional[str] = None,
              auto_resume: bool = False,
              int8_sidecar: bool = False,
              step_retries: int = 0,
              step_timeout_s: Optional[float] = None,
              profile_steps: Optional[Tuple[int, int]] = None,
              profile_dir: Optional[str] = None
              ) -> Dict[str, List[float]]:
    """`KerasNet.fit` backend. Returns a Keras-style history dict.
    `batch_iter_factory(epoch) -> iterator of (xb, yb, real)` overrides the
    default in-memory batching (lazy/disk-tier datasets).

    The loop is fully asynchronous: batches are device_put by a prefetch
    thread while the device computes, the per-step loss stays on device,
    and the ONLY host sync is one `_materialize` per epoch (plus any
    loss-reading trigger the caller installs). `prefetch_depth` (config
    `ZooConfig.prefetch_depth` / env ZOO_PREFETCH_DEPTH, default 2)
    bounds the transferred-batch backlog; the time the step loop spends
    BLOCKED on that queue is measured per step into
    `training_input_wait_ms` and per epoch into the
    `training_input_bound` gauge (+ the roofline snapshot's input-stall
    column) — the device-wait vs host-wait accounting that says whether
    a file-backed fit needs more `pipeline_workers`
    (docs/ProgrammingGuide/distributed-training.md "Input pipeline"). `steps_per_run=k` fuses k
    steps into one `lax.scan` program — one dispatch per k steps —
    trading trigger granularity (checked every k iterations) for dispatch
    overhead. `mixed_precision` runs fwd/bwd in bf16 with f32 masters.
    `flops_per_step` (fwd+bwd FLOPs of one step, e.g. from
    `utils.profiling.transformer_train_flops`) enables the
    `training_mfu` gauge; `metrics_report_s` runs a `MetricsReporter`
    for the duration of the fit, logging a one-line registry digest at
    that interval. Step/throughput/loss telemetry always publishes to
    the process-wide `MetricsRegistry` (and mirrors to TensorBoard when
    `set_tensorboard` is on).
    `fused_optimizer=True` (config `ZooConfig.fused_optimizer` / env
    `ZOO_FUSED_OPT=1`; None consults those) swaps a default-
    hyperparameter `adam`/`adamw` compile spec for the fused Pallas
    kernels (`pallas/fused_adam.py`): the whole Adam sweep becomes one
    blocked read-(g,m,v,p)/write-(m,v,p) HBM pass per leaf, in place.
    With `lazy_embeddings=True` the declared tables additionally take
    the sparse segment path (`pallas/segment_update.py`): batch row
    grads are segment-summed and ONLY the touched rows are read or
    written — no dense table gradient is ever materialized. An
    optimizer with no fused twin, or a backend where the kernels fail
    to lower, degrades to the plain optax path with one WARNING.
    (`flat_optimizer`, the earlier structural-repacking experiment, is
    retired — passing True raises with a pointer here; see
    docs/ROOFLINE.md round 5 for why repacking could not beat the
    per-pass cost the kernels remove.)
    `sharding_rules` turns the fit into a GSPMD-sharded pjit program
    (the training twin of serving's sharded placement): params and
    optimizer state shard over the mesh's `fsdp` axis per the SAME
    regex→PartitionSpec table serving consumes (`parallel/sharding.
    ShardingRules`; pass True for the default transformer table, or a
    ShardingRules instance; `ZooConfig.sharded_fit` / env
    ZOO_SHARDED_FIT=1 is the config spelling), the batch stays split
    over the (data × fsdp) batch axes, and explicit in/out shardings
    pin the rule layout through the donated step/run programs — XLA
    inserts the just-in-time all-gathers and gradient reduce-scatters
    (GSPMD + ZeRO-3). Per-device params+opt_state drop to ≈ 1/fsdp of
    the replicated footprint, which is what lets a model larger than
    one chip's HBM train at all. Checkpoints save in the ordinary
    gathered host layout and restore DIRECTLY onto the rule-derived
    shardings, so a sharded fit's checkpoint loads into serving's
    sharded placement with zero resharding. Incompatible with
    `lazy_embeddings` (the per-table state re-packs the param tree
    the rule table describes) and multi-process fits (for now);
    `fused_optimizer` composes — the kernels run on the fsdp-local
    shards via `shard_map`, so the 1/fsdp state footprint is kept.
    `compile_cache_dir` (or env `ZOO_COMPILE_CACHE_DIR`) enables the
    persistent compilation cache: the jitted step/run executables are
    AOT-serialized per input signature (`compile_cache/`), so a trainer
    re-run in a fresh process loads its programs from disk instead of
    re-lowering and re-compiling; JAX's built-in persistent cache
    (`jax_compilation_cache_dir`, under `<dir>/xla`) is enabled as the
    fallback layer for any shape AOT serialization can't carry.
    `profile_steps=(start, stop)` wraps iterations [start, stop) in a
    bounded `jax.profiler` capture (`observability/capture.py`): the
    trace artifact lands in a rotated dir under `profile_dir` (or
    `$ZOO_PROFILE_DIR`, default ./zoo_profiles) and its path is
    appended to `history["profile_artifacts"]`. Cost-analysis roofline
    gauges (`roofline_mfu{kind="train"}`,
    `roofline_hbm_utilization{kind="train"}` — no flops_per_step
    needed) publish automatically each epoch; set `ZOO_ROOFLINE=0` to
    skip the one-time per-signature lowering they cost.
    `int8_sidecar=True` runs the post-training quantization pass at
    every checkpoint save (ISSUE 12): per-output-channel scales are
    calibrated from the just-saved weights and persisted as an int8
    sidecar beside `model.<iteration>`
    (`serving/quantization.write_int8_sidecar`), so
    `InferenceModel.load_checkpoint(..., quantize="int8")` serves the
    pre-calibrated artifact with no quantize-at-load pass. A sidecar
    write failure logs one warning and never fails the fit.
    `auto_resume=True` (needs `model.set_checkpoint(...)`) scans the
    checkpoint root for the newest INTACT epoch-boundary checkpoint
    before training and continues from it: params, optimizer state,
    iteration counter and the RNG key are restored, so the continued
    run's losses are bitwise-identical to an uninterrupted run (the
    shuffle order is already `seed + epoch`-derived). A corrupt latest
    checkpoint falls back to the newest intact one
    (`learn/checkpoint.py` CRC discipline). `step_retries=N` retries a
    failed step N times before writing an emergency checkpoint and
    raising; `step_timeout_s` additionally runs each step under a
    watchdog thread so a hung dispatch surfaces as TimeoutError.
    After fit, `model.params` holds DEVICE arrays (no gratuitous
    device→host pull; save/checkpoint paths transfer on demand)."""
    if flat_optimizer:
        raise ValueError(
            "flat_optimizer was retired by ISSUE 9: the bucket-packed "
            "sweep is superseded by the fused Pallas optimizer kernels "
            "— use fused_optimizer=True (config fused_optimizer / "
            "ZOO_FUSED_OPT=1) instead")
    ctx = get_context()
    mesh = ctx.mesh if distributed else None
    dp = mesh.data_parallel_size if mesh else 1
    shard_rules = _resolve_sharding_rules(sharding_rules, ctx)
    if shard_rules is not None:
        if mesh is None:
            if sharding_rules is None:
                # config-driven default (ZooConfig.sharded_fit) quietly
                # steps aside for an explicitly non-distributed fit;
                # only the explicit kwarg is a hard contradiction
                shard_rules = None
            else:
                raise ValueError(
                    "sharding_rules needs distributed=True (the rule "
                    "table shards over the context mesh); drop "
                    "distributed=False or the rules")
    if shard_rules is not None:
        if lazy_embeddings:
            raise NotImplementedError(
                "sharding_rules is incompatible with lazy_embeddings: "
                "the per-table state re-packs the parameter tree the "
                "rule table is written against")
        if mesh.size("fsdp") == 1 and mesh.size("tensor") == 1:
            # every rule trims to replication on such a mesh: the fit
            # runs, but fully replicated — say so instead of letting a
            # sharded_fit=True config silently deliver none of the
            # 1/fsdp memory it was turned on for
            log.warning(
                "sharding_rules requested but the mesh has fsdp=1 and "
                "tensor=1 (%s): params/opt_state will be fully "
                "replicated. Set the fsdp axis (e.g. "
                "init_orca_context(data=1, fsdp=-1) or ZOO_MESH_FSDP) "
                "to actually shard state.", mesh)
    check_global_batch(batch_size, dp,
                       fsdp=mesh.size("fsdp") if mesh else 1)
    if steps_per_run < 1:
        raise ValueError(f"steps_per_run must be >=1, got {steps_per_run}")
    # prefetch-queue depth: explicit kwarg > config (ZOO_PREFETCH_DEPTH)
    # > 2. Bounds the host batch backlog — the input side never holds
    # more than `depth` transferred batches + one decoded shard per
    # pipeline worker.
    depth = int(prefetch_depth) if prefetch_depth else \
        int(getattr(getattr(ctx, "config", None), "prefetch_depth", 0)
            or 2)

    # Multi-process: `batch_size` stays GLOBAL (the reference's total-core
    # contract); each process feeds its LOCAL data shard, sliced at
    # global/process_count per step and assembled across hosts by
    # _put_batch.
    n_proc = jax.process_count()
    local_batch = batch_size
    if n_proc > 1:
        if batch_size % n_proc:
            raise ValueError(
                f"global batch_size ({batch_size}) must divide by the "
                f"process count ({n_proc})")
        if mesh is None or dp != jax.device_count():
            # _put_batch's cross-host assembly assumes the batch (data ×
            # fsdp) axes span every device; model axes crossing process
            # boundaries would mis-assemble the global shape
            raise NotImplementedError(
                "Multi-process fit currently supports pure data-parallel "
                "meshes (data×fsdp covering all devices); got "
                f"dp={dp} of {jax.device_count()} devices")
        if shard_rules is not None:
            # rule-sharded state would live partly on non-addressable
            # devices; checkpoint gather + resume re-shard are
            # single-process for now
            raise NotImplementedError(
                "sharding_rules is single-process for now: sharded "
                "params span non-addressable devices under "
                "multi-process, which the checkpoint gather/restore "
                "paths do not handle yet")
        if batch_iter_factory is not None and not getattr(
                batch_iter_factory, "shards_per_host", False):
            # a streaming factory that does NOT declare per-host shard
            # assignment would feed every process the same records —
            # silent sample duplication. TFRecord datasets declare it
            # (`_TFRecordDataset.shards_per_host`: disjoint files per
            # host over the mesh's data axis, `pipeline.host_shard`).
            raise NotImplementedError(
                "Multi-process fit over streaming datasets needs "
                "per-host shard assignment: every process would feed "
                "the same records. Use TPUDataset.from_tfrecord (which "
                "shards files per host) or materialize a per-host "
                "shard and pass arrays instead")
        local_batch = batch_size // n_proc

    if batch_iter_factory is None:
        n = _tree_len(x)
        if n_proc > 1:
            # unequal shards would desync the per-step collectives and
            # deadlock mid-epoch; gather counts BEFORE any local raise
            # (a rank bailing early would strand the others inside this
            # very collective)
            from jax.experimental import multihost_utils
            counts = np.asarray(multihost_utils.process_allgather(
                np.asarray(n, np.int64)))
            if not (counts == counts[0]).all():
                raise ValueError(
                    "Every process must hold the same number of local "
                    f"samples; got {counts.tolist()} across ranks")
        if n < local_batch:
            raise ValueError(
                f"Dataset has {n} samples but the per-process batch is "
                f"{local_batch}; training batches are whole-batch only "
                "(static shapes). Lower batch_size or add data.")

        def batch_iter_factory(epoch):  # noqa: F811 — default factory
            return iter_batches(x, y, local_batch, shuffle=shuffle,
                                seed=seed + epoch)

        use_device_cache = _device_cache_eligible(
            x, y, mesh, n_proc, device_cache,
            checkpoint_trigger=checkpoint_trigger, end_trigger=end_trigger)
        if device_cache is True and n_proc > 1:
            raise NotImplementedError(
                "device_cache=True is single-process only (each process "
                "would pin the full global dataset); drop the flag for "
                "multi-process fits")
    else:
        use_device_cache = False
        if device_cache is True:
            raise NotImplementedError(
                "device_cache=True needs in-memory arrays; streaming "
                "datasets (TFRecord/FeatureSet/batch_iter_factory) have "
                "no host copy to pin in HBM")

    rng = jax.random.PRNGKey(seed)
    rng, init_rng = jax.random.split(rng)
    if model.params is None:
        # shape probe — skipped when already built (streaming datasets
        # prebuild from a cheap first_sample instead of paying a full
        # shuffle-buffer fill here)
        try:
            sample = next(iter(batch_iter_factory(0)))[0]
        except StopIteration:
            raise ValueError(
                "Dataset produced no full batches; lower batch_size")
        model.ensure_built(sample, init_rng)

    optimizer = model.optimizer
    if optimizer is None:
        raise RuntimeError("Model must be compiled before fit "
                           "(`Topology.scala:139` contract)")

    # -- auto-resume (ISSUE 5): continue from the newest intact
    # epoch-boundary checkpoint instead of step 0 -------------------------
    start_epoch = 0
    iteration = 0
    resume_opt_tree = None
    resume_meta = None
    if auto_resume:
        if not model._checkpoint_path:
            raise ValueError(
                "auto_resume=True needs a checkpoint directory; call "
                "model.set_checkpoint(path) first")
        from analytics_zoo_tpu.learn.checkpoint import (
            find_resume_checkpoint, load_checkpoint)
        found = find_resume_checkpoint(model._checkpoint_path)
        if found is not None:
            run_dir, version, _ = found
            # verify=False: find_resume_checkpoint CRC-verified exactly
            # this version moments ago — no second full-file pass
            r_params, resume_opt_tree, resume_meta = load_checkpoint(
                run_dir, version, verify=False)
            # a fresh process's auto-generated layer names differ from
            # the checkpointing process's — remap onto this instance
            remap = getattr(model, "_remap_loaded", None)
            model.params = remap(r_params) if remap is not None \
                else r_params
            start_epoch = int(resume_meta.get("epoch", 0))
            iteration = int(resume_meta.get("iteration", version))
            if "rng" in resume_meta:
                # the checkpointed key IS the key the uninterrupted run
                # held at this boundary — restoring it (plus the
                # seed+epoch shuffle order) is what makes continuation
                # bitwise-identical
                rng = jnp.asarray(
                    np.asarray(resume_meta["rng"], dtype=np.uint32))
            else:
                log.warning(
                    "auto-resume: checkpoint has no RNG state (pre-"
                    "ISSUE-5 layout); continuing with a fresh key — "
                    "losses will diverge from the uninterrupted run")
            log.info(
                "auto-resume: continuing from %s/model.%d "
                "(epoch %d, iteration %d)",
                run_dir, version, start_epoch, iteration)

    param_shardings = step_shardings = None
    if shard_rules is not None:
        from analytics_zoo_tpu.parallel.sharding import (
            check_fsdp_divisibility, tree_shardings)
        # fail at config time, not at OOM time: a large param that
        # can't shard over fsdp would silently replicate everywhere
        check_fsdp_divisibility(model.params, mesh, shard_rules)
        param_shardings = tree_shardings(model.params, mesh, shard_rules)
        # host params (fresh build or checkpoint restore) land DIRECTLY
        # on the rule layout — the resume path never materializes a
        # replicated copy
        params = _put_with_shardings(model.params, param_shardings)
    else:
        params = _put_replicated(model.params, mesh)
    lazy_specs = None
    if lazy_embeddings:
        from analytics_zoo_tpu.learn.lazy_embedding import resolve_specs
        lazy_specs = resolve_specs(model)
    # -- fused-kernel optimizer (ISSUE 9): one HBM pass per leaf ----------
    fused = fused_optimizer
    if fused is None:
        fused = bool(getattr(getattr(ctx, "config", None),
                             "fused_optimizer", False)) \
            or os.environ.get("ZOO_FUSED_OPT", "0") == "1"
    fused = bool(fused)
    if fused:
        from analytics_zoo_tpu.pallas.fused_adam import fused_available
        if not fused_available():
            # the probe logged the one WARNING; plain optax from here
            fused = False
        else:
            from analytics_zoo_tpu.ops.optimizers import as_fused
            # the twin memoizes on the model: a fresh transformation per
            # fit would change id(optimizer) in the step cache key and
            # re-jit every warm restart
            spec = getattr(model, "_optimizer_spec", None)
            tkey = (id(optimizer), str(spec))
            twin = getattr(model, "_fused_twin_cache", None)
            if twin is not None and twin[0] == tkey:
                fused_opt, warn = twin[1], False
            else:
                fused_opt, warn = as_fused(optimizer, spec), True
                model._fused_twin_cache = (tkey, fused_opt)
            if fused_opt is not None:
                optimizer = fused_opt
            elif lazy_specs:
                # the declared tables still take the sparse fused path;
                # only the rest-of-model sweep stays plain optax. One
                # WARNING per model (the no-twin result is cached): a
                # fleet-wide ZOO_FUSED_OPT=1 retrain loop must not log
                # per fit
                if warn:
                    log.warning(
                        "fused_optimizer: compiled optimizer %r has no "
                        "exact fused twin; embedding tables take the "
                        "fused segment path, the rest stays on plain "
                        "optax", spec)
            else:
                if warn:
                    log.warning(
                        "fused_optimizer requested but the compiled "
                        "optimizer (%r) has no exact fused twin (only "
                        "default-hyperparameter adam/adamw specs map); "
                        "keeping the plain optax path", spec)
                fused = False

    # the layout marker auto-resume uses to refuse a structurally
    # mismatched restore: a fused fit's state tree (FusedAdamState /
    # fused rest) differs from the stock optax chain's
    opt_layout = "fused" if getattr(optimizer, "fused_apply", None) \
        is not None else "tree"
    opt_shardings = None
    if lazy_specs:
        from analytics_zoo_tpu.learn.lazy_embedding import init_state
        opt_state = _put_replicated(
            init_state(params, lazy_specs, optimizer), mesh)
    elif shard_rules is not None:
        # eager init on sharded params: elementwise leaves (Adam moments)
        # inherit their param's sharding; the explicit re-put mirrors the
        # rule table onto EVERY leaf (step counters and any moment the
        # propagation missed land replicated / rule-sharded exactly) —
        # the match_partition_rules pattern: one table resolves params
        # and optimizer state
        opt_state = optimizer.init(params)
        from analytics_zoo_tpu.parallel.sharding import tree_shardings
        opt_shardings = tree_shardings(opt_state, mesh, shard_rules)
        opt_state = _put_with_shardings(opt_state, opt_shardings)
        step_shardings = _step_shardings(mesh, param_shardings,
                                         opt_shardings)
    else:
        opt_state = _put_replicated(optimizer.init(params), mesh)
    if resume_opt_tree is not None:
        from analytics_zoo_tpu.learn.checkpoint import restore_opt_state
        saved_layout = (resume_meta or {}).get("opt_state_layout", "tree")
        if saved_layout != opt_layout:
            raise ValueError(
                f"auto_resume: checkpoint optimizer state is "
                f"{saved_layout!r} but this fit would build "
                f"{opt_layout!r} (fused_optimizer toggled between "
                "runs?); re-run with the original setting")
        restored = restore_opt_state(jax.device_get(opt_state),
                                     resume_opt_tree)
        # sharded resume: saved host leaves re-shard DIRECTLY onto the
        # rule-derived layout (no replicate-then-reshard hop)
        opt_state = _put_with_shardings(restored, opt_shardings) \
            if opt_shardings is not None else _put_replicated(restored,
                                                              mesh)

    # Cache the jitted step on the model: repeated fit calls (warm restarts,
    # per-round loops) must hit the compile cache, not rebuild a fresh
    # closure every call.
    multi = steps_per_run > 1
    dc_steps = (_tree_len(x) // local_batch) if use_device_cache else 0
    cc_dir = compile_cache_dir if compile_cache_dir is not None \
        else os.environ.get("ZOO_COMPILE_CACHE_DIR") or None
    # sharding descriptor: mesh axis extents + the rule table's content
    # hash. Part of BOTH the in-process step memo key and the on-disk
    # AOT key — a replicated fit and an fsdp-sharded fit (or two
    # different rule tables / mesh factorizations) are different
    # programs and must never share an executable. Stable across
    # processes (no id()), so a sharded re-fit in a fresh process still
    # hits its own entries.
    shard_desc = ""
    if shard_rules is not None:
        from analytics_zoo_tpu.parallel.sharding import sharding_descriptor
        shard_desc = sharding_descriptor(mesh, shard_rules)
    if use_device_cache:
        cache_key = (id(optimizer), id(model.loss), "devcache",
                     mixed_precision, lazy_embeddings, dc_steps,
                     local_batch, shuffle, fused, cc_dir,
                     shard_desc)
    else:
        cache_key = (id(optimizer), id(model.loss), multi,
                     mixed_precision, lazy_embeddings, fused, cc_dir,
                     shard_desc)
    cached = getattr(model, "_train_cache", None)
    if cached is not None and cached[0] == cache_key:
        train_step = cached[1]
    else:
        if use_device_cache:
            builder = lambda *a, **kw: build_device_epoch_run(  # noqa: E731
                *a, steps=dc_steps, batch=local_batch, shuffle=shuffle,
                **kw)
        else:
            builder = build_train_run if multi else build_train_step
        train_step = builder(
            model.apply, model.loss, optimizer,
            apply_and_state_fn=getattr(model, "apply_and_state", None),
            mixed_precision=mixed_precision, lazy_specs=lazy_specs,
            fused=fused, shardings=step_shardings)
        if cc_dir:
            # persistent compilation cache: AOT-serialize the step/run
            # executable per input signature — a re-run in a fresh
            # process loads its program from disk instead of
            # re-compiling — with jax's own persistent cache as the
            # fallback layer for shapes AOT can't carry
            from analytics_zoo_tpu.compile_cache import (
                AOTFunctionCache, enable_jax_persistent_cache, fingerprint,
                get_cache)
            enable_jax_persistent_cache(cc_dir)
            # every program discriminator the in-memory cache_key
            # carries must reach the DISK key too: a single-step
            # executable and a multi-step run with coinciding arg
            # shapes are different programs (3- vs 4-tuple outputs).
            # steps_per_run itself stays OUT: the run program scans
            # the leading axis, so k only lives in the arg shapes and
            # a tail group may legitimately hit another run's entry.
            # `fused` is an explicit key component (ISSUE 9): the fused
            # and plain programs share every arg shape, so WITHOUT it a
            # toggle could load the other mode's stale executable
            step_fp = fingerprint(
                [model, model.loss, optimizer.update, mixed_precision,
                 lazy_embeddings, multi, bool(use_device_cache), dc_steps,
                 shuffle if use_device_cache else None,
                 fused, shard_desc])
            train_step = AOTFunctionCache(train_step, get_cache(cc_dir),
                                          step_fp, sharding=shard_desc)
        model._train_cache = (cache_key, train_step)
    x_dev = y_dev = None
    if use_device_cache:
        x_dev, y_dev = _device_cached_data(model, x, y, mesh)

    ckpt_mgr = None
    if model._checkpoint_path:
        from analytics_zoo_tpu.learn.checkpoint import (CheckpointManager,
                                                        gather_tree)
        ckpt_mgr = CheckpointManager(model._checkpoint_path)
        if checkpoint_trigger is None:
            checkpoint_trigger = tg.EveryEpoch()

    writer = None
    if model._tensorboard_dir:
        from analytics_zoo_tpu.utils.tensorboard import SummaryWriter
        writer = SummaryWriter(model._tensorboard_dir + "/train")

    telemetry = _TrainingMetrics()
    telemetry.mesh_axes(mesh if shard_rules is not None else None)
    reporter = None
    if metrics_report_s:
        from analytics_zoo_tpu.observability.reporter import MetricsReporter
        reporter = MetricsReporter(interval_s=metrics_report_s,
                                   writer=writer).start()

    if resume_meta is not None:
        telemetry.resumes.inc()

    # cost-analysis roofline (ISSUE 6): XLA-counted FLOPs/bytes per step
    # signature, accounted per epoch — the MFU/HBM gauges without a
    # hand-supplied flops_per_step
    cost_tracker = None
    if os.environ.get("ZOO_ROOFLINE", "1") != "0":
        memo_root = getattr(model, "_roofline_cost_memo", None)
        if memo_root is None:
            memo_root = model._roofline_cost_memo = {}
        # sub-dict per train-step program, under the SAME cache_key the
        # step cache memoizes on: two fits that share an executable
        # share harvested costs, two that don't cannot alias
        step_memo = memo_root.setdefault(cache_key, {})
        cost_tracker = _StepCostTracker(train_step, step_memo)
        try:
            from analytics_zoo_tpu.observability.roofline import \
                get_accountant
            get_accountant().reset("train")
        except Exception:  # noqa: BLE001 — telemetry only
            cost_tracker = None
        if cost_tracker is not None and fused:
            # Pallas regions are invisible to HLO cost analysis — patch
            # the tracker with the analytic kernel model so the MFU/HBM
            # gauges stay honest (memoized beside the sig-keyed costs;
            # string key cannot collide with signature tuples)
            if "__fused_correction__" not in step_memo:
                step_memo["__fused_correction__"] = \
                    _fused_kernel_correction(optimizer, lazy_specs, params,
                                             opt_state, step_shardings,
                                             local_batch)
            cost_tracker.correction = step_memo["__fused_correction__"]

    if fused and not lazy_specs \
            and getattr(optimizer, "fused_apply", None) is not None:
        # one measured fused sweep, compile excluded: the direct A/B
        # lever benches read against the unfused update's share of step
        # time. Observed only when the probe is built (once per
        # model/cache_key, NOT per fit): a warm re-fit re-timing it
        # would add two full sweeps of HBM traffic inside the very
        # bench loops the histogram exists to explain
        try:
            sw_cached = getattr(model, "_fused_sweep_cache", None)
            if sw_cached is None or sw_cached[0] != cache_key:
                # under a sharded fit the probe must time the SAME
                # shard_mapped sweep the step runs — a bare jit would
                # replicate the full params/moments on every device
                # (the memory blow-up the sharded fit exists to avoid)
                fa = optimizer.fused_apply
                if step_shardings is not None:
                    fa = _shard_mapped_fused(fa, step_shardings)
                sweep = jax.jit(fa)
                model._fused_sweep_cache = (cache_key, sweep)
                zg = jax.tree_util.tree_map(jnp.zeros_like, params)
                jax.block_until_ready(sweep(zg, opt_state, params))
                t_sw = time.time()
                jax.block_until_ready(sweep(zg, opt_state, params))
                telemetry.fused_update_ms.observe(
                    (time.time() - t_sw) * 1e3)
        except Exception as e:  # noqa: BLE001 — telemetry only
            log.debug("fused sweep timing skipped: %s: %s",
                      type(e).__name__, e)

    # on-demand profiler window (ISSUE 6): capture iterations
    # [start, stop) into a bounded, rotated artifact dir
    profiler = None
    profile_state = {"active": False, "done": False}
    if profile_steps is not None:
        p_start, p_stop = (int(profile_steps[0]), int(profile_steps[1]))
        if not (0 <= p_start < p_stop):
            raise ValueError(
                f"profile_steps={profile_steps!r} must be (start, stop) "
                "with 0 <= start < stop")
        from analytics_zoo_tpu.observability.capture import ProfileCapture
        profiler = ProfileCapture(
            profile_dir or os.environ.get("ZOO_PROFILE_DIR")
            or "zoo_profiles")

    def _profile_tick(it: int):
        """Crossing-edge profiler control: start when the iteration
        counter reaches `start`, stop once it reaches `stop` (multi-step
        runs cross in jumps of k — the window rounds up to run
        boundaries, same granularity trade as every trigger)."""
        if profiler is None or profile_state["done"]:
            return
        try:
            if not profile_state["active"] and it >= p_start:
                profiler.start(tag=f"fit-it{it}")
                profile_state["active"] = True
            elif profile_state["active"] and it >= p_stop:
                manifest = profiler.stop()
                profile_state["active"] = False
                profile_state["done"] = True
                history.setdefault("profile_artifacts", []).append(
                    manifest["dir"])
                log.info("profiler capture written to %s (%d files)",
                         manifest["dir"], len(manifest["files"]))
        except Exception as e:  # noqa: BLE001 — profiling must never
            # take down the fit it watches
            log.warning("profiler capture failed: %s: %s",
                        type(e).__name__, e)
            profile_state["done"] = True

    def _call_step(*step_args):
        """Every branch's train_step dispatch funnels through the step
        watchdog (retries + optional timeout); with step_retries=0 and
        no timeout this is a plain call. Roofline cost harvest and the
        profiler edge-check run first — both need the pre-dispatch
        (donation-alive) view."""
        if cost_tracker is not None:
            cost_tracker.before(step_args)
        _profile_tick(iteration)
        out = _step_with_watchdog(train_step, step_args, step_retries,
                                  step_timeout_s, telemetry.step_retries,
                                  iteration)
        if cost_tracker is not None:
            # post-call: a just-built AOT executable answers
            # cost_analysis directly; only the plain-jit path lowers
            cost_tracker.after()
        return out

    def _ckpt_extra(ep: int, finished: bool) -> Dict[str, Any]:
        """Checkpoint sidecar: everything auto-resume needs for bitwise
        continuation — epoch/iteration cursors, the live RNG key, and
        the opt-state layout marker."""
        return {"epoch": ep, "iteration": iteration,
                "epoch_finished": finished,
                "rng": np.asarray(jax.device_get(rng)).ravel().tolist(),
                "opt_state_layout": opt_layout}

    def _ckpt_save(extra: Dict[str, Any]) -> None:
        """ONE checkpoint-commit funnel for every save site (mid-epoch
        trigger, epoch boundary, emergency): gather the sharded state to
        host exactly once, commit the checkpoint set, and — with
        `int8_sidecar` — run the post-training quantization pass on the
        SAME gathered params so the sidecar always matches the version
        it sits beside. Sidecar failure is one warning, never a failed
        fit (serving falls back to quantize-at-load).

        Publication (ISSUE 14) is the LAST act: the publish marker —
        what the fleet's rollout watcher keys on — commits only once
        params, opt_state AND the sidecar are all durable. A kill
        anywhere before the marker rename leaves the version resumable
        but UNPUBLISHED; a sidecar failure skips the marker too (the
        version the fleet would quantize-at-load is not the version
        the trainer meant to publish)."""
        host_params = gather_tree(params)
        ckpt_mgr.save(iteration, host_params, gather_tree(opt_state),
                      extra=extra)
        publishable = True
        if int8_sidecar:
            try:
                from analytics_zoo_tpu.serving.quantization import \
                    write_int8_sidecar
                write_int8_sidecar(ckpt_mgr.run_dir, iteration, model,
                                   params=host_params)
            except Exception as e:  # noqa: BLE001 — sidecar is optional
                publishable = False
                log.warning("int8 sidecar write failed at iteration %d "
                            "(%s: %s); serving will quantize at load "
                            "and the version stays unpublished",
                            iteration, type(e).__name__, e)
        if publishable:
            try:
                from analytics_zoo_tpu.learn.checkpoint import \
                    write_publish_marker
                write_publish_marker(ckpt_mgr.run_dir, iteration,
                                     extra=extra)
            except Exception as e:  # noqa: BLE001 — resume still works
                log.warning("publish marker failed at iteration %d "
                            "(%s: %s); the version resumes but will "
                            "not roll out", iteration,
                            type(e).__name__, e)

    history: Dict[str, List[float]] = {"loss": []}
    batches = None
    epoch = start_epoch
    try:
        for epoch in range(start_epoch, epochs):
          it0 = iteration
          losses_dev: List[Any] = []   # device scalars/vectors; sync at end
          t0 = time.time()
          n_seen = 0

          if use_device_cache:
              # whole epoch in ONE dispatch over device-resident data:
              # zero per-step host transfer. Mid-epoch (iteration) trigger
              # checks collapse to the epoch boundary — the same
              # granularity trade as steps_per_run=steps.
              batches = None
              rng, erng = jax.random.split(rng)
              params, opt_state, ep_losses = _call_step(
                  params, opt_state, x_dev, y_dev, erng)
              losses_dev.append(ep_losses)
              iteration += dc_steps
              n_seen = dc_steps * local_batch
          else:
            if multi:
                def transfer(group):
                    return _stack_group(group, mesh)
                source = _chunk_batches(batch_iter_factory(epoch),
                                        steps_per_run)
            else:
                def transfer(item):
                    xb, yb, real = item
                    return (_put_batch(xb, mesh),
                            _put_batch(yb, mesh) if yb is not None
                            else None,
                            real, 1)
                source = batch_iter_factory(epoch)
            batches = _Prefetcher(
                source, transfer, depth=depth,
                on_wait=lambda w: telemetry.input_wait_ms.observe(
                    w * 1e3)) if prefetch else map(transfer, source)

            for xb, yb, real, k in batches:
                if multi:
                    rng, run_rng = jax.random.split(rng)
                    params, opt_state, _, loss = _call_step(
                        params, opt_state, xb, yb, run_rng)
                else:
                    rng, step_rng = jax.random.split(rng)
                    params, opt_state, loss = _call_step(params, opt_state,
                                                         xb, yb, step_rng)
                iteration += k
                n_seen += real * n_proc       # local count × processes
                losses_dev.append(loss)
                # loss stays a device scalar: triggers that read .loss
                # (Min/MaxLoss) force their own sync; counter triggers
                # stay async
                last_loss = loss[-1] if multi else loss
                if checkpoint_trigger and ckpt_mgr and checkpoint_trigger(
                        tg.TriggerState(epoch=epoch, iteration=iteration,
                                        loss=last_loss)):
                    # the meta sidecar records the opt-state layout
                    # (plus the resume cursors/RNG), so a future
                    # restore can't silently structurally mismatch a
                    # fused fit's state against a plain one.
                    # gather_tree, not bare device_get: correct (and
                    # actionably failing cross-host) for sharded leaves
                    _ckpt_save(_ckpt_extra(epoch, False))
                if end_trigger and end_trigger(
                        tg.TriggerState(epoch=epoch, iteration=iteration,
                                        loss=last_loss)):
                    break
            if isinstance(batches, _Prefetcher):
                batches.close()  # early break leaves the worker mid-queue
          # ONE host sync per epoch: materialize every step loss together.
          # This blocks until the last step's program has finished, so dt
          # measures device compute, not dispatch.
          if epoch == 0 and not losses_dev:
              # prebuilt models skip the shape probe, so an empty/too-small
              # dataset must still fail loudly rather than "train" 0 steps
              raise ValueError(
                  "Dataset produced no full batches; lower batch_size")
          step_losses = np.concatenate(
              [np.atleast_1d(v) for v in _materialize(losses_dev)]) \
              if losses_dev else np.zeros((0,))
          dt = time.time() - t0
          mean_loss = float(step_losses.mean()) if len(step_losses) else 0.0
          history["loss"].append(mean_loss)
          throughput = n_seen / max(dt, 1e-9)
          step_ms = telemetry.epoch(iteration - it0, n_seen, dt, mean_loss,
                                    flops_per_step=flops_per_step)
          # device-wait vs host-wait verdict (ISSUE 15): the prefetch
          # queue's measured blocked time over the epoch wall time is
          # the fraction of the fit that was input-bound — a measured
          # answer, not a guess. Also lands in the roofline snapshot's
          # input-stall column.
          input_wait_s = batches.wait_s \
              if isinstance(batches, _Prefetcher) else 0.0
          telemetry.input_bound.set(
              min(1.0, input_wait_s / max(dt, 1e-9)))
          if input_wait_s > 0:
              try:
                  from analytics_zoo_tpu.observability.roofline import \
                      get_accountant
                  get_accountant().account_stall("train", input_wait_s)
              except Exception as ie:  # noqa: BLE001 — telemetry only
                  log.debug("input-stall accounting failed: %s", ie)
          if cost_tracker is not None and cost_tracker.calls:
              # dt is device wall time (the _materialize above synced),
              # so achieved = XLA-counted work / measured epoch seconds.
              # The harvested cost is PER-STEP (cost analysis counts a
              # scan body once — see _StepCostTracker), so scale the
              # per-call mean by the iterations this epoch ran: exact
              # for single-step, multi-step (steps_per_run) and
              # device-cache epoch programs alike.
              steps_done = max(iteration - it0, cost_tracker.calls)
              scale = steps_done / cost_tracker.calls
              telemetry.roofline(cost_tracker.flops * scale,
                                 cost_tracker.bytes * scale, dt,
                                 n_devices=cost_tracker.devices)
              cost_tracker.reset_epoch()
          if writer:
              writer.scalar("Loss", mean_loss, iteration)
              writer.scalar("Throughput", throughput, iteration)
              writer.scalar("StepTime_ms", step_ms, iteration)
          log.info("Epoch %d/%d  loss=%.4f  %.0f samples/s",
                   epoch + 1, epochs, mean_loss, throughput)

          if validation_data is not None:
              vx, vy = validation_data
              model.params = params  # device-resident hand-off
              val = evaluate_keras(model, vx, vy,
                                   batch_per_thread=max(batch_size // dp, 1))
              for k, v in val.items():
                  history.setdefault("val_" + k, []).append(v)
                  telemetry.val.set(v, name=k)
              if writer:
                  for k, v in val.items():
                      writer.scalar("val_" + k, v, iteration)

          # epoch-boundary checkpoint trigger (EveryEpoch semantics)
          if checkpoint_trigger and ckpt_mgr and checkpoint_trigger(
                  tg.TriggerState(epoch=epoch + 1, iteration=iteration,
                                  epoch_finished=True)):
              _ckpt_save(_ckpt_extra(epoch + 1, True))
          if end_trigger and end_trigger(
                  tg.TriggerState(epoch=epoch + 1, iteration=iteration,
                                  epoch_finished=True)):
              break

    except Exception:
        # the step watchdog exhausted its retries, or any other mid-run
        # failure: leave an emergency checkpoint behind so auto_resume
        # (or an operator) can continue instead of restarting at step 0.
        # Best-effort — a step that died mid-execution may have consumed
        # the donated parameter buffers, in which case the last periodic
        # checkpoint on disk remains the resume point.
        if ckpt_mgr is not None and iteration > 0 \
                and iteration not in ckpt_mgr._saved:
            # (skipped when this iteration is already on disk — an
            # emergency save would demote a boundary checkpoint's
            # metadata to mid-epoch for identical params)
            try:
                # through the SAME commit funnel as every other save
                # site — the emergency checkpoint gets the int8 sidecar
                # too, so a crash can't leave a newest version serving
                # falls back to quantize-at-load on
                _ckpt_save(dict(_ckpt_extra(epoch, False),
                                emergency=True))
                log.warning("emergency checkpoint written at iteration "
                            "%d", iteration)
            except Exception as ce:  # noqa: BLE001 — already failing
                log.warning("emergency checkpoint failed (%s: %s); the "
                            "last periodic checkpoint is the resume "
                            "point", type(ce).__name__, ce)
        raise
    finally:
        # Keep parameters on device (even on an interrupted fit, so the
        # model never points at donated/deleted buffers): repeated
        # fit/evaluate/predict chains stay in HBM; save/checkpoint
        # paths device_get on demand.
        model.params = params
        if isinstance(batches, _Prefetcher):
            batches.close()
        if profiler is not None and profile_state["active"]:
            # a fit that ends (or dies) inside the window still leaves
            # a finished, loadable artifact behind
            try:
                manifest = profiler.stop()
                history.setdefault("profile_artifacts", []).append(
                    manifest["dir"])
            except Exception:  # noqa: BLE001 — already tearing down
                pass
        if reporter is not None:
            reporter.stop()   # logs a final digest (before writer closes)
        if writer:
            writer.close()
    return history


def _localize_params(model):
    """Multi-process eval/predict run per-rank on local devices; params
    left on the global mesh by fit must be pulled to host first (every
    rank holds the full value when replicated; FSDP-sharded params would
    need collectives → clear error instead)."""
    def pull(a):
        if isinstance(a, jax.Array) and not a.is_fully_addressable:
            if a.is_fully_replicated:
                return np.asarray(a.addressable_data(0))
            raise NotImplementedError(
                "Multi-process evaluate/predict needs replicated "
                "parameters; params are sharded across hosts")
        return a
    model.params = jax.tree_util.tree_map(pull, model.params)


def evaluate_keras(model, x, y=None, batch_per_thread: int = 32,
                   metrics=None) -> Dict[str, float]:
    ctx = get_context()
    # Multi-process: each rank evaluates ITS OWN data locally (the
    # per-partition evaluation contract) — a cross-host eval batch would
    # both duplicate every sample per rank and produce outputs on
    # non-addressable devices.
    mesh = ctx.mesh if jax.process_count() == 1 else None
    if jax.process_count() > 1:
        _localize_params(model)
    dp_local = mesh.data_parallel_size if mesh \
        else jax.local_device_count()
    batch = batch_per_thread * dp_local
    model.ensure_built(next(iter_batches(x, y, batch,
                                         drop_remainder=False,
                                         pad_to_batch=True))[0])
    ms = metrics if metrics is not None else model.metrics
    if not ms:
        from analytics_zoo_tpu.ops.metrics import Loss
        ms = [Loss(model.loss)] if model.loss else []
    if not ms:
        raise ValueError("No metrics to evaluate; compile with metrics=[...]")
    params = _put_replicated(model.params, mesh)
    # cache the jitted eval step on the model — per-epoch validation must not
    # recompile (fresh closures defeat jax.jit's cache)
    cache_key = tuple(type(m).__name__ for m in ms)
    cached = getattr(model, "_eval_cache", None)
    if cached is not None and cached[0] == cache_key:
        eval_step = cached[1]
    else:
        eval_step = build_eval_step(model.apply, ms)
        model._eval_cache = (cache_key, eval_step)
    states = [m.init() for m in ms]
    # padding batches would contaminate accumulators → mask by slicing the
    # real rows on host for the tail batch instead
    for xb, yb, real in iter_batches(x, y, batch, drop_remainder=False,
                                     pad_to_batch=False):
        xb = _put_batch(xb, mesh)
        yb = _put_batch(yb, mesh) if yb is not None else None
        states = eval_step(params, states, xb, yb)
    # tail batch: pad to the SAME full-batch shape (reuses the predict jit,
    # no extra compile, no unjitted host apply), slice the real rows, and
    # fold them into the accumulators host-side
    n = _tree_len(x)
    tail = n % batch
    if tail:
        sel = np.concatenate([np.arange(n - tail, n),
                              np.repeat([n - 1], batch - tail)])
        xb = _put_batch(jax.tree_util.tree_map(
            lambda a: np.asarray(a)[sel], x), mesh)
        yb = jax.tree_util.tree_map(
            lambda a: np.asarray(a)[sel[:tail]], y) if y is not None \
            else None
        pred = jax.device_get(_forward_jit(model)(params, xb))
        pred = jax.tree_util.tree_map(lambda a: np.asarray(a)[:tail], pred)
        states = [m.update(s, yb, pred) for m, s in zip(ms, states)]
    return {m.name: float(m.compute(s)) for m, s in zip(ms, states)}


def _forward_jit(model):
    """Cached inference forward — shared by predict and the eval tail."""
    fj = getattr(model, "_predict_cache", None)
    if fj is None:
        fj = jax.jit(lambda p, xb: model.apply(p, xb, training=False))
        model._predict_cache = fj
    return fj


def predict_keras(model, x, batch_per_thread: int = 32) -> np.ndarray:
    ctx = get_context()
    # see evaluate_keras: per-rank local prediction under multi-process
    mesh = ctx.mesh if jax.process_count() == 1 else None
    if jax.process_count() > 1:
        _localize_params(model)
    dp_local = mesh.data_parallel_size if mesh \
        else jax.local_device_count()
    batch = batch_per_thread * dp_local
    model.ensure_built(next(iter_batches(x, None, batch,
                                         drop_remainder=False,
                                         pad_to_batch=True))[0])
    params = _put_replicated(model.params, mesh)
    apply_jit = _forward_jit(model)
    outs: List[np.ndarray] = []
    for xb, _, real in iter_batches(x, None, batch, drop_remainder=False,
                                    pad_to_batch=True):
        xb = _put_batch(xb, mesh)
        pred = jax.device_get(apply_jit(params, xb))
        pred_np = jax.tree_util.tree_map(lambda a: np.asarray(a)[:real], pred)
        outs.append(pred_np)
    if isinstance(outs[0], (list, tuple)):
        return type(outs[0])(np.concatenate([o[i] for o in outs])
                             for i in range(len(outs[0])))
    return np.concatenate(outs)
