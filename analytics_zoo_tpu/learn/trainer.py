"""The distributed training loop — TPU-native `InternalDistriOptimizer`.

The reference's hot loop (`Topology.scala:1160-1337`, via BigDL
DistriOptimizer) does, per iteration: broadcast weights from the BlockManager,
local forward/backward per executor thread, scatter-reduce gradient slices,
per-slice optimizer update, allgather weights. Here the whole iteration is ONE
jit-compiled XLA program: parameters live replicated (or fsdp-sharded) on the
mesh, the batch is split over the mesh's batch axes, and GSPMD inserts the
gradient all-reduce over ICI automatically. Triggers, checkpoints, metrics and
the retry/resume semantics (`Topology.scala:1255-1337`) are host-side around
that one program.

Batch-size contract (`tfpark/tf_dataset.py:116-157`): training takes a GLOBAL
`batch_size` that must divide by the data-parallel size; eval/predict take
per-device `batch_per_thread`.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common.context import get_context
from analytics_zoo_tpu.common import triggers as tg

log = logging.getLogger("analytics_zoo_tpu.trainer")


# ---------------------------------------------------------------------------
# Data plumbing: numpy structures -> shard-ready batches
# ---------------------------------------------------------------------------
def _tree_len(x) -> int:
    leaves = jax.tree_util.tree_leaves(x)
    if not leaves:
        raise ValueError("Empty input data")
    return int(np.shape(leaves[0])[0])


def _tree_take(x, idx):
    return jax.tree_util.tree_map(lambda a: np.asarray(a)[idx], x)


def _num_batches(n: int, batch: int, drop_remainder: bool) -> int:
    return n // batch if drop_remainder else -(-n // batch)


def iter_batches(x, y=None, batch_size: int = 32, shuffle: bool = False,
                 seed: int = 0, drop_remainder: bool = True,
                 pad_to_batch: bool = False):
    """Yield (x_batch, y_batch, real_count) of numpy arrays. Static batch
    shapes (pad or drop) keep jit from recompiling — the TPU analogue of the
    reference's `hard_code_batch_size` (`tf_dataset.py:158-173`)."""
    n = _tree_len(x)
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    nb = _num_batches(n, batch_size, drop_remainder and not pad_to_batch)
    for b in range(nb):
        sel = idx[b * batch_size:(b + 1) * batch_size]
        real = len(sel)
        if real < batch_size:
            if pad_to_batch:
                sel = np.concatenate([sel, np.repeat(sel[-1:],
                                                     batch_size - real)])
            else:
                continue
        xb = _tree_take(x, sel)
        yb = _tree_take(y, sel) if y is not None else None
        yield xb, yb, real


def check_global_batch(batch_size: int, dp: int) -> None:
    if batch_size % dp != 0:
        raise ValueError(
            f"global batch_size ({batch_size}) must be a multiple of the "
            f"data-parallel size ({dp}) — the reference's total-core-number "
            f"contract (tf_dataset.py:142-147)")


def _put_batch(tree, mesh):
    """mesh=None → single default device (non-distributed escape hatch)."""
    if mesh is None:
        return jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.asarray(a)), tree)
    sharding = mesh.batch_sharding()
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(jnp.asarray(a), sharding), tree)


def _put_replicated(tree, mesh):
    if mesh is None:
        return jax.tree_util.tree_map(lambda a: jax.device_put(a), tree)
    sharding = mesh.replicated()
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding), tree)


# ---------------------------------------------------------------------------
# Core train/eval step builders
# ---------------------------------------------------------------------------
def _merge_state(params, state_updates):
    """Merge stateful-layer updates (nested dict subset) into params."""
    if not state_updates:
        return params
    merged = dict(params)
    for k, v in state_updates.items():
        if isinstance(v, dict) and isinstance(merged.get(k), dict):
            merged[k] = _merge_state(merged[k], v)
        else:
            merged[k] = v
    return merged


def build_train_step(apply_fn: Callable, loss_fn: Callable,
                     optimizer: optax.GradientTransformation,
                     apply_and_state_fn: Optional[Callable] = None
                     ) -> Callable:
    """One iteration as a pure function. jit + sharded inputs → GSPMD emits
    the gradient all-reduce; donation reuses parameter buffers in HBM.
    Stateful layers (BatchNorm moving stats) return updates through the aux
    channel and are merged outside the gradient path."""

    def train_step(params, opt_state, xb, yb, rng):
        def compute_loss(p):
            if apply_and_state_fn is not None:
                pred, state_upd = apply_and_state_fn(p, xb, training=True,
                                                     rng=rng)
            else:
                pred, state_upd = apply_fn(p, xb, training=True, rng=rng), {}
            return loss_fn(yb, pred), state_upd

        (loss, state_upd), grads = jax.value_and_grad(
            compute_loss, has_aux=True)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        params = _merge_state(params, state_upd)
        return params, opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1))


def build_eval_step(apply_fn: Callable, metrics: Sequence) -> Callable:
    def eval_step(params, states, xb, yb):
        pred = apply_fn(params, xb, training=False)
        return [m.update(s, yb, pred) for m, s in zip(metrics, states)]

    return jax.jit(eval_step)


# ---------------------------------------------------------------------------
# Keras front-door: fit / evaluate / predict
# ---------------------------------------------------------------------------
def fit_keras(model, x, y=None, batch_size: int = 32, epochs: int = 1,
              validation_data=None, distributed: bool = True,
              shuffle: bool = True, checkpoint_trigger=None,
              end_trigger=None, seed: int = 0,
              batch_iter_factory: Optional[Callable] = None
              ) -> Dict[str, List[float]]:
    """`KerasNet.fit` backend. Returns a Keras-style history dict.
    `batch_iter_factory(epoch) -> iterator of (xb, yb, real)` overrides the
    default in-memory batching (lazy/disk-tier datasets)."""
    ctx = get_context()
    mesh = ctx.mesh if distributed else None
    dp = mesh.data_parallel_size if mesh else 1
    check_global_batch(batch_size, dp)

    if batch_iter_factory is None:
        n = _tree_len(x)
        if n < batch_size:
            raise ValueError(
                f"Dataset has {n} samples but global batch_size is "
                f"{batch_size}; training batches are whole-batch only "
                "(static shapes). Lower batch_size or add data.")

        def batch_iter_factory(epoch):  # noqa: F811 — default factory
            return iter_batches(x, y, batch_size, shuffle=shuffle,
                                seed=seed + epoch)

    rng = jax.random.PRNGKey(seed)
    rng, init_rng = jax.random.split(rng)
    try:
        sample = next(iter(batch_iter_factory(0)))[0]
    except StopIteration:
        raise ValueError("Dataset produced no full batches; lower batch_size")
    model.ensure_built(sample, init_rng)

    optimizer = model.optimizer
    if optimizer is None:
        raise RuntimeError("Model must be compiled before fit "
                           "(`Topology.scala:139` contract)")
    params = _put_replicated(model.params, mesh)
    opt_state = _put_replicated(optimizer.init(params), mesh)
    train_step = build_train_step(
        model.apply, model.loss, optimizer,
        apply_and_state_fn=getattr(model, "apply_and_state", None))

    ckpt_mgr = None
    if model._checkpoint_path:
        from analytics_zoo_tpu.learn.checkpoint import CheckpointManager
        ckpt_mgr = CheckpointManager(model._checkpoint_path)
        if checkpoint_trigger is None:
            checkpoint_trigger = tg.EveryEpoch()

    writer = None
    if model._tensorboard_dir:
        from analytics_zoo_tpu.utils.tensorboard import SummaryWriter
        writer = SummaryWriter(model._tensorboard_dir + "/train")

    history: Dict[str, List[float]] = {"loss": []}
    iteration = 0
    for epoch in range(epochs):
        ep_loss, ep_batches = 0.0, 0
        t0 = time.time()
        n_seen = 0
        for xb, yb, real in batch_iter_factory(epoch):
            xb = _put_batch(xb, mesh)
            yb = _put_batch(yb, mesh) if yb is not None else None
            rng, step_rng = jax.random.split(rng)
            params, opt_state, loss = train_step(params, opt_state, xb, yb,
                                                 step_rng)
            iteration += 1
            ep_batches += 1
            n_seen += real
            ep_loss += float(loss)
            if checkpoint_trigger and ckpt_mgr and checkpoint_trigger(
                    tg.TriggerState(epoch=epoch, iteration=iteration,
                                    loss=float(loss))):
                ckpt_mgr.save(iteration, jax.device_get(params),
                              jax.device_get(opt_state),
                              extra={"epoch": epoch, "iteration": iteration})
            if end_trigger and end_trigger(
                    tg.TriggerState(epoch=epoch, iteration=iteration,
                                    loss=float(loss))):
                break
        dt = time.time() - t0
        mean_loss = ep_loss / max(ep_batches, 1)
        history["loss"].append(mean_loss)
        throughput = n_seen / max(dt, 1e-9)
        if writer:
            writer.scalar("Loss", mean_loss, iteration)
            writer.scalar("Throughput", throughput, iteration)
        log.info("Epoch %d/%d  loss=%.4f  %.0f samples/s",
                 epoch + 1, epochs, mean_loss, throughput)

        if validation_data is not None:
            vx, vy = validation_data
            model.params = jax.device_get(params)
            val = evaluate_keras(model, vx, vy,
                                 batch_per_thread=max(batch_size // dp, 1))
            for k, v in val.items():
                history.setdefault("val_" + k, []).append(v)
            if writer:
                for k, v in val.items():
                    writer.scalar("val_" + k, v, iteration)

        # epoch-boundary checkpoint trigger (EveryEpoch semantics)
        if checkpoint_trigger and ckpt_mgr and checkpoint_trigger(
                tg.TriggerState(epoch=epoch + 1, iteration=iteration,
                                epoch_finished=True)):
            ckpt_mgr.save(iteration, jax.device_get(params),
                          jax.device_get(opt_state),
                          extra={"epoch": epoch + 1, "iteration": iteration})
        if end_trigger and end_trigger(
                tg.TriggerState(epoch=epoch + 1, iteration=iteration,
                                epoch_finished=True)):
            break

    model.params = jax.device_get(params)
    if writer:
        writer.close()
    return history


def evaluate_keras(model, x, y=None, batch_per_thread: int = 32,
                   metrics=None) -> Dict[str, float]:
    ctx = get_context()
    mesh = ctx.mesh
    batch = batch_per_thread * mesh.data_parallel_size
    model.ensure_built(next(iter_batches(x, y, batch,
                                         drop_remainder=False,
                                         pad_to_batch=True))[0])
    ms = metrics if metrics is not None else model.metrics
    if not ms:
        from analytics_zoo_tpu.ops.metrics import Loss
        ms = [Loss(model.loss)] if model.loss else []
    if not ms:
        raise ValueError("No metrics to evaluate; compile with metrics=[...]")
    params = _put_replicated(model.params, mesh)
    # cache the jitted eval step on the model — per-epoch validation must not
    # recompile (fresh closures defeat jax.jit's cache)
    cache_key = tuple(type(m).__name__ for m in ms)
    cached = getattr(model, "_eval_cache", None)
    if cached is not None and cached[0] == cache_key:
        eval_step = cached[1]
    else:
        eval_step = build_eval_step(model.apply, ms)
        model._eval_cache = (cache_key, eval_step)
    states = [m.init() for m in ms]
    # padding batches would contaminate accumulators → mask by slicing the
    # real rows on host for the tail batch instead
    for xb, yb, real in iter_batches(x, y, batch, drop_remainder=False,
                                     pad_to_batch=False):
        xb = _put_batch(xb, mesh)
        yb = _put_batch(yb, mesh) if yb is not None else None
        states = eval_step(params, states, xb, yb)
    # tail batch (smaller; compiled separately once)
    n = _tree_len(x)
    tail = n % batch
    if tail:
        sel = np.arange(n - tail, n)
        xb = jax.tree_util.tree_map(lambda a: np.asarray(a)[sel], x)
        yb = jax.tree_util.tree_map(lambda a: np.asarray(a)[sel], y) \
            if y is not None else None
        states = [m.update(s, yb, model.apply(model.params, xb))
                  for m, s in zip(ms, states)]
    return {m.name: float(m.compute(s)) for m, s in zip(ms, states)}


def predict_keras(model, x, batch_per_thread: int = 32) -> np.ndarray:
    ctx = get_context()
    mesh = ctx.mesh
    batch = batch_per_thread * mesh.data_parallel_size
    model.ensure_built(next(iter_batches(x, None, batch,
                                         drop_remainder=False,
                                         pad_to_batch=True))[0])
    params = _put_replicated(model.params, mesh)
    apply_jit = getattr(model, "_predict_cache", None)
    if apply_jit is None:
        apply_jit = jax.jit(lambda p, xb: model.apply(p, xb, training=False))
        model._predict_cache = apply_jit
    outs: List[np.ndarray] = []
    for xb, _, real in iter_batches(x, None, batch, drop_remainder=False,
                                    pad_to_batch=True):
        xb = _put_batch(xb, mesh)
        pred = jax.device_get(apply_jit(params, xb))
        pred_np = jax.tree_util.tree_map(lambda a: np.asarray(a)[:real], pred)
        outs.append(pred_np)
    if isinstance(outs[0], (list, tuple)):
        return type(outs[0])(np.concatenate([o[i] for o in outs])
                             for i in range(len(outs[0])))
    return np.concatenate(outs)
