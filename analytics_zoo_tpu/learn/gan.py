"""GAN training — the TFPark GANEstimator equivalent.

Mirrors `pyzoo/zoo/tfpark/gan/gan_estimator.py:28` (GANEstimator: generator/
discriminator fns + per-network losses and optimizers) and the alternating
update schedule of `GanOptimMethod` (`zoo/.../tfpark/GanOptimMethod.scala` /
`gan/common.py:19`): with `d_steps` and `g_steps`, iteration `i` updates the
discriminator when `i % (d_steps + g_steps) < d_steps`, else the generator.

TPU-native design: instead of one TF graph with masked joint gradients (the
reference packs G+D variables into one flat tensor and zeroes the inactive
half each step), each network keeps its own params/optimizer state and there
are TWO jit-compiled step programs — `d_step` (grads w.r.t. discriminator
only, generator under `stop_gradient`) and `g_step` (grads flow through the
frozen discriminator into the generator). Batches are sharded over the mesh's
data axis; GSPMD inserts the gradient all-reduce.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.common.context import get_context
from analytics_zoo_tpu.keras.engine import KerasNet
from analytics_zoo_tpu.learn import trainer
from analytics_zoo_tpu.learn.checkpoint import (CheckpointManager,
                                                latest_checkpoint,
                                                load_checkpoint,
                                                restore_opt_state)

log = logging.getLogger("analytics_zoo_tpu.gan")


# ---------------------------------------------------------------------------
# Standard GAN losses (tf.contrib.gan loss-fn surface used by the reference's
# examples: fn(real_logits/fake_logits) -> scalar)
# ---------------------------------------------------------------------------
def minimax_generator_loss(fake_logits: jax.Array) -> jax.Array:
    """Non-saturating generator loss: -log D(G(z))."""
    return jnp.mean(optax.sigmoid_binary_cross_entropy(
        fake_logits, jnp.ones_like(fake_logits)))


def minimax_discriminator_loss(real_logits: jax.Array,
                               fake_logits: jax.Array) -> jax.Array:
    real = optax.sigmoid_binary_cross_entropy(
        real_logits, jnp.ones_like(real_logits))
    fake = optax.sigmoid_binary_cross_entropy(
        fake_logits, jnp.zeros_like(fake_logits))
    return jnp.mean(real) + jnp.mean(fake)


def wasserstein_generator_loss(fake_logits: jax.Array) -> jax.Array:
    return -jnp.mean(fake_logits)


def wasserstein_discriminator_loss(real_logits: jax.Array,
                                   fake_logits: jax.Array) -> jax.Array:
    return jnp.mean(fake_logits) - jnp.mean(real_logits)


def least_squares_generator_loss(fake_logits: jax.Array) -> jax.Array:
    return jnp.mean((fake_logits - 1.0) ** 2)


def least_squares_discriminator_loss(real_logits: jax.Array,
                                     fake_logits: jax.Array) -> jax.Array:
    return jnp.mean((real_logits - 1.0) ** 2) + jnp.mean(fake_logits ** 2)


def _remap_opt_tree(net, tree):
    """Rename saved layer names to this instance's names inside a loaded
    optimizer-state tree. Every dict in an optax state for our optimizers
    is a params-shaped moment tree, so the net's own param remap applies."""
    if isinstance(tree, dict):
        return net._remap_loaded(tree)
    if isinstance(tree, (list, tuple)):
        return [_remap_opt_tree(net, v) for v in tree]
    return tree


class GANEstimator:
    """Alternating G/D trainer over a device mesh.

    generator / discriminator: `KerasNet` models (Sequential/Model) or any
    object with `build(rng, input_shape)` + `apply(params, x, training, rng)`.
    Loss fns follow the reference's tfgan-style contract:
    `generator_loss_fn(fake_logits)`, `discriminator_loss_fn(real_logits,
    fake_logits)`.
    """

    def __init__(self, generator: KerasNet, discriminator: KerasNet,
                 generator_loss_fn: Callable = minimax_generator_loss,
                 discriminator_loss_fn: Callable = minimax_discriminator_loss,
                 generator_optimizer=None, discriminator_optimizer=None,
                 generator_steps: int = 1, discriminator_steps: int = 1,
                 model_dir: Optional[str] = None):
        self.generator = generator
        self.discriminator = discriminator
        self.g_loss_fn = generator_loss_fn
        self.d_loss_fn = discriminator_loss_fn
        self.g_opt = generator_optimizer or optax.adam(1e-4, b1=0.5)
        self.d_opt = discriminator_optimizer or optax.adam(1e-4, b1=0.5)
        self.g_steps = int(generator_steps)
        self.d_steps = int(discriminator_steps)
        if self.g_steps < 1 or self.d_steps < 1:
            raise ValueError("generator_steps/discriminator_steps must be >=1")
        self.model_dir = model_dir
        self._ckpt_mgr: Optional[CheckpointManager] = None
        self.g_params = None
        self.d_params = None
        self._counter = 0
        self._opt_tree = None

    # -- setup -------------------------------------------------------------
    def _ensure_built(self, noise_sample, real_sample, rng: jax.Array):
        if self.g_params is None:
            kg, kd = jax.random.split(rng)
            self.generator.ensure_built(noise_sample, kg)
            self.g_params = self.generator.params
            self.discriminator.ensure_built(real_sample, kd)
            self.d_params = self.discriminator.params

    def _build_steps(self):
        gen, disc = self.generator, self.discriminator
        g_loss_fn, d_loss_fn = self.g_loss_fn, self.d_loss_fn
        g_opt, d_opt = self.g_opt, self.d_opt

        def d_step(g_params, d_params, d_opt_state, noise, real, rng):
            k_gen, k_real, k_fake = jax.random.split(rng, 3)
            fake = jax.lax.stop_gradient(
                gen.apply(g_params, noise, training=True, rng=k_gen))

            def loss(dp):
                return d_loss_fn(
                    disc.apply(dp, real, training=True, rng=k_real),
                    disc.apply(dp, fake, training=True, rng=k_fake))

            l, grads = jax.value_and_grad(loss)(d_params)
            updates, d_opt_state = d_opt.update(grads, d_opt_state, d_params)
            return optax.apply_updates(d_params, updates), d_opt_state, l

        def g_step(g_params, g_opt_state, d_params, noise, rng):
            k_gen, k_disc = jax.random.split(rng)

            def loss(gp):
                fake = gen.apply(gp, noise, training=True, rng=k_gen)
                return g_loss_fn(disc.apply(d_params, fake, training=True,
                                            rng=k_disc))

            l, grads = jax.value_and_grad(loss)(g_params)
            updates, g_opt_state = g_opt.update(grads, g_opt_state, g_params)
            return optax.apply_updates(g_params, updates), g_opt_state, l

        return (jax.jit(d_step, donate_argnums=(1, 2)),
                jax.jit(g_step, donate_argnums=(0, 1)))

    # -- training ----------------------------------------------------------
    def train(self, real_data, noise_fn: Callable[[int, int], np.ndarray],
              batch_size: int = 32, end_iteration: int = 1000,
              seed: int = 0, checkpoint_every: int = 0
              ) -> Dict[str, List[float]]:
        """Run the alternating schedule for `end_iteration` total updates.

        real_data: ndarray (or pytree) of real samples; noise_fn(batch,
        seed) -> noise batch. `checkpoint_every` > 0 snapshots both nets to
        `model_dir` every that many iterations.
        """
        ctx = get_context()
        mesh = ctx.mesh
        dp = mesh.data_parallel_size if mesh else 1
        trainer.check_global_batch(batch_size, dp)

        # fold the cumulative counter into every stream so resumed /
        # continued training sees fresh noise and shuffle order
        base_seed = seed + self._counter
        rng = jax.random.fold_in(jax.random.PRNGKey(seed), self._counter)
        rng, init_rng = jax.random.split(rng)
        noise0 = noise_fn(batch_size, base_seed)
        real_iter = trainer.iter_batches(real_data, None, batch_size,
                                         shuffle=True, seed=base_seed)
        real0 = next(iter(trainer.iter_batches(real_data, None, batch_size)))[0]
        self._ensure_built(noise0, real0, init_rng)

        d_step, g_step = self._build_steps()
        g_params = trainer._put_replicated(self.g_params, mesh)
        d_params = trainer._put_replicated(self.d_params, mesh)
        g_opt_state = self.g_opt.init(g_params)
        d_opt_state = self.d_opt.init(d_params)
        if self._opt_tree is not None:
            restored = restore_opt_state(
                {"discriminator": d_opt_state, "generator": g_opt_state},
                self._opt_tree)
            g_opt_state = restored["generator"]
            d_opt_state = restored["discriminator"]
            self._opt_tree = None
        g_opt_state = trainer._put_replicated(g_opt_state, mesh)
        d_opt_state = trainer._put_replicated(d_opt_state, mesh)

        history: Dict[str, List[float]] = {"d_loss": [], "g_loss": []}
        period = self.d_steps + self.g_steps
        it = 0
        last_saved = -1
        while it < end_iteration:
            try:
                real_b = next(real_iter)[0]
            except StopIteration:
                real_iter = trainer.iter_batches(real_data, None, batch_size,
                                                 shuffle=True, seed=base_seed + it)
                real_b = next(real_iter)[0]
            noise_b = noise_fn(batch_size, base_seed + 1 + it)
            real_b = trainer._put_batch(real_b, mesh)
            noise_b = trainer._put_batch(noise_b, mesh)
            rng, step_rng = jax.random.split(rng)

            if self._counter % period < self.d_steps:
                d_params, d_opt_state, l = d_step(
                    g_params, d_params, d_opt_state, noise_b, real_b, step_rng)
                history["d_loss"].append(float(l))
            else:
                g_params, g_opt_state, l = g_step(
                    g_params, g_opt_state, d_params, noise_b, step_rng)
                history["g_loss"].append(float(l))
            self._counter += 1
            it += 1
            # versions use the CUMULATIVE counter so continued training
            # never writes a lower version than an earlier run
            if (checkpoint_every and self.model_dir
                    and self._counter % checkpoint_every == 0):
                self._snapshot(g_params, d_params, g_opt_state, d_opt_state)
                last_saved = self._counter

        self.g_params = jax.device_get(g_params)
        self.d_params = jax.device_get(d_params)
        self.generator.params = self.g_params
        self.discriminator.params = self.d_params
        if self.model_dir and last_saved != self._counter:
            self._snapshot(g_params, d_params, g_opt_state, d_opt_state)
        return history

    def _snapshot(self, g_params, d_params, g_opt_state, d_opt_state):
        if self._ckpt_mgr is None:
            self._ckpt_mgr = CheckpointManager(self.model_dir,
                                               optim_name="gan")
        self._ckpt_mgr.save(self._counter,
                            {"generator": jax.device_get(g_params),
                             "discriminator": jax.device_get(d_params)},
                            opt_state={"generator": g_opt_state,
                                       "discriminator": d_opt_state},
                            extra={"iteration": self._counter})

    def restore(self, path: Optional[str] = None,
                version: Optional[int] = None) -> "GANEstimator":
        path = path or self.model_dir
        if path is None or latest_checkpoint(path) is None:
            raise FileNotFoundError(f"No GAN checkpoint under {path!r}")
        params, opt_tree, meta = load_checkpoint(path, version,
                                                 optim_name="gan")
        if opt_tree is not None:
            # mu/nu subtrees are params-shaped dicts keyed by the SAVED
            # instance's auto layer names — remap them like the params
            opt_tree = {
                "generator": _remap_opt_tree(self.generator,
                                             opt_tree["generator"]),
                "discriminator": _remap_opt_tree(self.discriminator,
                                                 opt_tree["discriminator"]),
            }
        # remap saved auto-generated layer names onto this instance's names
        self.g_params = self.generator._remap_loaded(params["generator"])
        self.d_params = self.discriminator._remap_loaded(params["discriminator"])
        self.generator.params = self.g_params
        self.discriminator.params = self.d_params
        # resume the D/G alternation where the snapshot left off; optimizer
        # moments are poured back into fresh opt.init state on next train()
        self._counter = int(meta.get("iteration", 0))
        self._opt_tree = opt_tree
        return self

    # -- inference ---------------------------------------------------------
    def generate(self, noise: np.ndarray) -> np.ndarray:
        """Run the trained generator on a batch of noise."""
        if self.g_params is None:
            raise RuntimeError("GANEstimator.generate before train/restore")
        out = self.generator.apply(self.g_params, jnp.asarray(noise),
                                   training=False)
        return np.asarray(out)
