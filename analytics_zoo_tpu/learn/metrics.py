"""Orca metric names (`pyzoo/zoo/orca/learn/metrics.py:26-156`) — thin
wrappers over `analytics_zoo_tpu.ops.metrics` keeping the exact class-name
surface users import from `zoo.orca.learn.metrics`."""

from analytics_zoo_tpu.ops.metrics import (  # noqa: F401
    AUC, MAE, MSE, Accuracy, BinaryAccuracy, CategoricalAccuracy,
    SparseCategoricalAccuracy, Top5Accuracy)

__all__ = ["Accuracy", "SparseCategoricalAccuracy", "CategoricalAccuracy",
           "BinaryAccuracy", "Top5Accuracy", "MAE", "MSE", "AUC"]
