"""Learning-rate schedulers.

Reference: `pyzoo/zoo/orca/learn/optimizers/schedule.py:19-218` (Poly,
Exponential, Step, Default, Plateau, Warmup, MultiStep,
SequentialSchedule) — there thin wrappers over BigDL SGD schedules; here
each produces an `optax.Schedule` (a pure fn of the step counter) via
`make(base_lr)`, so the schedule compiles into the update. `Plateau` is
inherently feedback-driven (watches a validation metric), so it stays a
host-side object with `on_metric()` — the same place the reference runs it
(driver side, between epochs).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np


class Scheduler:
    def make(self, base_lr: float) -> Callable:
        raise NotImplementedError


class Default(Scheduler):
    """`schedule.py:89`: constant lr."""

    def make(self, base_lr):
        return lambda step: base_lr


class Poly(Scheduler):
    """`schedule.py:26`: lr · (1 − iter/max_iteration)^power."""

    def __init__(self, power: float, max_iteration: int):
        self.power = power
        self.max_iteration = max_iteration

    def make(self, base_lr):
        def fn(step):
            frac = jnp.clip(step / self.max_iteration, 0.0, 1.0)
            return base_lr * (1.0 - frac) ** self.power
        return fn


class Exponential(Scheduler):
    """`schedule.py:47`: lr · decay_rate^(iter/decay_step)."""

    def __init__(self, decay_step: int, decay_rate: float,
                 stair_case: bool = False):
        self.decay_step = decay_step
        self.decay_rate = decay_rate
        self.stair_case = stair_case

    def make(self, base_lr):
        def fn(step):
            p = step / self.decay_step
            if self.stair_case:
                p = jnp.floor(p)
            return base_lr * self.decay_rate ** p
        return fn


class Step(Scheduler):
    """`schedule.py:67`: lr · gamma^floor(iter/step_size)."""

    def __init__(self, step_size: int, gamma: float):
        self.step_size = step_size
        self.gamma = gamma

    def make(self, base_lr):
        return lambda step: base_lr * self.gamma ** jnp.floor(
            step / self.step_size)


class MultiStep(Scheduler):
    """`schedule.py:167`: gamma applied at each milestone."""

    def __init__(self, step_sizes: Sequence[int], gamma: float):
        self.step_sizes = list(step_sizes)
        self.gamma = gamma

    def make(self, base_lr):
        milestones = jnp.asarray(self.step_sizes)

        def fn(step):
            n = jnp.sum(step >= milestones)
            return base_lr * self.gamma ** n
        return fn


class Warmup(Scheduler):
    """`schedule.py:147`: lr grows by `delta` per iteration (used as a
    SequentialSchedule stage)."""

    def __init__(self, delta: float):
        self.delta = delta

    def make(self, base_lr):
        return lambda step: base_lr + self.delta * step


class SequentialSchedule(Scheduler):
    """`schedule.py:188`: chain stages, each active for `max_iteration`
    steps. `add(scheduler, max_iteration)` mirrors the reference; each
    stage's step counter restarts at 0."""

    def __init__(self, iteration_per_epoch: int = 1):
        self.iteration_per_epoch = iteration_per_epoch
        self.stages: List[Tuple[Scheduler, int]] = []

    def add(self, scheduler: Scheduler, max_iteration: int
            ) -> "SequentialSchedule":
        self.stages.append((scheduler, max_iteration))
        return self

    def make(self, base_lr):
        if not self.stages:
            return lambda step: base_lr
        fns = [s.make(base_lr) for s, _ in self.stages]
        bounds = np.cumsum([m for _, m in self.stages])

        def fn(step):
            out = fns[-1](step - (bounds[-2] if len(bounds) > 1 else 0))
            for i in range(len(fns) - 2, -1, -1):
                start = bounds[i - 1] if i > 0 else 0
                out = jnp.where(step < bounds[i], fns[i](step - start), out)
            return out
        return fn


class Plateau:
    """`schedule.py:109`: reduce lr when a monitored metric stops
    improving. Host-side: call `on_metric(value)` after each epoch/eval;
    read `.lr` for the current value (feed via optax.inject_hyperparams or
    rebuild the optimizer — the reference likewise mutates driver-side)."""

    def __init__(self, monitor: str = "score", factor: float = 0.1,
                 patience: int = 10, mode: str = "min",
                 epsilon: float = 1e-4, cooldown: int = 0,
                 min_lr: float = 0.0, base_lr: float = 0.01):
        if mode not in ("min", "max"):
            raise ValueError(f"Unsupported mode: {mode}")
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.mode = mode
        self.epsilon = epsilon
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.lr = base_lr
        self._best: Optional[float] = None
        self._wait = 0
        self._cooling = 0

    def _improved(self, value: float) -> bool:
        if self._best is None:
            return True
        if self.mode == "min":
            return value < self._best - self.epsilon
        return value > self._best + self.epsilon

    def on_metric(self, value: float) -> float:
        """Update state with the latest monitored value; returns lr."""
        if self._cooling > 0:
            self._cooling -= 1
            self._wait = 0
        if self._improved(value):
            self._best = value
            self._wait = 0
        elif self._cooling == 0:
            self._wait += 1
            if self._wait > self.patience:
                self.lr = max(self.lr * self.factor, self.min_lr)
                self._cooling = self.cooldown
                self._wait = 0
        return self.lr
