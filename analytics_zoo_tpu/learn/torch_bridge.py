"""torch.nn → native layer conversion for `Estimator.from_torch`.

The reference runs PyTorch *inside* executor JVMs through JEP, flattening
weights into a JVM tensor for allreduce (`pipeline/api/net/TorchModel.scala:
34-77`, `TorchOptim.scala:41`). On TPU a torch module cannot execute in the
hot path — the model must lower to XLA — so the bridge converts supported
architectures (module tree + trained weights) into the native layer library
once, after which training/inference is pure jax. Weight layout notes:

- torch Linear stores [out, in] → transposed to [in, out] kernels;
- torch Conv2d stores [out, in, kh, kw] (NCHW) → HWIO kernels, NHWC layout
  (inputs are transposed by the inserted dim_ordering="th" conv);
- LSTM/GRU gate order is remapped (torch i,f,g,o == keras i,f,c,o; torch GRU
  r,z,n → keras z,r,h).
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.keras.engine import Sequential


def convert_torch_module(module) -> Sequential:
    import torch.nn as nn

    layers = _convert(module)
    model = Sequential(layers)
    return model


def _convert(module) -> List:
    import torch.nn as nn

    if isinstance(module, nn.Sequential):
        out = []
        for child in module:
            out.extend(_convert(child))
        return out

    if isinstance(module, nn.Linear):
        layer = L.Dense(module.out_features,
                        use_bias=module.bias is not None,
                        input_shape=(module.in_features,))
        w = module.weight.detach().numpy().T.copy()
        params = {"kernel": w}
        if module.bias is not None:
            params["bias"] = module.bias.detach().numpy().copy()
        return [_with_weights(layer, params)]

    if isinstance(module, nn.Conv2d):
        # 'same' is only equivalent to torch's symmetric padding when
        # pad == k//2 with odd kernels and stride 1
        pad = module.padding
        if pad == "same":
            same = True
        elif pad in ((0, 0), 0, "valid"):
            same = False
        elif (isinstance(pad, tuple)
              and all(p == k // 2 and k % 2 == 1
                      for p, k in zip(pad, module.kernel_size))
              and tuple(module.stride) == (1, 1)):
            same = True
        else:
            raise ValueError(
                f"Unsupported Conv2d padding {pad} for kernel "
                f"{module.kernel_size} stride {module.stride}: only valid "
                "(0) or exact-same (pad=k//2, odd k, stride 1) convert")
        layer = L.Convolution2D(
            module.out_channels, module.kernel_size[0], module.kernel_size[1],
            subsample=module.stride, border_mode="same" if same else "valid",
            dim_ordering="th", use_bias=module.bias is not None,
            groups=module.groups)
        w = module.weight.detach().numpy()            # [O, I/groups, H, W]
        params = {"kernel": np.transpose(w, (2, 3, 1, 0)).copy()}  # HWIO
        if module.bias is not None:
            params["bias"] = module.bias.detach().numpy().copy()
        return [_with_weights(layer, params)]

    if isinstance(module, (nn.MaxPool2d, nn.AvgPool2d)):
        if module.padding not in (0, (0, 0)):
            raise ValueError("Pooling with padding does not convert")
        if getattr(module, "ceil_mode", False):
            raise ValueError("Pooling with ceil_mode does not convert")
        if getattr(module, "dilation", 1) not in (1, (1, 1)):
            raise ValueError("Pooling with dilation does not convert")
        ks = module.kernel_size if isinstance(module.kernel_size, tuple) \
            else (module.kernel_size,) * 2
        st = module.stride if isinstance(module.stride, tuple) \
            else (module.stride,) * 2 if module.stride else ks
        cls = L.MaxPooling2D if isinstance(module, nn.MaxPool2d) \
            else L.AveragePooling2D
        return [cls(pool_size=ks, strides=st, dim_ordering="th")]

    if isinstance(module, nn.Flatten):
        return [L.Flatten()]

    if isinstance(module, nn.Dropout):
        return [L.Dropout(module.p)]

    if isinstance(module, (nn.BatchNorm1d, nn.BatchNorm2d)):
        axis = 1 if isinstance(module, nn.BatchNorm2d) else -1
        layer = L.BatchNormalization(epsilon=module.eps,
                                     momentum=1.0 - (module.momentum or 0.1),
                                     axis=axis)
        C = module.num_features
        params = {
            "gamma": (module.weight.detach().numpy().copy()
                      if module.weight is not None
                      else np.ones(C, np.float32)),
            "beta": (module.bias.detach().numpy().copy()
                     if module.bias is not None
                     else np.zeros(C, np.float32)),
            "moving_mean": (module.running_mean.detach().numpy().copy()
                            if module.running_mean is not None
                            else np.zeros(C, np.float32)),
            "moving_var": (module.running_var.detach().numpy().copy()
                           if module.running_var is not None
                           else np.ones(C, np.float32)),
        }
        return [_with_weights(layer, params)]

    if isinstance(module, nn.Embedding):
        layer = L.Embedding(module.num_embeddings, module.embedding_dim)
        return [_with_weights(
            layer, {"embeddings": module.weight.detach().numpy().copy()})]

    act_map = {
        "ReLU": "relu", "Tanh": "tanh", "Sigmoid": "sigmoid",
        "Softmax": "softmax", "GELU": "gelu", "SiLU": "silu", "ELU": "elu",
        "LogSoftmax": "log_softmax", "Softplus": "softplus",
    }
    name = type(module).__name__
    if name in act_map:
        return [L.Activation(act_map[name])]

    if isinstance(module, (nn.LSTM, nn.GRU)):
        return [_convert_rnn(module)]

    raise ValueError(
        f"Unsupported torch module for conversion: {type(module).__name__}. "
        "Supported: Sequential, Linear, Conv2d, pooling, Flatten, Dropout, "
        "BatchNorm1d/2d, Embedding, common activations, LSTM, GRU")


def _convert_rnn(module):
    import torch.nn as nn

    if module.num_layers != 1 or module.bidirectional:
        raise ValueError("Only single-layer unidirectional LSTM/GRU convert")
    if not module.batch_first:
        raise ValueError("Only batch_first=True RNNs convert (TPU batches "
                         "lead)")
    hidden = module.hidden_size
    w_ih = module.weight_ih_l0.detach().numpy()   # [G*H, in]
    w_hh = module.weight_hh_l0.detach().numpy()   # [G*H, H]
    b_ih = module.bias_ih_l0.detach().numpy()     # [G*H]
    b_hh = module.bias_hh_l0.detach().numpy()

    if isinstance(module, nn.LSTM):
        # torch gates i,f,g,o ; keras order i,f,c(=g),o → identical. torch
        # uses exact sigmoid, not Keras' default hard_sigmoid. The two bias
        # vectors always add.
        layer = L.LSTM(hidden, inner_activation="sigmoid",
                       return_sequences=False)
        perm = list(range(4))
    else:
        # torch GRU gates r,z,n ; keras order z,r,h. torch applies b_hh
        # inside the reset product (n-gate) → reset_after carries it
        # separately.
        layer = L.GRU(hidden, inner_activation="sigmoid",
                      return_sequences=False, reset_after=True)
        perm = [1, 0, 2]

    def reorder(w):
        blocks = np.split(w, len(perm), axis=0)
        return np.concatenate([blocks[p] for p in perm], axis=0)

    params = {"kernel": reorder(w_ih).T.copy(),
              "recurrent": reorder(w_hh).T.copy()}
    if isinstance(module, nn.LSTM):
        params["bias"] = reorder((b_ih + b_hh)[:, None])[:, 0].copy()
    else:
        params["bias"] = reorder(b_ih[:, None])[:, 0].copy()
        params["recurrent_bias"] = reorder(b_hh[:, None])[:, 0].copy()
    return _with_weights(layer, params)


def _with_weights(layer, params):
    """Pin converted weights: build() returns them instead of random init."""
    pinned = {k: np.asarray(v, np.float32) for k, v in params.items()}

    original_build = layer.build

    def build(rng, input_shape):
        built = original_build(rng, input_shape)
        for k, v in pinned.items():
            if k in built and np.shape(built[k]) != np.shape(v):
                raise ValueError(
                    f"{layer.name}.{k}: converted weight shape {np.shape(v)} "
                    f"!= expected {np.shape(built[k])}")
        built.update(pinned)
        return built

    layer.build = build
    return layer
