"""torch.nn → native layer conversion for `Estimator.from_torch`.

The reference runs PyTorch *inside* executor JVMs through JEP, flattening
weights into a JVM tensor for allreduce (`pipeline/api/net/TorchModel.scala:
34-77`, `TorchOptim.scala:41`). On TPU a torch module cannot execute in the
hot path — the model must lower to XLA — so the bridge converts supported
architectures (module tree + trained weights) into the native layer library
once, after which training/inference is pure jax. Weight layout notes:

- torch Linear stores [out, in] → transposed to [in, out] kernels;
- torch Conv2d stores [out, in, kh, kw] (NCHW) → HWIO kernels, NHWC layout
  (inputs are transposed by the inserted dim_ordering="th" conv);
- LSTM/GRU gate order is remapped (torch i,f,g,o == keras i,f,c,o; torch GRU
  r,z,n → keras z,r,h).
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from analytics_zoo_tpu.keras import layers as L
from analytics_zoo_tpu.keras.engine import Sequential


def convert_torch_module(module) -> Sequential:
    import torch.nn as nn

    layers = _convert(module)
    model = Sequential(layers)
    return model


def _convert(module) -> List:
    import torch.nn as nn

    if isinstance(module, nn.Sequential):
        out = []
        for child in module:
            out.extend(_convert(child))
        return out

    if isinstance(module, nn.Linear):
        layer = L.Dense(module.out_features,
                        use_bias=module.bias is not None,
                        input_shape=(module.in_features,))
        w = module.weight.detach().numpy().T.copy()
        params = {"kernel": w}
        if module.bias is not None:
            params["bias"] = module.bias.detach().numpy().copy()
        return [_with_weights(layer, params)]

    if isinstance(module, nn.Conv2d):
        # 'same' is only equivalent to torch's symmetric padding when
        # pad == k//2 with odd kernels and stride 1
        pad = module.padding
        if pad == "same":
            same = True
        elif pad in ((0, 0), 0, "valid"):
            same = False
        elif (isinstance(pad, tuple)
              and all(p == k // 2 and k % 2 == 1
                      for p, k in zip(pad, module.kernel_size))
              and tuple(module.stride) == (1, 1)):
            same = True
        else:
            raise ValueError(
                f"Unsupported Conv2d padding {pad} for kernel "
                f"{module.kernel_size} stride {module.stride}: only valid "
                "(0) or exact-same (pad=k//2, odd k, stride 1) convert")
        layer = L.Convolution2D(
            module.out_channels, module.kernel_size[0], module.kernel_size[1],
            subsample=module.stride, border_mode="same" if same else "valid",
            dim_ordering="th", use_bias=module.bias is not None,
            groups=module.groups)
        w = module.weight.detach().numpy()            # [O, I/groups, H, W]
        params = {"kernel": np.transpose(w, (2, 3, 1, 0)).copy()}  # HWIO
        if module.bias is not None:
            params["bias"] = module.bias.detach().numpy().copy()
        return [_with_weights(layer, params)]

    if isinstance(module, (nn.MaxPool2d, nn.AvgPool2d)):
        if module.padding not in (0, (0, 0)):
            raise ValueError("Pooling with padding does not convert")
        if getattr(module, "ceil_mode", False):
            raise ValueError("Pooling with ceil_mode does not convert")
        if getattr(module, "dilation", 1) not in (1, (1, 1)):
            raise ValueError("Pooling with dilation does not convert")
        ks = module.kernel_size if isinstance(module.kernel_size, tuple) \
            else (module.kernel_size,) * 2
        st = module.stride if isinstance(module.stride, tuple) \
            else (module.stride,) * 2 if module.stride else ks
        cls = L.MaxPooling2D if isinstance(module, nn.MaxPool2d) \
            else L.AveragePooling2D
        return [cls(pool_size=ks, strides=st, dim_ordering="th")]

    if isinstance(module, nn.Flatten):
        return [L.Flatten()]

    if isinstance(module, nn.Dropout):
        return [L.Dropout(module.p)]

    if isinstance(module, (nn.BatchNorm1d, nn.BatchNorm2d)):
        axis = 1 if isinstance(module, nn.BatchNorm2d) else -1
        layer = L.BatchNormalization(epsilon=module.eps,
                                     momentum=1.0 - (module.momentum or 0.1),
                                     axis=axis)
        C = module.num_features
        params = {
            "gamma": (module.weight.detach().numpy().copy()
                      if module.weight is not None
                      else np.ones(C, np.float32)),
            "beta": (module.bias.detach().numpy().copy()
                     if module.bias is not None
                     else np.zeros(C, np.float32)),
            "moving_mean": (module.running_mean.detach().numpy().copy()
                            if module.running_mean is not None
                            else np.zeros(C, np.float32)),
            "moving_var": (module.running_var.detach().numpy().copy()
                           if module.running_var is not None
                           else np.ones(C, np.float32)),
        }
        return [_with_weights(layer, params)]

    if isinstance(module, nn.Embedding):
        layer = L.Embedding(module.num_embeddings, module.embedding_dim)
        return [_with_weights(
            layer, {"embeddings": module.weight.detach().numpy().copy()})]

    act_map = {
        "ReLU": "relu", "Tanh": "tanh", "Sigmoid": "sigmoid",
        "Softmax": "softmax", "GELU": "gelu", "SiLU": "silu", "ELU": "elu",
        "LogSoftmax": "log_softmax", "Softplus": "softplus",
    }
    name = type(module).__name__
    if name in act_map:
        return [L.Activation(act_map[name])]

    if isinstance(module, (nn.LSTM, nn.GRU)):
        return [_convert_rnn(module)]

    raise ValueError(
        f"Unsupported torch module for conversion: {type(module).__name__}. "
        "Supported: Sequential, Linear, Conv2d, pooling, Flatten, Dropout, "
        "BatchNorm1d/2d, Embedding, common activations, LSTM, GRU")


def _convert_rnn(module):
    import torch.nn as nn

    if module.num_layers != 1 or module.bidirectional:
        raise ValueError("Only single-layer unidirectional LSTM/GRU convert")
    if not module.batch_first:
        raise ValueError("Only batch_first=True RNNs convert (TPU batches "
                         "lead)")
    hidden = module.hidden_size
    w_ih = module.weight_ih_l0.detach().numpy()   # [G*H, in]
    w_hh = module.weight_hh_l0.detach().numpy()   # [G*H, H]
    b_ih = module.bias_ih_l0.detach().numpy()     # [G*H]
    b_hh = module.bias_hh_l0.detach().numpy()

    if isinstance(module, nn.LSTM):
        # torch gates i,f,g,o ; keras order i,f,c(=g),o → identical. torch
        # uses exact sigmoid, not Keras' default hard_sigmoid. The two bias
        # vectors always add.
        layer = L.LSTM(hidden, inner_activation="sigmoid",
                       return_sequences=False)
        perm = list(range(4))
    else:
        # torch GRU gates r,z,n ; keras order z,r,h. torch applies b_hh
        # inside the reset product (n-gate) → reset_after carries it
        # separately.
        layer = L.GRU(hidden, inner_activation="sigmoid",
                      return_sequences=False, reset_after=True)
        perm = [1, 0, 2]

    def reorder(w):
        blocks = np.split(w, len(perm), axis=0)
        return np.concatenate([blocks[p] for p in perm], axis=0)

    params = {"kernel": reorder(w_ih).T.copy(),
              "recurrent": reorder(w_hh).T.copy()}
    if isinstance(module, nn.LSTM):
        params["bias"] = reorder((b_ih + b_hh)[:, None])[:, 0].copy()
    else:
        params["bias"] = reorder(b_ih[:, None])[:, 0].copy()
        params["recurrent_bias"] = reorder(b_hh[:, None])[:, 0].copy()
    return _with_weights(layer, params)


# ---------------------------------------------------------------------------
# Torch loss interop (`pipeline/api/net/TorchLoss.scala`): the reference
# pickles a torch loss module and executes it in-JVM via JEP per minibatch.
# On TPU the criterion must lower to XLA, so known torch losses convert to
# equivalent jax functions once; arbitrary torch callables cannot run in the
# jit hot path and are rejected with guidance.
# ---------------------------------------------------------------------------
def convert_torch_loss(loss) -> Any:
    """torch.nn loss module → `loss(y_true, y_pred)` jax callable.

    Handles the reduction flag ('mean'/'sum'); torch's (input, target)
    argument order is flipped to the Keras (y_true, y_pred) contract.
    """
    import jax
    import jax.numpy as jnp
    import torch.nn as nn

    reduction = getattr(loss, "reduction", "mean")
    if reduction not in ("mean", "sum"):
        raise ValueError(
            f"torch loss reduction {reduction!r} does not convert; use "
            "'mean' or 'sum'")

    def red(v):
        return jnp.mean(v) if reduction == "mean" else jnp.sum(v)

    if isinstance(loss, nn.MSELoss):
        return lambda yt, yp: red(jnp.square(yp - yt))
    if isinstance(loss, nn.L1Loss):
        return lambda yt, yp: red(jnp.abs(yp - yt))
    if isinstance(loss, (nn.SmoothL1Loss, nn.HuberLoss)):
        beta = float(getattr(loss, "beta", getattr(loss, "delta", 1.0)))

        def smooth_l1(yt, yp, beta=beta):
            d = jnp.abs(yp - yt)
            quad = 0.5 * d * d / beta
            lin = d - 0.5 * beta
            v = jnp.where(d < beta, quad, lin)
            if isinstance(loss, nn.HuberLoss):
                v = v * beta  # Huber = beta * SmoothL1(beta=delta)
            return red(v)
        return smooth_l1
    if isinstance(loss, (nn.CrossEntropyLoss, nn.NLLLoss)):
        # logits (CE) / log-probs (NLL) input + int class targets; honors
        # class weight, ignore_index, and (CE) label_smoothing — mean
        # reduction divides by the summed weight of non-ignored rows,
        # exactly torch's contract
        weight = (loss.weight.detach().numpy().copy()
                  if loss.weight is not None else None)
        ignore_index = int(loss.ignore_index)
        smoothing = float(getattr(loss, "label_smoothing", 0.0))
        is_ce = isinstance(loss, nn.CrossEntropyLoss)

        def ce_nll(yt, yp):
            if yp.ndim > 2:
                # torch K-dim form (N, C, d1..dk): class dim is 1 — move
                # it last and flatten to rows
                yp = jnp.moveaxis(yp, 1, -1).reshape(-1, yp.shape[1])
            logp = jax.nn.log_softmax(yp, axis=-1) if is_ce else yp
            yt_idx = jnp.reshape(yt, (-1,)).astype(jnp.int32)
            valid = yt_idx != ignore_index
            safe_idx = jnp.where(valid, yt_idx, 0)
            picked = jnp.take_along_axis(
                logp, safe_idx[:, None], axis=-1)[:, 0]
            wvec = jnp.asarray(weight, logp.dtype) if weight is not None \
                else jnp.ones((logp.shape[-1],), logp.dtype)
            w = wvec[safe_idx]
            # torch: per-class weights apply INSIDE the smoothing term,
            # while mean reduction divides by the target-class weights
            row = (1.0 - smoothing) * w * picked
            if smoothing:
                row = row + smoothing * jnp.mean(wvec * logp, axis=-1)
            row = jnp.where(valid, row, 0.0)
            w = jnp.where(valid, w, 0.0)
            total = jnp.sum(-row)
            if reduction == "sum":
                return total
            return total / jnp.maximum(jnp.sum(w), 1e-12)
        return ce_nll
    if isinstance(loss, nn.BCEWithLogitsLoss):
        if loss.weight is not None:
            raise ValueError(
                "BCEWithLogitsLoss per-sample weight does not convert")
        pos_weight = (loss.pos_weight.detach().numpy().copy()
                      if loss.pos_weight is not None else None)

        def bce_logits(yt, yp):
            logsig = -jnp.log1p(jnp.exp(-jnp.abs(yp))) \
                + jnp.minimum(yp, 0)          # log sigmoid(yp), stable
            logsig_neg = logsig - yp          # log sigmoid(-yp)
            pw = jnp.asarray(pos_weight, yp.dtype) if pos_weight is not None \
                else 1.0
            return red(-(pw * yt * logsig + (1 - yt) * logsig_neg))
        return bce_logits
    if isinstance(loss, nn.BCELoss):
        if loss.weight is not None:
            raise ValueError("BCELoss per-sample weight does not convert")

        def bce(yt, yp):
            eps = 1e-7
            yp = jnp.clip(yp, eps, 1 - eps)
            return red(-(yt * jnp.log(yp) + (1 - yt) * jnp.log1p(-yp)))
        return bce
    if isinstance(loss, nn.KLDivLoss):
        # torch: input is log-probs; target is probs, or log-probs when
        # log_target=True
        log_target = bool(getattr(loss, "log_target", False))

        def kld(yt, yp):
            if log_target:
                return red(jnp.exp(yt) * (yt - yp))
            return red(yt * (jnp.log(jnp.clip(yt, 1e-7, None)) - yp))
        return kld
    raise ValueError(
        f"Unsupported torch loss {type(loss).__name__}: it cannot execute "
        "inside the XLA hot path. Supported: MSELoss, L1Loss, SmoothL1Loss, "
        "HuberLoss, CrossEntropyLoss, NLLLoss, BCELoss, BCEWithLogitsLoss, "
        "KLDivLoss — or pass a pure jax fn(y_true, y_pred)")


# ---------------------------------------------------------------------------
# Torch optimizer / LR-scheduler interop (`TorchOptim.scala:41-60`): the
# reference deserializes a torch optimizer or _LRScheduler per worker and
# applies it to the allreduced flat weights, with epoch-based decay types
# mapping trigger state onto scheduler steps. Here the hyperparameters map
# onto optax transforms; schedulers become optax schedules (per-epoch
# schedulers scale by steps_per_epoch like the reference's EpochDecay).
# ---------------------------------------------------------------------------
def convert_torch_optimizer(opt, scheduler=None, steps_per_epoch: int = 1):
    """torch.optim.Optimizer (+ optional torch LR scheduler) → optax.

    Hyperparameters come from the optimizer's first param group (the
    reference also applies one optimizer to the single flat weight tensor).
    `steps_per_epoch` converts per-epoch schedulers (StepLR etc. stepped
    once per epoch, the torch idiom) into per-step optax schedules.
    """
    import optax
    import torch.optim as topt

    g = opt.param_groups[0] if getattr(opt, "param_groups", None) \
        else opt.defaults
    if g.get("maximize"):
        raise ValueError("maximize=True does not convert (negate your "
                         "loss instead)")
    lr = float(g["lr"])
    if scheduler is not None and getattr(scheduler, "base_lrs", None):
        # param_groups carry the CURRENT (possibly already-decayed) lr;
        # the schedule must start from the scheduler's base lr
        lr = float(scheduler.base_lrs[0])
    wd = float(g.get("weight_decay", 0.0) or 0.0)
    sched = _convert_torch_scheduler(scheduler, lr, steps_per_epoch) \
        if scheduler is not None else lr

    if isinstance(opt, topt.SGD):
        momentum = float(g.get("momentum", 0.0) or 0.0)
        if float(g.get("dampening", 0.0) or 0.0) != 0.0:
            raise ValueError("SGD dampening != 0 does not convert to optax")
        tx = optax.sgd(sched, momentum=momentum or None,
                       nesterov=bool(g.get("nesterov", False)))
    elif isinstance(opt, topt.AdamW):
        b1, b2 = g.get("betas", (0.9, 0.999))
        if g.get("amsgrad"):
            raise ValueError("AdamW amsgrad=True does not convert")
        tx = optax.adamw(sched, b1=float(b1), b2=float(b2),
                         eps=float(g.get("eps", 1e-8)), weight_decay=wd)
        wd = 0.0  # decoupled decay handled inside adamw
    elif isinstance(opt, topt.Adam):
        b1, b2 = g.get("betas", (0.9, 0.999))
        if g.get("amsgrad"):
            # optax.amsgrad orders bias correction differently from torch —
            # trajectories diverge, so refuse rather than silently drift
            raise ValueError("Adam amsgrad=True does not convert exactly")
        tx = optax.adam(sched, b1=float(b1), b2=float(b2),
                        eps=float(g.get("eps", 1e-8)))
    elif isinstance(opt, topt.RMSprop):
        tx = optax.rmsprop(sched, decay=float(g.get("alpha", 0.99)),
                           eps=float(g.get("eps", 1e-8)),
                           centered=bool(g.get("centered", False)),
                           momentum=float(g.get("momentum", 0.0) or 0.0))
    elif isinstance(opt, topt.Adagrad):
        if float(g.get("lr_decay", 0.0) or 0.0) != 0.0:
            raise ValueError("Adagrad lr_decay does not convert")
        tx = optax.adagrad(
            sched, eps=float(g.get("eps", 1e-10)),
            initial_accumulator_value=float(
                g.get("initial_accumulator_value", 0.0)))
    elif isinstance(opt, topt.Adadelta):
        tx = optax.adadelta(sched, rho=float(g.get("rho", 0.9)),
                            eps=float(g.get("eps", 1e-6)))
    else:
        raise ValueError(
            f"Unsupported torch optimizer {type(opt).__name__}. Supported: "
            "SGD, Adam, AdamW, RMSprop, Adagrad, Adadelta — or pass an "
            "optax transform directly")
    if wd and not isinstance(opt, topt.AdamW):
        # torch couples weight_decay into the gradient (L2), same here
        tx = optax.chain(optax.add_decayed_weights(wd), tx)
    return tx


def _convert_torch_scheduler(scheduler, base_lr: float,
                             steps_per_epoch: int):
    """torch lr_scheduler → optax schedule over optimizer steps."""
    import numpy as _np
    from torch.optim import lr_scheduler as tls

    spe = max(1, int(steps_per_epoch))
    if isinstance(scheduler, tls.StepLR):
        k, gamma = scheduler.step_size, scheduler.gamma

        def step_lr(count):
            epoch = count // spe
            return base_lr * gamma ** (epoch // k)
        return step_lr
    if isinstance(scheduler, tls.MultiStepLR):
        milestones = sorted(scheduler.milestones)
        gamma = scheduler.gamma

        def multistep(count):
            epoch = count // spe
            n = sum((epoch >= m) for m in _np.asarray(milestones))
            return base_lr * gamma ** n
        return multistep
    if isinstance(scheduler, tls.ExponentialLR):
        gamma = scheduler.gamma

        def exp_lr(count):
            return base_lr * gamma ** (count // spe)
        return exp_lr
    if isinstance(scheduler, tls.CosineAnnealingLR):
        # torch's closed form (continues the cosine past T_max rather than
        # clamping like optax.cosine_decay_schedule)
        t_max, eta_min = scheduler.T_max, scheduler.eta_min

        def cosine(count):
            import jax.numpy as jnp
            epoch = count // spe
            return eta_min + (base_lr - eta_min) * 0.5 * (
                1.0 + jnp.cos(jnp.pi * epoch / t_max))
        return cosine
    raise ValueError(
        f"Unsupported torch LR scheduler {type(scheduler).__name__}. "
        "Supported: StepLR, MultiStepLR, ExponentialLR, CosineAnnealingLR "
        "— or pass an optax schedule directly")


def _with_weights(layer, params):
    """Pin converted weights: build() returns them instead of random init."""
    pinned = {k: np.asarray(v, np.float32) for k, v in params.items()}

    original_build = layer.build

    def build(rng, input_shape):
        built = original_build(rng, input_shape)
        for k, v in pinned.items():
            if k in built and np.shape(built[k]) != np.shape(v):
                raise ValueError(
                    f"{layer.name}.{k}: converted weight shape {np.shape(v)} "
                    f"!= expected {np.shape(built[k])}")
        built.update(pinned)
        return built

    layer.build = build
    return layer
