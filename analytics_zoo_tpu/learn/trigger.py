"""Orca trigger names (`pyzoo/zoo/orca/learn/trigger.py:76`) — re-exports of
the shared trigger family."""

from analytics_zoo_tpu.common.triggers import (  # noqa: F401
    EveryEpoch, MaxEpoch, MaxIteration, MaxScore, MinLoss, SeveralIteration)

__all__ = ["EveryEpoch", "SeveralIteration", "MaxEpoch", "MaxIteration",
           "MinLoss", "MaxScore"]
