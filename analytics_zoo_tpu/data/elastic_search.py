"""Elasticsearch connector.

Reference: `pyzoo/zoo/orca/data/elastic_search.py:22-94` (`read_df`,
`write_df`, `read_rdd` over the ES-Hadoop Spark connector). Here the
official `elasticsearch` python client plays that role; the environment
does not bundle it, so every entry point degrades to a clear ImportError
(same shape as the reference, which needs the es-hadoop jar on the
classpath).
"""

from __future__ import annotations

from typing import Dict, Optional

import pandas as pd


def _client(es_config: Dict):
    try:
        from elasticsearch import Elasticsearch
    except ImportError as e:
        raise ImportError(
            "elastic_search needs the `elasticsearch` python package "
            "(the reference likewise needs the es-hadoop connector jar)"
        ) from e
    hosts = es_config.get("hosts") or [
        f"http://{es_config.get('host', 'localhost')}:"
        f"{es_config.get('port', 9200)}"]
    kwargs = {}
    if es_config.get("user"):
        kwargs["basic_auth"] = (es_config["user"],
                                es_config.get("password", ""))
    return Elasticsearch(hosts, **kwargs)


class elastic_search:  # noqa: N801 — reference spelling
    """`elastic_search.read_df/write_df` (elastic_search.py:32,77)."""

    @staticmethod
    def read_df(es_config: Dict, es_resource: str,
                query: Optional[Dict] = None,
                size: int = 10000) -> pd.DataFrame:
        es = _client(es_config)
        body = {"query": query or {"match_all": {}}, "size": size}
        res = es.search(index=es_resource, body=body)
        rows = [hit["_source"] for hit in res["hits"]["hits"]]
        return pd.json_normalize(rows)

    @staticmethod
    def write_df(es_config: Dict, es_resource: str,
                 df: pd.DataFrame) -> int:
        from elasticsearch import helpers
        es = _client(es_config)
        actions = ({"_index": es_resource, "_source": row.to_dict()}
                   for _, row in df.iterrows())
        ok, _ = helpers.bulk(es, actions)
        return int(ok)

    @staticmethod
    def flatten_df(df: pd.DataFrame) -> pd.DataFrame:
        """`flatten_df` (elastic_search.py:57): expand nested dict columns
        into dotted top-level columns."""
        flat = pd.json_normalize(df.to_dict(orient="records"))
        return flat
