"""TextSet + text preprocessing pipeline.

The reference's distributed text pipeline (`zoo/.../feature/text/
TextSet.scala`, ~800 LoC; python mirror `pyzoo/zoo/feature/text/`):
tokenize → normalize → word2idx → shapeSequence → generateSample, plus
pretrained GloVe embedding loading for `WordEmbedding`. Same stages here as
host-side numpy ops feeding padded int32 batches (static shapes for jit).
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from analytics_zoo_tpu.data.minibatch import pad_sequences

_TOKEN_RE = re.compile(r"[a-zA-Z]+|[0-9]+|[^\sa-zA-Z0-9]")


class TextFeature:
    """One text sample (`feature/text/TextFeature.scala`)."""

    def __init__(self, text: str, label: Optional[int] = None):
        self.text = text
        self.label = label
        self.tokens: Optional[List[str]] = None
        self.indices: Optional[List[int]] = None


class TextSet:
    """Batch of TextFeatures with chained preprocessing
    (`TextSet.scala` tokenize/normalize/word2idx/shapeSequence)."""

    def __init__(self, features: Sequence[TextFeature]):
        self.features = list(features)
        self.word_index: Optional[Dict[str, int]] = None

    @staticmethod
    def from_texts(texts: Sequence[str],
                   labels: Optional[Sequence[int]] = None) -> "TextSet":
        labels = labels if labels is not None else [None] * len(texts)
        return TextSet([TextFeature(t, l) for t, l in zip(texts, labels)])

    @staticmethod
    def read_csv(path: str, text_col: str = "text",
                 label_col: Optional[str] = "label") -> "TextSet":
        import pandas as pd
        df = pd.read_csv(path)
        labels = df[label_col].tolist() if label_col and label_col in df \
            else None
        return TextSet.from_texts(df[text_col].tolist(), labels)

    # -- pipeline stages ---------------------------------------------------
    def tokenize(self) -> "TextSet":
        for f in self.features:
            f.tokens = _TOKEN_RE.findall(f.text)
        return self

    def normalize(self) -> "TextSet":
        """Lower-case + strip non-alphanumeric tokens (`Normalizer`)."""
        for f in self.features:
            if f.tokens is None:
                raise ValueError("normalize() requires tokenize() first")
            f.tokens = [t.lower() for t in f.tokens if t.isalnum()]
        return self

    def word2idx(self, remove_topN: int = 0, max_words_num: int = -1,
                 min_freq: int = 1,
                 existing_map: Optional[Dict[str, int]] = None) -> "TextSet":
        """Build the vocab (1-based; 0 is the pad index) with the reference's
        knobs (`TextSet.scala` word2idx: removeTopN, maxWordsNum, minFreq,
        existingMap)."""
        if existing_map is not None:
            self.word_index = dict(existing_map)
        else:
            counts = Counter()
            for f in self.features:
                if f.tokens is None:
                    raise ValueError("word2idx() requires tokenize() first")
                counts.update(f.tokens)
            ordered = [w for w, c in counts.most_common() if c >= min_freq]
            ordered = ordered[remove_topN:]
            if max_words_num > 0:
                ordered = ordered[:max_words_num]
            self.word_index = {w: i + 1 for i, w in enumerate(ordered)}
        for f in self.features:
            f.indices = [self.word_index[t] for t in (f.tokens or [])
                         if t in self.word_index]
        return self

    def shape_sequence(self, len: int, trunc_mode: str = "pre",  # noqa: A002
                       pad_element: int = 0) -> "TextSet":
        """Fix sequence length (`TextSet.shapeSequence`; default truncation
        keeps the tail, BigDL semantics)."""
        self._seq_len = len
        self._trunc = trunc_mode
        self._pad = pad_element
        return self

    def generate_sample(self) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Materialize (x, y) arrays."""
        if not hasattr(self, "_seq_len"):
            raise ValueError("call shape_sequence(len) before generate_sample")
        seqs = [f.indices if f.indices is not None else [] for f in self.features]
        x = pad_sequences(seqs, self._seq_len, value=self._pad,
                          truncating=self._trunc)
        labels = [f.label for f in self.features]
        y = None if any(l is None for l in labels) \
            else np.asarray(labels, np.int32)
        return x, y

    def to_dataset(self, batch_size: int = -1, batch_per_thread: int = -1):
        from analytics_zoo_tpu.data.dataset import TPUDataset
        x, y = self.generate_sample()
        return TPUDataset(x, y, batch_size, batch_per_thread)

    def get_word_index(self) -> Dict[str, int]:
        if self.word_index is None:
            raise ValueError("word2idx has not been run")
        return self.word_index

    def __len__(self):
        return len(self.features)


def load_glove(path: str, word_index: Optional[Dict[str, int]] = None,
               dim: int = 100) -> np.ndarray:
    """Load GloVe vectors into an embedding matrix aligned with word_index
    (`WordEmbedding.scala` glove loading). Row 0 is the pad vector."""
    vectors: Dict[str, np.ndarray] = {}
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            parts = line.rstrip().split(" ")
            if len(parts) != dim + 1:
                continue
            vectors[parts[0]] = np.asarray(parts[1:], np.float32)
    if word_index is None:
        word_index = {w: i + 1 for i, w in enumerate(vectors)}
    mat = np.zeros((max(word_index.values()) + 1, dim), np.float32)
    for w, i in word_index.items():
        if w in vectors:
            mat[i] = vectors[w]
    return mat
