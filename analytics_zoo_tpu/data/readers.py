"""File readers producing XShards — orca's `zoo.orca.data.pandas` surface.

`read_csv`/`read_json` mirror `orca/data/pandas/preprocessing.py:26-120`
(file-or-directory paths, per-file shards, pandas backend per the
`OrcaContext.pandas_read_backend` flag); `read_parquet` covers the parquet
image-dataset reader (`orca/data/image/parquet_dataset.py`). Each file (or
row-group) becomes one shard so preprocessing parallelizes like the
reference's per-partition reads — and the reads themselves run on the
shared input-pipeline worker pool (`data/pipeline.py`, ISSUE 15): a
64-file directory is 64 concurrent `pd.read_csv` calls instead of 64
sequential ones, results in deterministic file order, and a per-file
failure surfaces as ONE error naming the file. `pipeline_workers`
defaults to `ZooConfig.pipeline_workers` (env ZOO_PIPELINE_WORKERS).
"""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, List, Optional, Sequence

from analytics_zoo_tpu.data.shards import XShards


def _expand(file_path: str, extensions: Sequence[str]) -> List[str]:
    if os.path.isdir(file_path):
        files = sorted(
            f for f in glob.glob(os.path.join(file_path, "*"))
            if f.rsplit(".", 1)[-1].lower() in extensions)
    elif any(ch in file_path for ch in "*?["):
        files = sorted(glob.glob(file_path))
    else:
        files = [file_path]
    if not files:
        raise FileNotFoundError(f"No input files under {file_path}")
    return files


def _read_shards(files: List[str], read_one: Callable[[str], Any],
                 pipeline_workers: Optional[int],
                 label_fn: Callable[[Any], str] = str) -> List[Any]:
    from analytics_zoo_tpu.data.pipeline import parallel_read
    return parallel_read(files, read_one, workers=pipeline_workers,
                         label_fn=label_fn)


def read_csv(file_path: str, num_shards: Optional[int] = None,
             pipeline_workers: Optional[int] = None, **kwargs) -> XShards:
    """Read csv file/dir/glob into XShards of pandas DataFrames
    (`zoo.orca.data.pandas.read_csv`), one concurrent read per file."""
    import pandas as pd
    files = _expand(file_path, ("csv",))
    shards = _read_shards(files, lambda f: pd.read_csv(f, **kwargs),
                          pipeline_workers)
    out = XShards(shards)
    if num_shards and num_shards != out.num_partitions():
        out = out.repartition(num_shards)
    return out


def read_json(file_path: str, num_shards: Optional[int] = None,
              pipeline_workers: Optional[int] = None, **kwargs) -> XShards:
    import pandas as pd
    files = _expand(file_path, ("json", "jsonl"))
    shards = _read_shards(files, lambda f: pd.read_json(f, **kwargs),
                          pipeline_workers)
    out = XShards(shards)
    if num_shards and num_shards != out.num_partitions():
        out = out.repartition(num_shards)
    return out


def read_parquet(file_path: str, columns: Optional[Sequence[str]] = None,
                 num_shards: Optional[int] = None,
                 pipeline_workers: Optional[int] = None) -> XShards:
    """Parquet → XShards, one shard per row-group/file
    (`orca/data/image/parquet_dataset.py` read side). Row-group
    metadata is listed sequentially (cheap footer reads), then the
    row-group DECODE — the expensive part — fans out over the worker
    pool with the (file, row-group) order preserved."""
    import threading

    import pyarrow.parquet as pq
    files = _expand(file_path, ("parquet", "pq"))
    units: List[tuple] = []
    for f in files:
        pf = pq.ParquetFile(f)
        units.extend((f, rg) for rg in range(pf.num_row_groups))

    # one footer parse per (file, thread), not per row-group: a
    # 1000-row-group file must not pay 1000 redundant metadata reads
    # (ParquetFile handles are not thread-safe, hence per-thread)
    tls = threading.local()

    def read_unit(unit):
        f, rg = unit
        cache = getattr(tls, "files", None)
        if cache is None:
            cache = tls.files = {}
        pf = cache.get(f)
        if pf is None:
            pf = cache[f] = pq.ParquetFile(f)
        return pf.read_row_group(rg, columns=columns).to_pandas()

    shards = _read_shards(units, read_unit, pipeline_workers,
                          label_fn=lambda u: f"{u[0]} row-group {u[1]}")
    out = XShards(shards)
    if num_shards and num_shards != out.num_partitions():
        out = out.repartition(num_shards)
    return out
