"""File readers producing XShards — orca's `zoo.orca.data.pandas` surface.

`read_csv`/`read_json` mirror `orca/data/pandas/preprocessing.py:26-120`
(file-or-directory paths, per-file shards, pandas backend per the
`OrcaContext.pandas_read_backend` flag); `read_parquet` covers the parquet
image-dataset reader (`orca/data/image/parquet_dataset.py`). Each file (or
row-group) becomes one shard so preprocessing parallelizes like the
reference's per-partition reads.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Callable, List, Optional, Sequence

from analytics_zoo_tpu.data.shards import XShards


def _expand(file_path: str, extensions: Sequence[str]) -> List[str]:
    if os.path.isdir(file_path):
        files = sorted(
            f for f in glob.glob(os.path.join(file_path, "*"))
            if f.rsplit(".", 1)[-1].lower() in extensions)
    elif any(ch in file_path for ch in "*?["):
        files = sorted(glob.glob(file_path))
    else:
        files = [file_path]
    if not files:
        raise FileNotFoundError(f"No input files under {file_path}")
    return files


def read_csv(file_path: str, num_shards: Optional[int] = None,
             **kwargs) -> XShards:
    """Read csv file/dir/glob into XShards of pandas DataFrames
    (`zoo.orca.data.pandas.read_csv`)."""
    import pandas as pd
    files = _expand(file_path, ("csv",))
    shards = [pd.read_csv(f, **kwargs) for f in files]
    out = XShards(shards)
    if num_shards and num_shards != out.num_partitions():
        out = out.repartition(num_shards)
    return out


def read_json(file_path: str, num_shards: Optional[int] = None,
              **kwargs) -> XShards:
    import pandas as pd
    files = _expand(file_path, ("json", "jsonl"))
    shards = [pd.read_json(f, **kwargs) for f in files]
    out = XShards(shards)
    if num_shards and num_shards != out.num_partitions():
        out = out.repartition(num_shards)
    return out


def read_parquet(file_path: str, columns: Optional[Sequence[str]] = None,
                 num_shards: Optional[int] = None) -> XShards:
    """Parquet → XShards, one shard per row-group/file
    (`orca/data/image/parquet_dataset.py` read side)."""
    import pandas as pd
    import pyarrow.parquet as pq
    files = _expand(file_path, ("parquet", "pq"))
    shards = []
    for f in files:
        pf = pq.ParquetFile(f)
        for rg in range(pf.num_row_groups):
            shards.append(pf.read_row_group(rg, columns=columns).to_pandas())
    out = XShards(shards)
    if num_shards and num_shards != out.num_partitions():
        out = out.repartition(num_shards)
    return out
